"""Sharded checkpoint / resume (SURVEY §5: the reference has data-level I/O only —
``ht.save``/``ht.load`` hyperslabs, heat/core/io.py:58-238 — and no training-state
checkpointing; users fall back to ``torch.save``. The TPU build adds the idiomatic
equivalent: manifest-backed atomic checkpoints of DNDarrays and parameter pytrees).

Checkpoint v2 (ISSUE 13 — parallel sharded state management)
------------------------------------------------------------

Schema ``heat-tpu-checkpoint/2`` stores every DNDarray leaf as a set of
**chunk files** on the canonical ``comm.chunk`` grid (chunk ``i`` holds logical
rows ``[i*c, min((i+1)*c, n))`` along the leaf's split, ``c = ceil(n / shards)``
— the same ceil-division rule ``io.save_zarr`` aligns its chunk layout to), so:

- **Parallel writes.** Each process writes only the chunks of the shards it
  addresses (``iter_shards``), overlapped through a small bounded writer pool
  (``HEAT_TPU_CKPT_WRITERS``, default ``min(8, cpu)``). Host gathering is
  leaf-by-leaf — each leaf's host copy is released once its chunks are on disk,
  so peak host memory is ONE leaf, not the tree (``checkpoint.gathered_bytes``
  / ``checkpoint.written_bytes`` count the traffic).
- **Resharding-on-restore.** The manifest records every chunk's (offset, rows,
  nbytes, sha256); :func:`load_checkpoint` accepts a template whose split /
  shard count differ from the writer's and each process reads only the chunk
  byte ranges overlapping its target shards (contiguous row ranges for
  split-0 chunk grids; whole-chunk reads — still bounded by one writer shard —
  otherwise), re-masks target pads to zero, and never materialises a full
  leaf on any host. Chunk reads are double-buffered against device transfer
  (a read-ahead thread stays one shard ahead of ``jax.device_put``).
  ``strict="layout"`` rejects any layout change instead
  (:class:`CheckpointLayoutMismatch`); the default ``strict="reshard"``
  permits it.

Failure contract (ISSUE 6, extended to partial chunk sets):

- **Atomic commit.** A checkpoint is assembled in a same-filesystem temp
  directory — every chunk payload written through ``resilience.atomic_write``
  (write-to-temp + fsync + rename) under the ``checkpoint.chunk_write`` site,
  the manifest written LAST (``checkpoint.manifest``) — and committed under
  the ``checkpoint.commit`` site by renaming the previous checkpoint ASIDE,
  the new one in, then deleting the old. A crash at ANY point (mid-chunk,
  between chunks, pre-manifest, between the two commit renames) leaves either
  the previous generation or the complete new one restorable — partial chunk
  sets only ever exist inside the uncommitted ``.tmp.*`` assembly dir, which
  the next save sweeps (:func:`_sweep_stale`, unchanged from v1, recovering a
  stranded ``.old.*`` backup when the commit died between its two renames).
- **Partial-write detection.** The manifest records every chunk's byte length
  and SHA-256; :func:`verify_checkpoint` checks them ALL — in parallel, one
  streamed digest per chunk on the writer-pool — and reports per-chunk
  problems. :func:`load_checkpoint` verifies before restoring and raises
  :class:`CheckpointCorrupt` naming each torn / missing / mismatched file.
- **Degradation ladder.** Chunk-write failures (after the per-write retry
  policy) feed the ``checkpoint.chunk_write`` circuit breaker and degrade THE
  SAVE to the serialized v1 single-writer path — never silently: a
  ``fallback`` resilience event (flight-recorded) and a
  ``diagnostics.record_fallback`` account every degradation, and an open
  breaker short-circuits later saves straight to v1 until its cooldown. v1
  checkpoints (schema ``heat-tpu-checkpoint/1``) remain fully readable.
- **Multi-controller crash symmetry.** Every rank reaches the same barrier
  sequence whether its local writes succeeded or not; a post-write agreement
  collective (and a second one after the writer's commit) turns any rank's
  failure into an exception ON EVERY RANK — a crashed save surfaces as a
  typed error, never a distributed hang. Writer-only work (sweep, manifest,
  commit) is host-local; collectives are emitted rank-symmetrically (the
  effective-path early-return idiom ``ht.analysis`` verifies).

Surface:

- :func:`save_checkpoint` / :func:`load_checkpoint` — a pytree of DNDarrays /
  jax.Arrays / numpy leaves to/from a checkpoint directory. Params, optimizer
  state (e.g. DASO's ``_opt_state``), and RNG counters
  (``ht.random.get_state()`` folded into plain integer leaves) ride one tree.
- :class:`CheckpointManager` — rolling step-numbered checkpoints with
  retention; pruning is routed through ``ht.resilience`` (site
  ``checkpoint.prune``) with a recorded event per deletion, skips (and retries
  next save) any step directory a concurrent restore holds open, and raises —
  loudly — when a deletion fails instead of best-effort ``rmtree``.

DNDarray leaves come back with the *template's* split/comm/device; payloads are
raw little-endian buffers named in the manifest (not ``.npy``), so extension
dtypes (bfloat16) round-trip without pickling.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import re
import shutil
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax

from . import diagnostics, io, resilience, supervision
from . import types as _types
from .communication import sanitize_comm
from .devices import sanitize_device
from .dndarray import DNDarray

__all__ = [
    "save_checkpoint",
    "load_checkpoint",
    "CheckpointManager",
    "CheckpointCorrupt",
    "CheckpointLayoutMismatch",
    "CheckpointWriteFailed",
    "last_restore_stats",
    "SCHEMA",
    "SCHEMA_V1",
    "MANIFEST_NAME",
]

SCHEMA = "heat-tpu-checkpoint/2"
SCHEMA_V1 = "heat-tpu-checkpoint/1"
MANIFEST_NAME = "manifest.json"

_WRITE_SITE = "checkpoint.write"            # v1 serialized leaf writes
_MANIFEST_SITE = "checkpoint.manifest"
_CHUNK_WRITE_SITE = "checkpoint.chunk_write"
_CHUNK_READ_SITE = "checkpoint.chunk_read"
_COMMIT_SITE = "checkpoint.commit"
_PRUNE_SITE = "checkpoint.prune"
_META_SITE = "checkpoint.chunk_meta"        # multi-controller sidecar metadata

#: chunk-write breaker config: repeated exhausted chunk writes open it and
#: later saves short-circuit straight to the serialized v1 path (recorded)
#: until the cooldown re-admits a parallel trial.
_CHUNK_BREAKER_THRESHOLD = 3
_CHUNK_BREAKER_COOLDOWN_S = 60.0

# Module state registries (see the module-lock note in _state_lock): which
# checkpoint directories a restore currently holds open (pruning defers on
# them), and the last restore's read-traffic gauges.
_state_lock = threading.Lock()
_open_restores: Dict[str, int] = {}
_restore_stats: Dict[str, int] = {"read_bytes": 0, "host_bytes_peak": 0}


class CheckpointCorrupt(RuntimeError):
    """A checkpoint failed integrity verification on restore. ``problems``
    lists one human-readable finding per torn / missing / mismatched file."""

    def __init__(self, directory: str, problems: List[str]):
        self.directory = directory
        self.problems = list(problems)
        detail = "; ".join(self.problems)
        super().__init__(
            f"checkpoint at {directory!r} is corrupt or partially written: {detail}"
        )


class CheckpointLayoutMismatch(ValueError):
    """``load_checkpoint(strict="layout")`` found the stored layout (split /
    shard count) differing from the restore template's. Resharding-on-restore
    would handle it — pass ``strict="reshard"`` (the default) to allow it."""


class CheckpointWriteFailed(RuntimeError):
    """A distributed save failed on some process: every rank raises this (or
    the originating error) instead of hanging at the commit barrier."""


# ------------------------------------------------------------------ helpers
def _to_storable(tree: Any):
    """Split a pytree into (array tree, split-metadata tree) — the v1 form."""
    leaves, treedef = jax.tree.flatten(tree)
    arrays, splits = [], []
    for leaf in leaves:
        if isinstance(leaf, DNDarray):
            arrays.append(leaf.larray)
            splits.append(leaf.split if leaf.split is not None else -1)
        else:
            # numpy scalars are not a storable leaf type; 0-d arrays are
            arrays.append(np.asarray(leaf) if isinstance(leaf, np.generic) else leaf)
            splits.append(-2)  # plain leaf, restore as-is
    return treedef, arrays, splits


def _dtype_name(dtype) -> str:
    return np.dtype(dtype).name


def _dtype_from_name(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # extension dtypes (bfloat16, float8_*) live here

        return np.dtype(getattr(ml_dtypes, name))


def _host_value(value) -> np.ndarray:
    """One leaf as a host numpy array. Multi-controller DNDarray shards were
    already collected by the caller; a non-addressable raw jax.Array still
    needs the cross-host gather. A replicated layout short-circuits (every
    process already holds a complete copy); the genuine gather uses the XLA
    allgather on accelerator backends and the coordination KV store on CPU
    meshes, where cross-process XLA computations do not exist."""
    if isinstance(value, jax.Array) and not value.is_fully_addressable:
        shard0 = value.addressable_shards[0]
        if _covers_all(shard0.index, value.shape):
            return np.asarray(shard0.data)
        if jax.default_backend() == "cpu":
            return _coord_gather(value)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(value))
    return np.asarray(value)


def _covers_all(index, shape) -> bool:
    """True when a shard's global index spans the whole array (replicated)."""
    return all(
        (sl.start or 0) == 0 and (sl.stop is None or int(sl.stop) >= int(dim))
        for sl, dim in zip(index, shape)
    )


def _is_writer() -> bool:
    return jax.process_index() == 0


#: Cross-process agreement rides the ``jax.distributed`` coordination service
#: (the KV store) — the same no-XLA channel as
#: ``communication._telemetry_bootstrap`` — so the crash contract holds on
#: every backend, CPU meshes included (multiprocess XLA collectives are
#: accelerator-only). KV keys are namespace-scoped per use: the sequence
#: counter below hands every rank the same fresh namespace per operation,
#: which stays aligned because every save's collective sequence is
#: rank-symmetric by construction (the module's core invariant). Every wait
#: goes through the supervised wrappers (``supervision.kv_wait`` /
#: ``kv_barrier``): bounded by the unified ``HEAT_TPU_COORD_TIMEOUT_MS``
#: budget (replacing the 600 s hardcoded here pre-supervision),
#: sentinel-abortable mid-wait (a dead peer raises typed
#: ``resilience.PeerFailed`` instead of stalling the save), and typed
#: ``resilience.CoordinationTimeout`` on exhaustion instead of an opaque
#: backend error.
_coord_seq = 0
_coord_my_keys: List[Tuple[int, str]] = []


def _coord_client():
    client = jax._src.distributed.global_state.client
    if client is None:
        raise CheckpointWriteFailed(
            "multi-process checkpoint agreement needs the jax.distributed "
            "coordination service, which is not initialized"
        )
    return client


def _coord_ns(tag: str) -> Tuple[int, str]:
    """A fresh, rank-identical coordination namespace for one collective."""
    global _coord_seq
    with _state_lock:
        _coord_seq += 1
        seq = _coord_seq
    safe = re.sub(r"[^A-Za-z0-9._-]", "_", tag)[-64:]
    return seq, f"heat_tpu/ckpt/{seq}/{safe}"


def _coord_publish(client, seq: int, key: str, value: str) -> None:
    client.key_value_set(key, value)
    with _state_lock:
        _coord_my_keys.append((seq, key))


def _coord_sweep(client, seq: int) -> None:
    """Delete this rank's KV keys from collectives strictly earlier than the
    one just completed. Safe by program order: finishing collective ``seq``
    (reading every rank's entry / passing its barrier) proves every rank
    finished ``seq - 1`` and earlier, so no peer can still be reading those
    keys — this bounds the coordination server's store across long-running
    jobs instead of leaking one namespace (including gathered leaf payloads)
    per collective."""
    with _state_lock:
        dead = [k for s, k in _coord_my_keys if s < seq]
        _coord_my_keys[:] = [(s, k) for s, k in _coord_my_keys if s >= seq]
    for key in dead:
        try:
            client.key_value_delete(key)
        except Exception as exc:  # a leaked key is benign; account, don't fail
            diagnostics.record_fallback(
                "checkpoint.coord_sweep", f"{key}: {type(exc).__name__}: {exc}"
            )


def _coord_gather(value) -> np.ndarray:
    """Assemble a non-addressable array on every host over the coordination
    KV store (CPU meshes only — accelerator backends take the XLA gather in
    :func:`_host_value`): each process publishes its replica-0 shard slabs,
    every process reads them all and fills the global value."""
    client = _coord_client()
    seq, ns = _coord_ns("gather")
    dtype = np.dtype(value.dtype)
    mine = []
    for s in value.addressable_shards:
        if s.replica_id != 0:
            continue
        host = np.ascontiguousarray(np.asarray(s.data))
        mine.append({
            "index": [
                [int(sl.start or 0),
                 int(sl.stop) if sl.stop is not None else int(dim)]
                for sl, dim in zip(s.index, value.shape)
            ],
            # the uint8 view sidesteps the missing buffer protocol on
            # extension dtypes (bfloat16), byte-identical to tobytes()
            "b64": base64.b64encode(
                host.reshape(-1).view(np.uint8).tobytes()
            ).decode("ascii"),
        })
    _coord_publish(client, seq, f"{ns}/{jax.process_index()}", json.dumps(mine))
    out = np.zeros(value.shape, dtype)
    co = supervision.ClientCoordinator(client)
    for r in range(jax.process_count()):
        items = json.loads(
            supervision.kv_wait(f"{ns}/{r}", site="checkpoint.coord",
                                coordinator=co)
        )
        for item in items:
            region = tuple(slice(b, e) for b, e in item["index"])
            shape = tuple(e - b for b, e in item["index"])
            out[region] = np.frombuffer(
                base64.b64decode(item["b64"]), dtype=dtype
            ).reshape(shape)
    _coord_sweep(client, seq)
    return out


def _barrier(tag: str) -> None:
    if jax.process_count() > 1:
        client = _coord_client()
        seq, ns = _coord_ns(f"barrier/{tag}")
        # the supervised KV barrier (not the native wait_at_barrier): it is
        # sentinel-abortable MID-WAIT and its timeout names the ranks that
        # never arrived; _coord_publish registers this rank's key for the
        # sweep (kv_barrier's own re-set of it is an idempotent overwrite)
        _coord_publish(client, seq, f"{ns}/{jax.process_index()}", "1")
        supervision.kv_barrier(
            ns, nprocs=jax.process_count(), rank=jax.process_index(),
            site="checkpoint.barrier",
            coordinator=supervision.ClientCoordinator(client),
        )
        _coord_sweep(client, seq)


def _agree_min(flag: int) -> int:
    """The MINIMUM of every process's ``flag`` — identical on all ranks, so a
    branch taken on the result can never diverge the collective sequence. The
    post-write agreement that turns one rank's failure into everyone's typed
    exception instead of a distributed hang."""
    if jax.process_count() == 1:
        return int(flag)
    client = _coord_client()
    seq, ns = _coord_ns("agree")
    _coord_publish(client, seq, f"{ns}/{jax.process_index()}", str(int(flag)))
    co = supervision.ClientCoordinator(client)
    agreed = min(
        int(supervision.kv_wait(f"{ns}/{i}", site="checkpoint.agree",
                                coordinator=co))
        for i in range(jax.process_count())
    )
    _coord_sweep(client, seq)
    return agreed


def _writer_pool_size() -> int:
    """Bounded writer/verifier pool width: ``HEAT_TPU_CKPT_WRITERS`` or
    ``min(8, cpu)`` — enough to overlap sha256 + file I/O, small enough to
    never look like a fork bomb on a shared box."""
    try:
        n = int(os.environ.get("HEAT_TPU_CKPT_WRITERS", "") or 0)
    except ValueError:
        n = 0
    return n if n >= 1 else min(8, os.cpu_count() or 1)


def _chunk_breaker() -> resilience.CircuitBreaker:
    return resilience.breaker(
        _CHUNK_WRITE_SITE,
        failure_threshold=_CHUNK_BREAKER_THRESHOLD,
        cooldown_s=_CHUNK_BREAKER_COOLDOWN_S,
    )


def _sweep_stale(directory: str) -> None:
    """Clean up what a crashed earlier save left behind, whatever its pid:
    uncommitted ``.tmp.*`` assembly dirs are deleted (a partial chunk set can
    only ever live there — it is never restorable); a ``.old.*`` backup is
    restored to the canonical path when the crash stranded it there (the
    commit died between the two renames and the target is gone), else
    deleted — it was an already-replaced generation."""
    base = os.path.basename(directory)
    parent = os.path.dirname(directory) or "."
    try:
        names = os.listdir(parent)
    except FileNotFoundError:
        return
    for name in sorted(names):
        full = os.path.join(parent, name)
        if name.startswith(f"{base}.tmp."):
            shutil.rmtree(full, ignore_errors=True)
        elif name.startswith(f"{base}.old."):
            if not os.path.exists(directory):
                try:
                    os.rename(full, directory)
                    diagnostics.record_resilience_event(
                        "checkpoint.save", "recovered",
                        f"restored crash-stranded backup {name} to {directory}",
                    )
                    continue
                except OSError:
                    pass
            shutil.rmtree(full, ignore_errors=True)


def _commit_dir(tmpdir: str, directory: str) -> None:
    """Commit an assembled checkpoint dir: rename the previous generation
    ASIDE (never rmtree'd first), the new one in, then delete the old — a
    crash between the renames leaves the old bits recoverable at
    ``<directory>.old.<pid>`` and the next save's sweep restores them. The
    ``checkpoint.commit`` fault site fires once before each rename, so chaos
    plans can kill the commit at either point deterministically."""
    backup = None
    if os.path.exists(directory):
        backup = f"{directory}.old.{os.getpid()}"
        shutil.rmtree(backup, ignore_errors=True)
        if resilience._armed:
            resilience.maybe_fault(_COMMIT_SITE)
        os.rename(directory, backup)
    try:
        if resilience._armed:
            resilience.maybe_fault(_COMMIT_SITE)
        os.rename(tmpdir, directory)
    except BaseException:
        if backup is not None:
            try:
                os.rename(backup, directory)
            except OSError:
                pass  # old bits stay recoverable at the backup path
        raise
    if backup is not None:
        shutil.rmtree(backup, ignore_errors=True)
    resilience.fsync_dir(os.path.dirname(directory) or ".")


# ------------------------------------------------------------------ v1 save
def _save_v1(tree: Any, directory: str) -> None:
    """The serialized single-writer path (schema ``heat-tpu-checkpoint/1``):
    every leaf cross-host-gathered, process 0 writes everything. Kept verbatim
    as the degradation target of the parallel v2 path — and the proof that v1
    checkpoints stay writable AND readable."""
    _, arrays, splits = _to_storable(tree)
    host = [_host_value(a) for a in arrays]  # collective: every process joins
    if not _is_writer():
        _barrier(f"save:{directory}")
        return
    _sweep_stale(directory)
    tmpdir = f"{directory}.tmp.{os.getpid()}"
    shutil.rmtree(tmpdir, ignore_errors=True)
    os.makedirs(tmpdir)
    try:
        entries = []
        for i, (value, split) in enumerate(zip(host, splits)):
            name = f"leaf_{i}.bin"
            payload = np.ascontiguousarray(value).tobytes()

            def write(tmp_path: str, _payload=payload) -> None:
                with open(tmp_path, "wb") as fh:
                    fh.write(_payload)

            resilience.atomic_write(
                os.path.join(tmpdir, name), write, site=_WRITE_SITE
            )
            entries.append(
                {
                    "file": name,
                    "shape": [int(s) for s in value.shape],
                    "dtype": _dtype_name(value.dtype),
                    "split": int(split),
                    "nbytes": len(payload),
                    "sha256": hashlib.sha256(payload).hexdigest(),
                }
            )
        manifest = {"schema": SCHEMA_V1, "leaves": entries}

        def write_manifest(tmp_path: str) -> None:
            with open(tmp_path, "w") as fh:
                json.dump(manifest, fh, indent=2, sort_keys=True)
                fh.write("\n")

        # manifest LAST: its presence marks the leaf set complete, so a crash
        # between leaf writes can never masquerade as a restorable checkpoint
        resilience.atomic_write(
            os.path.join(tmpdir, MANIFEST_NAME), write_manifest, site=_MANIFEST_SITE
        )
        resilience.fsync_dir(tmpdir)
        _commit_dir(tmpdir, directory)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
        # the barrier must run even when the writer FAILED: the other
        # processes are already parked in their matching sync, and a write
        # error must surface as this exception — never as a distributed hang
        _barrier(f"save:{directory}")


# ------------------------------------------------------------------ v2 save
def _chunk_file(leaf_idx: int, chunk_idx: int) -> str:
    return f"leaf_{leaf_idx}.c{chunk_idx:05d}.bin"


def _leaf_host_chunks(leaf_idx: int, leaf: Any) -> Tuple[dict, List[dict]]:
    """One leaf's manifest skeleton plus the chunk-payload jobs THIS process
    owns. Split DNDarray leaves yield one job per addressable shard (host
    memory O(local shards), no gather); replicated / plain leaves gather —
    collectively, every rank — and the writer owns the single chunk.

    Chunk grid = the canonical ``comm.chunk`` rule: chunk ``i`` holds logical
    rows ``[i*c, min((i+1)*c, n))``, ``c = ceil(n / shards)`` — which is
    exactly the per-shard slab ``iter_shards`` yields, so a shard IS a chunk.
    """
    jobs: List[dict] = []
    if isinstance(leaf, DNDarray) and leaf.split is not None and leaf.ndim > 0:
        split = int(leaf.split)
        shards = int(leaf.comm.size)
        n = int(leaf.gshape[split])
        c = -(-n // shards) if n else 0
        entry = {
            "shape": [int(s) for s in leaf.gshape],
            "dtype": _dtype_name(np.dtype(leaf.dtype.jax_type())),
            "split": split,
            "shards": shards,
        }
        for index, value in leaf.iter_shards():
            off = int(index[split].start or 0)
            if c <= 0 or off % c:
                raise CheckpointWriteFailed(
                    f"leaf {leaf_idx}: shard offset {off} is off the canonical "
                    f"chunk grid (c={c}, shards={shards}) — non-canonical layout"
                )
            # the device→host copy happens on the WRITER POOL (the job
            # carries the lazy shard value), so transfer + hash + write of
            # different chunks overlap and each host copy dies with its job
            jobs.append({
                "file": _chunk_file(leaf_idx, off // c),
                "offset": off,
                "rows": int(index[split].stop) - off,
                "value": value,
            })
        return entry, jobs
    # replicated DNDarray / raw jax.Array / numpy leaf: ONE chunk, writer-owned
    if isinstance(leaf, DNDarray):
        value = _host_value(leaf.larray)  # collective when non-addressable
        split_code = -1
    else:
        raw = np.asarray(leaf) if isinstance(leaf, np.generic) else leaf
        value = _host_value(raw)
        split_code = -2
    entry = {
        "shape": [int(s) for s in value.shape],
        "dtype": _dtype_name(value.dtype),
        "split": split_code,
        "shards": 1,
    }
    if _is_writer():
        jobs.append({
            "file": _chunk_file(leaf_idx, 0),
            "offset": 0,
            "rows": int(value.shape[0]) if value.ndim else 1,
            "value": value,
        })
    return entry, jobs


def _write_chunk(tmpdir: str, job: dict) -> dict:
    """Materialise one chunk on host and write + fsync it, on a writer-pool
    thread, under the ``checkpoint.chunk_write`` site policy.

    The file is written IN PLACE inside the (uncommitted) assembly dir: the
    manifest-last rule plus the directory commit rename own atomicity, so a
    per-chunk temp+rename would only serialize the save on directory-inode
    fsyncs — a crash mid-write leaves a torn file in a ``.tmp.*`` dir the next
    save sweeps, never a restorable checkpoint. An injected ``torn-write``
    fault truncates the written bytes AFTER the sha below is recorded — the
    committed-but-silently-short chunk that per-chunk verification must
    catch. Returns the chunk's manifest entry."""
    host = np.ascontiguousarray(np.asarray(job["value"]))
    # raw little-endian bytes WITHOUT a copy: extension dtypes (bfloat16,
    # float8_*) do not implement the buffer protocol, so a plain
    # memoryview(host) would raise — the uint8 view sidesteps that and
    # hashes/writes byte-identically to tobytes()
    payload = host.reshape(-1).view(np.uint8)
    path = os.path.join(tmpdir, job["file"])

    def attempt() -> None:
        cut = None
        entry = resilience.fault_signal(_CHUNK_WRITE_SITE)
        if entry is not None:
            if entry.kind == "torn-write":
                cut = int(len(payload) * entry.fraction)
            else:
                resilience.raise_entry(entry, _CHUNK_WRITE_SITE)
        with open(path, "wb") as fh:
            fh.write(payload if cut is None else payload[:cut])
            fh.flush()
            os.fsync(fh.fileno())

    resilience.get_policy(_CHUNK_WRITE_SITE).run(_CHUNK_WRITE_SITE, attempt)
    return {
        "file": job["file"],
        "offset": int(job["offset"]),
        "rows": int(job["rows"]),
        "nbytes": len(payload),
        "sha256": hashlib.sha256(payload).hexdigest(),
        "_gathered": host.nbytes,
    }


def _expected_offsets(entry: dict) -> List[int]:
    """The complete chunk-offset grid a leaf's manifest entry must cover."""
    if entry["split"] < 0:
        return [0]
    n = int(entry["shape"][entry["split"]])
    shards = int(entry["shards"])
    c = -(-n // shards) if n else 0
    return [i * c for i in range(shards) if i * c < n]


def _save_v2(tree: Any, directory: str) -> Optional[str]:
    """The parallel chunked save. Rank-symmetric by construction: gathers run
    on every rank in the same order, writer-only blocks (sweep, manifest,
    commit) contain no collectives, and the two agreement collectives plus the
    closing barrier run on every exit path.

    Returns ``None`` on commit, or a degradation reason when every rank agreed
    the chunk writes failed retriably — the caller then runs the serialized v1
    path (a RETURN value, not an exception, so the v1 collectives never run
    inside an except handler — the ``spmd-collective-in-except`` rule)."""
    leaves, _ = jax.tree.flatten(tree)
    if _is_writer():
        _sweep_stale(directory)
    tmpdir = f"{directory}.tmp.v2"
    if _is_writer():
        shutil.rmtree(tmpdir, ignore_errors=True)
        os.makedirs(tmpdir)
    _barrier(f"save-v2-setup:{directory}")
    breaker = _chunk_breaker()
    status = 0               # 0 ok | 1 degradable (chunk-write) | 2 hard
    first_error: Optional[BaseException] = None
    entries: List[dict] = []
    my_chunks: Dict[int, List[dict]] = {}
    gathered = written = 0
    pool = ThreadPoolExecutor(
        max_workers=_writer_pool_size(), thread_name_prefix="heat-tpu-ckpt"
    )
    try:
        for i, leaf in enumerate(leaves):
            # the gather side ALWAYS runs (it can be collective) — a rank
            # that already failed keeps emitting the same collective
            # sequence as its peers until the agreement below. A rank-LOCAL
            # gather failure (host OOM, non-canonical layout) is therefore
            # captured per leaf and the loop continues, so later leaves'
            # collective gathers stay aligned with the other ranks; a failure
            # inside a collective itself fails every rank anyway.
            try:
                entry, jobs = _leaf_host_chunks(i, leaf)
            except Exception as exc:
                if status != 2:
                    status, first_error = 2, exc
                    diagnostics.record_resilience_event(
                        "checkpoint.save", "hard-failure",
                        f"{directory}: leaf {i}: {type(exc).__name__}: {exc}",
                    )
                entries.append({})
                continue
            entries.append(entry)
            if status == 0 and jobs:
                # waiting per leaf bounds host memory to ONE leaf's chunks:
                # each job's host copy is created on a pool thread and dies
                # when its chunk is on disk
                futures = [pool.submit(_write_chunk, tmpdir, job) for job in jobs]
                metas: List[dict] = []
                for fut in futures:
                    try:
                        metas.append(fut.result())
                    except Exception as exc:
                        breaker.record_failure(f"{type(exc).__name__}: {exc}")
                        if status == 0:
                            status, first_error = 1, exc
                if status == 0:
                    gathered += sum(m.pop("_gathered") for m in metas)
                    my_chunks[i] = metas
                    written += sum(m["nbytes"] for m in metas)
            del jobs  # drop the shard references before the next gather
    except Exception as exc:  # gather/layout failures are not degradable
        status, first_error = 2, exc
        diagnostics.record_resilience_event(
            "checkpoint.save", "hard-failure",
            f"{directory}: {type(exc).__name__}: {exc}",
        )
    finally:
        pool.shutdown(wait=True)
    if diagnostics._enabled:
        diagnostics.counter("checkpoint.gathered_bytes", gathered)
        diagnostics.counter("checkpoint.written_bytes", written)
    # publish non-writer chunk metadata for the manifest through per-process
    # sidecars on the shared filesystem — BEFORE the agreement, so a sidecar
    # failure is part of the agreed verdict and can never strand peers at the
    # chunks barrier below
    if jax.process_count() > 1 and not _is_writer() and status == 0:
        sidecar = os.path.join(tmpdir, f"chunkmeta.p{jax.process_index()}.json")

        def write_meta(tmp_path: str) -> None:
            with open(tmp_path, "w") as fh:
                json.dump({str(k): v for k, v in my_chunks.items()}, fh)

        try:
            resilience.atomic_write(sidecar, write_meta, site=_META_SITE)
        except Exception as exc:
            status, first_error = 2, exc
            diagnostics.record_resilience_event(
                "checkpoint.save", "hard-failure",
                f"{directory}: chunk-metadata sidecar: "
                f"{type(exc).__name__}: {exc}",
            )
    verdict = _agree_min(
        {0: 2, 1: 1, 2: 0}[status]
    )  # encode so MIN yields the worst rank's verdict: 0 hard, 1 degrade, 2 ok
    try:
        if verdict != 2:
            # no commit will run, so the breaker gets no success/failure
            # verdict from THIS rank beyond what record_failure already
            # logged: release a held half-open probe token (no-op otherwise)
            # so the next save's parallel trial isn't stalled a cooldown
            breaker.abandon_probe()
        if verdict == 1:
            return (
                "chunk writes exhausted their retry policy ("
                + (f"{type(first_error).__name__}: {first_error}"
                   if first_error is not None else "peer failure")
                + ")"
            )
        if verdict == 0:
            if first_error is not None:
                raise first_error
            raise CheckpointWriteFailed(
                f"peer process reported a hard failure while assembling "
                f"{directory!r}; this rank's chunks were fine"
            )
        # every rank's chunks (and sidecars) landed
        _barrier(f"save-v2-chunks:{directory}")
        commit_error: Optional[BaseException] = None
        if _is_writer():
            try:
                _assemble_and_commit_v2(directory, tmpdir, entries, my_chunks)
            except BaseException as exc:
                commit_error = exc
        committed = _agree_min(1 if commit_error is None else 0)
        if commit_error is not None:
            raise commit_error
        if not committed:
            raise CheckpointWriteFailed(
                f"the writer process failed to commit {directory!r}; the "
                "previous generation (if any) is still restorable"
            )
        breaker.record_success()
        return None
    finally:
        if _is_writer():
            shutil.rmtree(tmpdir, ignore_errors=True)
        _barrier(f"save:{directory}")


def _assemble_and_commit_v2(
    directory: str, tmpdir: str, entries: List[dict],
    my_chunks: Dict[int, List[dict]],
) -> None:
    """Writer-only: fold every process's chunk metadata into the manifest,
    verify the chunk grid is complete, write the manifest LAST, commit."""
    merged: Dict[int, List[dict]] = {k: list(v) for k, v in my_chunks.items()}
    for name in os.listdir(tmpdir):
        if not name.startswith("chunkmeta.p"):
            continue
        with open(os.path.join(tmpdir, name)) as fh:
            side = json.load(fh)
        for key, metas in side.items():
            merged.setdefault(int(key), []).extend(metas)
        os.unlink(os.path.join(tmpdir, name))
    manifest_leaves = []
    for i, entry in enumerate(entries):
        chunks = sorted(merged.get(i, []), key=lambda c: c["offset"])
        have = [c["offset"] for c in chunks]
        want = _expected_offsets(entry)
        if have != want:
            raise CheckpointWriteFailed(
                f"leaf {i}: chunk grid incomplete — have offsets {have}, "
                f"the canonical grid needs {want}"
            )
        manifest_leaves.append({**entry, "chunks": chunks})
    manifest = {
        "schema": SCHEMA,
        "processes": jax.process_count(),
        "leaves": manifest_leaves,
    }

    def write_manifest(tmp_path: str) -> None:
        with open(tmp_path, "w") as fh:
            json.dump(manifest, fh, indent=2, sort_keys=True)
            fh.write("\n")

    # manifest LAST: its presence marks the chunk set complete, so a crash
    # between chunk writes can never masquerade as a restorable checkpoint
    resilience.atomic_write(
        os.path.join(tmpdir, MANIFEST_NAME), write_manifest, site=_MANIFEST_SITE
    )
    resilience.fsync_dir(tmpdir)
    _commit_dir(tmpdir, directory)


def save_checkpoint(
    tree: Any, directory: str, *, force: bool = True, parallel: bool = True
) -> None:
    """Write a pytree of DNDarrays / jax.Arrays / numpy leaves to ``directory``
    atomically (temp-dir assembly + manifest-last + backup-aside commit; see
    the module header for the failure contract and the v2 chunk layout).

    ``parallel=False`` forces the serialized v1 single-writer path (schema 1)
    — the explicit form of the degradation ladder's target, kept public so
    operators (and the bandwidth benchmark) can pin the old behaviour."""
    directory = os.path.abspath(directory)
    if os.path.exists(directory) and not force:
        raise FileExistsError(f"checkpoint directory {directory} exists (force=False)")
    degrade_reason = ""
    if parallel and not _chunk_breaker().allows():
        degrade_reason = (
            f"circuit breaker {_CHUNK_WRITE_SITE!r} is open after repeated "
            "chunk-write failures"
        )
    # the v1/v2 decision must be identical on every rank (the two paths emit
    # different collective sequences): any rank wanting v1 degrades them all
    use_v1 = _agree_min(0 if (not parallel or degrade_reason) else 1) == 0
    if use_v1:
        if parallel:  # degraded, not requested: never silent
            if not degrade_reason:
                # this rank's allows() may have consumed the half-open trial
                # probe; peers degraded us, so no chunk write will deliver a
                # verdict — release the token instead of stalling the next
                # parallel trial for a full extra cooldown
                _chunk_breaker().abandon_probe()
            _record_degraded(directory, degrade_reason or "peer breaker open")
        _save_v1(tree, directory)
        return
    degrade = _save_v2(tree, directory)
    if degrade is not None:
        _record_degraded(directory, degrade)
        _save_v1(tree, directory)


def _record_degraded(directory: str, reason: str) -> None:
    """Account one degradation to the serialized v1 path: an always-on
    resilience event (flight-recorded) plus the fallback counter/event stream
    — a save that silently got slower and serial would hide an I/O incident."""
    diagnostics.record_resilience_event(
        "checkpoint.save", "fallback",
        f"{directory}: degraded to serialized v1 single-writer — {reason}",
    )
    diagnostics.record_fallback("checkpoint.save", reason)


# ------------------------------------------------------------------ manifest
def read_manifest(directory: str, *, record: bool = True) -> dict:
    """The parsed manifest of a checkpoint directory, or :class:`CheckpointCorrupt`
    when it is absent or unparseable (a torn / foreign / pre-manifest layout).
    Accepts both schema 1 (per-leaf files) and schema 2 (per-chunk files).
    Every corrupt verdict is recorded in the always-on resilience event stream
    before raising — that record is what triggers the flight recorder's
    automatic post-mortem dump (``ht.telemetry``). ``record=False`` skips the
    event for callers that treat corruption as an expected, non-fatal answer
    (the ``CheckpointManager`` step scan records its own softer
    ``corrupt-step`` event instead of burning post-mortems on every scan of a
    known-bad step)."""
    path = os.path.join(os.path.abspath(directory), MANIFEST_NAME)
    if not os.path.exists(path):
        raise _corrupt(
            directory,
            f"{MANIFEST_NAME} missing (incomplete or torn checkpoint)",
            record,
        )
    try:
        with open(path) as fh:
            manifest = json.load(fh)
    except ValueError as exc:
        raise _corrupt(directory, f"{MANIFEST_NAME} unparseable: {exc}", record)
    if manifest.get("schema") not in (SCHEMA, SCHEMA_V1):
        raise _corrupt(
            directory, f"unknown manifest schema {manifest.get('schema')!r}", record
        )
    return manifest


def _corrupt(directory: str, problem: str, record: bool) -> "CheckpointCorrupt":
    """Build a :class:`CheckpointCorrupt`, recording the verdict first when
    the caller is on a hard-failure path."""
    if record:
        diagnostics.record_resilience_event(
            "checkpoint.manifest", "corrupt", f"{directory}: {problem}"
        )
    return CheckpointCorrupt(directory, [problem])


def _verify_one(directory: str, file: str, nbytes: int, sha256: str) -> Optional[str]:
    """One streamed integrity check (existence, byte length, SHA-256); host
    memory stays one 1 MiB block regardless of chunk size."""
    path = os.path.join(directory, file)
    if not os.path.exists(path):
        return f"{file}: missing"
    size = os.path.getsize(path)
    if size != nbytes:
        return (
            f"{file}: torn write — {size} bytes on disk, "
            f"manifest expects {nbytes}"
        )
    digest = hashlib.sha256()
    with open(path, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            digest.update(block)
    if digest.hexdigest() != sha256:
        return f"{file}: sha256 mismatch (silent corruption)"
    return None


def _manifest_files(manifest: dict) -> List[Tuple[str, int, str]]:
    """Every payload file a manifest names, as (file, nbytes, sha256) — one
    per leaf for schema 1, one per chunk for schema 2."""
    out = []
    for entry in manifest["leaves"]:
        if "chunks" in entry:
            for ch in entry["chunks"]:
                out.append((ch["file"], int(ch["nbytes"]), ch["sha256"]))
        else:
            out.append((entry["file"], int(entry["nbytes"]), entry["sha256"]))
    return out


def _grid_problems(manifest: dict) -> List[str]:
    """Chunk-grid completeness of a v2 manifest: every leaf's chunk offsets
    must cover the canonical grid exactly. Enforced at save time by
    ``_assemble_and_commit_v2`` — re-checked on the read side so a manifest
    that lost an entry (bitrot that keeps the JSON valid, a hand-edited copy)
    can never silently restore uninitialized memory for the missing rows."""
    if manifest.get("schema") != SCHEMA:
        return []
    problems = []
    for i, entry in enumerate(manifest.get("leaves", [])):
        have = sorted(int(c["offset"]) for c in entry.get("chunks", []))
        want = _expected_offsets(entry)
        if have != want:
            problems.append(
                f"leaf_{i}: chunk grid incomplete — manifest lists offsets "
                f"{have}, the canonical grid needs {want}"
            )
    return problems


def verify_checkpoint(directory: str, manifest: Optional[dict] = None) -> List[str]:
    """Integrity-check every payload against the manifest (existence, byte
    length, SHA-256, v2 chunk-grid completeness) — chunks are verified IN
    PARALLEL on the bounded writer pool, one streamed digest each. Returns
    the list of per-file problems — empty means sound. ``manifest`` skips the
    re-read when the caller already parsed it."""
    directory = os.path.abspath(directory)
    if manifest is None:
        manifest = read_manifest(directory)
    grid = _grid_problems(manifest)
    if grid:
        return grid
    files = _manifest_files(manifest)
    if not files:
        return []
    if len(files) == 1:
        results = [_verify_one(directory, *files[0])]
    else:
        with ThreadPoolExecutor(
            max_workers=min(len(files), _writer_pool_size()),
            thread_name_prefix="heat-tpu-ckpt-verify",
        ) as pool:
            results = list(
                pool.map(lambda f: _verify_one(directory, *f), files)
            )
    return [p for p in results if p is not None]


# ------------------------------------------------------------------ restore
class _ChunkReader:
    """Hyperslab reads over one leaf's chunk set, touching only the byte
    ranges that overlap the request.

    Chunks partition ``axis`` (the writer's split, or axis 0 for single-chunk
    leaves). A request's row range selects the overlapping chunks; for
    ``axis == 0`` the rows of each chunk are contiguous on disk, so only that
    byte range is read — otherwise the whole chunk (bounded by one writer
    shard, never the leaf) is read and sliced, with a one-chunk cache for the
    consecutive target shards that straddle it. Reads run under the
    ``checkpoint.chunk_read`` resilience site when a plan/policy is armed."""

    def __init__(self, directory: str, entry: dict, np_dtype):
        self.directory = directory
        self.shape = tuple(int(s) for s in entry["shape"])
        self.dtype = np_dtype
        self.axis = int(entry["split"]) if int(entry["split"]) >= 0 else 0
        self.chunks = sorted(entry["chunks"], key=lambda c: int(c["offset"]))
        self.read_bytes = 0
        self.peak_bytes = 0
        self._cache: Tuple[Optional[str], Optional[np.ndarray]] = (None, None)

    def _note(self, nbytes: int) -> None:
        self.read_bytes += nbytes
        self.peak_bytes = max(self.peak_bytes, nbytes)

    def _read_range(self, file: str, offset: int, nbytes: int) -> bytes:
        path = os.path.join(self.directory, file)

        def attempt() -> bytes:
            if resilience._armed:
                resilience.maybe_fault(_CHUNK_READ_SITE)
            with open(path, "rb") as fh:
                fh.seek(offset)
                data = fh.read(nbytes)
            if len(data) != nbytes:
                raise CheckpointCorrupt(
                    self.directory,
                    [f"{file}: short read — wanted [{offset}, {offset + nbytes}) "
                     f"but the file ends early (torn chunk)"],
                )
            return data

        if resilience._active:
            return resilience.guard(_CHUNK_READ_SITE, attempt, inject=False)
        return attempt()

    def _chunk_shape(self, ch: dict) -> Tuple[int, ...]:
        s = list(self.shape)
        if s:
            s[self.axis] = int(ch["rows"])
        return tuple(s)

    def _read_rows(self, ch: dict, r0: int, r1: int) -> np.ndarray:
        """Rows ``[r0, r1)`` of one chunk along ``axis``, full extent on every
        other dimension."""
        cshape = self._chunk_shape(ch)
        if self.axis == 0 and len(cshape) >= 1:
            rowbytes = int(np.prod(cshape[1:], dtype=np.int64)) * self.dtype.itemsize
            data = self._read_range(ch["file"], r0 * rowbytes, (r1 - r0) * rowbytes)
            self._note(len(data))
            return np.frombuffer(data, self.dtype).reshape((r1 - r0,) + cshape[1:])
        cached_file, cached = self._cache
        if cached_file != ch["file"]:
            data = self._read_range(ch["file"], 0, int(ch["nbytes"]))
            self._note(len(data))
            cached = np.frombuffer(data, self.dtype).reshape(cshape)
            self._cache = (ch["file"], cached)
        sel = [slice(None)] * len(cshape)
        sel[self.axis] = slice(r0, r1)
        return cached[tuple(sel)]

    def read(self, idx: Tuple[slice, ...]) -> np.ndarray:
        """The hyperslab ``idx`` (slices within the logical shape) assembled
        from the overlapping chunks' byte ranges."""
        w = self.axis
        lo, hi = idx[w].start or 0, idx[w].stop
        out_shape = tuple(s.stop - (s.start or 0) for s in idx)
        out = np.empty(out_shape, self.dtype)
        for ch in self.chunks:
            clo = int(ch["offset"])
            chi = clo + int(ch["rows"])
            a, b = max(lo, clo), min(hi, chi)
            if a >= b:
                continue
            block = self._read_rows(ch, a - clo, b - clo)
            # rows were already cut to [a, b); cut only the other dims, whose
            # block extent is the full global extent
            sel = tuple(
                slice(None) if d == w else idx[d] for d in range(len(idx))
            )
            dst = tuple(
                slice(a - lo, b - lo) if d == w else slice(None)
                for d in range(len(idx))
            )
            out[dst] = block[sel]
        return out


def _read_full(directory: str, entry: dict, np_dtype) -> np.ndarray:
    """One leaf fully assembled on host (plain leaves and replicated restore
    targets — the only consumers that inherently need the whole value)."""
    shape = tuple(int(s) for s in entry["shape"])
    if not shape or len(entry["chunks"]) == 1:
        ch = entry["chunks"][0] if entry["chunks"] else None
        if ch is None:
            return np.zeros(shape, np_dtype)
        reader = _ChunkReader(directory, entry, np_dtype)
        data = reader._read_range(ch["file"], 0, int(ch["nbytes"]))
        reader._note(len(data))
        _note_restore(reader)
        return np.frombuffer(data, np_dtype).reshape(shape).copy()
    reader = _ChunkReader(directory, entry, np_dtype)
    out = reader.read(tuple(slice(0, s) for s in shape))
    _note_restore(reader)
    return out


def _note_restore(reader: "_ChunkReader") -> None:
    with _state_lock:
        _restore_stats["read_bytes"] += reader.read_bytes
        _restore_stats["host_bytes_peak"] = max(
            _restore_stats["host_bytes_peak"], reader.peak_bytes
        )


def last_restore_stats() -> Dict[str, int]:
    """Read-traffic gauges of the most recent :func:`load_checkpoint`:
    ``read_bytes`` (chunk bytes actually read by this process — the byte-range
    property of resharding-on-restore is measurable here) and
    ``host_bytes_peak`` (largest single host buffer materialised — bounded by
    one leaf's shard on the streaming path, never the tree)."""
    with _state_lock:
        return dict(_restore_stats)


def _restore_split_leaf(
    directory: str, entry: dict, split_ax: int, comm, device
) -> DNDarray:
    """Streaming resharding restore of one leaf onto ``comm``'s ``split_ax``
    grid: each addressable target shard reads only the overlapping chunk byte
    ranges, target pads are re-masked to zero by construction (blocks start
    zero-filled), and chunk reads are double-buffered against device transfer
    (a read-ahead thread stays one shard ahead of ``jax.device_put``). The
    full leaf is never materialised on any host."""
    gshape = tuple(int(s) for s in entry["shape"])
    np_dtype = _dtype_from_name(entry["dtype"])
    reader = _ChunkReader(directory, entry, np_dtype)
    ndim = len(gshape)
    n = gshape[split_ax]
    size = comm.size
    c = -(-n // size) if n else 0
    padded = list(gshape)
    padded[split_ax] = c * size

    def host_block(i: int) -> np.ndarray:
        lo, hi = i * c, min((i + 1) * c, n)
        bshape = tuple(c if d == split_ax else s for d, s in enumerate(gshape))
        block = np.zeros(bshape, np_dtype)  # target pads re-masked to zero
        if hi > lo:
            idx = tuple(
                slice(lo, hi) if d == split_ax else slice(0, s)
                for d, s in enumerate(gshape)
            )
            dst = [slice(None)] * ndim
            dst[split_ax] = slice(0, hi - lo)
            block[tuple(dst)] = reader.read(idx)
        reader.peak_bytes = max(reader.peak_bytes, block.nbytes)
        return block

    value = io.streamed_shard_assembly(comm, gshape, padded, split_ax, host_block)
    _note_restore(reader)
    return DNDarray(
        value,
        gshape,
        _types.canonical_heat_type(np_dtype),
        split_ax,
        device,
        comm,
        True,
    )


def _rebuild_tree(tree: Any, restored: dict, comm, device) -> Any:
    """Reassemble the caller's pytree from a restored v1 payload.

    DNDarray leaves come back with the *template's* split, comm, and device (the
    documented contract: the tree passed to restore decides the target distribution;
    explicit ``comm=``/``device=`` arguments override per-leaf); the split stored at
    save time is metadata for structure-free consumers.
    """
    treedef = jax.tree.structure(tree)
    out_leaves = []
    for leaf, value, stored_split in zip(
        jax.tree.leaves(tree), restored["arrays"], restored["splits"]
    ):
        stored_split = int(stored_split)
        if stored_split == -2 or not isinstance(leaf, DNDarray):
            out_leaves.append(value)
        else:
            split_ax = leaf.split
            leaf_comm = comm if comm is not None else leaf.comm
            leaf_device = device if device is not None else leaf.device
            gshape = tuple(jax.numpy.asarray(value).shape)
            arr = leaf_comm.shard(jax.numpy.asarray(value), split_ax)
            out_leaves.append(
                DNDarray(
                    arr,
                    gshape,
                    _types.canonical_heat_type(arr.dtype),
                    split_ax,
                    leaf_device,
                    leaf_comm,
                    True,
                )
            )
    return jax.tree.unflatten(treedef, out_leaves)


def _load_v1(
    tree: Any, directory: str, manifest: dict, comm, device, strict: str
) -> Any:
    """Restore a schema-1 checkpoint (the pre-chunking layout): whole-leaf
    payloads, template-driven distribution. v1 stays readable forever."""
    template_leaves = jax.tree.leaves(tree)
    entries = manifest["leaves"]
    if len(entries) != len(template_leaves):
        raise CheckpointCorrupt(
            directory,
            [
                f"leaf count mismatch: checkpoint holds {len(entries)}, "
                f"template tree has {len(template_leaves)}"
            ],
        )
    if strict == "layout":
        # v1 stores whole-leaf payloads (no chunk grid), so the stored layout
        # is the split alone — a shard-count difference cannot exist
        for i, (leaf, entry) in enumerate(zip(template_leaves, entries)):
            stored_split = int(entry["split"])
            if stored_split == -2 or not isinstance(leaf, DNDarray):
                continue
            stored = stored_split if stored_split >= 0 else None
            if stored != leaf.split:
                raise CheckpointLayoutMismatch(
                    f"leaf {i}: checkpoint layout (split={stored}) differs "
                    f"from the template's (split={leaf.split}) and "
                    f'strict="layout" forbids resharding-on-restore'
                )
    values, splits = [], []
    for entry in entries:
        with open(os.path.join(directory, entry["file"]), "rb") as fh:
            payload = fh.read()
        if len(payload) != int(entry["nbytes"]):
            # the per-read byte-length check verify=False keeps (docstring
            # contract): typed, not an np.frombuffer shape error
            raise CheckpointCorrupt(
                directory,
                [f"{entry['file']}: torn read — {len(payload)} bytes on "
                 f"disk, manifest expects {entry['nbytes']}"],
            )
        arr = np.frombuffer(payload, dtype=_dtype_from_name(entry["dtype"]))
        arr = arr.reshape(tuple(entry["shape"]))
        if entry["split"] == -2:
            # plain leaves restore as-is into the user's tree: frombuffer views
            # are read-only, so hand back a writable array (DNDarray leaves go
            # through jnp.asarray, which copies anyway)
            arr = arr.copy()
        values.append(arr)
        splits.append(entry["split"])
        with _state_lock:
            _restore_stats["read_bytes"] += len(payload)
            _restore_stats["host_bytes_peak"] = max(
                _restore_stats["host_bytes_peak"], len(payload)
            )
    return _rebuild_tree(tree, {"arrays": values, "splits": splits}, comm, device)


def _load_v2(
    tree: Any, directory: str, manifest: dict, comm, device, strict: str
) -> Any:
    leaves, treedef = jax.tree.flatten(tree)
    entries = manifest["leaves"]
    if len(entries) != len(leaves):
        raise CheckpointCorrupt(
            directory,
            [
                f"leaf count mismatch: checkpoint holds {len(entries)}, "
                f"template tree has {len(leaves)}"
            ],
        )
    out_leaves = []
    for i, (leaf, entry) in enumerate(zip(leaves, entries)):
        stored_split = int(entry["split"])
        np_dtype = _dtype_from_name(entry["dtype"])
        if stored_split == -2 or not isinstance(leaf, DNDarray):
            out_leaves.append(_read_full(directory, entry, np_dtype))
            continue
        split_ax = leaf.split
        leaf_comm = comm if comm is not None else leaf.comm
        leaf_device = device if device is not None else leaf.device
        if strict == "layout":
            stored = stored_split if stored_split >= 0 else None
            # the shard count only shapes the chunk grid of SPLIT leaves: a
            # replicated leaf (one whole-value chunk) matches any comm size
            shards_differ = (
                stored_split >= 0 and int(entry.get("shards", 1)) != leaf_comm.size
            )
            if stored != split_ax or shards_differ:
                raise CheckpointLayoutMismatch(
                    f"leaf {i}: checkpoint layout (split={stored}, "
                    f"shards={entry.get('shards', 1)}) differs from the "
                    f"template's (split={split_ax}, shards={leaf_comm.size}) "
                    f'and strict="layout" forbids resharding-on-restore'
                )
        if split_ax is None:
            value = _read_full(directory, entry, np_dtype)
            arr = leaf_comm.shard(jax.numpy.asarray(value), None)
            out_leaves.append(
                DNDarray(
                    arr,
                    tuple(int(s) for s in entry["shape"]),
                    _types.canonical_heat_type(arr.dtype),
                    None,
                    leaf_device,
                    leaf_comm,
                    True,
                )
            )
        else:
            out_leaves.append(
                _restore_split_leaf(
                    directory, entry, int(split_ax), leaf_comm, leaf_device
                )
            )
    return jax.tree.unflatten(treedef, out_leaves)


class _hold_restore:
    """Registers a directory as held by an in-flight restore, so concurrent
    :class:`CheckpointManager` pruning defers it to the next save.

    The hold is process-local (the registry) and, on multi-controller runs,
    also cross-process: a ``<dir>.hold.*`` sentinel file next to the
    directory on the shared filesystem, so the writer rank's prune rotation
    defers on a restore in flight on ANY rank. A crashed restore's stale
    sentinel keeps deferring — loudly, one recorded ``prune-deferred`` event
    per rotation — until removed; never pruning under a possibly-live reader
    is the safer failure mode. A location where the sentinel cannot be
    created (read-only parent) degrades to the local-only hold, accounted
    via ``record_fallback``."""

    def __init__(self, directory: str):
        self.directory = directory
        self._sentinel: Optional[str] = None

    def __enter__(self):
        global _hold_seq
        with _state_lock:
            _open_restores[self.directory] = _open_restores.get(self.directory, 0) + 1
            _hold_seq += 1
            seq = _hold_seq
        if jax.process_count() > 1:
            path = (
                f"{self.directory}.hold."
                f"p{jax.process_index()}.{os.getpid()}.{seq}"
            )
            try:
                with open(path, "x") as fh:
                    fh.write("in-flight restore hold\n")
                self._sentinel = path
            except OSError as exc:
                diagnostics.record_fallback(
                    "checkpoint.restore_hold",
                    f"{path}: {type(exc).__name__}: {exc}",
                )
        return self

    def __exit__(self, *exc):
        with _state_lock:
            left = _open_restores.get(self.directory, 1) - 1
            if left <= 0:
                _open_restores.pop(self.directory, None)
            else:
                _open_restores[self.directory] = left
        if self._sentinel is not None:
            try:
                os.unlink(self._sentinel)
            except OSError:
                pass  # already gone; a stray sentinel defers pruning loudly
        return False


_hold_seq = 0


def _restore_holds(directory: str) -> bool:
    with _state_lock:
        if _open_restores.get(directory, 0) > 0:
            return True
    base = os.path.basename(directory)
    parent = os.path.dirname(directory) or "."
    try:
        return any(n.startswith(f"{base}.hold.") for n in os.listdir(parent))
    except FileNotFoundError:
        return False


def load_checkpoint(
    tree: Any,
    directory: str,
    *,
    device=None,
    comm=None,
    strict: str = "reshard",
    verify: bool = True,
) -> Any:
    """Restore a checkpoint written by :func:`save_checkpoint` (either schema).

    ``tree`` supplies the structure and, for DNDarray leaves, the target
    split/comm/device — pass the model/optimizer pytree you want overwritten,
    the standard functional restore shape. The stored layout may differ: a v2
    checkpoint saved at 8 shards restores onto 32 (or onto a different split)
    by streaming only the overlapping chunk byte ranges per target shard —
    set ``strict="layout"`` to forbid that and demand the exact stored layout
    (:class:`CheckpointLayoutMismatch` otherwise; default ``"reshard"``).

    ``verify=True`` (default) integrity-checks every chunk (parallel streamed
    sha256) before any state is touched; a torn or corrupt checkpoint raises
    :class:`CheckpointCorrupt` (reported into the diagnostics resilience-event
    stream) instead of restoring garbage — note that in multi-controller runs
    EVERY process hashes every chunk, so full verification costs one
    whole-checkpoint read per process. ``verify=False`` trusts the manifest
    and performs only per-read byte-length checks — the pure byte-range
    restore path (each process touches only the ranges overlapping its target
    shards) for very large states whose chunks were verified out of band,
    e.g. by one ``verify_checkpoint`` run right after the save.
    """
    if strict not in ("reshard", "layout"):
        raise ValueError(f'strict must be "reshard" or "layout", got {strict!r}')
    directory = os.path.abspath(directory)
    comm = sanitize_comm(comm) if comm is not None else None
    device = sanitize_device(device) if device is not None else None
    with _hold_restore(directory):
        manifest = read_manifest(directory)
        # grid completeness guards BOTH verify settings: a valid-JSON manifest
        # missing a chunk entry must never restore uninitialized rows
        grid = _grid_problems(manifest)
        if grid:
            diagnostics.record_resilience_event(
                "checkpoint.restore", "corrupt", f"{directory}: " + "; ".join(grid)
            )
            raise CheckpointCorrupt(directory, grid)
        if verify:
            problems = verify_checkpoint(directory, manifest)
            if problems:
                diagnostics.record_resilience_event(
                    "checkpoint.restore", "corrupt",
                    f"{directory}: " + "; ".join(problems),
                )
                raise CheckpointCorrupt(directory, problems)
        with _state_lock:
            _restore_stats["read_bytes"] = 0
            _restore_stats["host_bytes_peak"] = 0
        if manifest["schema"] == SCHEMA_V1:
            return _load_v1(tree, directory, manifest, comm, device, strict)
        return _load_v2(tree, directory, manifest, comm, device, strict)


# ------------------------------------------------------------------ manager
_STEP_RE = re.compile(r"^step_(\d+)$")


class CheckpointManager:
    """Rolling step-numbered checkpoints with retention — resume-oriented training
    checkpointing (no reference equivalent; SURVEY §5 notes the gap).

    Each step lives in its own atomically-committed ``step_<n>`` directory.
    Enumeration (``all_steps`` / ``latest_step``) counts only directories whose
    manifest parses — a corrupt or partially-deleted step directory is skipped
    (and reported via diagnostics) rather than crashing resume or masquerading
    as the latest state; restoring it explicitly still raises
    :class:`CheckpointCorrupt` with the per-file findings.

    Pruning contract (ISSUE 13): every old-step deletion runs under the
    ``checkpoint.prune`` resilience site with a recorded ``pruned`` event; a
    step directory a concurrent restore holds open is SKIPPED (``prune-deferred``
    event) and retried on the next save's rotation; a deletion that fails
    raises after recording ``prune-failed`` — disk that should have been freed
    but wasn't is an incident, not a debug log line."""

    def __init__(self, directory: str, *, max_to_keep: int = 3):
        if max_to_keep < 1:
            raise ValueError(f"max_to_keep must be >= 1, got {max_to_keep}")
        self._directory = os.path.abspath(directory)
        self._max_to_keep = max_to_keep
        os.makedirs(self._directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self._directory, f"step_{int(step)}")

    def _prune(self, path: str, reason: str) -> bool:
        """Delete one step directory through ``ht.resilience``; returns False
        when a concurrent restore holds it open (deferred to the next save).
        Failures are recorded AND raised — never best-effort."""
        if _restore_holds(path):
            diagnostics.record_resilience_event(
                _PRUNE_SITE, "prune-deferred",
                f"{path}: held open by an in-flight restore; retrying next save",
            )
            return False

        def rm() -> None:
            shutil.rmtree(path)

        try:
            if resilience._active:
                resilience.guard(_PRUNE_SITE, rm)
            else:
                rm()
        except FileNotFoundError:
            return True  # already gone — the goal state, not a failure
        except Exception as exc:
            diagnostics.record_resilience_event(
                _PRUNE_SITE, "prune-failed", f"{path}: {type(exc).__name__}: {exc}"
            )
            raise
        diagnostics.record_resilience_event(_PRUNE_SITE, "pruned", f"{path}: {reason}")
        return True

    def save(self, step: int, tree: Any, *, parallel: bool = True) -> None:
        save_checkpoint(tree, self._step_dir(step), force=True, parallel=parallel)
        steps = self.all_steps()
        if _is_writer():
            # corrupt (unrestorable) step dirs don't count toward the
            # retention bound, but they must not leak disk forever either —
            # GC them alongside the rotation
            valid = set(steps)
            for name in os.listdir(self._directory):
                m = _STEP_RE.match(name)
                if m and int(m.group(1)) not in valid:
                    self._prune(
                        os.path.join(self._directory, name), "corrupt step GC"
                    )
        while len(steps) > self._max_to_keep:
            oldest = steps.pop(0)
            if _is_writer():
                self._prune(self._step_dir(oldest), "retention rotation")

    def restore(self, tree: Any, step: Optional[int] = None, *, device=None,
                comm=None, strict: str = "reshard") -> Any:
        if step is None:
            step = self.latest_step
            if step is None:
                raise FileNotFoundError(f"no checkpoints under {self._directory}")
        return load_checkpoint(
            tree, self._step_dir(step), device=device, comm=comm, strict=strict
        )

    def all_steps(self) -> List[int]:
        """Sorted steps with a readable manifest; corrupt step directories are
        skipped and reported, never fatal."""
        steps = []
        try:
            names = os.listdir(self._directory)
        except FileNotFoundError:
            return []
        for name in names:
            m = _STEP_RE.match(name)
            if not m:
                continue
            step = int(m.group(1))
            try:
                read_manifest(os.path.join(self._directory, name), record=False)
            except CheckpointCorrupt as exc:
                diagnostics.record_resilience_event(
                    "checkpoint.scan", "corrupt-step",
                    f"step {step} at {self._directory}: {exc.problems[0]}",
                )
                continue
            steps.append(step)
        return sorted(steps)

    @property
    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def close(self) -> None:
        """Kept for API compatibility with the previous orbax-backed manager."""
