"""Sharded checkpoint / resume (SURVEY §5: the reference has data-level I/O only —
``ht.save``/``ht.load`` hyperslabs, heat/core/io.py:58-238 — and no training-state
checkpointing; users fall back to ``torch.save``. The TPU build adds the idiomatic
equivalent: orbax/tensorstore sharded checkpoints of DNDarrays and parameter pytrees,
written per-shard from device buffers, restored with the target sharding).

Surface:

- :func:`save_checkpoint` / :func:`load_checkpoint` — a pytree of DNDarrays /
  jax.Arrays / numpy leaves to a checkpoint directory.
- :class:`CheckpointManager` — rolling step-numbered checkpoints with retention,
  the shape training loops want for resume.

DNDarray leaves are stored as their global ``jax.Array`` plus ``split`` metadata and
come back as DNDarrays with the same distribution.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np

import jax

from .communication import sanitize_comm
from .devices import sanitize_device
from .dndarray import DNDarray
from . import types as _types

__all__ = ["save_checkpoint", "load_checkpoint", "CheckpointManager"]


def _to_storable(tree: Any):
    """Split a pytree into (array tree, split-metadata tree)."""
    leaves, treedef = jax.tree.flatten(tree)
    arrays, splits = [], []
    for leaf in leaves:
        if isinstance(leaf, DNDarray):
            arrays.append(leaf.larray)
            splits.append(leaf.split if leaf.split is not None else -1)
        else:
            # numpy scalars are not a storable leaf type; 0-d arrays are
            arrays.append(np.asarray(leaf) if isinstance(leaf, np.generic) else leaf)
            splits.append(-2)  # plain leaf, restore as-is
    return treedef, arrays, splits


def _rebuild_tree(tree: Any, restored: dict, comm, device) -> Any:
    """Reassemble the caller's pytree from a restored payload.

    DNDarray leaves come back with the *template's* split, comm, and device (the
    documented contract: the tree passed to restore decides the target distribution;
    explicit ``comm=``/``device=`` arguments override per-leaf); the split stored at
    save time is metadata for structure-free consumers.
    """
    treedef = jax.tree.structure(tree)
    out_leaves = []
    for leaf, value, stored_split in zip(
        jax.tree.leaves(tree), restored["arrays"], restored["splits"]
    ):
        stored_split = int(stored_split)
        if stored_split == -2 or not isinstance(leaf, DNDarray):
            out_leaves.append(value)
        else:
            split_ax = leaf.split
            leaf_comm = comm if comm is not None else leaf.comm
            leaf_device = device if device is not None else leaf.device
            gshape = tuple(jax.numpy.asarray(value).shape)
            arr = leaf_comm.shard(jax.numpy.asarray(value), split_ax)
            out_leaves.append(
                DNDarray(
                    arr,
                    gshape,
                    _types.canonical_heat_type(arr.dtype),
                    split_ax,
                    leaf_device,
                    leaf_comm,
                    True,
                )
            )
    return jax.tree.unflatten(treedef, out_leaves)


def save_checkpoint(tree: Any, directory: str, *, force: bool = True) -> None:
    """Write a pytree of DNDarrays / jax.Arrays / numpy leaves to ``directory``.

    Each shard streams from its own device buffer through tensorstore — the
    checkpoint analogue of the per-rank hyperslab writes in ``save_hdf5``.
    """
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    _, arrays, splits = _to_storable(tree)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(
        directory,
        {"arrays": arrays, "splits": np.asarray(splits, dtype=np.int64)},
        force=force,
    )
    ckptr.wait_until_finished()


def load_checkpoint(
    tree: Any, directory: str, *, device=None, comm=None
) -> Any:
    """Restore a checkpoint written by :func:`save_checkpoint`.

    ``tree`` supplies the structure and, for DNDarray leaves, the target split:
    pass the model/optimizer pytree you want overwritten — the standard functional
    restore shape.
    """
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    comm = sanitize_comm(comm) if comm is not None else None
    device = sanitize_device(device) if device is not None else None
    _, arrays, _ = _to_storable(tree)
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(
        directory,
        {"arrays": arrays, "splits": np.zeros(len(arrays), dtype=np.int64)},
    )
    return _rebuild_tree(tree, restored, comm, device)


class CheckpointManager:
    """Rolling step-numbered checkpoints with retention — resume-oriented training
    checkpointing (no reference equivalent; SURVEY §5 notes the gap)."""

    def __init__(self, directory: str, *, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._directory = os.path.abspath(directory)
        self._manager = ocp.CheckpointManager(
            self._directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, tree: Any) -> None:
        import orbax.checkpoint as ocp

        _, arrays, splits = _to_storable(tree)
        self._manager.save(
            step,
            args=ocp.args.StandardSave(
                {"arrays": arrays, "splits": np.asarray(splits, dtype=np.int64)}
            ),
        )
        self._manager.wait_until_finished()

    def restore(self, tree: Any, step: Optional[int] = None, *, device=None, comm=None) -> Any:
        import orbax.checkpoint as ocp

        comm = sanitize_comm(comm) if comm is not None else None
        device = sanitize_device(device) if device is not None else None
        if step is None:
            step = self._manager.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints under {self._directory}")
        _, arrays, _ = _to_storable(tree)
        restored = self._manager.restore(
            step,
            args=ocp.args.StandardRestore(
                {"arrays": arrays, "splits": np.zeros(len(arrays), dtype=np.int64)}
            ),
        )
        return _rebuild_tree(tree, restored, comm, device)

    @property
    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def all_steps(self):
        return sorted(self._manager.all_steps())

    def close(self) -> None:
        self._manager.close()
