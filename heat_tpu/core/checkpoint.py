"""Sharded checkpoint / resume (SURVEY §5: the reference has data-level I/O only —
``ht.save``/``ht.load`` hyperslabs, heat/core/io.py:58-238 — and no training-state
checkpointing; users fall back to ``torch.save``. The TPU build adds the idiomatic
equivalent: manifest-backed atomic checkpoints of DNDarrays and parameter pytrees).

Failure contract (ISSUE 6 — the resilience tentpole):

- **Atomic commit.** A checkpoint is assembled in a same-filesystem temp
  directory — every leaf payload written through ``resilience.atomic_write``
  (write-to-temp + fsync + rename), the manifest written LAST — and committed
  by renaming the previous checkpoint ASIDE, the new one in, then deleting the
  old. Readers see either the previous checkpoint or the complete new one; a
  crash mid-save leaves an uncommitted ``.tmp.<pid>`` (and possibly a
  ``.old.<pid>`` holding the pre-crash state), which the next save of the same
  target sweeps — recovering a stranded ``.old`` back into place when the
  commit itself died between the two renames.
- **Partial-write detection.** ``manifest.json`` records every leaf's byte
  length and SHA-256. :func:`load_checkpoint` verifies all of them before
  rebuilding the tree and raises :class:`CheckpointCorrupt` naming each torn /
  missing / mismatched file — a torn write can never silently restore garbage.
- **Policy-driven retry.** Leaf and manifest writes run under the
  ``checkpoint.write`` / ``checkpoint.manifest`` resilience policies (three
  attempts, exponential backoff by default; override with
  ``resilience.set_policy``), and the fault-injection plan can tear or fail
  any write deterministically (``tests/test_checkpoint.py``).

Surface (unchanged):

- :func:`save_checkpoint` / :func:`load_checkpoint` — a pytree of DNDarrays /
  jax.Arrays / numpy leaves to a checkpoint directory.
- :class:`CheckpointManager` — rolling step-numbered checkpoints with retention;
  ``latest_step`` / ``all_steps`` skip (and report) corrupt step directories
  instead of tripping over them.

DNDarray leaves are stored as their global value plus ``split`` metadata and
come back as DNDarrays with the template tree's distribution. Payloads are raw
little-endian buffers named in the manifest (not ``.npy``), so extension dtypes
(bfloat16) round-trip without pickling.

Scale note: collection is host-memory O(global) per leaf (multi-controller
leaves cross-host-gather and process 0 serialises all I/O) — correct at every
world size, but not the per-shard streaming a pod-scale save needs. The
ROADMAP "parallel checkpoint/ingest I/O" item builds per-process chunked
writes ON TOP of this manifest/verification format; the integrity and
atomicity contracts here are the part that stays.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
from typing import Any, List, Optional

import numpy as np

import jax

from . import diagnostics, resilience
from . import types as _types
from .communication import sanitize_comm
from .devices import sanitize_device
from .dndarray import DNDarray

__all__ = [
    "save_checkpoint",
    "load_checkpoint",
    "CheckpointManager",
    "CheckpointCorrupt",
    "SCHEMA",
    "MANIFEST_NAME",
]

SCHEMA = "heat-tpu-checkpoint/1"
MANIFEST_NAME = "manifest.json"

_WRITE_SITE = "checkpoint.write"
_MANIFEST_SITE = "checkpoint.manifest"


class CheckpointCorrupt(RuntimeError):
    """A checkpoint failed integrity verification on restore. ``problems``
    lists one human-readable finding per torn / missing / mismatched file."""

    def __init__(self, directory: str, problems: List[str]):
        self.directory = directory
        self.problems = list(problems)
        detail = "; ".join(self.problems)
        super().__init__(
            f"checkpoint at {directory!r} is corrupt or partially written: {detail}"
        )


def _to_storable(tree: Any):
    """Split a pytree into (array tree, split-metadata tree)."""
    leaves, treedef = jax.tree.flatten(tree)
    arrays, splits = [], []
    for leaf in leaves:
        if isinstance(leaf, DNDarray):
            arrays.append(leaf.larray)
            splits.append(leaf.split if leaf.split is not None else -1)
        else:
            # numpy scalars are not a storable leaf type; 0-d arrays are
            arrays.append(np.asarray(leaf) if isinstance(leaf, np.generic) else leaf)
            splits.append(-2)  # plain leaf, restore as-is
    return treedef, arrays, splits


def _rebuild_tree(tree: Any, restored: dict, comm, device) -> Any:
    """Reassemble the caller's pytree from a restored payload.

    DNDarray leaves come back with the *template's* split, comm, and device (the
    documented contract: the tree passed to restore decides the target distribution;
    explicit ``comm=``/``device=`` arguments override per-leaf); the split stored at
    save time is metadata for structure-free consumers.
    """
    treedef = jax.tree.structure(tree)
    out_leaves = []
    for leaf, value, stored_split in zip(
        jax.tree.leaves(tree), restored["arrays"], restored["splits"]
    ):
        stored_split = int(stored_split)
        if stored_split == -2 or not isinstance(leaf, DNDarray):
            out_leaves.append(value)
        else:
            split_ax = leaf.split
            leaf_comm = comm if comm is not None else leaf.comm
            leaf_device = device if device is not None else leaf.device
            gshape = tuple(jax.numpy.asarray(value).shape)
            arr = leaf_comm.shard(jax.numpy.asarray(value), split_ax)
            out_leaves.append(
                DNDarray(
                    arr,
                    gshape,
                    _types.canonical_heat_type(arr.dtype),
                    split_ax,
                    leaf_device,
                    leaf_comm,
                    True,
                )
            )
    return jax.tree.unflatten(treedef, out_leaves)


def _dtype_name(dtype) -> str:
    return np.dtype(dtype).name


def _dtype_from_name(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # extension dtypes (bfloat16, float8_*) live here

        return np.dtype(getattr(ml_dtypes, name))


def _host_value(value) -> np.ndarray:
    """One leaf as a host numpy array. Multi-controller DNDarray shards were
    already collected by the caller; a non-addressable raw jax.Array still
    needs the cross-host gather."""
    if isinstance(value, jax.Array) and not value.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(value))
    return np.asarray(value)


def _is_writer() -> bool:
    return jax.process_index() == 0


def _barrier(tag: str) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"heat_tpu.checkpoint:{tag}")


def _sweep_stale(directory: str) -> None:
    """Clean up what a crashed earlier save left behind, whatever its pid:
    uncommitted ``.tmp.*`` assembly dirs are deleted; a ``.old.*`` backup is
    restored to the canonical path when the crash stranded it there (the
    commit died between the two renames and the target is gone), else
    deleted — it was an already-replaced generation."""
    base = os.path.basename(directory)
    parent = os.path.dirname(directory) or "."
    try:
        names = os.listdir(parent)
    except FileNotFoundError:
        return
    for name in sorted(names):
        full = os.path.join(parent, name)
        if name.startswith(f"{base}.tmp."):
            shutil.rmtree(full, ignore_errors=True)
        elif name.startswith(f"{base}.old."):
            if not os.path.exists(directory):
                try:
                    os.rename(full, directory)
                    diagnostics.record_resilience_event(
                        "checkpoint.save", "recovered",
                        f"restored crash-stranded backup {name} to {directory}",
                    )
                    continue
                except OSError:
                    pass
            shutil.rmtree(full, ignore_errors=True)


def save_checkpoint(tree: Any, directory: str, *, force: bool = True) -> None:
    """Write a pytree of DNDarrays / jax.Arrays / numpy leaves to ``directory``
    atomically (temp-dir assembly + manifest-last + one-rename commit; see the
    module header for the failure contract)."""
    directory = os.path.abspath(directory)
    if os.path.exists(directory) and not force:
        raise FileExistsError(f"checkpoint directory {directory} exists (force=False)")
    _, arrays, splits = _to_storable(tree)
    host = [_host_value(a) for a in arrays]  # collective: every process joins
    if not _is_writer():
        _barrier(f"save:{directory}")
        return
    _sweep_stale(directory)
    tmpdir = f"{directory}.tmp.{os.getpid()}"
    shutil.rmtree(tmpdir, ignore_errors=True)
    os.makedirs(tmpdir)
    try:
        entries = []
        for i, (value, split) in enumerate(zip(host, splits)):
            name = f"leaf_{i}.bin"
            payload = np.ascontiguousarray(value).tobytes()

            def write(tmp_path: str, _payload=payload) -> None:
                with open(tmp_path, "wb") as fh:
                    fh.write(_payload)

            resilience.atomic_write(
                os.path.join(tmpdir, name), write, site=_WRITE_SITE
            )
            entries.append(
                {
                    "file": name,
                    "shape": [int(s) for s in value.shape],
                    "dtype": _dtype_name(value.dtype),
                    "split": int(split),
                    "nbytes": len(payload),
                    "sha256": hashlib.sha256(payload).hexdigest(),
                }
            )
        manifest = {"schema": SCHEMA, "leaves": entries}

        def write_manifest(tmp_path: str) -> None:
            with open(tmp_path, "w") as fh:
                json.dump(manifest, fh, indent=2, sort_keys=True)
                fh.write("\n")

        # manifest LAST: its presence marks the leaf set complete, so a crash
        # between leaf writes can never masquerade as a restorable checkpoint
        resilience.atomic_write(
            os.path.join(tmpdir, MANIFEST_NAME), write_manifest, site=_MANIFEST_SITE
        )
        resilience.fsync_dir(tmpdir)
        # overwrite without an unprotected window: the previous checkpoint is
        # renamed ASIDE (never rmtree'd first), the new one renamed in, and
        # only then is the old one deleted — a crash between the renames
        # leaves the old bits recoverable at <directory>.old.<pid>, and a
        # failed commit rename puts them straight back
        backup = None
        if os.path.exists(directory):
            backup = f"{directory}.old.{os.getpid()}"
            shutil.rmtree(backup, ignore_errors=True)
            os.rename(directory, backup)
        try:
            os.rename(tmpdir, directory)
        except BaseException:
            if backup is not None:
                try:
                    os.rename(backup, directory)
                except OSError:
                    pass  # old bits stay recoverable at the backup path
            raise
        if backup is not None:
            shutil.rmtree(backup, ignore_errors=True)
        resilience.fsync_dir(os.path.dirname(directory) or ".")
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
        # the barrier must run even when the writer FAILED: the other
        # processes are already parked in their matching sync, and a write
        # error must surface as this exception — never as a distributed hang
        _barrier(f"save:{directory}")


def read_manifest(directory: str, *, record: bool = True) -> dict:
    """The parsed manifest of a checkpoint directory, or :class:`CheckpointCorrupt`
    when it is absent or unparseable (a torn / foreign / pre-manifest layout).
    Every corrupt verdict is recorded in the always-on resilience event stream
    before raising — that record is what triggers the flight recorder's
    automatic post-mortem dump (``ht.telemetry``). ``record=False`` skips the
    event for callers that treat corruption as an expected, non-fatal answer
    (the ``CheckpointManager`` step scan records its own softer
    ``corrupt-step`` event instead of burning post-mortems on every scan of a
    known-bad step)."""
    path = os.path.join(os.path.abspath(directory), MANIFEST_NAME)
    if not os.path.exists(path):
        raise _corrupt(
            directory,
            f"{MANIFEST_NAME} missing (incomplete or torn checkpoint)",
            record,
        )
    try:
        with open(path) as fh:
            manifest = json.load(fh)
    except ValueError as exc:
        raise _corrupt(directory, f"{MANIFEST_NAME} unparseable: {exc}", record)
    if manifest.get("schema") != SCHEMA:
        raise _corrupt(
            directory, f"unknown manifest schema {manifest.get('schema')!r}", record
        )
    return manifest


def _corrupt(directory: str, problem: str, record: bool) -> "CheckpointCorrupt":
    """Build a :class:`CheckpointCorrupt`, recording the verdict first when
    the caller is on a hard-failure path."""
    if record:
        diagnostics.record_resilience_event(
            "checkpoint.manifest", "corrupt", f"{directory}: {problem}"
        )
    return CheckpointCorrupt(directory, [problem])


def verify_checkpoint(directory: str, manifest: Optional[dict] = None) -> List[str]:
    """Integrity-check every leaf payload against the manifest (existence, byte
    length, SHA-256). Returns the list of problems — empty means sound.
    ``manifest`` skips the re-read when the caller already parsed it."""
    directory = os.path.abspath(directory)
    if manifest is None:
        manifest = read_manifest(directory)
    problems = []
    for entry in manifest["leaves"]:
        path = os.path.join(directory, entry["file"])
        if not os.path.exists(path):
            problems.append(f"{entry['file']}: missing")
            continue
        size = os.path.getsize(path)
        if size != entry["nbytes"]:
            problems.append(
                f"{entry['file']}: torn write — {size} bytes on disk, "
                f"manifest expects {entry['nbytes']}"
            )
            continue
        digest = hashlib.sha256()
        with open(path, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                digest.update(chunk)
        if digest.hexdigest() != entry["sha256"]:
            problems.append(f"{entry['file']}: sha256 mismatch (silent corruption)")
    return problems


def load_checkpoint(tree: Any, directory: str, *, device=None, comm=None) -> Any:
    """Restore a checkpoint written by :func:`save_checkpoint`.

    ``tree`` supplies the structure and, for DNDarray leaves, the target split:
    pass the model/optimizer pytree you want overwritten — the standard functional
    restore shape. Every payload is verified against the manifest first; a torn
    or corrupt checkpoint raises :class:`CheckpointCorrupt` (reported into the
    diagnostics resilience-event stream) instead of restoring garbage.
    """
    directory = os.path.abspath(directory)
    comm = sanitize_comm(comm) if comm is not None else None
    device = sanitize_device(device) if device is not None else None
    manifest = read_manifest(directory)
    problems = verify_checkpoint(directory, manifest)
    if problems:
        diagnostics.record_resilience_event(
            "checkpoint.restore", "corrupt", f"{directory}: " + "; ".join(problems)
        )
        raise CheckpointCorrupt(directory, problems)
    template_leaves = jax.tree.leaves(tree)
    entries = manifest["leaves"]
    if len(entries) != len(template_leaves):
        raise CheckpointCorrupt(
            directory,
            [
                f"leaf count mismatch: checkpoint holds {len(entries)}, "
                f"template tree has {len(template_leaves)}"
            ],
        )
    values, splits = [], []
    for entry in entries:
        with open(os.path.join(directory, entry["file"]), "rb") as fh:
            payload = fh.read()
        arr = np.frombuffer(payload, dtype=_dtype_from_name(entry["dtype"]))
        arr = arr.reshape(tuple(entry["shape"]))
        if entry["split"] == -2:
            # plain leaves restore as-is into the user's tree: frombuffer views
            # are read-only, so hand back a writable array (DNDarray leaves go
            # through jnp.asarray, which copies anyway)
            arr = arr.copy()
        values.append(arr)
        splits.append(entry["split"])
    return _rebuild_tree(tree, {"arrays": values, "splits": splits}, comm, device)


_STEP_RE = re.compile(r"^step_(\d+)$")


class CheckpointManager:
    """Rolling step-numbered checkpoints with retention — resume-oriented training
    checkpointing (no reference equivalent; SURVEY §5 notes the gap).

    Each step lives in its own atomically-committed ``step_<n>`` directory.
    Enumeration (``all_steps`` / ``latest_step``) counts only directories whose
    manifest parses — a corrupt or partially-deleted step directory is skipped
    (and reported via diagnostics) rather than crashing resume or masquerading
    as the latest state; restoring it explicitly still raises
    :class:`CheckpointCorrupt` with the per-file findings."""

    def __init__(self, directory: str, *, max_to_keep: int = 3):
        if max_to_keep < 1:
            raise ValueError(f"max_to_keep must be >= 1, got {max_to_keep}")
        self._directory = os.path.abspath(directory)
        self._max_to_keep = max_to_keep
        os.makedirs(self._directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self._directory, f"step_{int(step)}")

    def save(self, step: int, tree: Any) -> None:
        save_checkpoint(tree, self._step_dir(step), force=True)
        steps = self.all_steps()
        if _is_writer():
            # corrupt (unrestorable) step dirs don't count toward the
            # retention bound, but they must not leak disk forever either —
            # GC them alongside the rotation
            valid = set(steps)
            for name in os.listdir(self._directory):
                m = _STEP_RE.match(name)
                if m and int(m.group(1)) not in valid:
                    shutil.rmtree(
                        os.path.join(self._directory, name), ignore_errors=True
                    )
        while len(steps) > self._max_to_keep:
            oldest = steps.pop(0)
            if _is_writer():
                shutil.rmtree(self._step_dir(oldest), ignore_errors=True)

    def restore(self, tree: Any, step: Optional[int] = None, *, device=None, comm=None) -> Any:
        if step is None:
            step = self.latest_step
            if step is None:
                raise FileNotFoundError(f"no checkpoints under {self._directory}")
        return load_checkpoint(tree, self._step_dir(step), device=device, comm=comm)

    def all_steps(self) -> List[int]:
        """Sorted steps with a readable manifest; corrupt step directories are
        skipped and reported, never fatal."""
        steps = []
        try:
            names = os.listdir(self._directory)
        except FileNotFoundError:
            return []
        for name in names:
            m = _STEP_RE.match(name)
            if not m:
                continue
            step = int(m.group(1))
            try:
                read_manifest(os.path.join(self._directory, name), record=False)
            except CheckpointCorrupt as exc:
                diagnostics.record_resilience_event(
                    "checkpoint.scan", "corrupt-step",
                    f"step {step} at {self._directory}: {exc.problems[0]}",
                )
                continue
            steps.append(step)
        return sorted(steps)

    @property
    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def close(self) -> None:
        """Kept for API compatibility with the previous orbax-backed manager."""
