"""Communication layer: the TPU-native replacement for the reference's MPI wrapper.

The reference (heat/core/communication.py:84-2064) wraps every MPI primitive so that
process-local torch tensors can be used as send/recv buffers, with derived datatypes for
strided buffers, GPU staging, and axis-permutation tricks so any axis can be the
concatenation axis of a collective.

On TPU none of that machinery is needed: arrays are *global* ``jax.Array``s laid out over a
``jax.sharding.Mesh``, and XLA SPMD materialises the collectives (all-reduce, all-gather,
all-to-all, collective-permute) over ICI/DCN directly from sharding annotations. What
remains of the communication layer is therefore small and explicit:

- a :class:`Communication` object owning the device ``Mesh`` and its axis name,
- the canonical chunking rule :meth:`Communication.chunk` (reference
  ``communication.py:157-215``) used for lshape maps and parallel I/O,
- sharding helpers that translate Heat's ``split`` axis into a ``NamedSharding``,
- thin functional collectives (:meth:`Allreduce`-style names kept for parity) that are
  usable *inside* ``jax.shard_map`` blocks for algorithms with explicit communication
  schedules (hSVD merge tree, ring cdist, TSQR).

Multi-host bootstrap is ``jax.distributed.initialize`` instead of ``mpirun`` — see
:func:`initialize`.
"""

from __future__ import annotations

import math
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

import os

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from . import diagnostics, forensics, profiler, resilience, supervision, telemetry


def _guarded(site, fn, *args, **kwargs):
    """Run one collective (or layout) invocation under ht.supervision,
    ht.resilience, ht.profiler, and ht.telemetry.

    Idle fast path: one module-attribute read per subsystem. When the
    supervision plane is armed (multi-process jobs by default), the abort
    sentinel is polled before AND after the invocation — a peer failure
    raises typed ``PeerFailed`` on this rank instead of entering a collective
    its dead peer will never join — and, with
    ``HEAT_TPU_COLLECTIVE_TIMEOUT_S`` set, the invocation window is armed on
    the collective watchdog (``supervision.watch``). When a fault plan
    is armed or a site policy is registered, the call goes through
    ``resilience.guard`` — injected faults fire per attempt and the site
    policy retries. When the profiler is active the invocation is additionally
    recorded as a ``collective`` slice attributed to the ambient request scope
    — collectives run at trace time, so the slice nests inside the program's
    ``compile`` slice. When telemetry collection is on, the whole invocation
    (retries included) is timed into a :func:`telemetry.collective_window` —
    the per-(site, seq) enter/exit record the cross-process merge turns into
    skew histograms and straggler attribution. All of it is host-side timing
    only; nothing enters the traced body, so the compiled HLO never changes
    (the byte-parity contracts in ``tests/test_resilience.py``,
    ``tests/test_profiler.py`` and ``tests/test_supervision.py``)."""
    if supervision._armed:
        with supervision.watch(site):
            return _guarded_telemetry(site, fn, *args, **kwargs)
    return _guarded_telemetry(site, fn, *args, **kwargs)


def _guarded_telemetry(site, fn, *args, **kwargs):
    if telemetry._collecting:
        with telemetry.collective_window(site):
            return _guarded_forensics(site, fn, *args, **kwargs)
    return _guarded_forensics(site, fn, *args, **kwargs)


def _guarded_forensics(site, fn, *args, **kwargs):
    # request-forensics leg: time the whole invocation (retries included)
    # onto the ambient request's lifecycle record. Auxiliary timing only —
    # collectives run at trace time, nested inside the compile stage, so the
    # reducer reports this beside the stages rather than summing it.
    if forensics._enabled:
        with forensics.collective_timer(site):
            return _guarded_run(site, fn, *args, **kwargs)
    return _guarded_run(site, fn, *args, **kwargs)


def _guarded_run(site, fn, *args, **kwargs):
    if profiler._active:
        with profiler.scope("collective", site):
            if resilience._active:
                return resilience.guard(site, fn, *args, **kwargs)
            return fn(*args, **kwargs)
    if resilience._active:
        return resilience.guard(site, fn, *args, **kwargs)
    return fn(*args, **kwargs)

# Multi-controller bootstrap must run BEFORE anything touches the XLA backend —
# and importing heat_tpu itself does (the COMM_WORLD mesh below calls
# jax.devices()). The launcher therefore passes the coordination parameters by
# environment, the TPU-native analogue of mpirun's environment contract:
#
#   HEAT_TPU_COORDINATOR_ADDRESS=host:port \
#   HEAT_TPU_NUM_PROCESSES=N HEAT_TPU_PROCESS_ID=i python program.py
#
# Programs that want to call :func:`initialize` explicitly must do so before
# importing heat_tpu (i.e. call jax.distributed.initialize themselves).
if os.environ.get("HEAT_TPU_COORDINATOR_ADDRESS"):
    _missing = [
        name
        for name in ("HEAT_TPU_NUM_PROCESSES", "HEAT_TPU_PROCESS_ID")
        if not os.environ.get(name)
    ]
    if _missing:
        raise RuntimeError(
            "HEAT_TPU_COORDINATOR_ADDRESS is set but "
            f"{' and '.join(_missing)} {'is' if len(_missing) == 1 else 'are'} not; "
            "the multi-controller launch contract needs all three of "
            "HEAT_TPU_COORDINATOR_ADDRESS, HEAT_TPU_NUM_PROCESSES, "
            "HEAT_TPU_PROCESS_ID"
        )
    if jax._src.distributed.global_state.client is None:  # not already initialized
        if supervision.enabled():
            # the supervised runtime: identical observable bootstrap, but
            # XLA's fail-stop error propagation is disabled — peer-failure
            # detection, typed delivery, and elastic restart belong to
            # ht.supervision (see its module header)
            supervision.bootstrap_distributed(
                os.environ["HEAT_TPU_COORDINATOR_ADDRESS"],
                int(os.environ["HEAT_TPU_NUM_PROCESSES"]),
                int(os.environ["HEAT_TPU_PROCESS_ID"]),
            )
        else:
            jax.distributed.initialize(
                coordinator_address=os.environ["HEAT_TPU_COORDINATOR_ADDRESS"],
                num_processes=int(os.environ["HEAT_TPU_NUM_PROCESSES"]),
                process_id=int(os.environ["HEAT_TPU_PROCESS_ID"]),
            )

__all__ = [
    "Communication",
    "MeshCommunication",
    "COMM_WORLD",
    "COMM_SELF",
    "get_comm",
    "use_comm",
    "sanitize_comm",
    "initialize",
    "compat_shard_map",
]

# The default mesh axis name carried by every split DNDarray dimension.
MESH_AXIS = "d"


def _payload_bytes(x) -> int:
    """Per-participant payload bytes of a collective operand — works on concrete
    arrays AND tracers (collectives run inside shard_map/jit traces, so the
    diagnostics hooks see abstract values; shape/dtype are always static)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return int(np.dtype(type(x)).itemsize) if np.isscalar(x) else 0
    size = 1
    for s in shape:
        size *= int(s)
    return size * np.dtype(dtype).itemsize


try:  # jax >= 0.6: top-level export, replication check spelled check_vma=
    _shard_map_impl = jax.shard_map
    _SHARD_MAP_CHECK_KW = "check_vma"
except AttributeError:  # pragma: no cover - jax 0.4.x: experimental home, check_rep=
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    _SHARD_MAP_CHECK_KW = "check_rep"


def compat_shard_map(fn, mesh, in_specs, out_specs, check: bool = False):
    """``shard_map`` across the jax versions this repo supports.

    ``jax.shard_map`` only exists from jax 0.6 (with the replication check
    spelled ``check_vma=``); on 0.4.x the implementation lives in
    ``jax.experimental.shard_map`` and the same switch is ``check_rep=``.
    Explicit-collective program bodies (the comm-plan ring/reduce-scatter
    matmuls, the all_to_all resplit) go through this resolver so one spelling
    traces on both. ``check=False`` (the default) also sidesteps the 0.4.x
    requirement to ``pcast`` replicated outputs, which has no stable spelling
    across versions."""
    return _shard_map_impl(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_SHARD_MAP_CHECK_KW: check},
    )


class Communication:
    """Base class / protocol for communication backends (reference ``communication.py:84``)."""

    @staticmethod
    def is_distributed() -> bool:
        raise NotImplementedError()

    def chunk(self, shape, split, rank=None):
        raise NotImplementedError()


class MeshCommunication(Communication):
    """A communicator backed by a 1-D ``jax.sharding.Mesh`` over a set of devices.

    Replaces ``MPICommunication`` (reference ``communication.py:116``). ``rank``/``size``
    keep their meaning as *shard index* / *number of shards* along the mesh axis; in a
    multi-controller deployment ``process_rank`` additionally reports the host process.
    """

    def __init__(
        self,
        devices: Optional[Sequence[jax.Device]] = None,
        axis_name: str = MESH_AXIS,
        mesh_shape: Optional[Sequence[int]] = None,
        axis_names: Optional[Sequence[str]] = None,
    ):
        if devices is None:
            devices = jax.devices()
        self._devices: List[jax.Device] = list(devices)
        if mesh_shape is None:
            self.axis_names: Tuple[str, ...] = (axis_name,)
            self.mesh = Mesh(np.array(self._devices), self.axis_names)
            self.axis_name = axis_name
        else:
            # N-D mesh (reference DASO's node-local × global hierarchy maps to the
            # ici × dcn axes of a 2-D device mesh, SURVEY §2.4). A ``split`` dimension
            # is sharded over ALL axes jointly; per-axis collectives go through the
            # ``axis_name=`` argument of the collective helpers.
            self.axis_names = tuple(axis_names or ("dcn", "ici"))
            if len(self.axis_names) != len(tuple(mesh_shape)):
                raise ValueError(
                    f"axis_names {self.axis_names} does not match mesh_shape {mesh_shape}"
                )
            self.mesh = Mesh(np.array(self._devices).reshape(tuple(mesh_shape)), self.axis_names)
            # collectives over a multi-axis comm default to reducing over all axes
            self.axis_name = self.axis_names if len(self.axis_names) > 1 else self.axis_names[0]

    @classmethod
    def hierarchical(
        cls,
        n_nodes: int,
        devices: Optional[Sequence[jax.Device]] = None,
        axis_names: Sequence[str] = ("dcn", "ici"),
    ) -> "MeshCommunication":
        """A 2-D (slow × fast) communicator: ``n_nodes`` groups over the slow ``dcn``
        axis, remaining devices per group on the fast ``ici`` axis.

        This is the TPU shape of the reference DASO's hierarchy — torch-DDP inside a
        node, skipped MPI syncs across nodes (reference ``optim/dp_optimizer.py:64-155``).
        """
        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        if n_nodes <= 0 or len(devices) % n_nodes != 0:
            raise ValueError(
                f"cannot split {len(devices)} devices into {n_nodes} equal node groups"
            )
        return cls(devices, mesh_shape=(n_nodes, len(devices) // n_nodes), axis_names=axis_names)

    # ------------------------------------------------------------------ topology
    @property
    def size(self) -> int:
        """Number of shards along the mesh axis (≙ MPI world size)."""
        return len(self._devices)

    @property
    def rank(self) -> int:
        """Index of this controller's first device along the mesh (0 in single-controller)."""
        proc = jax.process_index()
        for i, d in enumerate(self._devices):
            if d.process_index == proc:
                return i
        return 0

    @property
    def process_rank(self) -> int:
        return jax.process_index()

    @property
    def devices(self) -> List[jax.Device]:
        return self._devices

    @property
    def is_hierarchical(self) -> bool:
        return len(self.axis_names) > 1

    @property
    def n_nodes(self) -> int:
        """Size of the slow (first) mesh axis — 1 on a flat mesh."""
        return int(self.mesh.shape[self.axis_names[0]]) if self.is_hierarchical else 1

    @property
    def node_size(self) -> int:
        """Devices per node group — the fast-axis extent."""
        return self.size // self.n_nodes

    @staticmethod
    def is_distributed() -> bool:
        return len(jax.devices()) > 1

    def __repr__(self) -> str:
        return f"MeshCommunication(size={self.size}, axis={self.axis_name!r})"

    # ------------------------------------------------------------------ chunking
    def chunk(
        self, shape: Sequence[int], split: Optional[int], rank: Optional[int] = None
    ) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        """Calculate the chunk of the global ``shape`` owned by ``rank`` along ``split``.

        Mirrors reference ``communication.py:157-215`` but uses the XLA-canonical
        *ceil-division* rule (shard ``i`` owns ``[i*c, min((i+1)*c, n))`` with
        ``c = ceil(n / size)``) instead of MPI-Heat's front-loaded remainder rule, so that
        the metadata agrees with how ``NamedSharding`` actually lays shards out in HBM.

        Returns ``(offset, local_shape, slices)``.
        """
        if rank is None:
            rank = self.rank
        shape = tuple(int(s) for s in shape)
        if split is None:
            return 0, shape, tuple(slice(0, s) for s in shape)
        split = int(split)
        n = shape[split]
        c = -(-n // self.size) if n else 0  # ceil division; 0-size stays 0
        start = min(rank * c, n)
        end = min((rank + 1) * c, n)
        lshape = shape[:split] + (end - start,) + shape[split + 1 :]
        slices = tuple(
            slice(start, end) if i == split else slice(0, s) for i, s in enumerate(shape)
        )
        return start, lshape, slices

    def counts_displs_shape(
        self, shape: Sequence[int], split: int
    ) -> Tuple[Tuple[int, ...], Tuple[int, ...], Tuple[int, ...]]:
        """Per-rank counts/displacements along ``split`` (reference ``communication.py:216``)."""
        counts, displs = [], []
        for r in range(self.size):
            offset, lshape, _ = self.chunk(shape, split, rank=r)
            counts.append(lshape[split])
            displs.append(offset)
        _, lshape, _ = self.chunk(shape, split)
        return tuple(counts), tuple(displs), tuple(lshape)

    def lshape_map(self, shape: Sequence[int], split: Optional[int]) -> np.ndarray:
        """(size, ndim) array of every shard's local shape (reference ``dndarray.py:304``)."""
        out = np.empty((self.size, len(shape)), dtype=np.int64)
        for r in range(self.size):
            _, lshape, _ = self.chunk(shape, split, rank=r)
            out[r] = lshape
        return out

    # ------------------------------------------------------------------ sharding
    def spec(self, ndim: int, split: Optional[int]) -> PartitionSpec:
        """The ``PartitionSpec`` encoding Heat's ``split`` for an ``ndim``-d array.

        On a multi-axis mesh the split dimension is sharded over all axes jointly
        (major-to-minor), so ``size`` shards exist either way."""
        if split is None:
            return PartitionSpec()
        entries = [None] * ndim
        entries[split] = self.axis_names if len(self.axis_names) > 1 else self.axis_names[0]
        return PartitionSpec(*entries)

    def sharding(self, ndim: int, split: Optional[int]) -> NamedSharding:
        """The ``NamedSharding`` encoding Heat's ``split`` for an ``ndim``-d array."""
        return NamedSharding(self.mesh, self.spec(ndim, split))

    def padded_dim(self, n: int) -> int:
        """The physical extent of a split dimension: ``n`` rounded up to a multiple of
        the mesh size, so every shard holds exactly ``ceil(n/P)`` elements."""
        n = int(n)
        c = -(-n // self.size) if n else 0
        return c * self.size

    def padded_shape(
        self, shape: Sequence[int], split: Optional[int]
    ) -> Tuple[int, ...]:
        """The physical shape of a logical ``shape`` laid out along ``split``: the
        split dimension rounded up to :meth:`padded_dim`, every other dimension
        unchanged. Equals ``shape`` for ``split=None`` and divisible extents. The
        static half of :meth:`shard` — the dispatch executor (``_executor``) uses
        it to stage the physical pad inside a jitted program."""
        shape = tuple(int(s) for s in shape)
        if split is None or split >= len(shape):
            return shape
        return shape[:split] + (self.padded_dim(shape[split]),) + shape[split + 1 :]

    def shard(self, array: jax.Array, split: Optional[int]) -> jax.Array:
        """Lay ``array`` out with dimension ``split`` sharded over the mesh.

        This is the physical half of ``resplit_`` (reference ``dndarray.py:1407``): XLA
        emits the all-gather / all-to-all / slice that the reference hand-writes.

        ``array`` is a *logical* value. Ragged extents (``n % P != 0``) return a
        **padded physical** value — the split dimension zero-padded to
        :meth:`padded_dim` so a true 1/P ``NamedSharding`` applies (jax.Array cannot
        represent uneven shards, and GSPMD resolves a forced ragged constraint to
        replication) — the padded-chunks representation SURVEY §7 prescribes. Callers
        wrap the result together with the logical gshape (``DNDarray`` keeps the
        logical/physical distinction); a padded input (whose extent is already a
        multiple of P) passes through the divisible path unchanged, so the operation
        is idempotent on physical values.
        """
        if jnp.issubdtype(getattr(array, "dtype", None), jnp.complexfloating):
            from .devices import complex_needs_host, cpu_fallback_device

            if (
                complex_needs_host(array.dtype)
                and self._devices
                and self._devices[0].platform != "cpu"
            ):
                # the accelerator cannot hold complex values (see
                # devices.accelerator_capabilities); complex arrays live on host CPU,
                # un-sharded — on such systems the accelerator mesh is the wrong home
                # for this dtype and the split is metadata only
                return jax.device_put(array, cpu_fallback_device())
        if diagnostics._enabled:
            # counts every layout REQUEST with its logical payload: an operand
            # that already matches the target (the early return below) costs no
            # device movement but is still one counted shard call — the counter
            # tracks the framework's layout traffic, not XLA's wire bytes
            diagnostics.record_collective(
                "shard", self.axis_name, self.size, _payload_bytes(array)
            )
        target = self.sharding(array.ndim, split)
        if isinstance(array, jax.Array):
            try:
                if array.sharding == target:
                    return array
            except AttributeError:
                pass  # tracer under jit: device_put below becomes a sharding constraint
        ragged = split is not None and array.shape[split] % self.size != 0
        if jax.process_count() > 1:
            # multi-controller: a host value can only populate addressable shards —
            # build per-shard via callback (each process fills only its own devices);
            # an existing global array reshard compiles to the XLA collective.
            if isinstance(array, jax.Array) and not array.is_fully_addressable:
                return _pad_reshard(array, target, split, self.padded_dim(array.shape[split]) if ragged else None)
            np_value = np.asarray(array)
            if ragged:
                widths = [(0, 0)] * np_value.ndim
                widths[split] = (0, self.padded_dim(np_value.shape[split]) - np_value.shape[split])
                np_value = np.pad(np_value, widths)
            return _guarded(
                "comm.shard", jax.make_array_from_callback,
                np_value.shape, target, lambda idx: np_value[idx],
            )
        if not ragged:
            return _guarded("comm.shard", jax.device_put, array, target)
        m = self.padded_dim(array.shape[split])
        pad_shape = array.shape[:split] + (m - array.shape[split],) + array.shape[split + 1 :]
        padded = jnp.concatenate(
            [jnp.asarray(array), jnp.zeros(pad_shape, jnp.asarray(array).dtype)], axis=split
        )
        return _guarded("comm.shard", jax.device_put, padded, target)

    # ------------------------------------------------------------------ collectives
    # Functional collectives usable inside shard_map blocks. Names kept close to the
    # reference's MPI surface (communication.py:541-1996) for discoverability, but these
    # are *pure functions of device-local values*, not buffer mutations.
    #
    # Every collective reports (op, mesh axis, participants, logical bytes) to
    # ht.diagnostics when metrics are enabled. The hooks run at Python call time —
    # inside a shard_map/jit trace that is TRACE time, so a cached executable's
    # replays are not re-counted (documented in doc/source/observability.rst).
    # Nested convenience forms count both layers (scan also records its inner
    # exscan, scatter its inner broadcast).
    def _axis_participants(self, axis_name=None) -> int:
        """Static shard count of the (possibly tuple-valued) named axis."""
        name = axis_name or self.axis_name
        names = (name,) if isinstance(name, str) else tuple(name)
        try:
            return int(np.prod([self.mesh.shape[n] for n in names]))
        except (KeyError, TypeError):
            return self.size

    def _record_collective(self, op: str, axis_name, x) -> None:
        """Report one collective's logical bytes (= per-participant payload ×
        participants) to ht.diagnostics and/or the forensics cost meters —
        each consumer gated on its own switch here. Callers gate on
        ``diagnostics._enabled or forensics._enabled`` so the disabled cost
        stays one attribute read per plane."""
        participants = self._axis_participants(axis_name)
        nbytes = _payload_bytes(x) * participants
        if diagnostics._enabled:
            diagnostics.record_collective(
                op, axis_name or self.axis_name, participants, nbytes,
            )
        if forensics._enabled:
            # bytes only: the invocation's wall time is recorded by the
            # _guarded_forensics leg around the actual dispatch
            forensics.note_collective(op, 0.0, nbytes=nbytes)

    def psum(self, x, axis_name: Optional[str] = None):
        if diagnostics._enabled or forensics._enabled:
            self._record_collective("psum", axis_name, x)
        return _guarded("comm.psum", jax.lax.psum, x, axis_name or self.axis_name)

    Allreduce = psum

    def pmax(self, x, axis_name: Optional[str] = None):
        if diagnostics._enabled or forensics._enabled:
            self._record_collective("pmax", axis_name, x)
        return _guarded("comm.pmax", jax.lax.pmax, x, axis_name or self.axis_name)

    def pmin(self, x, axis_name: Optional[str] = None):
        if diagnostics._enabled or forensics._enabled:
            self._record_collective("pmin", axis_name, x)
        return _guarded("comm.pmin", jax.lax.pmin, x, axis_name or self.axis_name)

    def all_gather(self, x, axis: int = 0, axis_name: Optional[str] = None, tiled: bool = True):
        """Allgather along array axis ``axis`` (reference ``__allgather_like``
        ``communication.py:1047-1128``; the axis-permutation machinery there is subsumed
        by ``jax.lax.all_gather(axis=...)``)."""
        if diagnostics._enabled or forensics._enabled:
            self._record_collective("all_gather", axis_name, x)
        return _guarded(
            "comm.all_gather", jax.lax.all_gather,
            x, axis_name or self.axis_name, axis=axis, tiled=tiled,
        )

    Allgather = all_gather

    def psum_scatter(
        self, x, scatter_axis: int = 0, axis_name: Optional[str] = None, tiled: bool = True,
    ):
        """Reduce-scatter (reference ``Reduce_scatter`` / ``__reduce_like`` with a
        scattered result): sums ``x`` across the axis and leaves each participant
        only its 1/P tile along array axis ``scatter_axis`` — the (P−1)/P-byte
        half of an all-reduce, for consumers that keep the result sharded (the
        comm-plan ``rs`` contraction plan)."""
        if diagnostics._enabled or forensics._enabled:
            self._record_collective("psum_scatter", axis_name, x)
        return _guarded(
            "comm.psum_scatter", jax.lax.psum_scatter,
            x, axis_name or self.axis_name, scatter_dimension=scatter_axis,
            tiled=tiled,
        )

    Reduce_scatter = psum_scatter

    def all_to_all(self, x, split_axis: int, concat_axis: int, axis_name: Optional[str] = None):
        """Alltoall (reference ``__alltoall_like`` ``communication.py:1236``)."""
        if diagnostics._enabled or forensics._enabled:
            self._record_collective("all_to_all", axis_name, x)
        return _guarded(
            "comm.all_to_all", jax.lax.all_to_all,
            x, axis_name or self.axis_name, split_axis=split_axis,
            concat_axis=concat_axis, tiled=True,
        )

    Alltoall = all_to_all

    def ppermute(self, x, perm, axis_name: Optional[str] = None):
        """Point-to-point send/recv pattern (reference Send/Recv ``communication.py:541-707``)."""
        if diagnostics._enabled or forensics._enabled:
            self._record_collective("ppermute", axis_name, x)
        return _guarded(
            "comm.ppermute", jax.lax.ppermute,
            x, axis_name or self.axis_name, perm=perm,
        )

    def ring_shift(self, x, shift: int = 1, axis_name: Optional[str] = None):
        """Rotate shards around the ring — the TPU form of the reference's ring algorithms
        (``spatial/distance.py:209``)."""
        if diagnostics._enabled or forensics._enabled:
            self._record_collective("ring_shift", axis_name, x)
        n = self.size
        perm = [(i, (i + shift) % n) for i in range(n)]
        return _guarded(
            "comm.ring_shift", jax.lax.ppermute,
            x, axis_name or self.axis_name, perm=perm,
        )

    def broadcast(self, x, root: int = 0, axis_name: Optional[str] = None):
        """Bcast from shard ``root`` (reference ``communication.py:736``).

        Binomial-tree dissemination over ``ppermute``: ⌈log₂P⌉ rounds, P−1 unit
        payloads on the wire in total — the MPI tree shape. (The naive masked-psum
        spelling is a full-payload all-reduce: ~2× payload per link and no
        latency win at pod scale.) Multi-axis communicators keep the psum form,
        whose all-axis reduction is what their semantics need.
        """
        if diagnostics._enabled or forensics._enabled:
            self._record_collective("broadcast", axis_name, x)
        return _guarded("comm.broadcast", self._broadcast_impl, x, root, axis_name)

    def _broadcast_impl(self, x, root, axis_name):
        name = axis_name or self.axis_name
        if not isinstance(name, str):
            idx = jax.lax.axis_index(name)
            src = jnp.where(idx == root, x, jnp.zeros_like(x))
            return jax.lax.psum(src, name)
        p = jax.lax.psum(1, name)
        idx = jax.lax.axis_index(name)
        # tree slots are relabeled relative to the root (slot = (idx - root) mod p),
        # so no physical pre/post-rotation rounds are needed for root != 0
        slot = (idx - root) % p
        val = jnp.where(slot == 0, x, jnp.zeros_like(x))
        h = 1
        while h < p:
            # slots [0, h) hold the value; each forwards to its mirror slot + h
            pairs = [
                ((i + root) % p, (i + h + root) % p) for i in range(min(h, p - h))
            ]
            recv = jax.lax.ppermute(val, name, perm=pairs)
            val = jnp.where(slot < h, val, val + recv)
            h <<= 1
        return val

    Bcast = broadcast

    def exscan(self, x, axis_name: Optional[str] = None):
        """Exclusive prefix-sum over shards (reference Exscan ``communication.py:1004``).

        Hillis–Steele doubling over ``ppermute``: ⌈log₂P⌉+1 rounds of unit
        payload, O(log P) latency — versus the naive ``all_gather`` + masked-sum
        form whose per-device payload is P×. Works for any P (not just powers of
        two); shard 0 receives the additive identity.
        """
        if diagnostics._enabled or forensics._enabled:
            self._record_collective("exscan", axis_name, x)
        return _guarded("comm.exscan", self._exscan_impl, x, axis_name)

    def _exscan_impl(self, x, axis_name):
        name = axis_name or self.axis_name
        if not isinstance(name, str):
            idx = jax.lax.axis_index(name)
            full = jax.lax.all_gather(x, name, axis=0)
            mask = (jnp.arange(self.size) < idx).reshape((-1,) + (1,) * (full.ndim - 1))
            return jnp.sum(full * mask.astype(full.dtype), axis=0)
        p = jax.lax.psum(1, name)
        # right-shift by one (slot 0 gets zeros), then inclusive doubling scan
        acc = jax.lax.ppermute(x, name, perm=[(i, i + 1) for i in range(p - 1)])
        d = 1
        while d < p:
            acc = acc + jax.lax.ppermute(acc, name, perm=[(i, i + d) for i in range(p - d)])
            d <<= 1
        return acc

    Exscan = exscan

    def scan(self, x, axis_name: Optional[str] = None):
        """Inclusive prefix-sum over shards (reference Scan ``communication.py:1881``):
        the exclusive scan plus the local contribution."""
        if diagnostics._enabled or forensics._enabled:
            self._record_collective("scan", axis_name, x)
        return self.exscan(x, axis_name) + x

    Scan = scan

    def reduce(self, x, root: int = 0, axis_name: Optional[str] = None):
        """Sum-reduce with the result significant only at shard ``root`` (reference
        Reduce ``communication.py:1823``): SPMD collectives are symmetric, so this
        is the all-reduce with non-root shards zeroed — the rooted contract without
        a second collective."""
        if diagnostics._enabled or forensics._enabled:
            self._record_collective("reduce", axis_name, x)
        name = axis_name or self.axis_name
        total = _guarded("comm.reduce", jax.lax.psum, x, name)
        idx = jax.lax.axis_index(name)
        return jnp.where(idx == root, total, jnp.zeros_like(total))

    Reduce = reduce

    def gather(self, x, axis: int = 0, root: int = 0, axis_name: Optional[str] = None):
        """Gather shards to ``root`` (reference Gather ``communication.py:1299``):
        the all-gather with non-root shards zeroed — rooted semantics on a
        symmetric collective."""
        if diagnostics._enabled or forensics._enabled:
            self._record_collective("gather", axis_name, x)
        name = axis_name or self.axis_name
        full = _guarded("comm.gather", jax.lax.all_gather, x, name, axis=axis, tiled=True)
        idx = jax.lax.axis_index(name)
        return jnp.where(idx == root, full, jnp.zeros_like(full))

    Gather = gather

    def scatter(self, x, axis: int = 0, root: int = 0, axis_name: Optional[str] = None):
        """Scatter ``root``'s value in equal chunks along ``axis`` (reference
        Scatter ``communication.py:1936``). Binomial-tree broadcast of the full
        payload followed by a local slice: XLA has no rooted scatter primitive, so
        the wire cost is the broadcast's P−1 full payloads rather than MPI's 1/P
        chunks — acceptable because every framework path that needs 1/P placement
        uses shardings (``comm.shard``), not this rooted op."""
        if diagnostics._enabled or forensics._enabled:
            self._record_collective("scatter", axis_name, x)
        name = axis_name or self.axis_name
        full = self.broadcast(x, root=root, axis_name=name)
        idx = jax.lax.axis_index(name)
        # the size of the NAMED axis (a sub-axis on hierarchical meshes), which is
        # static at trace time — dynamic_slice needs a static chunk size
        names = (name,) if isinstance(name, str) else tuple(name)
        axsize = int(np.prod([self.mesh.shape[n] for n in names]))
        if full.shape[axis] % axsize:
            raise ValueError(
                f"scatter: extent {full.shape[axis]} along axis {axis} is not "
                f"divisible by the {axsize}-shard axis {name!r} (MPI_Scatter "
                f"semantics require exact chunks)"
            )
        c = full.shape[axis] // axsize
        return jax.lax.dynamic_slice_in_dim(full, idx * c, c, axis=axis)

    Scatter = scatter

    # ------------------------------------------------------------------ misc parity
    def Split(self, color=0, key: int = 0) -> "MeshCommunication":
        """Sub-communicator by colour (reference MPI ``Comm.Split``, ``communication.py:465``).

        MPI's Split is collective — each rank passes its own colour. In single-controller
        JAX one call sees every shard, so ``color`` may be a sequence assigning a colour
        per shard index; the sub-communicator returned is the group containing shard
        ``self.rank``. A scalar colour means every shard shares it (≙ MPI dup).
        """
        axis = self.axis_names[-1] if self.is_hierarchical else self.axis_name
        if np.isscalar(color):
            if self.is_hierarchical:  # true dup: keep the mesh topology
                return MeshCommunication(
                    self._devices,
                    mesh_shape=self.mesh.devices.shape,
                    axis_names=self.axis_names,
                )
            return MeshCommunication(self._devices, axis_name=axis)
        colors = list(color)
        if len(colors) != self.size:
            raise ValueError(f"need one color per shard ({self.size}), got {len(colors)}")
        mine = colors[self.rank]
        devs = [d for i, d in enumerate(self._devices) if colors[i] == mine]
        return MeshCommunication(devs, axis_name=axis)


# A jitted, cached reshard for ragged (non-divisible) dims: GSPMD pads internally.
_pad_cache: dict = {}


def _pad_reshard(
    array: jax.Array, target: NamedSharding, split: Optional[int], padded: Optional[int]
) -> jax.Array:
    """Reshard a (possibly non-addressable) global array, zero-padding a ragged split
    dimension to ``padded`` inside the jitted program so the output satisfies a true
    1/P NamedSharding."""
    if diagnostics._enabled:
        diagnostics.record_collective(
            "_pad_reshard", target.mesh.axis_names, target.mesh.size,
            _payload_bytes(array),
        )
    key = (target, array.ndim, split, padded)  # NamedSharding hashes mesh + devices,
    # so two same-shape meshes over different device sets cannot collide
    fn = _pad_cache.get(key)
    if fn is None:
        if padded is None:
            fn = jax.jit(lambda x: x, out_shardings=target)
        else:

            def _pad(x):
                widths = [(0, 0)] * x.ndim
                widths[split] = (0, padded - x.shape[split])
                return jnp.pad(x, widths)

            fn = jax.jit(_pad, out_shardings=target)
        _pad_cache[key] = fn
    return _guarded("comm.reshard", fn, array)


# Every bootstrap (import, then each explicit initialize() — and each elastic
# restart) gets its own barrier id + KV namespace: coordination KV keys are
# namespace-scoped per use, and SPMD symmetry keeps the counter in step on
# every process, so a re-init re-anchors instead of failing the handshake.
# The wait budget is the unified HEAT_TPU_COORD_TIMEOUT_MS knob
# (supervision.coord_timeout_ms — replacing the old hardcoded 60 s here and
# 600 s in checkpoint), and every wait goes through the supervised wrappers:
# bounded, sentinel-abortable, and typed (resilience.CoordinationTimeout /
# PeerFailed) instead of an opaque backend error.
_handshake_generation = 0


def _telemetry_bootstrap() -> None:
    """Stamp this process's rank into ht.telemetry and, on multi-process jobs,
    run the boot-time clock-offset handshake: a coordination-service barrier
    (the supervised KV form), then every process samples
    ``time.monotonic_ns()`` and publishes it through the distributed KV store
    (one logical allgather of the anchors) — the zero point that lets
    ``telemetry.merge`` align trace timestamps across ranks. The handshake
    rides the ``jax.distributed`` coordination channel, never an XLA
    computation, so it works on every backend (CPU meshes included) and
    cannot touch any compiled program — HLO-untouched by construction.
    Accuracy is the barrier's exit skew (sub-millisecond on one host,
    network-RTT across hosts; the docs state the caveat). Afterwards the
    supervision plane is armed for the job (heartbeats + sentinel polling)
    and this process's rank is stamped for ``rank``-targeted fault plans."""
    global _handshake_generation
    try:
        telemetry.set_process_info(jax.process_index(), jax.process_count())
        resilience.set_fault_rank(jax.process_index())
        if (
            jax.process_count() > 1
            and os.environ.get("HEAT_TPU_TELEMETRY_HANDSHAKE") != "0"
        ):
            client = jax._src.distributed.global_state.client
            if client is None:
                raise RuntimeError("jax.distributed client not initialized")
            co = supervision.ClientCoordinator(client)
            gen = _handshake_generation
            _handshake_generation += 1  # ht: ignore[lock-racing-increment] -- bootstrap-only: runs at module import and inside initialize(), both single-threaded launch paths; SPMD symmetry (not thread-safety) is what keeps the counter aligned
            index = jax.process_index()
            # boot-time liveness wait, capped at the old 60 s handshake
            # budget: the supervision plane is not armed yet (auto_arm runs
            # after the handshake), so a peer that died pre-handshake cannot
            # be sentinel-aborted mid-wait — letting this wait default to
            # the full 600 s coordination budget would stall every
            # survivor's boot 10x longer than pre-supervision. The unified
            # knob still bounds it downward (HEAT_TPU_COORD_TIMEOUT_MS
            # below 60 s shortens the handshake too).
            boot_ms = min(supervision.coord_timeout_ms(), 60_000)
            supervision.kv_barrier(
                f"heat_tpu/telemetry/clock/{gen}",
                nprocs=jax.process_count(), rank=index, timeout_ms=boot_ms,
                site="telemetry.handshake", coordinator=co,
            )
            anchor = time.monotonic_ns()
            co.set(f"heat_tpu/telemetry/anchor/{gen}/{index}", str(anchor))
            anchors = [
                int(supervision.kv_wait(
                    f"heat_tpu/telemetry/anchor/{gen}/{i}", boot_ms,
                    site="telemetry.handshake", coordinator=co,
                ))
                for i in range(jax.process_count())
            ]
            telemetry.record_clock_anchor(anchor, anchors)
    except Exception as exc:
        # a failed handshake must never block the job: the shards fall back
        # to unaligned per-process anchors, and the degradation is accounted
        # in the always-on resilience event stream
        diagnostics.record_resilience_event(
            "telemetry.handshake", "degraded", f"{type(exc).__name__}: {exc}"
        )
    supervision.auto_arm()


# --------------------------------------------------------------------------- singletons
COMM_WORLD: MeshCommunication = MeshCommunication()
"""World communicator over all visible devices (reference ``MPI_WORLD`` ``communication.py:2013``)."""

COMM_SELF: MeshCommunication = MeshCommunication(jax.devices()[:1])
"""Single-device communicator (reference ``MPI_SELF`` ``communication.py:2014``)."""

# The env-contract bootstrap (module top) has already initialised
# jax.distributed by this point, so rank identity and the clock handshake can
# be stamped into the telemetry plane for every launch path.
_telemetry_bootstrap()

__default_comm = COMM_WORLD


def get_comm() -> MeshCommunication:
    """Return the current default communicator (reference ``communication.py:2020``)."""
    return __default_comm


def use_comm(comm: Optional[MeshCommunication] = None) -> None:
    """Set the default communicator (reference ``communication.py:2050``)."""
    global __default_comm
    if comm is None:
        comm = COMM_WORLD
    if not isinstance(comm, Communication):
        raise TypeError(f"expected a Communication object, got {type(comm)}")
    __default_comm = comm


def sanitize_comm(comm: Optional[Communication]) -> MeshCommunication:
    """Validate ``comm`` or fall back to the default (reference ``devices.py`` analogue)."""
    if comm is None:
        return get_comm()
    if not isinstance(comm, Communication):
        raise TypeError(f"expected a Communication object, got {type(comm)}")
    return comm


def initialize(**kwargs) -> None:
    """Multi-host bootstrap: ``jax.distributed.initialize`` replaces the mpirun launcher
    (reference launches via ``mpirun -np N python script.py``, ``scripts/heat_test.py:1-9``).

    NOTE: must run before anything initialises the XLA backend — and importing
    ``heat_tpu`` does. The supported launch paths are therefore (a) the
    ``HEAT_TPU_COORDINATOR_ADDRESS`` / ``HEAT_TPU_NUM_PROCESSES`` /
    ``HEAT_TPU_PROCESS_ID`` environment contract, honoured automatically at
    import (see module header), or (b) calling ``jax.distributed.initialize``
    yourself before the first ``import heat_tpu``.

    Multi-controller contract (every process runs the same program, SPMD):

    - compute on DNDarrays is global — XLA emits the cross-host collectives; nothing
      special to do;
    - collection (``numpy()``/``tolist()``/``item()``/printing) performs a cross-host
      ``process_allgather`` and returns the identical global value on every process;
    - ``ht.save*`` gathers and writes from process 0 only (see ``io._is_writer``);
      ``ht.load*`` reads the file on every process (shared filesystem assumed, like
      the reference's MPI-IO setups) and populates only addressable shards;
    - per-process ingest of pre-distributed data uses ``ht.array(..., is_split=k)``.

    With the supervision plane enabled (the default) and the full explicit
    coordination triple given, the runtime is built in SUPERVISED mode
    (``supervision.bootstrap_distributed``): observably identical, but peer
    failures deliver typed errors instead of XLA's process-terminating
    fail-stop, and elastic restart (``ht.resilience.run_supervised``) becomes
    possible. Auto-detected launches (TPU/Slurm args omitted) keep the stock
    ``jax.distributed.initialize`` path.
    """
    explicit = {"coordinator_address", "num_processes", "process_id"}
    if supervision.enabled() and explicit.issubset(kwargs):
        supervision.bootstrap_distributed(
            kwargs["coordinator_address"], int(kwargs["num_processes"]),
            int(kwargs["process_id"]),
        )
    else:
        jax.distributed.initialize(**kwargs)
    global COMM_WORLD, COMM_SELF, __default_comm
    COMM_WORLD = MeshCommunication()
    COMM_SELF = MeshCommunication(jax.devices()[:1])
    __default_comm = COMM_WORLD
    _telemetry_bootstrap()
