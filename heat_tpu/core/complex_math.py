"""Complex number operations (reference heat/core/complex_math.py, 5 exports)."""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations, types
from .dndarray import DNDarray

__all__ = ["angle", "conj", "conjugate", "imag", "real"]


def angle(x: DNDarray, deg: bool = False, out=None) -> DNDarray:
    """Argument of the complex values (reference ``complex_math.py`` angle)."""
    return _operations.local_op(jnp.angle, x, out, deg=deg)


def conjugate(x: DNDarray, out=None) -> DNDarray:
    """Complex conjugate (reference ``complex_math.py`` conjugate)."""
    return _operations.local_op(jnp.conjugate, x, out)


conj = conjugate


def imag(x: DNDarray, out=None) -> DNDarray:
    """Imaginary part (zero array for real inputs)."""
    return _operations.local_op(jnp.imag, x, out)


def real(x: DNDarray, out=None) -> DNDarray:
    """Real part (identity for real inputs)."""
    if isinstance(x, DNDarray) and not types.heat_type_is_complexfloating(x.dtype):
        return x
    return _operations.local_op(jnp.real, x, out)
