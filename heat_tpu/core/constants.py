"""Mathematical constants (reference heat/core/constants.py)."""

import math

__all__ = ["e", "Euler", "inf", "Inf", "Infty", "Infinity", "nan", "NaN", "pi"]

e = math.e
"""Euler's number"""
Euler = e
inf = math.inf
"""IEEE 754 floating point representation of (positive) infinity"""
Inf = inf
Infty = inf
Infinity = inf
nan = math.nan
"""IEEE 754 floating point representation of Not a Number"""
NaN = nan
pi = math.pi
"""Archimedes' constant"""
