"""Device registry (reference heat/core/devices.py:14-181, re-targeted at TPU).

The reference maps Heat devices onto torch devices with a round-robin GPU→rank rule
(``devices.py:114-118``). Here a :class:`Device` names a JAX platform; actual placement of
distributed arrays is governed by the mesh in :mod:`heat_tpu.core.communication`, so the
device object is a label + default-platform selector rather than an address.
"""

from __future__ import annotations

from typing import Optional, Union

import jax

__all__ = ["Device", "cpu", "tpu", "gpu", "get_device", "use_device", "sanitize_device"]


class Device:
    """Implements a compute device. ``device_type`` is a JAX platform name
    (``"cpu"``, ``"tpu"``, ``"gpu"``); ``device_id`` selects among local devices.

    Mirrors reference ``heat/core/devices.py:17-94``.
    """

    def __init__(self, device_type: str, device_id: int = 0):
        self.__device_type = device_type.strip().lower()
        self.__device_id = int(device_id)

    @property
    def device_type(self) -> str:
        return self.__device_type

    @property
    def device_id(self) -> int:
        return self.__device_id

    @property
    def jax_device(self) -> Optional[jax.Device]:
        """The concrete ``jax.Device`` this label resolves to, or None if absent."""
        try:
            devs = jax.devices(self.__device_type)
        except RuntimeError:
            return None
        if not devs:
            return None
        return devs[self.__device_id % len(devs)]

    def __repr__(self) -> str:
        return f"device({str(self)!r})"

    def __str__(self) -> str:
        return f"{self.__device_type}:{self.__device_id}"

    def __eq__(self, other) -> bool:
        if isinstance(other, Device):
            return self.device_type == other.device_type and self.device_id == other.device_id
        if isinstance(other, str):
            return str(self) == other or self.device_type == other
        return NotImplemented

    def __hash__(self) -> int:
        return hash(str(self))


cpu = Device("cpu")
"""The host CPU device (reference ``devices.py:95``)."""

# TPU/GPU singletons exist whenever the platform is present; on this build the default
# accelerator platform is whatever jax initialised with (axon TPU in production).
_default_platform = jax.default_backend()

tpu = Device("tpu") if _default_platform not in ("cpu", "gpu") else Device(_default_platform)
gpu = tpu  # alias for source compatibility with reference code written for ``ht.gpu``

__default_device = Device(_default_platform)


def get_device() -> Device:
    """Return the current default device (reference ``devices.py:160``)."""
    return __default_device


def use_device(device: Optional[Union[str, Device]] = None) -> None:
    """Set the default device (reference ``devices.py:171``)."""
    global __default_device
    __default_device = sanitize_device(device)


def sanitize_device(device: Optional[Union[str, Device]]) -> Device:
    """Validate ``device`` or fall back to the default (reference ``devices.py:130``)."""
    if device is None:
        return get_device()
    if isinstance(device, Device):
        return device
    if isinstance(device, str):
        dev = device.strip().lower()
        if ":" in dev:
            kind, _, idx = dev.partition(":")
            return Device(kind, int(idx))
        if dev in ("cpu", "tpu", "gpu", "axon"):
            return Device(dev)
    raise ValueError(f"Unknown device, must be 'cpu', 'tpu' or 'gpu', got {device!r}")
