"""Device registry (reference heat/core/devices.py:14-181, re-targeted at TPU).

The reference maps Heat devices onto torch devices with a round-robin GPU→rank rule
(``devices.py:114-118``). Here a :class:`Device` names a JAX platform; actual placement of
distributed arrays is governed by the mesh in :mod:`heat_tpu.core.communication`, so the
device object is a label + default-platform selector rather than an address.
"""

from __future__ import annotations

from typing import Optional, Union

import jax

__all__ = ["Device", "cpu", "tpu", "gpu", "get_device", "use_device", "sanitize_device"]


class Device:
    """Implements a compute device. ``device_type`` is a JAX platform name
    (``"cpu"``, ``"tpu"``, ``"gpu"``); ``device_id`` selects among local devices.

    Mirrors reference ``heat/core/devices.py:17-94``.
    """

    def __init__(self, device_type: str, device_id: int = 0):
        self.__device_type = device_type.strip().lower()
        self.__device_id = int(device_id)

    @property
    def device_type(self) -> str:
        return self.__device_type

    @property
    def device_id(self) -> int:
        return self.__device_id

    @property
    def torch_device(self) -> str:
        """Interop shim (reference ``devices.py:59`` returns the torch device
        *string*): heat_tpu data lives in jax, so this is the torch device a host
        copy would land on — always ``"cpu"`` (TPUs have no torch backing here).
        The str is valid everywhere torch accepts a device argument."""
        return "cpu"

    @property
    def jax_device(self) -> Optional[jax.Device]:
        """The concrete ``jax.Device`` this label resolves to, or None if absent."""
        try:
            devs = jax.devices(self.__device_type)
        except RuntimeError:
            return None
        if not devs:
            return None
        return devs[self.__device_id % len(devs)]

    def __repr__(self) -> str:
        return f"device({str(self)!r})"

    def __str__(self) -> str:
        return f"{self.__device_type}:{self.__device_id}"

    def __eq__(self, other) -> bool:
        if isinstance(other, Device):
            return self.device_type == other.device_type and self.device_id == other.device_id
        if isinstance(other, str):
            return str(self) == other or self.device_type == other
        return NotImplemented

    def __hash__(self) -> int:
        return hash(str(self))


cpu = Device("cpu")
"""The host CPU device (reference ``devices.py:95``)."""

# TPU/GPU singletons exist whenever the platform is present; on this build the default
# accelerator platform is whatever jax initialised with (axon TPU in production).
_default_platform = jax.default_backend()

tpu = Device("tpu") if _default_platform not in ("cpu", "gpu") else Device(_default_platform)
gpu = tpu  # alias for source compatibility with reference code written for ``ht.gpu``

__default_device = Device(_default_platform)


def get_device() -> Device:
    """Return the current default device (reference ``devices.py:160``)."""
    return __default_device


def use_device(device: Optional[Union[str, Device]] = None) -> None:
    """Set the default device (reference ``devices.py:171``)."""
    global __default_device
    __default_device = sanitize_device(device)


def sanitize_device(device: Optional[Union[str, Device]]) -> Device:
    """Validate ``device`` or fall back to the default (reference ``devices.py:130``)."""
    if device is None:
        return get_device()
    if isinstance(device, Device):
        return device
    if isinstance(device, str):
        dev = device.strip().lower()
        if ":" in dev:
            kind, _, idx = dev.partition(":")
            return Device(kind, int(idx))
        if dev in ("cpu", "tpu", "gpu", "axon"):
            return Device(dev)
    raise ValueError(f"Unknown device, must be 'cpu', 'tpu' or 'gpu', got {device!r}")


# --------------------------------------------------------------- capability probe
_ACCEL_CAPS = None


def accelerator_capabilities() -> dict:
    """Capabilities of the default accelerator backend: ``{"complex": bool,
    "fft": bool}`` (always both True on CPU).

    Some TPU runtimes cannot hold complex values or lower FFT HLOs at all — and a
    failed attempt POISONS the issuing process's backend (observed: after one
    UNIMPLEMENTED complex/fft op, every later op including plain f32 reductions
    fails). The probe therefore runs in a subprocess, once per *machine* rather than
    once per process: the outcome is persisted to a cache file keyed by platform /
    device kind / jax version (``HEAT_TPU_CAPS_CACHE`` overrides the path), so fresh
    processes don't re-pay the probe — on exclusively-held accelerators the child
    cannot initialize and each un-cached probe would stall until its timeout.
    Overrides: HEAT_TPU_COMPLEX_BACKEND=cpu|device, HEAT_TPU_FFT_BACKEND=cpu|device.
    """
    global _ACCEL_CAPS
    if _ACCEL_CAPS is not None:
        return _ACCEL_CAPS
    import os

    caps = {}
    forced_c = os.environ.get("HEAT_TPU_COMPLEX_BACKEND")
    forced_f = os.environ.get("HEAT_TPU_FFT_BACKEND")
    if forced_c:
        caps["complex"] = forced_c == "device"
    if forced_f:
        caps["fft"] = forced_f == "device"
    if len(caps) < 2:
        if jax.default_backend() == "cpu":
            caps.setdefault("complex", True)
            caps.setdefault("fft", True)
        else:
            cached = _read_caps_cache()
            if cached is not None:
                caps.setdefault("complex", cached["complex"])
                caps.setdefault("fft", cached["fft"])
            else:
                probed, probe_ok = _probe_caps_subprocess()
                caps.setdefault("complex", probed["complex"])
                caps.setdefault("fft", probed["fft"])
                _write_caps_cache(probed, probe_ok)
    _ACCEL_CAPS = caps
    return caps


def _caps_cache_path() -> str:
    import os
    import tempfile

    override = os.environ.get("HEAT_TPU_CAPS_CACHE")
    if override:
        return override
    try:
        kind = jax.devices()[0].device_kind.replace(" ", "_").replace("/", "_")
    except (RuntimeError, IndexError, AttributeError):
        kind = "unknown"  # backend not initialisable / no devices: generic key
    try:
        import jaxlib

        runtime = jaxlib.__version__  # capability limits live in the runtime build,
        # not the jax front-end — key on it so runtime up/downgrades re-probe
    except (ImportError, AttributeError):
        runtime = "unknown"
    uid = os.getuid() if hasattr(os, "getuid") else 0
    name = (
        f"heat_tpu_caps_u{uid}_{jax.default_backend()}_{kind}"
        f"_jax{jax.__version__}_rt{runtime}.json"
    )
    return os.path.join(tempfile.gettempdir(), name)


# How long a FAILED probe (child could not run at all — e.g. the accelerator was
# exclusively held) stays cached. A clean probe that *ran* and reported
# unsupported ops is a stable hardware fact and is cached indefinitely; a probe
# that couldn't run must not permanently condemn a capable chip.
_FAILED_PROBE_TTL_S = 900.0


def _read_caps_cache() -> Optional[dict]:
    import json
    import os
    import time

    try:
        path = _caps_cache_path()
        if hasattr(os, "getuid") and os.stat(path).st_uid != os.getuid():
            return None  # never trust a cache file another user planted
        with open(path) as fh:
            data = json.load(fh)
        if not data.get("probe_ok", True):
            if time.time() - float(data.get("time", 0)) > _FAILED_PROBE_TTL_S:
                return None
        return {"complex": bool(data["complex"]), "fft": bool(data["fft"])}
    except (OSError, ValueError, KeyError, TypeError):
        return None  # unreadable/malformed/foreign cache: treat as absent


def _write_caps_cache(caps: dict, probe_ok: bool) -> None:
    import json
    import os
    import time

    try:
        path = _caps_cache_path()
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as fh:
            json.dump({**caps, "probe_ok": probe_ok, "time": time.time()}, fh)
    except OSError:
        pass  # cache is best-effort; the in-process memo still holds


def relay_breaker():
    """The per-process circuit breaker every backend/relay probe feeds
    (``resilience.relay_breaker`` — config centralised there): while open,
    capability probes short-circuit to the conservative negative verdict
    instead of re-paying the 90 s subprocess timeout; after the cooldown it
    half-opens and the next probe is a real trial."""
    from . import resilience

    return resilience.relay_breaker()


def _probe_caps_subprocess() -> tuple:
    """Returns ``(caps, probe_ok)``: ``probe_ok`` is True when the child actually ran
    the probe (its verdict — positive or negative — is a stable hardware fact) and
    False when the child itself failed (timeout, init failure), i.e. the conservative
    all-False answer is a guess.

    The probe honors (and feeds) the ``backend.relay`` circuit breaker: an open
    breaker short-circuits straight to the negative guess — the 90 s child
    timeout is paid at most ``failure_threshold`` times per process, and again
    only when the breaker half-opens for a re-probe."""
    import subprocess
    import sys

    from . import resilience

    breaker = relay_breaker()
    if resilience._armed:
        entry = resilience.fault_signal("probe.caps")
        if entry is not None:
            # injected relay failure: same negative verdict + breaker feedback
            # a real dead relay would produce, with zero wall-clock cost
            breaker.record_failure(f"injected {entry.kind}")
            return {"complex": False, "fft": False}, False
    if not breaker.allows():
        return {"complex": False, "fft": False}, False

    # the child must land on the SAME accelerator platform as the parent —
    # on exclusively-locked devices it may fail to initialize (or silently
    # fall back to CPU, which would report false support); both cases are
    # treated as "unsupported", which is slow-but-safe (host execution)
    # rather than process-poisoning
    parent_platform = jax.devices()[0].platform
    code = (
        "import jax, jax.numpy as jnp, numpy as np\n"
        f"assert jax.devices()[0].platform == {parent_platform!r}\n"
        "ok_c = ok_f = False\n"
        "try:\n"
        "    np.asarray(jnp.array(np.ones(4, np.complex64)) + 1j); ok_c = True\n"
        "except Exception: pass\n"
        "try:\n"
        "    np.asarray(jnp.fft.fft(jnp.ones(4, jnp.complex64))); ok_f = True\n"
        "except Exception: pass\n"
        "print('CAPS', int(ok_c), int(ok_f))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, timeout=90, text=True
        )
        line = next(
            (l for l in proc.stdout.splitlines() if l.startswith("CAPS")), None
        )
        if line is None:
            breaker.record_failure(f"caps probe child rc={proc.returncode}, no verdict")
            return {"complex": False, "fft": False}, False
        _, c, f = line.split()
        breaker.record_success()
        return {"complex": bool(int(c)), "fft": bool(int(f))}, True
    except Exception as exc:
        breaker.record_failure(f"caps probe child failed: {type(exc).__name__}")
        return {"complex": False, "fft": False}, False


def complex_supported() -> bool:
    """Whether the default accelerator holds complex values (see
    :func:`accelerator_capabilities`)."""
    return accelerator_capabilities()["complex"]


def cpu_fallback_device() -> jax.Device:
    """The host CPU device complex values live on when the accelerator can't hold
    them."""
    return jax.local_devices(backend="cpu")[0]


def complex_needs_host(*dtypes_or_values) -> bool:
    """True when a value of the promoted dtype of ``dtypes_or_values`` cannot live
    on the default accelerator (complex unsupported there) — the single predicate
    behind every complex→host fallback site."""
    if jax.default_backend() == "cpu":
        return False
    import jax.numpy as jnp
    import numpy as np

    try:
        rt = np.result_type(
            *[getattr(v, "dtype", v) for v in dtypes_or_values]
        ) if dtypes_or_values else None
    except TypeError:
        try:
            rt = jnp.result_type(*dtypes_or_values)
        except TypeError:
            return False  # unpromotable operand mix: not complex, no host hop
    if rt is None or not np.issubdtype(rt, np.complexfloating):
        return False
    return not complex_supported()


def complex_creation_ctx(*dtypes_or_values):
    """Context manager that places array creation on host CPU when the promoted
    dtype of ``dtypes_or_values`` cannot live on the accelerator (see
    :func:`complex_needs_host`); a nullcontext otherwise. The one helper behind
    every factory/dispatch complex-fallback site."""
    from contextlib import nullcontext

    if complex_needs_host(*dtypes_or_values):
        return jax.default_device(cpu_fallback_device())
    return nullcontext()
