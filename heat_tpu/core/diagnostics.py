"""``ht.diagnostics`` — framework-wide tracing, metrics, and backend-health telemetry.

The framework has three hot subsystems whose behavior is otherwise invisible at
runtime: the signature-cached dispatch executor (:mod:`_executor`), the L0
collective layer (:class:`communication.MeshCommunication`), and the accelerator
relay whose outages used to surface only as a null metric at round end. Heat's
MPI lineage leans on external tools (mpiP, Score-P) for this; the TPU-native
stack carries its own instrumentation so device traces and round artifacts
explain themselves. This module is the registry those hooks report into:

- **Counters & spans** — :func:`counter` named tallies; :func:`span` wall-clock
  aggregation (count / total / max seconds per name).
- **Collective telemetry** — every ``MeshCommunication`` collective (``psum`` …
  ``scatter``, plus ``shard`` and ``_pad_reshard``) records (op name, mesh axis,
  participant count, logical bytes moved). Collectives called inside a traced
  program (``shard_map`` / ``jit`` bodies) are recorded **at trace time**:
  replays of a cached executable do not re-execute the Python hook, so a count
  of 1 means "one traced occurrence", not "one device execution". Nested
  convenience collectives record both layers (``scan`` also records its inner
  ``exscan``; ``scatter`` its inner ``broadcast``).
- **Executor telemetry** — per-signature compile wall time, and miss events
  annotated with the *reason*: which signature component (operand aval, split,
  kwargs, mesh, …) changed versus the nearest cached key.
- **Result-cache counters** (``HEAT_TPU_RESULT_CACHE=1``; see
  :mod:`_result_cache`) — ``executor.result_cache_hit`` /
  ``executor.result_cache_store`` / ``executor.result_cache_invalidation`` /
  ``executor.result_cache_reject`` ride :func:`counter`; a poisoned entry is
  additionally a typed ``cache-corrupt`` resilience event through
  :func:`record_resilience_event`, the same contract as the compile cache.
- **Padded-layout waste gauges** — the dispatch wrappers record the pad
  fraction ``(physical - logical) / physical`` of every padded ``(gshape,
  split)`` family they dispatch on.
- **Backend-health events** — timestamped relay up/down *transitions*
  (:func:`record_backend_event`), summarised into outage windows
  (:func:`relay_outage_windows`). ``bench.py`` and ``__graft_entry__`` feed
  this stream so a null benchmark round is attributable to a measured outage
  window rather than silence.
- **Provider sections** — :func:`register_provider` attaches named report
  sections computed at :func:`report` time; the executor, resilience,
  supervision and the live operations plane (:mod:`ops` — whose ``slo-burn``
  alert transitions also arrive as typed events through
  :func:`record_resilience_event`) all report through this hook.

Zero-cost contract
------------------
When disabled (the default) the hooks are a single module-attribute read and a
branch not taken, and nothing is ever injected into traced program bodies —
compiled HLO is byte-identical to an uninstrumented build
(``tests/test_diagnostics.py::TestZeroOverheadContract``). Backend-health
events are the one always-on stream: they are only produced by explicit probe
calls in the driver entry points, never on a compute path.

Env knobs (read once at import)
-------------------------------
- ``HEAT_TPU_METRICS=1``   — start with metrics collection enabled.
- ``HEAT_TPU_TRACE=1``     — start with tracing enabled: ``jax.named_scope``
  framework-level op names compiled into program metadata (visible in XLA
  device traces / HLO dumps) and ``jax.profiler.TraceAnnotation`` host spans
  around compile + dispatch. Programs cached before the flag flips keep their
  old annotations — ``clear_executor_cache()`` forces a re-trace.
- ``HEAT_TPU_DIAG_DUMP=path`` — dump the full JSON report to ``path`` at
  interpreter exit (the CI tier-1 artifact).
- ``HEAT_TPU_DIAG_LOG=path``  — append backend-health transitions to ``path``
  as JSON lines (survives the process; shared by bench.py / __graft_entry__).

This module deliberately imports only the stdlib at top level so the driver
entry points (``bench.py``, ``__graft_entry__.py``) can load it by file path
*before* deciding whether touching the JAX backend is safe.

Thread-safety (audited for the multi-threaded serving harness)
--------------------------------------------------------------
Every mutation of the shared registries — counters, spans, collective and
pad-waste aggregates, the bounded event deques, the backend-state transition
check, provider registration — runs under the one module ``_lock``, and
:func:`report`/:func:`reset` snapshot/clear under the same lock, so counts
are EXACT under concurrent requests (``tests/test_diagnostics.py::
TestThreadSafety`` hammers this). The deliberate exceptions, relaxed rather
than locked:

- the ``_enabled`` / ``_tracing`` switches are bare module attributes: hot
  paths read them un-locked (the zero-cost contract), so a concurrent
  ``enable()``/``disable()`` takes effect on other threads at their next
  hook — no torn state is possible (bool writes are atomic), only a few
  events either side of the flip may or may not be collected;
- the ``HEAT_TPU_DIAG_LOG`` file append in :func:`record_backend_event` runs
  OUTSIDE the lock (a slow disk must not stall telemetry); interleaved lines
  from two processes are whole-line atomic on POSIX appends of this size;
- the late-bound collaborator hooks (``_atomic_writer``, ``_resilience_tee``,
  ``_fallback_tee``) are written exactly once at their owning module's import
  and read bare afterwards; tee invocations happen OUTSIDE ``_lock`` so the
  flight-recorder ring's lock stays strictly below this one;
- the executor's ``_stats`` tallies (in :mod:`_executor`) are PER-THREAD
  accumulator cells merged at report time: increments stay lock-free on the
  hot paths (``retraces`` inside a traced body, the memo-hit
  ``reexec_avoided`` fast path, the scheduler thread's execution tallies)
  yet counts are EXACT — the async dispatch scheduler made the old
  relaxed-racing-``+=`` undercount a real risk instead of a curiosity. The
  signature table itself and every decision made from it are fully
  lock-protected.
"""

from __future__ import annotations

import atexit
import calendar
import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "enable",
    "disable",
    "enabled",
    "tracing",
    "reset",
    "report",
    "dump",
    "span",
    "counter",
    "record_collective",
    "record_compile",
    "record_dispatch_event",
    "record_fallback",
    "record_resilience_event",
    "record_pad_waste",
    "record_backend_event",
    "relay_outage_windows",
    "register_provider",
]

SCHEMA = "heat-tpu-diagnostics/1"

# Hot-path hooks read these module attributes directly (`diagnostics._enabled`):
# one attribute load + branch when off — the zero-cost-when-disabled contract.
_enabled: bool = False
_tracing: bool = False

_lock = threading.RLock()

# Bounded event streams: telemetry must never become the memory leak it exists
# to find. Aggregates (counters/spans/collectives/pad gauges) are dicts keyed by
# identity and stay small; raw event streams evict OLDEST on overflow (deque
# maxlen) so the report always holds the most recent tail of the run.
_MAX_EVENTS = 10_000

_counters: Dict[str, float] = {}
_spans: Dict[str, Dict[str, float]] = {}
_collectives: Dict[Any, Dict[str, int]] = {}
_pad_gauges: Dict[Any, Dict[str, Any]] = {}
_compile_events: "deque[dict]" = deque(maxlen=_MAX_EVENTS)
_dispatch_events: "deque[dict]" = deque(maxlen=_MAX_EVENTS)
_fallback_events: "deque[dict]" = deque(maxlen=_MAX_EVENTS)
_resilience_events: "deque[dict]" = deque(maxlen=_MAX_EVENTS)
_backend_events: "deque[dict]" = deque(maxlen=_MAX_EVENTS)
_backend_state: Optional[bool] = None

# Subsystems register report sections lazily (the executor registers its
# ``executor_stats`` here) so this module never imports the package — it must
# stay loadable standalone, before JAX, by the relay-probing entry points.
_providers: Dict[str, Callable[[], Any]] = {}

# Late-bound collaborators, installed by modules this one must not import
# (each would be a cycle — resilience and telemetry both import diagnostics).
# All three are written once at their owner's import and read bare afterwards
# (relaxed, like the switches): ``_atomic_writer`` is
# ``resilience.atomic_write`` so :func:`dump` commits whole artifacts;
# ``_resilience_tee`` / ``_fallback_tee`` are ``telemetry.flight_record``
# adapters so every failure-path event also lands in the flight-recorder ring
# (and can trigger its automatic post-mortem dump); ``_forensics_tee`` is the
# forensics event adapter so typed failures also land on the active request's
# critical path. Tees are invoked OUTSIDE ``_lock`` — the flight ring and the
# forensics store have their own locks and must stay leaves.
_atomic_writer: Optional[Callable[..., Any]] = None
_resilience_tee: Optional[Callable[[str, str, str], None]] = None
_fallback_tee: Optional[Callable[[str, str], None]] = None
_forensics_tee: Optional[Callable[[str, str, str], None]] = None


def _utcnow() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _parse_utc(stamp: str) -> Optional[float]:
    try:
        return calendar.timegm(time.strptime(stamp, "%Y-%m-%dT%H:%M:%SZ"))
    except (ValueError, TypeError):
        return None


# ------------------------------------------------------------------ switches
def enable(trace: Optional[bool] = None) -> None:
    """Turn on metrics collection; ``trace=True`` additionally turns on trace
    annotations (``trace=False`` turns them off, ``None`` leaves them as-is).

    Tracing affects programs at *trace* time: executables cached while tracing
    was off keep their unannotated HLO until ``clear_executor_cache()``."""
    global _enabled, _tracing
    _enabled = True
    if trace is not None:
        _tracing = bool(trace)


def disable(trace: Optional[bool] = None) -> None:
    """Stop collecting metrics (collected data is kept — :func:`report` still
    works; :func:`reset` clears it). ``trace`` as in :func:`enable`, default
    turns tracing off too."""
    global _enabled, _tracing
    _enabled = False
    _tracing = bool(trace) if trace is not None else False


def enabled() -> bool:
    """Whether metrics collection is currently on."""
    return _enabled


def tracing() -> bool:
    """Whether trace annotations (named_scope / TraceAnnotation) are on."""
    return _tracing


def reset() -> None:
    """Drop every collected datum (counters, spans, collectives, pad gauges,
    compile/dispatch/backend events). The enabled/tracing switches and the
    last-known backend state are kept."""
    with _lock:
        _counters.clear()
        _spans.clear()
        _collectives.clear()
        _pad_gauges.clear()
        _compile_events.clear()
        _dispatch_events.clear()
        _fallback_events.clear()
        _resilience_events.clear()
        _backend_events.clear()


def register_provider(name: str, fn: Callable[[], Any]) -> None:
    """Attach a named report section computed at :func:`report` time (the
    executor registers its stats here; avoids an import cycle and keeps this
    module standalone-loadable)."""
    with _lock:
        _providers[name] = fn


# ------------------------------------------------------------------ primitives
def counter(name: str, value: float = 1) -> None:
    """Add ``value`` to the named counter (no-op while disabled)."""
    if not _enabled:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + value


@contextlib.contextmanager
def span(name: str):
    """Time a ``with`` block into the span registry: per-name count / total
    seconds / max seconds. No-op (and near-free) while disabled."""
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            agg = _spans.get(name)
            if agg is None:
                agg = _spans[name] = {"count": 0, "total_s": 0.0, "max_s": 0.0}
            agg["count"] += 1
            agg["total_s"] += dt
            agg["max_s"] = max(agg["max_s"], dt)


def record_collective(op: str, axis: Any, participants: int, nbytes: int) -> None:
    """Count one (traced) collective: ``nbytes`` is the *logical* payload —
    per-participant payload bytes × participants for the symmetric collectives,
    the logical array size for layout ops (``shard`` / ``_pad_reshard``)."""
    if not _enabled:
        return
    key = (op, str(axis), int(participants))
    with _lock:
        agg = _collectives.get(key)
        if agg is None:
            agg = _collectives[key] = {"count": 0, "bytes": 0}
        agg["count"] += 1
        agg["bytes"] += int(nbytes)


def record_compile(label: str, seconds: float) -> None:
    """One executor program compile: signature label + wall seconds (first-call
    wall time — trace + XLA compile + the first execution)."""
    if not _enabled:
        return
    rec = {"t": _utcnow(), "label": label, "seconds": round(float(seconds), 6)}
    with _lock:
        _compile_events.append(rec)


def record_dispatch_event(kind: str, label: str, reason: str) -> None:
    """An executor cache event worth explaining — currently ``miss`` with the
    signature component(s) that changed vs. the nearest cached key."""
    if not _enabled:
        return
    rec = {"t": _utcnow(), "kind": kind, "label": label, "reason": reason}
    with _lock:
        _dispatch_events.append(rec)


def record_fallback(site: str, reason: str) -> None:
    """One eager-path fallback that used to be a silent ``except Exception``:
    counted per site (``fallback.<site>``) and recorded with its reason
    (exception type + op label), so a workload that quietly lost its staged
    programs is visible in the report instead of just slow."""
    if not _enabled:
        return
    rec = {"t": _utcnow(), "site": site, "reason": str(reason)}
    with _lock:
        _counters[f"fallback.{site}"] = _counters.get(f"fallback.{site}", 0) + 1
        _fallback_events.append(rec)
    tee = _fallback_tee
    if tee is not None:
        tee(site, rec["reason"])


def record_resilience_event(site: str, kind: str, detail: str = "") -> None:
    """A resilience-subsystem event: policy ``retry``/``exhausted``, circuit
    ``breaker`` transitions, injected ``fault`` firings, executor ``fallback``
    and quarantine decisions. Always on (not gated by :func:`enabled`), like
    backend-health events: these come from explicit failure-path machinery,
    never from a hot compute path, and a null round must stay attributable
    even when metrics were off."""
    rec = {"t": _utcnow(), "site": site, "kind": kind, "detail": str(detail)}
    with _lock:
        _resilience_events.append(rec)
    tee = _resilience_tee
    if tee is not None:
        tee(site, kind, rec["detail"])
    ftee = _forensics_tee
    if ftee is not None:
        ftee(site, kind, rec["detail"])


def record_pad_waste(gshape, split: int, padded_dim: int) -> None:
    """Gauge the padded-layout waste of one dispatched op's ``(gshape, split)``
    family: pad fraction ``(padded - n) / padded`` of the split dimension."""
    if not _enabled:
        return
    gshape = tuple(int(s) for s in gshape)
    n = gshape[split]
    padded_dim = int(padded_dim)
    frac = (padded_dim - n) / padded_dim if padded_dim else 0.0
    key = (gshape, int(split), padded_dim)
    with _lock:
        agg = _pad_gauges.get(key)
        if agg is None:
            agg = _pad_gauges[key] = {"pad_fraction": round(frac, 6), "observations": 0}
        agg["observations"] += 1


# ------------------------------------------------------------------ backend health
def record_backend_event(up: bool, detail: str = "") -> dict:
    """Record an accelerator-backend probe result. Only *transitions* (and the
    first probe) enter the event stream and the ``HEAT_TPU_DIAG_LOG`` file —
    steady-state probes just confirm the known state. Always on (not gated by
    :func:`enabled`): health events come from explicit driver probes, never
    from a compute path."""
    global _backend_state
    up = bool(up)
    rec = {"t": _utcnow(), "up": up, "detail": str(detail)}
    with _lock:
        transition = _backend_state is None or _backend_state != up
        _backend_state = up
        if transition:
            _backend_events.append(rec)
    if transition:
        path = os.environ.get("HEAT_TPU_DIAG_LOG")
        if path:
            try:
                with open(path, "a") as f:
                    f.write(json.dumps({"backend": rec}) + "\n")
            except OSError:
                pass
    rec = dict(rec)
    rec["transition"] = transition
    return rec


def relay_outage_windows(events: Optional[List[dict]] = None) -> List[dict]:
    """Fold a time-ordered up/down event stream (default: the recorded backend
    transitions) into outage windows ``{"start", "end", "duration_s"}`` —
    ``end``/``duration_s`` are ``None`` for an outage still open at the last
    event. This is the summary ``bench.py`` attaches to ``BENCH_*.json`` so a
    null round points at a measured window."""
    if events is None:
        with _lock:
            events = list(_backend_events)
    windows: List[dict] = []
    current: Optional[dict] = None
    for ev in events:
        if not ev.get("up"):
            if current is None:
                current = {"start": ev.get("t"), "end": None, "duration_s": None}
        elif current is not None:
            current["end"] = ev.get("t")
            t0, t1 = _parse_utc(current["start"]), _parse_utc(current["end"])
            if t0 is not None and t1 is not None:
                current["duration_s"] = max(0, int(t1 - t0))
            windows.append(current)
            current = None
    if current is not None:
        windows.append(current)
    return windows


# ------------------------------------------------------------------ reporting
def report() -> dict:
    """The full structured snapshot — the JSON schema documented in
    ``doc/source/observability.rst``."""
    with _lock:
        rep = {
            "schema": SCHEMA,
            "generated_at": _utcnow(),
            "enabled": _enabled,
            "tracing": _tracing,
            "counters": dict(_counters),
            "spans": {k: dict(v) for k, v in _spans.items()},
            "collectives": [
                {
                    "op": op,
                    "axis": axis,
                    "participants": participants,
                    "count": agg["count"],
                    "bytes": agg["bytes"],
                }
                for (op, axis, participants), agg in sorted(_collectives.items())
            ],
            "pad_waste": [
                {
                    "gshape": list(gshape),
                    "split": split,
                    "physical_dim": padded,
                    "logical_dim": gshape[split],
                    "pad_fraction": agg["pad_fraction"],
                    "observations": agg["observations"],
                }
                for (gshape, split, padded), agg in sorted(_pad_gauges.items())
            ],
            "compile_events": list(_compile_events),
            "dispatch_events": list(_dispatch_events),
            "fallback_events": list(_fallback_events),
            "resilience_events": list(_resilience_events),
            "backend_events": list(_backend_events),
        }
    rep["relay_outage_windows"] = relay_outage_windows(rep["backend_events"])
    with _lock:
        providers = list(_providers.items())
    for name, provider in providers:
        try:
            rep[name] = provider()
        except Exception as exc:  # ht: ignore[silent-except] -- not silent: the error lands in the report payload itself; a broken provider must not kill the report
            rep[name] = {"error": repr(exc)}
    return rep


def dump(path: str) -> None:
    """Write :func:`report` as JSON to ``path``.

    Routed through ``resilience.atomic_write`` (site ``diagnostics.dump``)
    when the resilience module has installed itself: a crash mid-dump leaves
    the previous artifact (or nothing), never a torn half-JSON — merged
    telemetry reads these artifacts back, so partial writes must be
    impossible, not just unlikely."""
    payload = report()

    def _write(target: str) -> None:
        with open(target, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")

    writer = _atomic_writer
    if writer is not None:
        writer(path, _write, site="diagnostics.dump")
    else:  # standalone load before resilience exists: plain write
        _write(path)


# ------------------------------------------------------------------ env bootstrap
if os.environ.get("HEAT_TPU_METRICS") == "1":
    _enabled = True
if os.environ.get("HEAT_TPU_TRACE") == "1":
    _tracing = True

# Only the PACKAGE instance registers the exit dump. The driver entry points
# (bench.py, __graft_entry__) also load this file standalone via
# spec_from_file_location (no parent package, __package__ falsy) — that second
# module instance holds only backend events, and atexit's LIFO order would let
# its near-empty report overwrite the package instance's full one.
_dump_path = os.environ.get("HEAT_TPU_DIAG_DUMP")
if _dump_path and __package__:

    @atexit.register
    def _dump_at_exit(path: str = _dump_path) -> None:  # pragma: no cover - exit hook
        try:
            dump(path)
        except Exception:  # ht: ignore[silent-except] -- atexit hook: raising here would mask the process's real exit status
            pass
