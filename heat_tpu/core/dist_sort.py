"""Distributed sort along a split axis with O(n/P) memory per device.

The reference hand-writes a distributed sample-sort (``heat/core/manipulations.py:2429``):
local sort, sampled splitters, Alltoallv redistribution, local merge. That shape relies on
*variable-count* collectives — bucket sizes are data-dependent — which XLA cannot express
with static shapes: a padded all-to-all would need worst-case O(n/P) padding per bucket and
degenerate to O(n) per device.

The TPU-native equivalent is a **merge-split sorting network over blocks**: each device
keeps its block of c = n/P elements locally sorted; a compare-exchange between devices i
and j merges their blocks (one ``ppermute`` hop + one local sort of 2c elements) and keeps
the lower/upper half. By the 0-1 principle generalisation (Knuth 5.3.4), running any
sorting network with this block compare-exchange yields globally sorted blocks in device
order. We use Batcher's bitonic network (log²P rounds) when P is a power of two and
odd-even transposition (P rounds, nearest-neighbour only — ideal on the ICI torus)
otherwise. Every round touches O(n/P) elements per device; peak device memory is O(n/P),
never O(n) — the property the reference's sample-sort exists to provide.

Elements are sorted by a composite key via multi-operand ``lax.sort`` with
``num_keys=2`` — a total order, so the network result is deterministic and tie order
matches ``jnp.argsort(..., stable=True)`` in both directions:

- ascending: keys ``(value, index)``; ragged extents pad with a sentinel that sorts
  *last* (NaN for floats — ``lax.sort`` canonicalises NaNs after +inf with ties broken
  by the second key, so pads land after real NaNs too), sliced off the tail.
- descending: keys ``(value, reversed-index)`` with the true index riding as a third
  operand; the ascending network then holds ties in *descending* index order, so the
  final axis flip yields descending values with ties in original order and NaNs first —
  exactly ``jnp.sort(descending=True)``. Pads use a sentinel that sorts *first*
  (-inf / int-min / False, pad slots winning ties via the reversed key) and are sliced
  off the head before the flip.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

__all__ = ["distributed_sort", "can_distribute_sort"]


def _network_rounds(nproc: int) -> List[Tuple[List[int], List[bool]]]:
    """Static per-round (partner, keep_lower) tables for the sorting network.

    Power-of-two P: Batcher bitonic, log²P rounds. Other P: odd-even transposition,
    P rounds of nearest-neighbour pairs (devices without a partner idle that round,
    encoded as partner == self).
    """
    rounds: List[Tuple[List[int], List[bool]]] = []
    if nproc & (nproc - 1) == 0:  # power of two → bitonic
        k = 2
        while k <= nproc:
            j = k // 2
            while j >= 1:
                partner = [i ^ j for i in range(nproc)]
                keep_lower = [
                    (i < (i ^ j)) == ((i & k) == 0) for i in range(nproc)
                ]
                rounds.append((partner, keep_lower))
                j //= 2
            k *= 2
    else:  # odd-even transposition
        for t in range(nproc):
            partner = list(range(nproc))
            for i in range(t % 2, nproc - 1, 2):
                partner[i], partner[i + 1] = i + 1, i
            keep_lower = [i <= partner[i] for i in range(nproc)]
            rounds.append((partner, keep_lower))
    return rounds


def can_distribute_sort(comm, gshape, split, axis, dtype) -> bool:
    """Whether the merge-split network applies: sorting along the split axis of a
    1-D-mesh communicator with an orderable dtype. Extents below 4 elements per block
    take the single-program path — the network is a memory-at-scale tool and tiny
    arrays neither need it nor amortise its compile."""
    return (
        split is not None
        and split == axis
        and comm.is_distributed()
        and len(comm.axis_names) == 1
        and comm.size > 1
        and int(gshape[axis]) >= 4 * comm.size
        and not jnp.issubdtype(dtype, jnp.complexfloating)
    )


def _pad_sentinel(dtype, descending: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(-jnp.inf if descending else jnp.nan, dtype)
    if dtype == jnp.bool_:
        return jnp.array(not descending, jnp.bool_)
    info = jnp.iinfo(dtype)
    return jnp.array(info.min if descending else info.max, dtype)


_SORTER_CACHE: dict = {}


def distributed_sort(
    comm, value: jax.Array, axis: int, descending: bool = False, logical_n: int = None
) -> Tuple[jax.Array, jax.Array]:
    """Sort a globally-sharded array along its sharded ``axis``.

    ``value`` may be the logical array or the padded physical layout of a
    ``logical_n``-extent array (``comm.shard``'s zero-padding is overwritten with the
    proper sort sentinel in place, shard-locally). Returns ``(values, indices)`` in
    **padded physical form** — the logical result occupies ``[0:logical_n)`` along
    ``axis``; pad slots hold sentinels past it. Indices are positions into the
    original global axis with ``jnp.argsort(stable=True)`` tie order in both
    directions. End-to-end the computation touches O(n/P) per device.
    """
    n = int(logical_n) if logical_n is not None else value.shape[axis]
    key = (comm.mesh, comm.axis_name, axis, bool(descending), n, value.shape)
    fn = _SORTER_CACHE.get(key)
    if fn is None:
        if len(_SORTER_CACHE) >= 256:
            _SORTER_CACHE.clear()
        mesh, axis_name, nproc = comm.mesh, comm.axis_name, comm.size
        fn = jax.jit(
            lambda v: _sort_impl(comm, mesh, axis_name, nproc, v, axis, descending, n)
        )
        _SORTER_CACHE[key] = fn
    return fn(value)


def _sort_impl(
    comm, mesh, axis_name: str, nproc: int, value: jax.Array, axis: int,
    descending: bool, n: int,
) -> Tuple[jax.Array, jax.Array]:
    c = -(-n // nproc) if n else 0
    m = c * nproc
    sentinel = _pad_sentinel(value.dtype, descending)
    if value.shape[axis] == n and m > n:
        # logical input: append the pad region
        pad_shape = value.shape[:axis] + (m - n,) + value.shape[axis + 1 :]
        value = jnp.concatenate(
            [value, jnp.full(pad_shape, sentinel, value.dtype)], axis=axis
        )
    elif value.shape[axis] != m:
        raise ValueError(
            f"extent {value.shape[axis]} along axis {axis} is neither the logical {n} "
            f"nor the padded {m}"
        )
    iota = jax.lax.broadcasted_iota(jnp.int64, value.shape, axis)
    if m > n:
        # overwrite comm.shard's zero padding with the sort sentinel, shard-locally
        value = jnp.where(iota >= n, sentinel, value)
    if descending:
        operands = (value, (m - 1) - iota, iota)
    else:
        operands = (value, iota)

    rounds = _network_rounds(nproc)
    partner_tab = np.array([r[0] for r in rounds], dtype=np.int32)
    keep_lower_tab = np.array([r[1] for r in rounds], dtype=bool)

    def network(*ops):
        i = jax.lax.axis_index(axis_name)
        ops = jax.lax.sort(ops, dimension=axis, num_keys=2)
        for r, (partner, _) in enumerate(rounds):
            perm = [(src, partner[src]) for src in range(nproc)]
            received = [
                comm.ppermute(o, perm, axis_name=axis_name) for o in ops
            ]
            merged = jax.lax.sort(
                tuple(
                    jnp.concatenate([o, ro], axis=axis)
                    for o, ro in zip(ops, received)
                ),
                dimension=axis,
                num_keys=2,
            )
            keep_lower = jnp.asarray(keep_lower_tab[r])[i]
            start = jnp.where(keep_lower, 0, c)
            sliced = [
                jax.lax.dynamic_slice_in_dim(mo, start, c, axis) for mo in merged
            ]
            has_partner = jnp.asarray(partner_tab[r])[i] != i
            ops = tuple(
                jnp.where(has_partner, s, o) for s, o in zip(sliced, ops)
            )
        return ops

    spec_entries = [None] * value.ndim
    spec_entries[axis] = axis_name
    spec = PartitionSpec(*spec_entries)
    out = jax.shard_map(
        network,
        mesh=mesh,
        in_specs=tuple(spec for _ in operands),
        out_specs=tuple(spec for _ in operands),
    )(*operands)
    values, indices = out[0], out[-1]

    # descending: ascending network with min-sentinels leaves pads at the head; the
    # axis flip yields descending values with ties in original order AND moves the
    # pads to the tail — the padded-physical convention, with no slicing (shard-local)
    if descending:
        values = jnp.flip(values, axis=axis)
        indices = jnp.flip(indices, axis=axis)
    return values, indices
