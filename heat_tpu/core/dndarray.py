"""DNDarray: a distributed n-D array as a thin wrapper over a *global* ``jax.Array``.

Reference: ``heat/core/dndarray.py:39-1940``. There, a DNDarray is a process-local
``torch.Tensor`` plus metadata (global shape, ``split`` axis, comm), and every method
hand-rolls the MPI choreography. Here the payload **is already global**: a ``jax.Array``
laid out over the communicator's device mesh with ``NamedSharding``; ``split=k`` means
mesh axis ``'d'`` is mapped onto array dimension ``k``, ``split=None`` means fully
replicated. Distribution verbs therefore collapse:

- ``resplit_`` (reference ``:1407-1536``, tile-wise Isend/Irecv) → one ``device_put`` /
  sharding constraint; XLA emits the all-to-all.
- ``balance_``/``redistribute_`` (reference ``:501,:1208``) → no-ops on data (XLA shard
  layouts are canonical ceil-division chunks by construction); they only refresh metadata.
- halo exchange (reference ``get_halo :387-455``) → slicing the global array; XLA inserts
  the neighbour communication (collective-permute on the ICI torus).
- ``__getitem__``/``__setitem__`` (reference ``:828,:1538``, a 700-line distributed
  indexing engine over a meta-tensor proxy) → ``jax.numpy`` indexing on the global value
  plus split bookkeeping.
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import types
from ._executor import Deferred
from .communication import Communication, MeshCommunication, get_comm
from .devices import Device, get_device
from .stride_tricks import sanitize_axis

__all__ = ["DNDarray", "LocalIndex"]

Scalar = Union[int, float, bool, complex]


class _CallableTuple(tuple):
    """A tuple that may also be called (torch's ``x.stride()`` spelling and
    numpy's ``x.stride`` both work against the same property)."""

    def __call__(self, dim: Optional[int] = None):
        return self if dim is None else self[dim]


class LocalIndex:
    """Marker for indexing the process-local data (reference ``dndarray.py:23``)."""

    def __init__(self, obj):
        self.obj = obj

    def __getitem__(self, key):
        return LocalIndex((self.obj, key))


class DNDarray:
    """Distributed N-Dimensional array (reference ``dndarray.py:39``).

    Parameters
    ----------
    array : jax.Array
        The **global** array value, sharded according to ``split``.
    gshape : tuple of int
        Global shape (equals ``array.shape``; kept explicitly for parity and for
        zero-size bookkeeping).
    dtype : datatype
        Heat datatype class.
    split : int or None
        Dimension carrying the mesh axis, or None for replicated.
    device : Device
        Device label.
    comm : Communication
        The mesh communicator.
    balanced : bool
        Whether shards follow the canonical chunking (always True for arrays produced by
        this framework; kept for API parity with reference ``dndarray.py:166``).
    """

    def __init__(
        self,
        array: jax.Array,
        gshape: Tuple[int, ...],
        dtype: type,
        split: Optional[int],
        device: Device,
        comm: Communication,
        balanced: Optional[bool] = True,
    ):
        self.__array = array
        self.__gshape = tuple(int(s) for s in gshape)
        self.__dtype = dtype
        self.__split = split
        self.__device = device
        self.__comm = comm
        self.__balanced = balanced
        self.__halo_next: Optional[jax.Array] = None
        self.__halo_prev: Optional[jax.Array] = None

    # ------------------------------------------------------------------ properties
    def _is_padded(self) -> bool:
        """Whether the physical value carries SURVEY §7's padded-chunk layout: a ragged
        split extent stored rounded up to P-divisibility so shards are a true 1/P."""
        return (
            self.__split is not None
            and self.__array.ndim > self.__split
            and self.__array.shape[self.__split] != self.__gshape[self.__split]
        )

    def _padded_gshape(self, gshape=None) -> Tuple[int, ...]:
        gshape = self.__gshape if gshape is None else gshape
        if self.__split is None or self.__split >= len(gshape):
            return tuple(gshape)
        m = self.__comm.padded_dim(gshape[self.__split])
        return gshape[: self.__split] + (m,) + gshape[self.__split + 1 :]

    def _logical(self) -> jax.Array:
        """The logical global value: the physical array with any layout padding sliced
        off. For divisible extents this IS the stored array (no copy); for ragged ones
        the eager slice materialises a replicated temporary — callers that care about
        per-device memory should consume :attr:`parray` / :meth:`iter_shards`."""
        if not self._is_padded():
            return self.parray
        sl = tuple(slice(0, s) for s in self.__gshape)
        return self.parray[sl]

    @property
    def larray(self) -> jax.Array:
        """The underlying global ``jax.Array`` (logical shape).

        In the reference this is the process-local torch tensor (``dndarray.py:131``); in
        single-controller JAX the addressable value *is* the global array (per-shard views
        are exposed via :attr:`lshards`). Multi-controller processes see their
        addressable shards through the same object. Ragged split extents are stored
        physically padded (:attr:`parray`); this accessor always returns the logical
        extent.
        """
        return self._logical()

    @larray.setter
    def larray(self, array: jax.Array) -> None:
        """Rebind the payload (reference setter ``dndarray.py:146-168``).

        Accepts either a logical-shape value or the padded physical form of the
        *current* gshape (as produced by ``comm.shard``). The padded interpretation
        only applies when the value is actually laid out in the split's sharding —
        a host/replicated value whose shape merely coincides with the padded extent
        rebinds the logical gshape to the value's shape instead."""
        if not isinstance(array, jax.Array):
            raise TypeError(f"larray must be a jax.Array, got {type(array)}")
        shape = tuple(array.shape)
        if shape != self.__gshape and not (
            shape == self._padded_gshape() and self._sharding_matches(array)
        ):
            self.__gshape = shape
        self.__array = array
        self.__dtype = types.canonical_heat_type(array.dtype)

    def _sharding_matches(self, array: jax.Array) -> bool:
        """Whether ``array`` carries the communicator's sharding for this split."""
        try:
            return array.sharding == self.__comm.sharding(array.ndim, self.__split)
        except AttributeError:
            # tracer under jit: a traced value has no committed sharding to inspect,
            # so the padded-layout interpretation cannot be inferred here. Internal
            # producers of padded physical values (comm.shard consumers, the
            # dispatch executor) declare their intent via _rebind_physical instead
            # of relying on shape coincidence (ADVICE r5 #1).
            return False
        except Exception:  # ht: ignore[silent-except] -- layout-inference probe: False is the conservative verdict, and _rebind_physical is the intent-declared path (ADVICE r5 #1)
            return False

    def _rebind_physical(self, array: jax.Array) -> None:
        """Rebind the payload with a value **known by the caller** to be the
        physical form of the *current* ``(gshape, split)`` — logical shape, or the
        padded layout ``comm.shard`` / the dispatch executor produce for it. This
        is the internal path that replaces the larray setter's layout-inference
        heuristic: intent is declared, not guessed from shape equality, so it also
        works for traced values under jit (where ``_sharding_matches`` cannot).
        Dtype may differ (out=-style casts rebind through here); gshape and split
        never change."""
        shape = tuple(array.shape)
        if shape != self.__gshape and shape != self._padded_gshape():
            raise ValueError(
                f"_rebind_physical: value shape {shape} is neither the logical "
                f"gshape {self.__gshape} nor its padded layout {self._padded_gshape()}"
            )
        self.__array = array
        self.__dtype = types.canonical_heat_type(array.dtype)

    @property
    def parray(self) -> jax.Array:
        """The physical ``jax.Array`` as laid out in device memory — equal to
        :attr:`larray` except for ragged split extents, where the split dimension is
        zero-padded to ``ceil(n/P)*P`` so shards are an exact 1/P.

        A payload deferred by the dispatch executor (a pending fused-op graph
        node) is **forced** here: the whole reachable graph compiles/replays as
        one (possibly multi-output) program and the concrete result replaces
        the node. If a previous force already emitted this node's value as an
        interior program output, ``force()`` returns that memoised value with
        no new program at all.

        Lifecycle note: replacing the payload here also ends this array's role
        in the executor's liveness registry — ``_executor.note_wrapped`` holds
        only a *weak* reference to this DNDarray, and the force path's
        emission check additionally verifies ``holder._payload is node``, so
        neither this rebind, :meth:`_rebind_physical`, nor plain garbage
        collection of the DNDarray needs an explicit ``__del__``
        deregistration hook."""
        arr = self.__array
        if isinstance(arr, Deferred):
            arr = arr.force()
            self.__array = arr
        return arr

    @property
    def _payload(self):
        """The raw payload WITHOUT forcing: a concrete ``jax.Array`` or a pending
        :class:`~._executor.Deferred` node. Only the dispatch layer should read
        this — everything else wants :attr:`parray`. (The executor's liveness
        check reads it through the weakref registry: a node whose wrapping
        DNDarray died, or whose wrapper was rebound to a different payload, no
        longer counts as reachable and is not memoised at force time.)"""
        return self.__array

    @property
    def garray(self) -> jax.Array:
        """Alias emphasising the global nature of the payload."""
        return self._logical()

    @property
    def lshards(self) -> List[jax.Array]:
        """Per-device local shard values addressable from this process, trimmed to the
        logical extents (layout padding never escapes)."""
        return [data for _, data in self.iter_shards()]

    def iter_shards(self):
        """Yield ``(global_index, shard_value)`` per addressable shard of the physical
        array, with indices and values trimmed to the logical gshape. Pure-padding
        shards are skipped. The backbone for per-shard I/O and per-shard algorithms
        (reference: rank-local hyperslabs, ``io.py:211-238``)."""
        for shard in self.parray.addressable_shards:
            if shard.index is None:
                continue
            trimmed = []
            local = []
            skip = False
            for d in range(len(self.__gshape)):
                sl = shard.index[d] if d < len(shard.index) else slice(None)
                start = sl.start or 0
                stop = sl.stop if sl.stop is not None else self.__array.shape[d]
                stop = min(stop, self.__gshape[d])
                if stop <= start:
                    skip = True
                    break
                trimmed.append(slice(start, stop))
                local.append(slice(0, stop - start))
            if skip:
                continue
            data = shard.data
            if self._is_padded():
                data = data[tuple(local)]
            yield tuple(trimmed), data

    @property
    def balanced(self) -> Optional[bool]:
        return self.__balanced

    @property
    def comm(self) -> Communication:
        return self.__comm

    @comm.setter
    def comm(self, comm: Communication) -> None:
        if not isinstance(comm, Communication):
            raise TypeError(f"comm must be a Communication, got {type(comm)}")
        self.__comm = comm

    @property
    def device(self) -> Device:
        return self.__device

    @device.setter
    def device(self, device: Device) -> None:
        self.__device = device

    @property
    def dtype(self):
        return self.__dtype

    @property
    def gshape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def ndim(self) -> int:
        return len(self.__gshape)

    @property
    def size(self) -> int:
        return int(np.prod(self.__gshape)) if self.__gshape else 1

    gnumel = size

    @property
    def lnumel(self) -> int:
        return int(np.prod(self.lshape)) if self.lshape else 1

    @property
    def nbytes(self) -> int:
        return self.size * np.dtype(self.__dtype.jax_type()).itemsize

    gnbytes = nbytes

    @property
    def lnbytes(self) -> int:
        return self.lnumel * np.dtype(self.__dtype.jax_type()).itemsize

    @property
    def lshape(self) -> Tuple[int, ...]:
        """This rank's chunk shape under the canonical chunking (reference ``:117``)."""
        _, lshape, _ = self.__comm.chunk(self.__gshape, self.__split)
        return lshape

    @property
    def split(self) -> Optional[int]:
        return self.__split

    @property
    def stride(self) -> Tuple[int, ...]:
        """Row-major strides in elements. The reference exposes the torch bound
        method (usage ``x.stride()``, ``dndarray.py:330-335``); numpy users expect
        a tuple. A callable tuple serves both spellings."""
        strides = []
        acc = 1
        for s in reversed(self.__gshape):
            strides.append(acc)
            acc *= max(s, 1)
        return _CallableTuple(reversed(strides))

    @property
    def strides(self) -> Tuple[int, ...]:
        itemsize = np.dtype(self.__dtype.jax_type()).itemsize
        return tuple(s * itemsize for s in self.stride)

    @property
    def T(self) -> "DNDarray":
        from .linalg import transpose

        return transpose(self, None)

    @property
    def real(self) -> "DNDarray":
        from .complex_math import real

        return real(self)

    @property
    def imag(self) -> "DNDarray":
        from .complex_math import imag

        return imag(self)

    @property
    def lloc(self) -> LocalIndex:
        return LocalIndex(self.parray)

    @property
    def __partitioned__(self) -> dict:
        """Partition interface for cross-framework interop (reference ``dndarray.py:680``)."""
        return self.create_partition_interface()

    # ------------------------------------------------------------------ distribution
    def lshape_map(self, force_check: bool = False) -> "DNDarray":
        """(size, ndim) map of shard shapes (reference ``dndarray.py:304,647``)."""
        from . import factories

        lmap = self.__comm.lshape_map(self.__gshape, self.__split)
        return factories.array(lmap, dtype=types.int64, split=None, device=self.__device, comm=self.__comm)

    def create_lshape_map(self, force_check: bool = False) -> "DNDarray":
        return self.lshape_map(force_check)

    def counts_displs(self) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """Per-shard counts and offsets along the split axis (reference
        ``dndarray.py:626``)."""
        if self.__split is None:
            raise ValueError(
                "Non-distributed DNDarray. Cannot calculate counts and displacements."
            )
        counts, displs, _ = self.__comm.counts_displs_shape(self.__gshape, self.__split)
        return counts, displs

    def is_balanced(self, force_check: bool = False) -> bool:
        """Canonical XLA layouts are balanced by construction (reference ``:466``)."""
        return True

    def is_distributed(self) -> bool:
        """True if data lives on more than one device and is not replicated
        (reference ``dndarray.py:484``)."""
        return self.__split is not None and self.__comm.size > 1

    def balance_(self) -> "DNDarray":
        """Rebalance in place (reference ``dndarray.py:501``). XLA shard layouts are always
        the canonical ceil-division chunks, so this only normalises metadata."""
        self.__balanced = True
        return self

    def redistribute_(self, lshape_map=None, target_map=None) -> "DNDarray":
        """Redistribute to a target lshape map (reference ``dndarray.py:1208-1358``).

        Arbitrary target maps are intentionally unsupported: XLA owns the physical layout
        and always uses canonical chunks, so the only meaningful redistribution is a
        rebalance, which is the identity here. Raises if a genuinely non-canonical target
        is requested.
        """
        if target_map is not None:
            tmap = np.asarray(
                target_map.larray if isinstance(target_map, DNDarray) else target_map
            )
            canonical = self.__comm.lshape_map(self.__gshape, self.__split)
            if not np.array_equal(tmap, canonical):
                raise NotImplementedError(
                    "non-canonical shard layouts are owned by XLA on TPU; "
                    "arbitrary target lshape maps are not representable"
                )
        self.__balanced = True
        return self

    def resplit_(self, axis: Optional[int] = None) -> "DNDarray":
        """In-place redistribution along a new split axis (reference ``dndarray.py:1407``).

        split→None ≙ Allgatherv, None→split ≙ local slice, split→split ≙ all-to-all — all
        emitted by XLA from a single re-sharding.
        """
        axis = sanitize_axis(self.__gshape, axis)
        if axis == self.__split:
            return self
        self.__array = self._reshard(axis)
        self.__split = axis
        self.__balanced = True
        return self

    def _reshard(self, axis: Optional[int]) -> jax.Array:
        """The physical value laid out for ``axis``. split→split goes through
        the comm planner's ``all_to_all`` program when eligible — each device
        exchanges only the (P−1)/P of its shard the peers need, never a
        gathered copy (``linalg/comm_plan.py``; disabled along with the rest
        of the planner by ``HEAT_TPU_LINALG_PLAN=xla``). Otherwise a ragged
        source resplits padded-value-first: the all-to-all moves O(n/P)
        buffers and the old padding is trimmed afterwards on the now-unsharded
        dim (a shard-local slice) — the logical (replicated) trim never
        materialises. ``axis=None`` replicates by definition, so it takes the
        plain path; the unpadded path is one re-sharding as before."""
        if axis is not None and self.__split is not None and axis != self.__split:
            from .linalg import comm_plan

            moved = comm_plan.try_resplit(self, axis)
            if moved is not NotImplemented:
                return moved
        if self._is_padded() and axis is not None and axis != self.__split:
            moved = self.__comm.shard(self.parray, axis)
            sl = tuple(
                slice(0, s) if d == self.__split else slice(None)
                for d, s in enumerate(self.__gshape)
            )
            return self.__comm.shard(moved[sl], axis)
        return self.__comm.shard(self._logical(), axis)

    def resplit(self, axis: Optional[int] = None) -> "DNDarray":
        """Out-of-place resplit (reference ``manipulations.py:3480``)."""
        axis = sanitize_axis(self.__gshape, axis)
        if axis == self.__split:
            return DNDarray(
                self.parray, self.__gshape, self.__dtype, axis, self.__device,
                self.__comm, True,
            )
        new = self._reshard(axis)
        return DNDarray(new, self.__gshape, self.__dtype, axis, self.__device, self.__comm, True)

    def collect_(self, target_rank: int = 0) -> "DNDarray":
        """Gather the full array (reference ``dndarray.py:573``): becomes split=None."""
        self.resplit_(None)
        return self

    # ------------------------------------------------------------------ halos
    def get_halo(self, halo_size: int, prev: bool = True, next: bool = True) -> None:
        """Fetch halo regions of the neighbouring shards (reference ``dndarray.py:387-455``).

        With a global array, a halo is just a slice at this rank's chunk boundary; XLA
        turns the cross-shard reads into collective-permutes on the ICI torus.
        """
        if not isinstance(halo_size, int) or halo_size < 0:
            raise (TypeError if not isinstance(halo_size, int) else ValueError)(
                f"halo_size needs to be a non-negative Python int, got {halo_size}"
            )
        if self.__split is None or not self.is_distributed():
            self.__halo_prev = None
            self.__halo_next = None
            return
        start, lshape, _ = self.__comm.chunk(self.__gshape, self.__split)
        end = start + lshape[self.__split]
        ax = self.__split

        def _slab(a, b):
            idx = tuple(
                slice(a, b) if i == ax else slice(None) for i in range(self.ndim)
            )
            return self.parray[idx]

        self.__halo_prev = _slab(max(start - halo_size, 0), start) if (prev and start > 0) else None
        self.__halo_next = (
            _slab(end, min(end + halo_size, self.__gshape[ax])) if (next and end < self.__gshape[ax]) else None
        )

    @property
    def halo_prev(self) -> Optional[jax.Array]:
        return self.__halo_prev

    @property
    def halo_next(self) -> Optional[jax.Array]:
        return self.__halo_next

    @property
    def array_with_halos(self) -> jax.Array:
        """Local chunk with fetched halos attached (reference ``dndarray.py:360``)."""
        _, _, slices = self.__comm.chunk(self.__gshape, self.__split)
        local = self.parray[slices] if self.__split is not None else self.parray
        parts = [p for p in (self.__halo_prev, local, self.__halo_next) if p is not None]
        if len(parts) == 1:
            return parts[0]
        return jnp.concatenate(parts, axis=self.__split or 0)

    # ------------------------------------------------------------------ conversion
    def astype(self, dtype, copy: bool = True) -> "DNDarray":
        """Cast to a new datatype (reference ``dndarray.py:222``)."""
        from ._operations import _safe_astype

        dtype = types.canonical_heat_type(dtype)
        casted = _safe_astype(self.parray, dtype.jax_type())
        casted = self.__comm.shard(casted, self.__split)
        if copy:
            return DNDarray(casted, self.__gshape, dtype, self.__split, self.__device, self.__comm, self.__balanced)
        self.__array = casted
        self.__dtype = dtype
        return self

    def item(self) -> Scalar:
        """The single element as a Python scalar (reference ``dndarray.py:1144``)."""
        if self.size != 1:
            raise ValueError("only one-element DNDarrays can be converted to Python scalars")
        if not self.parray.is_fully_addressable:
            return self.numpy().reshape(()).item()
        return self._logical().reshape(()).item()

    def numpy(self) -> np.ndarray:
        """Gather into a numpy array (reference ``dndarray.py:1169``).

        Multi-controller contract: when this process does not address every shard
        (``jax.process_count() > 1``), the value is fetched with a cross-host
        ``process_allgather`` so every controller returns the same global array —
        the TPU form of the reference's rank-0 gather + Bcast."""
        if self.parray.is_fully_addressable:
            return np.asarray(self._logical())
        from jax.experimental import multihost_utils

        full = np.asarray(multihost_utils.process_allgather(self.parray, tiled=True))
        if full.shape != self.__gshape:  # strip layout padding gathered from shards
            full = full[tuple(slice(0, s) for s in self.__gshape)]
        return full

    def tolist(self, keepsplit: bool = False) -> list:
        """Nested Python lists (reference ``dndarray.py:1861``)."""
        return self.numpy().tolist()

    def __array__(self, dtype=None) -> np.ndarray:
        out = self.numpy()
        return out.astype(dtype) if dtype is not None else out

    def cpu(self) -> "DNDarray":
        """Move to host (reference ``dndarray.py:300``)."""
        from . import devices, factories

        arr = np.asarray(self._logical())
        return factories.array(arr, dtype=self.__dtype, split=self.__split, device=devices.cpu, comm=self.__comm)

    def create_partition_interface(self, no_data: bool = False) -> dict:
        """``__partitioned__`` protocol dict (reference ``dndarray.py:680``)."""
        lmap = self.__comm.lshape_map(self.__gshape, self.__split)
        partitions = {}
        for r in range(self.__comm.size):
            start, lshape, slices = self.__comm.chunk(self.__gshape, self.__split, rank=r)
            pos = tuple(0 if i != (self.__split or 0) else r for i in range(self.ndim)) if self.__split is not None else (0,) * self.ndim
            partitions[pos] = {
                "start": tuple(sl.start or 0 for sl in slices),
                "shape": tuple(lshape),
                "data": None if no_data else self.parray[slices],
                "location": [r],
                "dtype": np.dtype(self.__dtype.jax_type()),
            }
        grid = [1] * self.ndim
        if self.__split is not None:
            grid[self.__split] = self.__comm.size
        return {
            "shape": self.__gshape,
            "partition_tiling": tuple(grid),
            "partitions": partitions,
            "locals": [tuple(0 if i != (self.__split or 0) else self.__comm.rank for i in range(self.ndim)) if self.__split is not None else (0,) * self.ndim],
            "get": lambda x: np.asarray(x),
        }

    # ------------------------------------------------------------------ fills
    def fill_diagonal(self, value: Scalar) -> "DNDarray":
        """Fill the main diagonal in place (reference ``dndarray.py:744``)."""
        if self.ndim != 2:
            raise ValueError("fill_diagonal requires a 2-D DNDarray")
        n = min(self.__gshape)
        idx = jnp.arange(n)
        new = self.parray.at[idx, idx].set(jnp.asarray(value, dtype=self.parray.dtype))
        self.__array = self.__comm.shard(new, self.__split)
        return self

    # ------------------------------------------------------------------ indexing
    def _index_split(self, key) -> Optional[int]:
        """Split bookkeeping for basic indexing: how the split axis survives ``key``."""
        if self.__split is None:
            return None
        if not isinstance(key, tuple):
            key = (key,)
        # expand ellipsis
        if any(k is Ellipsis for k in key):
            n_explicit = sum(1 for k in key if k is not Ellipsis and k is not None)
            expanded = []
            for k in key:
                if k is Ellipsis:
                    expanded.extend([slice(None)] * (self.ndim - n_explicit))
                else:
                    expanded.append(k)
            key = tuple(expanded)
        dim = 0  # input dim cursor
        out_dim = 0  # output dim cursor
        adv_seen = False
        for k in key:
            if k is None:
                out_dim += 1
                continue
            if dim == self.__split:
                if isinstance(k, slice):
                    return out_dim
                return None  # integer/advanced index consumed the split dim
            if isinstance(k, (int, np.integer)):
                dim += 1
            elif isinstance(k, slice):
                dim += 1
                out_dim += 1
            else:  # advanced index (array-like / bool mask)
                adv = np.ndim(np.asarray(k) if not isinstance(k, DNDarray) else k.numpy())
                if isinstance(k, DNDarray) and k.dtype is types.bool or (
                    not isinstance(k, DNDarray) and np.asarray(k).dtype == np.bool_
                ):
                    dim += adv
                else:
                    dim += 1
                if not adv_seen:
                    out_dim += 1
                    adv_seen = True
        if dim <= self.__split:
            # remaining dims are untouched
            return out_dim + (self.__split - dim)
        return None

    def __getitem__(self, key) -> "DNDarray":
        """Global indexing (reference ``dndarray.py:828-1086``)."""
        from . import factories

        new_split = self._index_split(key)
        jkey = _jaxify_key(key)
        result = self._logical()[jkey]
        if result.ndim == 0:
            return factories.array(result, dtype=self.__dtype, device=self.__device, comm=self.__comm)
        if new_split is not None and new_split >= result.ndim:
            new_split = None
        gshape = tuple(result.shape)
        result = self.__comm.shard(result, new_split)
        return DNDarray(
            result, gshape, self.__dtype, new_split, self.__device, self.__comm, True
        )

    def __setitem__(self, key, value) -> None:
        """Global assignment (reference ``dndarray.py:1538``)."""
        jkey = _jaxify_key(key)
        if isinstance(value, DNDarray):
            value = value.larray
        value = jnp.asarray(value, dtype=self.parray.dtype)
        new = self._logical().at[jkey].set(value)
        self.__array = self.__comm.shard(new, self.__split)

    def __iter__(self):
        for i in range(self.__gshape[0] if self.ndim else 0):
            yield self[i]

    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.__gshape[0]

    # ------------------------------------------------------------------ scalar casts
    def __bool__(self) -> bool:
        return bool(self.item())

    def __int__(self) -> int:
        return int(self.item())

    def __float__(self) -> float:
        return float(self.item())

    def __complex__(self) -> complex:
        return complex(self.item())

    def __index__(self) -> int:
        return int(self.item())

    # ------------------------------------------------------------------ printing
    def __repr__(self) -> str:
        from . import printing

        return printing.__str__(self)

    __str__ = __repr__

    # ------------------------------------------------------------------ arithmetic dunders
    # (bound to the ops modules at import time by heat_tpu/__init__.py, mirroring the
    # reference's late binding in heat/core/arithmetics.py etc.)
    def __add__(self, other):
        from . import arithmetics

        return arithmetics.add(self, other)

    def __radd__(self, other):
        from . import arithmetics

        return arithmetics.add(other, self)

    def __iadd__(self, other):
        from . import arithmetics

        res = arithmetics.add(self, other)
        self._rebind(res)
        return self

    def __sub__(self, other):
        from . import arithmetics

        return arithmetics.sub(self, other)

    def __rsub__(self, other):
        from . import arithmetics

        return arithmetics.sub(other, self)

    def __isub__(self, other):
        from . import arithmetics

        res = arithmetics.sub(self, other)
        self._rebind(res)
        return self

    def __mul__(self, other):
        from . import arithmetics

        return arithmetics.mul(self, other)

    def __rmul__(self, other):
        from . import arithmetics

        return arithmetics.mul(other, self)

    def __imul__(self, other):
        from . import arithmetics

        res = arithmetics.mul(self, other)
        self._rebind(res)
        return self

    def __truediv__(self, other):
        from . import arithmetics

        return arithmetics.div(self, other)

    def __rtruediv__(self, other):
        from . import arithmetics

        return arithmetics.div(other, self)

    def __itruediv__(self, other):
        from . import arithmetics

        res = arithmetics.div(self, other)
        self._rebind(res)
        return self

    def __floordiv__(self, other):
        from . import arithmetics

        return arithmetics.floordiv(self, other)

    def __rfloordiv__(self, other):
        from . import arithmetics

        return arithmetics.floordiv(other, self)

    def __mod__(self, other):
        from . import arithmetics

        return arithmetics.mod(self, other)

    def __rmod__(self, other):
        from . import arithmetics

        return arithmetics.mod(other, self)

    def __pow__(self, other):
        from . import arithmetics

        return arithmetics.pow(self, other)

    def __rpow__(self, other):
        from . import arithmetics

        return arithmetics.pow(other, self)

    def __divmod__(self, other):
        from . import arithmetics

        return arithmetics.divmod(self, other)

    def __matmul__(self, other):
        from .linalg import matmul

        return matmul(self, other)

    def __neg__(self):
        from . import arithmetics

        return arithmetics.neg(self)

    def __pos__(self):
        from . import arithmetics

        return arithmetics.pos(self)

    def __abs__(self):
        from . import rounding

        return rounding.abs(self)

    def __invert__(self):
        from . import arithmetics

        return arithmetics.invert(self)

    def __lshift__(self, other):
        from . import arithmetics

        return arithmetics.left_shift(self, other)

    def __rshift__(self, other):
        from . import arithmetics

        return arithmetics.right_shift(self, other)

    def __and__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_and(self, other)

    def __or__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_or(self, other)

    def __xor__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_xor(self, other)

    # comparisons
    def __eq__(self, other):
        from . import relational

        return relational.eq(self, other)

    def __ne__(self, other):
        from . import relational

        return relational.ne(self, other)

    def __lt__(self, other):
        from . import relational

        return relational.lt(self, other)

    def __le__(self, other):
        from . import relational

        return relational.le(self, other)

    def __gt__(self, other):
        from . import relational

        return relational.gt(self, other)

    def __ge__(self, other):
        from . import relational

        return relational.ge(self, other)

    __hash__ = None  # mutable container, like the reference

    # ------------------------------------------------------------------ method aliases
    # NumPy-style methods delegating to the functional API (reference defines these across
    # the op modules and attaches them to DNDarray).
    def _rebind(self, other: "DNDarray") -> None:
        self.__array = other.larray
        self.__gshape = other.gshape
        self.__dtype = other.dtype
        self.__split = other.split
        self.__balanced = other.balanced

    def abs(self, out=None, dtype=None):
        from . import rounding

        return rounding.abs(self, out, dtype)

    def all(self, axis=None, out=None, keepdims=False):
        from . import logical

        return logical.all(self, axis, out, keepdims)

    def any(self, axis=None, out=None, keepdims=False):
        from . import logical

        return logical.any(self, axis, out, keepdims)

    def argmax(self, axis=None, out=None, **kwargs):
        from . import statistics

        return statistics.argmax(self, axis, out, **kwargs)

    def argmin(self, axis=None, out=None, **kwargs):
        from . import statistics

        return statistics.argmin(self, axis, out, **kwargs)

    def mean(self, axis=None):
        from . import statistics

        return statistics.mean(self, axis)

    def median(self, axis=None, keepdims=False):
        from . import statistics

        return statistics.median(self, axis, keepdims=keepdims)

    def std(self, axis=None, ddof=0, **kwargs):
        from . import statistics

        return statistics.std(self, axis, ddof=ddof, **kwargs)

    def var(self, axis=None, ddof=0, **kwargs):
        from . import statistics

        return statistics.var(self, axis, ddof=ddof, **kwargs)

    def max(self, axis=None, out=None, keepdims=None):
        from . import statistics

        return statistics.max(self, axis, out, keepdims)

    def min(self, axis=None, out=None, keepdims=None):
        from . import statistics

        return statistics.min(self, axis, out, keepdims)

    def sum(self, axis=None, out=None, keepdims=False):
        from . import arithmetics

        return arithmetics.sum(self, axis, out, keepdims)

    def prod(self, axis=None, out=None, keepdims=False):
        from . import arithmetics

        return arithmetics.prod(self, axis, out, keepdims)

    def cumsum(self, axis, out=None):
        from . import arithmetics

        return arithmetics.cumsum(self, axis, out)

    def cumprod(self, axis, out=None):
        from . import arithmetics

        return arithmetics.cumprod(self, axis, out)

    def reshape(self, *shape, new_split=None, **kwargs):
        from . import manipulations

        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return manipulations.reshape(self, shape, new_split=new_split, **kwargs)

    def flatten(self):
        from . import manipulations

        return manipulations.flatten(self)

    def ravel(self):
        from . import manipulations

        return manipulations.ravel(self)

    def squeeze(self, axis=None):
        from . import manipulations

        return manipulations.squeeze(self, axis)

    def expand_dims(self, axis):
        from . import manipulations

        return manipulations.expand_dims(self, axis)

    def transpose(self, axes=None):
        from .linalg import transpose

        return transpose(self, axes)

    def tril(self, k=0):
        from .linalg import tril

        return tril(self, k)

    def triu(self, k=0):
        from .linalg import triu

        return triu(self, k)

    def flip(self, axis=None):
        from . import manipulations

        return manipulations.flip(self, axis)

    def roll(self, shift, axis=None):
        from . import manipulations

        return manipulations.roll(self, shift, axis)

    def nonzero(self):
        from . import indexing

        return indexing.nonzero(self)

    def unique(self, sorted=False, return_inverse=False, axis=None):
        from . import manipulations

        return manipulations.unique(self, sorted, return_inverse, axis)

    def round(self, decimals=0, out=None, dtype=None):
        from . import rounding

        return rounding.round(self, decimals, out, dtype)

    def floor(self, out=None):
        from . import rounding

        return rounding.floor(self, out)

    def ceil(self, out=None):
        from . import rounding

        return rounding.ceil(self, out)

    def trunc(self, out=None):
        from . import rounding

        return rounding.trunc(self, out)

    def clip(self, min=None, max=None, out=None):
        from . import rounding

        return rounding.clip(self, min, max, out)

    def copy(self):
        from . import memory

        return memory.copy(self)

    def exp(self, out=None):
        from . import exponential

        return exponential.exp(self, out)

    def log(self, out=None):
        from . import exponential

        return exponential.log(self, out)

    def sqrt(self, out=None):
        from . import exponential

        return exponential.sqrt(self, out)

    def sin(self, out=None):
        from . import trigonometrics

        return trigonometrics.sin(self, out)

    def cos(self, out=None):
        from . import trigonometrics

        return trigonometrics.cos(self, out)

    def tanh(self, out=None):
        from . import trigonometrics

        return trigonometrics.tanh(self, out)

    def isclose(self, other, rtol=1e-05, atol=1e-08, equal_nan=False):
        from . import logical

        return logical.isclose(self, other, rtol, atol, equal_nan)

    def tile(self, reps):
        from . import manipulations

        return manipulations.tile(self, reps)


def _jaxify_key(key):
    """Convert DNDarray / numpy members of an index expression to jax values."""
    if isinstance(key, DNDarray):
        return key.larray
    if isinstance(key, tuple):
        return tuple(_jaxify_key(k) for k in key)
    if isinstance(key, list):
        return jnp.asarray(np.asarray(key))
    return key
