"""Exponential and logarithmic functions (reference heat/core/exponential.py, 11 exports)."""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = ["exp", "expm1", "exp2", "log", "log2", "log10", "log1p", "logaddexp", "logaddexp2", "sqrt", "square"]


def exp(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.exp, x, out)


def expm1(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.expm1, x, out)


def exp2(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.exp2, x, out)


def log(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.log, x, out)


def log2(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.log2, x, out)


def log10(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.log10, x, out)


def log1p(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.log1p, x, out)


def logaddexp(x1, x2, out=None, where=None) -> DNDarray:
    return _operations.binary_op(jnp.logaddexp, x1, x2, out, where)


def logaddexp2(x1, x2, out=None, where=None) -> DNDarray:
    return _operations.binary_op(jnp.logaddexp2, x1, x2, out, where)


def sqrt(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.sqrt, x, out)


def square(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.square, x, out)
