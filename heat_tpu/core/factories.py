"""Array factories (reference heat/core/factories.py:20-1502).

The reference's central ingest chunked a global source per-rank with ``comm.chunk`` and
wrapped the local torch slice. Here factories materialise the global value with jnp and
lay it out over the mesh in one ``shard`` call — for large on-device constructions the
value is *created* sharded by XLA (fill/iota fuse with the sharding; no host round-trip).
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional, Sequence, Tuple, Type, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import types
from .communication import Communication, sanitize_comm
from .devices import Device, sanitize_device
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape

__all__ = [
    "arange",
    "array",
    "asarray",
    "empty",
    "empty_like",
    "eye",
    "from_partitioned",
    "from_partition_dict",
    "full",
    "full_like",
    "linspace",
    "logspace",
    "meshgrid",
    "ones",
    "ones_like",
    "zeros",
    "zeros_like",
]


def _complex_to_host(value, target_dtype=None):
    """When the accelerator can't hold complex values (the failed attempt poisons
    the process — see devices.accelerator_capabilities), values that are or are
    about to become complex move to the host CPU. All factory paths converge here
    through ``_wrap``."""
    from ._operations import _on_accelerator
    from .devices import complex_needs_host, cpu_fallback_device

    if complex_needs_host(target_dtype if target_dtype is not None else value):
        if not isinstance(value, jax.Array) or _on_accelerator(value):
            return jax.device_put(value, cpu_fallback_device())
    return value


def _wrap(
    value: jax.Array,
    dtype: Optional[Type[types.datatype]],
    split: Optional[int],
    device,
    comm,
    balanced: bool = True,
) -> DNDarray:
    device = sanitize_device(device)
    comm = sanitize_comm(comm)
    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
        if value.dtype != np.dtype(dtype.jax_type()):
            # an accelerator-resident cast to complex would run on-device:
            # move to host first when the accelerator can't hold complex
            value = _complex_to_host(value, target_dtype=np.dtype(dtype.jax_type()))
            value = value.astype(dtype.jax_type())
    else:
        dtype = types.canonical_heat_type(value.dtype)
    split = sanitize_axis(value.shape, split)
    gshape = tuple(value.shape)
    value = comm.shard(value, split)
    return DNDarray(value, gshape, dtype, split, device, comm, balanced)


def arange(*args, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """``arange(stop)`` / ``arange(start, stop[, step])`` (reference ``factories.py:41``)."""
    num_args = len(args)
    if num_args == 1:
        start, stop, step = 0, args[0], 1
    elif num_args == 2:
        start, stop, step = args[0], args[1], 1
    elif num_args == 3:
        start, stop, step = args
    else:
        raise TypeError(f"function takes minimum one and at most 3 positional arguments ({num_args} given)")
    from .devices import complex_creation_ctx

    if dtype is None:
        # match the reference: all-int args → int32, otherwise default float
        if all(isinstance(a, (int, np.integer)) for a in (start, stop, step)):
            value = jnp.arange(start, stop, step, dtype=jnp.int32)
        else:
            value = jnp.arange(start, stop, step, dtype=jnp.float32)
    else:
        jt = types.canonical_heat_type(dtype).jax_type()
        with complex_creation_ctx(np.dtype(jt)):
            value = jnp.arange(start, stop, step, dtype=jt)
    return _wrap(value, dtype, split, device, comm)


def array(
    obj: Any,
    dtype=None,
    copy: Optional[bool] = None,
    ndmin: int = 0,
    order: str = "C",
    split: Optional[int] = None,
    is_split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Central array ingest (reference ``factories.py:149``).

    Accepts nested sequences, numpy arrays, jax arrays, torch tensors and DNDarrays.
    ``split`` chunks a global source over the mesh; ``is_split`` declares ``obj`` to be
    this *process*'s pre-distributed chunk along that axis (reference ``:188`` infers the
    global shape by allgathering local shapes — in single-controller JAX the process owns
    every shard, so the local chunk is the global value).
    """
    if split is not None and is_split is not None:
        raise ValueError(f"split and is_split are mutually exclusive, got {split}, {is_split}")
    if order not in ("C", "K"):
        raise NotImplementedError("only row-major memory layout is supported on TPU")

    if isinstance(obj, DNDarray):
        comm = comm or obj.comm
        device = device or obj.device
        if split is None and is_split is None:
            split = obj.split
        value = obj.larray
    else:
        # torch tensors (CPU) convert via numpy; everything else through jnp/np
        if type(obj).__module__.startswith("torch"):
            obj = obj.detach().cpu().numpy()
        if isinstance(obj, jax.Array):
            value = obj
        else:
            np_value = np.asarray(obj)
            if dtype is None and np_value.dtype == np.float64 and not (
                isinstance(obj, np.ndarray) or isinstance(obj, np.generic)
            ):
                # python floats default to the framework float type (f32), like torch/heat
                np_value = np_value.astype(np.float32)
            from .devices import complex_needs_host, cpu_fallback_device

            if complex_needs_host(np_value.dtype):
                # the accelerator can't even materialize complex values (and the
                # failed attempt poisons the process); create on host CPU —
                # comm.shard keeps this dtype there
                value = jax.device_put(np_value, cpu_fallback_device())
            else:
                value = jnp.asarray(np_value)

    while value.ndim < ndmin:
        value = value[jnp.newaxis]

    if is_split is not None:
        is_split = sanitize_axis(value.shape, is_split)
        if jax.process_count() > 1:
            # each process declared its own pre-distributed chunk: infer the global
            # shape by allgathering local shapes (reference factories.py:188) and
            # assemble without moving data off-host
            from jax.experimental import multihost_utils

            comm_obj = sanitize_comm(comm)
            np_value = np.asarray(value)
            all_shapes = np.asarray(
                multihost_utils.process_allgather(jnp.asarray(np.array(np_value.shape)))
            ).reshape(jax.process_count(), np_value.ndim)
            for d in range(np_value.ndim):
                if d != is_split and not np.all(all_shapes[:, d] == np_value.shape[d]):
                    raise ValueError(
                        f"is_split chunks disagree on non-split dim {d}: {all_shapes[:, d]}"
                    )
            gshape = list(np_value.shape)
            gshape[is_split] = int(all_shapes[:, is_split].sum())
            # jax can only assemble process-local chunks that match the even canonical
            # partition; the reference accepts arbitrary chunk sizes (factories.py:188)
            # — reject the unrepresentable case loudly rather than mis-assemble
            per_proc = gshape[is_split] // jax.process_count()
            if gshape[is_split] % jax.process_count() != 0 or not np.all(
                all_shapes[:, is_split] == per_proc
            ):
                raise NotImplementedError(
                    f"multi-controller is_split needs equal per-process chunks "
                    f"(got extents {all_shapes[:, is_split].tolist()}); pad or "
                    f"rebalance the local chunks before ingest"
                )
            garr = jax.make_array_from_process_local_data(
                comm_obj.sharding(np_value.ndim, is_split), np_value, tuple(gshape)
            )
            return _wrap(garr, dtype, is_split, device, comm)
        return _wrap(value, dtype, is_split, device, comm)
    return _wrap(value, dtype, split, device, comm)


def asarray(obj, dtype=None, copy=None, order="C", is_split=None, device=None) -> DNDarray:
    """Convert to DNDarray, no-copy when possible (reference ``factories.py:463``)."""
    if (
        is_split is None
        and copy is not True
        and isinstance(obj, DNDarray)
        and (dtype is None or obj.dtype is types.canonical_heat_type(dtype))
        and (device is None or obj.device == sanitize_device(device))
    ):
        return obj
    return array(obj, dtype=dtype, copy=copy, order=order, is_split=is_split, device=device)


def __factory(shape, dtype, split, maker, device, comm, order="C") -> DNDarray:
    """Shared logic of empty/ones/zeros/full (reference ``factories.py:699``)."""
    shape = sanitize_shape(shape)
    dtype = types.canonical_heat_type(dtype)
    from .devices import complex_creation_ctx

    # complex creation happens on host when the accelerator can't hold it
    # (devices.accelerator_capabilities); nullcontext otherwise
    with complex_creation_ctx(np.dtype(dtype.jax_type())):
        value = maker(shape, dtype=dtype.jax_type())
    return _wrap(value, dtype, split, device, comm)


def empty(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Uninitialised array (reference ``factories.py:522``); XLA has no uninitialised
    allocation, so this is a zero fill fused into consumers."""
    return __factory(shape, dtype, split, jnp.zeros, device, comm, order)


def zeros(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Zeros (reference ``factories.py:1388``)."""
    return __factory(shape, dtype, split, jnp.zeros, device, comm, order)


def ones(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Ones (reference ``factories.py:1184``)."""
    return __factory(shape, dtype, split, jnp.ones, device, comm, order)


def full(shape, fill_value, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Constant fill (reference ``factories.py:957``)."""
    from .devices import complex_creation_ctx

    shape = sanitize_shape(shape)
    target = (
        np.result_type(fill_value)
        if dtype is None
        else np.dtype(types.canonical_heat_type(dtype).jax_type())
    )
    with complex_creation_ctx(target):
        if dtype is None:
            value = jnp.full(shape, fill_value)
            if value.dtype == jnp.float64 and isinstance(fill_value, float):
                value = value.astype(jnp.float32)
        else:
            value = jnp.full(shape, fill_value, dtype=types.canonical_heat_type(dtype).jax_type())
    return _wrap(value, dtype, split, device, comm)


def __factory_like(a, dtype, split, factory, device, comm, **kwargs) -> DNDarray:
    """Shared logic of the *_like factories (reference ``factories.py:753``)."""
    shape = a.shape if isinstance(a, (DNDarray, np.ndarray, jax.Array)) else np.asarray(a).shape
    if dtype is None:
        try:
            dtype = types.heat_type_of(a)
        except TypeError:
            dtype = types.float32
    if split is None and isinstance(a, DNDarray):
        split = a.split
    if device is None and isinstance(a, DNDarray):
        device = a.device
    if comm is None and isinstance(a, DNDarray):
        comm = a.comm
    return factory(shape, dtype=dtype, split=split, device=device, comm=comm, **kwargs)


def _sanitize_order(order: str) -> None:
    """Same stance as :func:`array`: row-major only on TPU; anything else is loud."""
    if order not in ("C", "K", None):
        raise NotImplementedError("only row-major memory layout is supported on TPU")


def empty_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    _sanitize_order(order)
    return __factory_like(a, dtype, split, empty, device, comm)


def zeros_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    _sanitize_order(order)
    return __factory_like(a, dtype, split, zeros, device, comm)


def ones_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    _sanitize_order(order)
    return __factory_like(a, dtype, split, ones, device, comm)


def full_like(a, fill_value, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    _sanitize_order(order)
    shape = a.shape if isinstance(a, (DNDarray, np.ndarray, jax.Array)) else np.asarray(a).shape
    if split is None and isinstance(a, DNDarray):
        split = a.split
    return full(shape, fill_value, dtype=dtype, split=split, device=device, comm=comm)


def eye(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Identity-like 2-D array (reference ``factories.py:865``)."""
    _sanitize_order(order)
    if isinstance(shape, (int, np.integer)):
        n, m = int(shape), int(shape)
    else:
        shape = tuple(shape)
        if len(shape) == 1:
            n = m = int(shape[0])
        else:
            n, m = int(shape[0]), int(shape[1])
    dtype = types.canonical_heat_type(dtype)
    from .devices import complex_creation_ctx

    with complex_creation_ctx(np.dtype(dtype.jax_type())):
        value = jnp.eye(n, m, dtype=dtype.jax_type())
    return _wrap(value, dtype, split, device, comm)


def linspace(
    start,
    stop,
    num: int = 50,
    endpoint: bool = True,
    retstep: bool = False,
    dtype=None,
    split=None,
    device=None,
    comm=None,
):
    """Evenly spaced samples (reference ``factories.py:1021``)."""
    num = int(num)
    if num < 0:
        raise ValueError(f"number of samples 'num' must be non-negative, got {num}")
    step = (stop - start) / max(1, num - (1 if endpoint else 0))
    value = jnp.linspace(start, stop, num, endpoint=endpoint)
    if dtype is None and value.dtype == jnp.float64:
        value = value.astype(jnp.float32)
    ht = _wrap(value, dtype, split, device, comm)
    if retstep:
        return ht, step
    return ht


def logspace(
    start, stop, num=50, endpoint=True, base=10.0, dtype=None, split=None, device=None, comm=None
) -> DNDarray:
    """Log-spaced samples (reference ``factories.py:1101``)."""
    value = jnp.logspace(start, stop, int(num), endpoint=endpoint, base=base)
    if dtype is None and value.dtype == jnp.float64:
        value = value.astype(jnp.float32)
    return _wrap(value, dtype, split, device, comm)


def meshgrid(*arrays: DNDarray, indexing: str = "xy") -> List[DNDarray]:
    """Coordinate matrices from coordinate vectors (reference ``factories.py:1140``).

    The reference splits the output along the dimension that carried a split input; same
    bookkeeping here.
    """
    if indexing not in ("xy", "ij"):
        raise ValueError(f"indexing must be 'xy' or 'ij', got {indexing}")
    arrs = [asarray(a) for a in arrays]
    split_in = next((i for i, a in enumerate(arrs) if a.split is not None), None)
    values = jnp.meshgrid(*[a.larray for a in arrs], indexing=indexing)
    out_split = None
    if split_in is not None and len(arrs) > 1:
        # dim order of the output: 'xy' swaps the first two dims
        out_split = split_in
        if indexing == "xy":
            if split_in == 0:
                out_split = 1
            elif split_in == 1:
                out_split = 0
    comm = arrs[0].comm if arrs else None
    device = arrs[0].device if arrs else None
    return [_wrap(v, None, out_split, device, comm) for v in values]


def from_partitioned(x, comm=None) -> DNDarray:
    """Build a DNDarray from an object exposing ``__partitioned__``
    (reference ``factories.py:823``)."""
    parts = x.__partitioned__ if not isinstance(x, dict) else x
    return from_partition_dict(parts, comm=comm)


def from_partition_dict(parted: dict, comm=None) -> DNDarray:
    """Build a DNDarray from a ``__partitioned__`` dict (reference ``factories.py:868``)."""
    comm = sanitize_comm(comm)
    shape = tuple(parted["shape"])
    getter = parted.get("get", lambda v: v)
    tiling = tuple(parted.get("partition_tiling", (1,) * len(shape)))
    split_dims = [i for i, t in enumerate(tiling) if t > 1]
    if len(split_dims) > 1:
        raise ValueError(f"Only one split-dimension allowed, got {len(split_dims)}")
    split = split_dims[0] if split_dims else None
    ordered = sorted(parted["partitions"].items(), key=lambda kv: kv[1]["start"])
    locals_ = [np.asarray(getter(p["data"])) for _, p in ordered if p["data"] is not None]
    if split is None:
        value = jnp.asarray(locals_[0])
    else:
        value = jnp.concatenate([jnp.asarray(l) for l in locals_], axis=split)
    if tuple(value.shape) != shape:
        raise ValueError(f"partitioned data of shape {tuple(value.shape)} does not match declared {shape}")
    return _wrap(value, None, split, None, comm)
