"""``ht.forensics`` — per-request forensics: lifecycle records, critical-path
attribution, tail exemplars, and per-tenant cost metering.

The rest of the observability stack answers *whether* serving is healthy
(diagnostics counters, profiler traces, telemetry shards, the ops plane's SLO
burn alerts). This module answers the question every one of those planes ends
at: **why was this specific request slow, and who pays for it?**

Lifecycle records
-----------------
While armed (``HEAT_TPU_FORENSICS=1`` or :func:`arm`), every
``profiler.request(tag)`` scope accumulates one compact record as it crosses
the chokepoints the system already instruments:

- **admission** — verdict (``admitted`` / ``shed`` / ``deadline-expired``)
  plus the deadline headroom observed at each lifecycle checkpoint;
- **scheduling** — shard assignment, queue-wait and batch-window-hold
  durations, batch width, and steal provenance (which shard the work was
  stolen from, if any);
- **caches** — result-cache hit/miss/bypass with the *reason* a consult was
  bypassed (``no-replay-spec`` / ``rng-label`` / ``undigestable-operand``),
  compile-cache outcome counts (``miss`` / ``aot-load`` / ``off``);
- **programs** — compile-vs-execute wall split, batch calls folded by
  width-share;
- **collectives** — wall time and logical payload bytes (auxiliary: at trace
  time collectives nest *inside* the compile stage, so their time is reported
  alongside the stages, never added to the stage sum — adding it would double
  count);
- **failure path** — typed-failure, eager-replay, retry and injected-fault
  events, teed from the always-on resilience stream.

Finished records land in a bounded ring (``HEAT_TPU_FORENSICS_RING``), and a
**critical-path reducer** labels each with its dominant stage: the disjoint
timed stages (``queue_wait`` / ``window_hold`` / ``compile`` / ``execute``
plus the residual ``host`` stage — un-instrumented application time between
dispatches) sorted by share, followed by event legs (``typed-failure``,
``eager-replay``, ...). By construction the timed stages sum to the measured
request latency, so one artifact answers "where did the time go".

Tail exemplars
--------------
A per-tenant reservoir retains the **slowest-K** full records
(``HEAT_TPU_FORENSICS_EXEMPLARS``), deterministically ordered by
``(-total_s, rid)``. When the profiler is also collecting, an exemplar grabs
its request's span tree at capture time. ``ht.explain(tag)`` and
``python -m heat_tpu.telemetry slow`` read them; the ops plane's ``slo-burn``
post-mortems name the matching exemplars in their detail payload.

Per-tenant cost meters
----------------------
Device execute-time (program wall seconds; batch calls billed per item at
``dt / width``), logical collective bytes, result-cache bytes saved, and
per-signature FLOPs (memoised once from ``compiled.cost_analysis()`` by the
executor) fold into per-tenant meters. Work outside any request scope bills
to the ``"-"`` tenant, so the meters **reconcile exactly**: :func:`totals` is
defined as the fold over :func:`tenant_cost` — nothing is metered twice and
nothing escapes attribution. Surfaced through ``executor_stats()``, the ops
exporter (``ht_tenant_device_seconds_total``, ``ht_tenant_flops_total``,
``ht_tenant_collective_bytes_total``, ``ht_tenant_stage_share``) and the cost
column of ``telemetry top``.

Contracts
---------
- **Zero-cost when off**: every producer hook gates on one relaxed module
  attribute read (``forensics._enabled``), the same idle contract the
  profiler/telemetry/ops planes honour; the dispatch ops/s baseline and the
  HLO byte-parity gates hold off vs. armed-idle (forensics never touches a
  traced body).
- **Stdlib-only at load**: importable with no accelerator stack present
  (enforced by ``heat_tpu.analysis`` rule ``stdlib-only-core``).
- **Leaf lock**: ``_lock`` guards every mutable store below and is acquired
  strictly last — producers call in from *outside* their own locks (the
  scheduler after releasing its condvar, the result cache after its shard
  mutex, diagnostics' tee after its ring append), and forensics never calls
  back into another locked module while holding ``_lock`` (exemplar span
  capture re-enters the profiler only *between* two separate acquisitions).
  The committed lock graph gains no edges.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

try:  # guarded for standalone file-path loads (mirrors ops.py)
    from . import diagnostics, profiler
except ImportError:  # pragma: no cover - standalone load only
    diagnostics = profiler = None  # type: ignore[assignment]

__all__ = [
    "arm",
    "disarm",
    "armed",
    "reload",
    "reset",
    "explain",
    "records",
    "exemplars",
    "exemplar_refs",
    "tenant_cost",
    "totals",
    "forensics_stats",
    "SCHEMA",
]

SCHEMA = "heat-tpu-forensics/1"

#: Hot-path hooks read this module attribute directly (``forensics._enabled``):
#: one attribute load + branch when off — the zero-cost-when-disabled contract.
_enabled: bool = False

_lock = threading.Lock()

#: The timed stages of a record, disjoint by construction: ``host`` is the
#: residual (total − sum of measured stages), so the decomposition always
#: sums to the measured request latency.
STAGES = ("queue_wait", "window_hold", "compile", "execute", "host")

#: Event kinds promoted to critical-path legs, in report order.
_EVENT_LEGS = ("typed-failure", "eager-replay", "retry", "fault")

_MAX_LIVE = 8_192  # leak guard: abandoned records evict oldest-first
_MAX_ADMISSION = 16  # admission checkpoints kept per record
_MAX_EVENTS = 32  # failure-path events kept per record

_UNATTRIBUTED = "-"  # meter key for work outside any request scope


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


class _Knobs:
    """Env knobs, read once at import/arm and on :func:`reload` — never per
    record (same memoisation contract as the executor's ``_EnvKnobs``)."""

    __slots__ = ("ring", "exemplars")

    def __init__(self):
        self.reload()

    def reload(self):
        self.ring = max(16, _env_int("HEAT_TPU_FORENSICS_RING", 1024))
        self.exemplars = max(1, _env_int("HEAT_TPU_FORENSICS_EXEMPLARS", 8))


_knobs = _Knobs()

# ------------------------------------------------------------------ stores
# All four mutate only under `_lock`. The ring holds *finished* record dicts
# (evict-oldest); reservoirs hold the slowest-K per tenant; meters are the
# per-tenant cost ledger; `_live` maps rid -> in-flight _Record.
_live: "OrderedDict[int, _Record]" = OrderedDict()
_ring: "deque[dict]" = deque(maxlen=_knobs.ring)
_reservoirs: Dict[str, List[dict]] = {}
_meters: Dict[str, dict] = {}
_finished: int = 0  # records completed since reset (ring may have evicted)
_dropped: int = 0  # ring evictions + abandoned live records


class _Record:
    """One in-flight request's accumulating lifecycle record."""

    __slots__ = (
        "rid", "tenant", "deadline", "stages", "collective_s",
        "collective_bytes", "admission", "shard", "width", "stolen_from",
        "result_cache", "compile_cache", "device_s", "flops", "events",
    )

    def __init__(self, rid: int, tenant: str, deadline: Optional[float]):
        self.rid = rid
        self.tenant = tenant
        self.deadline = deadline
        self.stages: Dict[str, float] = {}
        self.collective_s = 0.0
        self.collective_bytes = 0.0
        self.admission: List[dict] = []
        self.shard: Optional[int] = None
        self.width = 0
        self.stolen_from: Optional[int] = None
        self.result_cache = {"hits": 0, "misses": 0, "bypass": {},
                             "bytes_saved": 0.0}
        self.compile_cache: Dict[str, int] = {}
        self.device_s = 0.0
        self.flops = 0.0
        self.events: List[dict] = []

    def add_stage(self, stage: str, seconds: float) -> None:
        if seconds > 0.0:
            self.stages[stage] = self.stages.get(stage, 0.0) + seconds

    def finish(self, total_s: float) -> dict:
        """Close the record: add the residual ``host`` stage, reduce the
        critical path, and return the finished record dict."""
        total_s = max(0.0, float(total_s))
        stages = dict(self.stages)
        host = total_s - sum(stages.values())
        if host > 0.0:
            stages["host"] = host
        path = [
            {"stage": s, "seconds": round(v, 9),
             "share": round(v / total_s, 6) if total_s > 0.0 else 0.0}
            for s, v in sorted(stages.items(), key=lambda kv: (-kv[1], kv[0]))
            if v > 0.0
        ]
        for kind in _EVENT_LEGS:
            n = sum(1 for e in self.events if e["kind"] == kind)
            if n:
                path.append({"stage": kind, "events": n})
        if not path:  # zero-duration, zero-event record: still non-empty
            path = [{"stage": "host", "seconds": 0.0, "share": 1.0}]
        headroom = None
        if self.deadline is not None:
            headroom = round(self.deadline - time.monotonic(), 9)
        return {
            "schema": SCHEMA,
            "rid": self.rid,
            "tenant": self.tenant,
            "total_s": round(total_s, 9),
            "deadline_headroom_s": headroom,
            "shard": self.shard,
            "width": self.width,
            "stolen_from": self.stolen_from,
            "stages": {s: round(v, 9) for s, v in stages.items()},
            "collective_s": round(self.collective_s, 9),
            "collective_bytes": self.collective_bytes,
            "admission": list(self.admission),
            "result_cache": {
                "hits": self.result_cache["hits"],
                "misses": self.result_cache["misses"],
                "bypass": dict(self.result_cache["bypass"]),
                "bytes_saved": self.result_cache["bytes_saved"],
            },
            "compile_cache": dict(self.compile_cache),
            "device_s": round(self.device_s, 9),
            "flops": self.flops,
            "events": list(self.events),
            "critical_path": path,
            "dominant": path[0]["stage"],
        }


# ------------------------------------------------------------------ switches
def arm() -> None:
    """Start recording request forensics (re-reads the env knobs). Idempotent."""
    global _enabled
    _knobs.reload()
    _resize_ring_locked_out()
    _enabled = True


def disarm() -> None:
    """Stop recording. Collected records, exemplars and meters are kept —
    :func:`explain` / :func:`tenant_cost` still work; :func:`reset` clears."""
    global _enabled
    _enabled = False


def armed() -> bool:
    """Whether forensics is currently recording."""
    return _enabled


def reload() -> None:
    """Re-read the ``HEAT_TPU_FORENSICS*`` env knobs (chained from
    ``ht.reload_env_knobs()``); re-arms/disarms from ``HEAT_TPU_FORENSICS``."""
    global _enabled
    _knobs.reload()
    _resize_ring_locked_out()
    env = os.environ.get("HEAT_TPU_FORENSICS")
    if env is not None:
        _enabled = env == "1"


def _resize_ring_locked_out() -> None:
    global _ring
    with _lock:
        if _ring.maxlen != _knobs.ring:
            _ring = deque(_ring, maxlen=_knobs.ring)


def reset() -> None:
    """Drop every record, exemplar and meter (the switch state is kept)."""
    global _finished, _dropped
    with _lock:
        _live.clear()
        _ring.clear()
        _reservoirs.clear()
        _meters.clear()
        _finished = 0
        _dropped = 0


# ------------------------------------------------------------------ producers
def _ambient_rid() -> Optional[int]:
    if profiler is None:
        return None
    return profiler._current_request.get()


def _meter_locked(tenant: str) -> dict:
    m = _meters.get(tenant)
    if m is None:
        m = _meters[tenant] = {
            "requests": 0,
            "device_seconds": 0.0,
            "flops": 0.0,
            "collective_bytes": 0.0,
            "cache_bytes_saved": 0.0,
            "stage_seconds": {},
        }
    return m


def begin_request(rid: int, tenant: str, deadline: Optional[float] = None) -> None:
    """Open the lifecycle record for ``rid`` (called by ``profiler.request``
    at scope entry; ``deadline`` is the absolute monotonic deadline, if any)."""
    if not _enabled:
        return
    global _dropped
    rec = _Record(int(rid), str(tenant), deadline)
    with _lock:
        _live[rec.rid] = rec
        while len(_live) > _MAX_LIVE:
            _live.popitem(last=False)
            _dropped += 1


def finish_request(rid: int, total_s: float) -> None:
    """Close ``rid``'s record (called by ``profiler.request`` at scope exit
    with the measured wall latency): reduce the critical path, append to the
    ring, fold the per-tenant meters, and offer the record to the slowest-K
    reservoir. When the record becomes an exemplar while the profiler is
    collecting, its span tree is captured in a second, separate lock
    acquisition (the profiler's lock is never taken under ``_lock``)."""
    if not _enabled:
        return
    global _finished, _dropped
    with _lock:
        rec = _live.pop(rid, None)
        if rec is None:
            return
        done = rec.finish(total_s)
        if _ring.maxlen is not None and len(_ring) == _ring.maxlen:
            _dropped += 1
        _ring.append(done)
        _finished += 1
        m = _meter_locked(rec.tenant)
        m["requests"] += 1
        shares = m["stage_seconds"]
        for stage, seconds in done["stages"].items():
            shares[stage] = shares.get(stage, 0.0) + seconds
        inserted = _reservoir_offer_locked(done)
    if inserted and profiler is not None and profiler._active:
        slices = profiler.request_slices(rid)
        if slices:
            tree = _span_tree(slices)
            with _lock:  # `done` is the exemplar object itself
                done["spans"] = tree


def _reservoir_offer_locked(done: dict) -> bool:
    res = _reservoirs.setdefault(done["tenant"], [])
    res.append(done)
    res.sort(key=lambda r: (-r["total_s"], r["rid"]))
    del res[_knobs.exemplars:]
    return any(r is done for r in res)


def _span_tree(slices: List[dict]) -> List[dict]:
    """Nest flat ``{cat, name, t0_us, t1_us}`` slices into a forest by
    interval containment (stack sweep over slices sorted by start, widest
    first on ties)."""
    root: List[dict] = []
    stack: List[dict] = []
    for s in sorted(slices, key=lambda x: (x["t0_us"], -x["t1_us"])):
        node = dict(s)
        node["children"] = []
        while stack and s["t0_us"] >= stack[-1]["t1_us"]:
            stack.pop()
        (stack[-1]["children"] if stack else root).append(node)
        stack.append(node)
    return root


def note_admission(checkpoint: str, verdict: str,
                   headroom_s: Optional[float] = None,
                   rid: Optional[int] = None) -> None:
    """One lifecycle-checkpoint admission decision: ``verdict`` is
    ``admitted`` / ``shed`` / ``deadline-expired``, ``headroom_s`` the
    deadline headroom observed there (negative = already past)."""
    if not _enabled:
        return
    if rid is None:
        rid = _ambient_rid()
    if rid is None:
        return
    entry = {"checkpoint": str(checkpoint), "verdict": str(verdict)}
    if headroom_s is not None:
        entry["headroom_s"] = round(float(headroom_s), 9)
    with _lock:
        rec = _live.get(rid)
        if rec is not None and len(rec.admission) < _MAX_ADMISSION:
            rec.admission.append(entry)


def note_scheduled(rid: Optional[int], shard: int, queue_wait_s: float,
                   hold_s: float = 0.0, width: int = 1,
                   stolen_from: Optional[int] = None) -> None:
    """One work item leaving the dispatch queue: which shard ran it, how long
    it waited queued, how long the batch window held it, the batch width it
    rode, and — when it was stolen — the shard it came from. Called by the
    scheduler loop after releasing its condvar."""
    if not _enabled or rid is None:
        return
    with _lock:
        rec = _live.get(rid)
        if rec is None:
            return
        rec.add_stage("queue_wait", queue_wait_s)
        rec.add_stage("window_hold", hold_s)
        rec.shard = int(shard)
        rec.width = max(rec.width, int(width))
        if stolen_from is not None:
            rec.stolen_from = int(stolen_from)


def note_program(label: str, seconds: float, phase: str,
                 flops: float = 0.0, rid: Optional[int] = None) -> None:
    """One program invocation attributed to the ambient (or given) request:
    ``phase`` is ``"compile"`` (first call: trace+lower+compile wall) or
    ``"execute"``. Execute time and FLOPs also bill the tenant's cost meter;
    work outside any request scope bills tenant ``"-"``."""
    if not _enabled:
        return
    if rid is None:
        rid = _ambient_rid()
    seconds = max(0.0, float(seconds))
    with _lock:
        rec = _live.get(rid) if rid is not None else None
        tenant = rec.tenant if rec is not None else _UNATTRIBUTED
        if rec is not None:
            rec.add_stage(phase, seconds)
            if phase == "execute":
                rec.device_s += seconds
            rec.flops += flops
        if phase == "execute":
            m = _meter_locked(tenant)
            m["device_seconds"] += seconds
            m["flops"] += flops


def note_batch_execute(rids: List[Optional[int]], label: str, seconds: float,
                       flops_each: float = 0.0) -> None:
    """One batched program call folded by width-share: each of the ``width``
    items is billed ``seconds / width`` of device time (and its own single
    program's FLOPs), so the meters reconcile with the unbatched accounting."""
    if not _enabled or not rids:
        return
    share = max(0.0, float(seconds)) / len(rids)
    with _lock:
        for rid in rids:
            rec = _live.get(rid) if rid is not None else None
            tenant = rec.tenant if rec is not None else _UNATTRIBUTED
            if rec is not None:
                rec.add_stage("execute", share)
                rec.device_s += share
                rec.flops += flops_each
            m = _meter_locked(tenant)
            m["device_seconds"] += share
            m["flops"] += flops_each


def note_result_cache(outcome: str, reason: Optional[str] = None,
                      nbytes: float = 0.0, rid: Optional[int] = None) -> None:
    """One result-cache consult: ``outcome`` is ``hit`` / ``miss`` /
    ``bypass`` (with ``reason`` naming *why* the consult was skipped —
    ``no-replay-spec``, ``rng-label``, ``undigestable-operand``). A hit's
    ``nbytes`` credits the tenant's ``cache_bytes_saved`` meter."""
    if not _enabled:
        return
    if rid is None:
        rid = _ambient_rid()
    with _lock:
        rec = _live.get(rid) if rid is not None else None
        tenant = rec.tenant if rec is not None else _UNATTRIBUTED
        if rec is not None:
            rc = rec.result_cache
            if outcome == "hit":
                rc["hits"] += 1
                rc["bytes_saved"] += nbytes
            elif outcome == "miss":
                rc["misses"] += 1
            else:
                key = reason or "bypass"
                rc["bypass"][key] = rc["bypass"].get(key, 0) + 1
        if outcome == "hit" and nbytes:
            _meter_locked(tenant)["cache_bytes_saved"] += nbytes


def note_compile_cache(outcome: str, rid: Optional[int] = None) -> None:
    """One first-call compile's persistent-cache outcome (``aot-load`` /
    ``miss`` / ``off``), counted on the record."""
    if not _enabled:
        return
    if rid is None:
        rid = _ambient_rid()
    if rid is None:
        return
    with _lock:
        rec = _live.get(rid)
        if rec is not None:
            rec.compile_cache[outcome] = rec.compile_cache.get(outcome, 0) + 1


def note_collective(site: str, seconds: float, nbytes: float = 0.0) -> None:
    """One collective invocation: wall time is *auxiliary* (collectives run
    at trace time, nested inside the ``compile`` stage — adding them to the
    stage sum would double count); logical payload bytes bill the tenant's
    ``collective_bytes`` meter."""
    if not _enabled:
        return
    rid = _ambient_rid()
    with _lock:
        rec = _live.get(rid) if rid is not None else None
        tenant = rec.tenant if rec is not None else _UNATTRIBUTED
        if rec is not None:
            rec.collective_s += max(0.0, float(seconds))
            rec.collective_bytes += nbytes
        if nbytes:
            _meter_locked(tenant)["collective_bytes"] += nbytes


@contextlib.contextmanager
def collective_timer(site: str):
    """Time one collective invocation (retries included) onto the ambient
    record as auxiliary collective time — the wrapper communication's
    guarded chain puts around the actual dispatch. The clock reads live
    HERE, not in the (trace-reachable) caller, mirroring
    ``telemetry.collective_window``: the purity rule bans wall-clock reads
    inside traced bodies, and this plane keeps its own clocks."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        note_collective(site, time.perf_counter() - t0)


def note_event(kind: str, detail: str = "", rid: Optional[int] = None) -> None:
    """One failure-path event (``typed-failure`` / ``eager-replay`` /
    ``retry`` / ``fault`` / ...) on the ambient or given request; promoted to
    a critical-path leg at finish."""
    if not _enabled:
        return
    if rid is None:
        rid = _ambient_rid()
    if rid is None:
        return
    with _lock:
        rec = _live.get(rid)
        if rec is not None and len(rec.events) < _MAX_EVENTS:
            rec.events.append({"kind": str(kind), "detail": str(detail)})


def _note_resilience(site: str, kind: str, detail: str) -> None:
    """``diagnostics._forensics_tee`` adapter: attribute retry / fault /
    exhausted / breaker events from the always-on resilience stream to the
    ambient request (invoked outside the diagnostics lock)."""
    if not _enabled:
        return
    note_event(kind, f"{site}: {detail}")


# ------------------------------------------------------------------ consumers
def records(tag: Optional[str] = None, limit: int = 64) -> List[dict]:
    """The most recent finished records (newest last), optionally filtered to
    one tenant tag. Copies — safe to mutate."""
    with _lock:
        out = [dict(r) for r in _ring
               if tag is None or r["tenant"] == tag]
    return out[-limit:]


def exemplars(tenant: Optional[str] = None) -> Dict[str, List[dict]]:
    """The slowest-K full records per tenant (deterministic ``(-total_s,
    rid)`` order), or just ``tenant``'s."""
    with _lock:
        if tenant is not None:
            return {tenant: [dict(r) for r in _reservoirs.get(tenant, [])]}
        return {t: [dict(r) for r in res] for t, res in _reservoirs.items()}


def exemplar_refs(tenant: Optional[str] = None, k: int = 3) -> List[dict]:
    """Compact exemplar references (``rid`` / ``tenant`` / ``total_ms`` /
    ``dominant``) for embedding in alert payloads — the ``slo-burn``
    post-mortem detail names these."""
    with _lock:
        if tenant is not None:
            pool = list(_reservoirs.get(tenant, []))
        else:
            pool = [r for res in _reservoirs.values() for r in res]
        pool.sort(key=lambda r: (-r["total_s"], r["rid"]))
        return [
            {"rid": r["rid"], "tenant": r["tenant"],
             "total_ms": round(r["total_s"] * 1e3, 3),
             "dominant": r["dominant"]}
            for r in pool[:max(0, int(k))]
        ]


def tenant_cost() -> Dict[str, dict]:
    """The per-tenant cost meters: ``requests`` / ``device_seconds`` /
    ``flops`` / ``collective_bytes`` / ``cache_bytes_saved`` /
    ``stage_seconds`` (per-stage wall totals). Unattributed work meters under
    ``"-"``. Copies."""
    with _lock:
        return {
            t: {**{k: v for k, v in m.items() if k != "stage_seconds"},
                "stage_seconds": dict(m["stage_seconds"])}
            for t, m in _meters.items()
        }


def totals() -> dict:
    """The module-wide cost totals, defined as the *fold* over
    :func:`tenant_cost` — the meter reconciliation rule: per-tenant meters
    sum exactly to these totals because these totals ARE that sum."""
    agg = {"requests": 0, "device_seconds": 0.0, "flops": 0.0,
           "collective_bytes": 0.0, "cache_bytes_saved": 0.0,
           "stage_seconds": {}}
    for m in tenant_cost().values():
        agg["requests"] += m["requests"]
        agg["device_seconds"] += m["device_seconds"]
        agg["flops"] += m["flops"]
        agg["collective_bytes"] += m["collective_bytes"]
        agg["cache_bytes_saved"] += m["cache_bytes_saved"]
        for stage, seconds in m["stage_seconds"].items():
            agg["stage_seconds"][stage] = (
                agg["stage_seconds"].get(stage, 0.0) + seconds)
    return agg


def explain(tag: Optional[str] = None, limit: int = 5) -> dict:
    """Answer "why was this slow" for ``tag``'s requests (or all traffic)
    from the forensic artifact: dominant-stage distribution over the ring,
    the tenant's cost meter, and the slowest exemplars with their critical
    paths. Exported as ``ht.explain``."""
    with _lock:
        ring = [r for r in _ring if tag is None or r["tenant"] == tag]
        dominants: Dict[str, int] = {}
        for r in ring:
            dominants[r["dominant"]] = dominants.get(r["dominant"], 0) + 1
        if tag is not None:
            pool = list(_reservoirs.get(tag, []))
        else:
            pool = [r for res in _reservoirs.values() for r in res]
        pool.sort(key=lambda r: (-r["total_s"], r["rid"]))
        slowest = [dict(r) for r in pool[:max(0, int(limit))]]
    cost = tenant_cost()
    return {
        "schema": SCHEMA,
        "tag": tag,
        "records": len(ring),
        "dominant_stages": dominants,
        "cost": cost.get(tag) if tag is not None else totals(),
        "slowest": slowest,
    }


def forensics_stats() -> dict:
    """The diagnostics report section (provider ``"forensics"``): switch
    state, ring/reservoir occupancy, the cost meters and their fold, and the
    exemplars — this is what rides telemetry shard dumps, so
    ``telemetry slow`` can read exemplars from merged artifacts offline."""
    with _lock:
        live = len(_live)
        ring = len(_ring)
        finished = _finished
        dropped = _dropped
    return {
        "schema": SCHEMA,
        "armed": _enabled,
        "live": live,
        "ring": ring,
        "finished": finished,
        "dropped": dropped,
        "knobs": {"ring": _knobs.ring, "exemplars": _knobs.exemplars},
        "tenant_cost": tenant_cost(),
        "totals": totals(),
        "exemplars": exemplars(),
    }


# ------------------------------------------------------------------ wiring
# Late-bound collaborator hooks, installed once at import (the same pattern
# telemetry uses for the diagnostics tees): the profiler drives record
# open/close from `request()` even while itself disabled ("lite-active"),
# and the always-on resilience stream tees failure events onto the ambient
# record. Both collaborators invoke us OUTSIDE their own locks.
if profiler is not None:
    profiler._forensics = sys.modules[__name__]
if diagnostics is not None:
    diagnostics._forensics_tee = _note_resilience
    diagnostics.register_provider("forensics", forensics_stats)

if os.environ.get("HEAT_TPU_FORENSICS") == "1":
    arm()
