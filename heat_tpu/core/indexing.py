"""Indexing functions (reference heat/core/indexing.py, 149 LoC)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from . import sanitation, types
from .dndarray import DNDarray

__all__ = ["nonzero", "where"]


def nonzero(x: DNDarray) -> DNDarray:
    """Indices of non-zero elements as an (n, ndim) array (reference ``indexing.py:16``,
    torch.nonzero layout). The result is replicated — the reference gathers the per-rank
    index lists the same way."""
    sanitation.sanitize_in(x)
    idx = jnp.nonzero(x.larray)
    result = jnp.stack(idx, axis=1).astype(jnp.int64) if idx else jnp.zeros((0, 0), jnp.int64)
    result_split = 0 if x.split is not None else None
    out = x.comm.shard(result, result_split)
    return DNDarray(
        out, tuple(result.shape), types.canonical_heat_type(result.dtype), result_split,
        x.device, x.comm, True,
    )


def where(cond: DNDarray, x=None, y=None) -> DNDarray:
    """Elements chosen from ``x`` or ``y`` depending on ``cond``
    (reference ``indexing.py:91``)."""
    if x is None and y is None:
        return nonzero(cond)
    if x is None or y is None:
        raise TypeError("either both or neither of x and y should be given")
    from . import _operations, stride_tricks

    proto = next((t for t in (cond, x, y) if isinstance(t, DNDarray)), None)
    if proto is None:
        from . import factories

        cond = factories.array(cond)
        proto = cond

    def val(t):
        return t.larray if isinstance(t, DNDarray) else jnp.asarray(t)

    cv, xv, yv = val(cond), val(x), val(y)
    result = jnp.where(cv, xv, yv)
    out_shape = tuple(result.shape)
    # dominant-split rule over all three operands, shifted into the output rank
    operands = [t for t in (cond, x, y) if isinstance(t, DNDarray)]
    split = _operations._out_split_binary(out_shape, *operands)
    return _operations.wrap_result(result, proto, split)
