"""Indexing functions (reference heat/core/indexing.py, 149 LoC)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from . import sanitation, types
from .dndarray import DNDarray

__all__ = ["nonzero", "where"]


def nonzero(x: DNDarray) -> DNDarray:
    """Indices of non-zero elements as an (n, ndim) array (reference ``indexing.py:16``,
    torch.nonzero layout). The result is replicated — the reference gathers the per-rank
    index lists the same way."""
    sanitation.sanitize_in(x)
    idx = jnp.nonzero(x.larray)
    result = jnp.stack(idx, axis=1).astype(jnp.int64) if idx else jnp.zeros((0, 0), jnp.int64)
    result_split = 0 if x.split is not None else None
    out = x.comm.shard(result, result_split)
    return DNDarray(
        out, tuple(result.shape), types.canonical_heat_type(result.dtype), result_split,
        x.device, x.comm, True,
    )


def where(cond: DNDarray, x=None, y=None) -> DNDarray:
    """Elements chosen from ``x`` or ``y`` depending on ``cond``
    (reference ``indexing.py:91``)."""
    if x is None and y is None:
        return nonzero(cond)
    if x is None or y is None:
        raise TypeError("either both or neither of x and y should be given")
    from . import _operations

    cv = cond.larray if isinstance(cond, DNDarray) else jnp.asarray(cond)
    return _operations.binary_op(lambda a, b: jnp.where(cv, a, b), x, y)
