"""Parallel I/O (reference heat/core/io.py, 1134 LoC).

The reference's HDF5/NetCDF/CSV loaders compute each rank's hyperslab from
``comm.chunk`` and read/write it independently (``io.py:211-238``). The TPU build keeps
the same extension-dispatch ``load``/``save`` surface; each host process reads the
slabs of its addressable shards and assembles the global ``jax.Array`` with
``jax.make_array_from_single_device_arrays`` semantics via the factories. HDF5 rides
h5py; NetCDF is gated on the optional netCDF4 package exactly like the reference.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

import jax.numpy as jnp

from . import factories, resilience, types
from .communication import sanitize_comm
from .devices import sanitize_device
from .dndarray import DNDarray


# In-place writes (HDF5 append, NetCDF) are NOT idempotent: a half-applied
# attempt followed by a blind replay would duplicate appends or trip over the
# already-created dataset, masking the real error. They therefore run
# single-attempt by default — injected faults still fire (and surface), and an
# operator can opt a site into retries with resilience.set_policy, owning the
# idempotency question. Whole-file mode='w' saves go through
# resilience.atomic_write instead (temp + fsync + rename, safely retried).
_SINGLE_ATTEMPT = resilience.Policy(max_attempts=1)


def _guarded_write(site: str, fn, *args, **kwargs):
    """Run an in-place file write under ht.resilience when a fault plan is
    armed or a site policy is registered (same idle fast path as the
    communication layer); see the single-attempt note above."""
    if resilience._active:
        policy = resilience.site_policy(site) or _SINGLE_ATTEMPT
        return resilience.guard(site, fn, *args, policy=policy, **kwargs)
    return fn(*args, **kwargs)

__all__ = [
    "load",
    "load_csv",
    "load_npy",
    "save_csv",
    "save_npy",
    "save",
    "supports_hdf5",
    "supports_netcdf",
    "supports_zarr",
]

try:
    import h5py

    _HAS_HDF5 = True
except ImportError:  # pragma: no cover - h5py is baked into the image
    _HAS_HDF5 = False

try:
    import netCDF4 as nc

    _HAS_NETCDF = True
except ImportError:
    _HAS_NETCDF = False

try:
    import tensorstore as _ts

    _HAS_ZARR = True
except ImportError:
    _HAS_ZARR = False


_VALID_WRITE_MODES = frozenset(["w", "a", "r+"])


def _is_writer() -> bool:
    """Multi-controller contract: process 0 creates files / writes unsplit data.

    Split data is written per-shard by every process in serialized rounds
    (:func:`_serialized_shard_write`) — the reference's no-MPI-IO scheme of
    rank-by-rank hyperslab writes (``io.py:231-238``); only formats that cannot
    target hyperslabs (csv/npy) gather to this single writer.
    """
    import jax

    return jax.process_index() == 0


def _serialized_shard_write(tag: str, write_my_shards) -> None:
    """Each controller writes its ADDRESSABLE shards, one process at a time
    (reference ``io.py:231-238``: ``Recv`` from the previous rank, write own
    hyperslab, ``Isend`` to the next — here the token ring is a barrier round).
    Host memory per process stays O(local shards); no global gather."""
    import jax

    nproc = jax.process_count()
    if nproc == 1:
        write_my_shards()
        return
    from jax.experimental import multihost_utils

    for p in range(nproc):
        if jax.process_index() == p:
            write_my_shards()
        multihost_utils.sync_global_devices(f"heat_tpu.io:{tag}:round{p}")


def _writer_barrier(tag: str) -> None:
    """Block every controller until the single writer's file is on disk, so
    ``ht.save(...)`` followed by ``ht.load(...)`` is race-free on all processes
    (the reference gets this ordering from MPI-IO's collective writes)."""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"heat_tpu.io:{tag}")


def streamed_shard_assembly(comm, gshape, padded_gshape, split: int,
                            host_block, *, depth: int = 2):
    """Assemble a sharded ``jax.Array`` from per-shard host blocks with the
    block production DOUBLE-BUFFERED against device transfer.

    ``host_block(i)`` produces shard ``i``'s host buffer (the padded-grid
    block of ``padded_gshape`` along ``split``); a read-ahead thread stays up
    to ``depth`` blocks ahead of the main thread's ``jax.device_put``, so
    file/chunk reads overlap the host→device copies — the streaming-ingest
    shape the checkpoint v2 resharding restore rides (the hyperslab loaders'
    ``make_array_from_callback`` path trades this overlap for simplicity).
    Only this process's addressable shards are produced; the result carries
    ``comm``'s canonical sharding for ``split`` and is assembled via
    ``jax.make_array_from_single_device_arrays``.
    """
    import queue
    import threading

    import jax

    sharding = comm.sharding(len(gshape), split)
    mine = [
        (i, dev)
        for i, dev in enumerate(comm.devices)
        if dev.process_index == jax.process_index()
    ]
    fifo: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()

    def _put(item) -> None:
        # bounded put that honours cancellation: an abandoned consumer (its
        # device_put raised) sets ``stop`` and the producer exits instead of
        # re-filling the queue and parking forever on a full put
        while not stop.is_set():
            try:
                fifo.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def produce() -> None:
        try:
            for i, dev in mine:
                if stop.is_set():
                    return
                _put((dev, host_block(i), None))
        except BaseException as exc:  # delivered to the consumer, re-raised
            _put((None, None, exc))

    t = threading.Thread(
        target=produce, name="heat-tpu-shard-read", daemon=True
    )
    t.start()
    arrays = []
    try:
        for _ in mine:
            dev, block, err = fifo.get()
            if err is not None:
                raise err
            arrays.append(jax.device_put(block, dev))
    finally:
        stop.set()
        while not fifo.empty():
            try:
                fifo.get_nowait()
            except queue.Empty:
                break
        t.join(timeout=10.0)
    return jax.make_array_from_single_device_arrays(
        tuple(padded_gshape), sharding, arrays
    )


def _sharded_read(data, gshape, np_dtype, split: int, comm):
    """Per-shard hyperslab reads of an indexable file dataset (reference io.py:211-238).

    All shapes go through ``jax.make_array_from_callback`` — it invokes the callback
    once per *addressable* shard, so each process reads only its own slabs straight
    into device buffers and host memory stays O(local) for ANY extent. Ragged split
    extents (which that API rejects) read on the zero-padded canonical grid
    (``ceil(n/P)·P``) and slice back to the true extent on device; the sliced result
    is replicated by GSPMD (jax cannot represent a ragged NamedSharding — see the
    deviations doc), but no process ever materialises the global array on host.
    """
    import jax

    ndim = len(gshape)
    split = split % ndim  # the slice-back below compares positional indices
    if gshape[split] % comm.size == 0:
        return jax.make_array_from_callback(
            gshape,
            comm.sharding(ndim, split),
            lambda idx: np.asarray(data[idx], dtype=np_dtype),
        )
    n = gshape[split]
    c = -(-n // comm.size)
    padded = list(gshape)
    padded[split] = c * comm.size

    def _read_shard(idx):
        starts = [s.start or 0 for s in idx]
        stops = [s.stop if s.stop is not None else padded[i] for i, s in enumerate(idx)]
        shard_shape = tuple(hi - lo for lo, hi in zip(starts, stops))
        real_stop = min(stops[split], n)
        if real_stop <= starts[split]:
            return np.zeros(shard_shape, np_dtype)  # fully in the padding
        src = list(idx)
        src[split] = slice(starts[split], real_stop)
        block = np.asarray(data[tuple(src)], dtype=np_dtype)
        if real_stop == stops[split]:
            return block
        buf = np.zeros(shard_shape, np_dtype)
        out = [slice(None)] * ndim
        out[split] = slice(0, real_stop - starts[split])
        buf[tuple(out)] = block
        return buf

    padded_arr = jax.make_array_from_callback(
        tuple(padded), comm.sharding(ndim, split), _read_shard
    )
    cut = tuple(slice(0, n) if i == split else slice(None) for i in range(ndim))
    return padded_arr[cut]


def supports_hdf5() -> bool:
    """True if HDF5 I/O is available (reference ``io.py:36``)."""
    return _HAS_HDF5


def supports_netcdf() -> bool:
    """True if NetCDF I/O is available (reference ``io.py:50``)."""
    return _HAS_NETCDF


def supports_zarr() -> bool:
    """True if the tensorstore-backed zarr path is available (SURVEY §7: the
    TPU-native checkpoint/data store; no reference equivalent)."""
    return _HAS_ZARR


if _HAS_ZARR:
    __all__.extend(["load_zarr", "save_zarr"])

    def _zarr_spec(path: str) -> dict:
        return {"driver": "zarr", "kvstore": {"driver": "file", "path": os.path.abspath(path)}}

    def save_zarr(data: DNDarray, path: str) -> None:
        """Write a DNDarray to a zarr store with chunking aligned to the shard grid —
        every device buffer streams to its own chunk files, the cloud-native form of
        the reference's per-rank HDF5 hyperslabs (``io.py:211-238``). Under
        multi-controller, process 0 creates the store, then every process writes its
        own addressable chunks concurrently (chunk-aligned writes need no locking)."""
        if not isinstance(data, DNDarray):
            raise TypeError(f"data must be a DNDarray, not {type(data)}")
        np_dtype = np.dtype(data.dtype.jax_type())
        # chunk shape = the canonical FIRST shard chunk (identical on every rank,
        # including ragged splits where later shards are smaller)
        _, lshape, _ = data.comm.chunk(data.gshape, data.split, rank=0)
        chunk_shape = [max(1, int(s)) for s in lshape]

        def _create_store():
            return _ts.open(
                _zarr_spec(path),
                create=True,
                delete_existing=True,
                dtype=_ts.dtype(np_dtype),
                shape=list(data.gshape),
                chunk_layout=_ts.ChunkLayout(chunk_shape=chunk_shape),
            ).result()

        if data.split is None:
            value = data.numpy()
            if _is_writer():
                _create_store()[...] = value
            return
        if data.larray.is_fully_addressable:
            store = _create_store()
        else:
            # multi-controller: only process 0 creates/deletes; everyone then opens
            # the existing store and streams its own shard chunks
            from jax.experimental import multihost_utils

            if _is_writer():
                _create_store()
            multihost_utils.sync_global_devices(f"heat_tpu.save_zarr:{path}")
            store = _ts.open(_zarr_spec(path)).result()
        futures = [
            store[index].write(np.asarray(value))
            for index, value in data.iter_shards()
        ]
        for f in futures:
            f.result()

    def load_zarr(
        path: str,
        dtype=None,
        split: Optional[int] = None,
        device=None,
        comm=None,
    ) -> DNDarray:
        """Load a zarr store; each process reads only its addressable shard chunks."""
        comm = sanitize_comm(comm)
        store = _ts.open(_zarr_spec(path)).result()
        gshape = tuple(store.shape)
        np_dtype = np.dtype(store.dtype.numpy_dtype) if dtype is None else np.dtype(
            types.canonical_heat_type(dtype).jax_type()
        )
        if split is None or comm.size == 1:
            arr = np.asarray(store.read().result(), dtype=np_dtype)
            return factories.array(arr, dtype=dtype, split=split, device=device, comm=comm)

        class _Reader:
            def __getitem__(self, idx):
                return np.asarray(store[idx].read().result(), dtype=np_dtype)

        value = _sharded_read(_Reader(), gshape, np_dtype, split, comm)
        return factories.array(value, dtype=dtype, split=split, device=device, comm=comm)


if _HAS_HDF5:
    __all__.extend(["load_hdf5", "save_hdf5"])

    def load_hdf5(
        path: str,
        dataset: str,
        dtype=types.float32,
        load_fraction: float = 1.0,
        split: Optional[int] = None,
        device=None,
        comm=None,
    ) -> DNDarray:
        """Load an HDF5 dataset (reference ``io.py:58``): every host reads only the
        hyperslabs of the shards it addresses."""
        if not isinstance(path, str):
            raise TypeError(f"path must be str, not {type(path)}")
        if not isinstance(dataset, str):
            raise TypeError(f"dataset must be str, not {type(dataset)}")
        if not isinstance(load_fraction, float):
            raise TypeError(f"load_fraction must be float, not {type(load_fraction)}")
        if not 0.0 < load_fraction <= 1.0:
            raise ValueError(f"load_fraction must be in (0, 1], got {load_fraction}")
        comm = sanitize_comm(comm)
        dtype = types.canonical_heat_type(dtype)
        np_dtype = np.dtype(dtype.jax_type())
        with h5py.File(path, "r") as handle:
            data = handle[dataset]
            gshape = tuple(data.shape)
            if load_fraction < 1.0 and split == 0:
                gshape = (int(gshape[0] * load_fraction),) + gshape[1:]
            if split is None or comm.size == 1:
                arr = np.asarray(data[tuple(slice(0, s) for s in gshape)], dtype=np_dtype)
                return factories.array(arr, dtype=dtype, split=split, device=device, comm=comm)
            value = _sharded_read(data, gshape, np_dtype, split, comm)
        return factories.array(value, dtype=dtype, split=split, device=device, comm=comm)

    def save_hdf5(data: DNDarray, path: str, dataset: str, mode: str = "w", **kwargs) -> None:
        """Save to an HDF5 dataset (reference ``io.py:167-238``): per-shard hyperslab
        writes. Multi-controller jobs serialize rank-by-rank like the reference's
        no-MPI-IO path — each process writes only its addressable shards; the global
        array is never gathered."""
        if not isinstance(data, DNDarray):
            raise TypeError(f"data must be a DNDarray, not {type(data)}")
        if not isinstance(path, str):
            raise TypeError(f"path must be str, not {type(path)}")
        if mode not in _VALID_WRITE_MODES:
            raise ValueError(f"mode was {mode}, not in possible modes {_VALID_WRITE_MODES}")
        np_dtype = np.dtype(data.dtype.jax_type())
        if not data.parray.is_fully_addressable:
            # process 0 creates the dataset, then serialized per-process slab rounds
            if _is_writer():
                with h5py.File(path, mode) as handle:
                    handle.create_dataset(dataset, data.gshape, dtype=np_dtype, **kwargs)
            _writer_barrier(f"save_hdf5:create:{path}")

            def write_my_shards():
                with h5py.File(path, "r+") as handle:
                    dset = handle[dataset]
                    for index, value in data.iter_shards():
                        dset[index] = np.asarray(value)

            _serialized_shard_write(f"save_hdf5:{path}", write_my_shards)
            return
        def write_file(target_path: str, file_mode: str) -> None:
            with h5py.File(target_path, file_mode) as handle:
                dset = handle.create_dataset(dataset, data.gshape, dtype=np_dtype, **kwargs)
                if data.split is None:
                    dset[...] = np.asarray(data.larray)
                else:
                    for index, value in data.iter_shards():
                        dset[index] = np.asarray(value)

        if mode == "w":
            # whole-file write: assembled at a temp path and committed with one
            # rename, retried under the io.save_hdf5 policy — a crashed or
            # injected-fault save never leaves a torn .h5 behind
            resilience.atomic_write(
                path, lambda tmp: write_file(tmp, "w"), site="io.save_hdf5"
            )
        else:
            _guarded_write("io.save_hdf5", write_file, path, mode)


def _netcdf_has_fancy_keys(file_slices) -> bool:
    """True when ``file_slices`` contains anything but plain forward slices /
    Ellipsis — such keys take the whole-variable write path. Decidable without
    opening the file, so multi-controller jobs can pick their collective path
    consistently BEFORE any serialized per-process round."""
    keys = file_slices if isinstance(file_slices, tuple) else (file_slices,)
    return any(
        not (k is Ellipsis or (isinstance(k, slice) and (k.step is None or k.step > 0)))
        for k in keys
    )


def _compose_netcdf_slices(file_slices, gshape, var_shape, unlimited):
    """Resolve ``file_slices`` into one ``range`` per variable dimension mapping
    data indices to file indices, or ``None`` when the keys cannot address the
    data per-shard (fancy keys, extent mismatch, or overrun of a LIMITED
    dimension). Unlimited dimensions may address past the current extent — that
    is the append."""
    nd = len(var_shape)
    if len(gshape) != nd:
        return None  # dim-count mismatch (e.g. 1-d data into a 2-d variable)
    if not isinstance(file_slices, tuple):
        file_slices = (file_slices,)
    if Ellipsis in file_slices:
        i = file_slices.index(Ellipsis)
        fill = nd - (len(file_slices) - 1)
        file_slices = file_slices[:i] + (slice(None),) * fill + file_slices[i + 1 :]
    file_slices = file_slices + (slice(None),) * (nd - len(file_slices))
    if len(file_slices) != nd or _netcdf_has_fancy_keys(file_slices):
        return None
    ranges = []
    for d, (fs, vs) in enumerate(zip(file_slices, var_shape)):
        step = fs.step if fs.step is not None else 1
        start = fs.start if fs.start is not None else 0
        if start < 0:
            start += vs
        if fs.stop is None:
            if unlimited[d]:
                # cover the data extent exactly; on an unlimited dimension this
                # may grow the file — that is the append
                stop = start + step * gshape[d]
            else:
                # numpy/netCDF semantics: an omitted stop on a limited dimension
                # addresses the WHOLE remaining extent. If the existing variable
                # is larger than the data, the length check below rejects the
                # keys, so the caller raises the explicit extent-mismatch error
                # instead of silently prefix-writing (ADVICE r5 #5; plain
                # netCDF4 assignment would raise a broadcast error here too).
                stop = vs
        else:
            stop = fs.stop + vs if fs.stop < 0 else fs.stop
        rng = range(start, stop, step)
        if len(rng) != gshape[d]:
            return None  # keys must address exactly the data's extent
        if not unlimited[d] and rng and rng[-1] >= vs:
            return None  # writing past the end of a limited dimension
        ranges.append(rng)
    return ranges


if _HAS_NETCDF:
    __all__.extend(["load_netcdf", "save_netcdf"])

    def load_netcdf(
        path: str,
        variable: str,
        dtype=types.float32,
        split: Optional[int] = None,
        device=None,
        comm=None,
    ) -> DNDarray:
        """Load a NetCDF variable (reference ``io.py:284``)."""
        comm = sanitize_comm(comm)
        dtype = types.canonical_heat_type(dtype)
        np_dtype = np.dtype(dtype.jax_type())
        with nc.Dataset(path, "r") as handle:
            data = handle.variables[variable]
            gshape = tuple(data.shape)
            if split is None or comm.size == 1:
                arr = np.asarray(data[...], dtype=np_dtype)
                return factories.array(arr, dtype=dtype, split=split, device=device, comm=comm)
            # per-shard hyperslab reads, same treatment as HDF5 (reference io.py:444)
            value = _sharded_read(data, gshape, np_dtype, split, comm)
        return factories.array(value, dtype=dtype, split=split, device=device, comm=comm)

    def save_netcdf(
        data: DNDarray,
        path: str,
        variable: str,
        mode: str = "w",
        dimension_names=None,
        is_unlimited: bool = False,
        file_slices=slice(None),
        **kwargs,
    ) -> None:
        """Save to a NetCDF variable (reference ``io.py:367-571``).

        Writes are per-shard hyperslabs through ``iter_shards`` — never a global
        gather; multi-controller jobs serialize rank-by-rank
        (:func:`_serialized_shard_write`). Append semantics match the reference:
        ``mode='a'/'r+'`` reuses an existing variable, ``is_unlimited`` creates
        every new dimension unlimited, and ``file_slices`` addresses the region
        written — e.g. ``ht.save_netcdf(x, p, "v", mode="r+",
        file_slices=slice(n, n + len(x)))`` grows an unlimited record dimension.
        """
        if not isinstance(data, DNDarray):
            raise TypeError(f"data must be a DNDarray, not {type(data)}")
        if not isinstance(path, str):
            raise TypeError(f"path must be str, not {type(path)}")
        if not isinstance(variable, str):
            raise TypeError(f"variable must be str, not {type(variable)}")
        if mode not in _VALID_WRITE_MODES:
            raise ValueError(f"mode was {mode}, not in possible modes {_VALID_WRITE_MODES}")
        if dimension_names is None:
            dimension_names = [f"{variable}_dim_{i}" for i in range(data.ndim)]
        elif isinstance(dimension_names, str):
            dimension_names = [dimension_names]
        elif isinstance(dimension_names, tuple):
            dimension_names = list(dimension_names)
        elif not isinstance(dimension_names, list):
            raise TypeError(
                f"dimension_names must be list or tuple or string, not {type(dimension_names)}"
            )
        if len(dimension_names) != data.ndim:
            raise ValueError(
                f"{len(dimension_names)} names given for {data.ndim} dimensions"
            )
        np_dtype = np.dtype(data.dtype.jax_type())

        def _ensure_variable(handle):
            if variable in handle.variables:
                return handle.variables[variable]
            for name, size in zip(dimension_names, data.gshape):
                if name not in handle.dimensions:
                    handle.createDimension(name, None if is_unlimited else size)
            return handle.createVariable(variable, np_dtype, tuple(dimension_names), **kwargs)

        def _shard_writes(handle, ranges):
            var = handle.variables[variable]
            for index, value in data.iter_shards():
                key = tuple(
                    slice(r[sl.start], r[sl.stop - 1] + r.step, r.step)
                    for r, sl in zip(ranges, index)
                )
                var[key] = np.asarray(value)

        fancy = _netcdf_has_fancy_keys(file_slices)
        if not data.parray.is_fully_addressable:
            # multi-controller. Pick the path by conditions every process evaluates
            # identically (fancy keys / unsplit data / file geometry read by all),
            # because data.numpy() is a cross-host collective and must never run
            # inside a one-process-at-a-time serialized round.
            if data.split is None or fancy:
                value = data.numpy()  # collective: all processes participate
                if _is_writer():
                    with nc.Dataset(path, mode) as handle:
                        var = _ensure_variable(handle)
                        var[file_slices] = value
                _writer_barrier(f"save_netcdf:{path}")
                return
            if _is_writer():
                with nc.Dataset(path, mode) as handle:
                    _ensure_variable(handle)
            _writer_barrier(f"save_netcdf:create:{path}")
            # every process reads the (now existing) variable's geometry and
            # resolves the same ranges, so every process takes the same branch
            with nc.Dataset(path, "r") as handle:
                var = handle.variables[variable]
                var_shape = tuple(var.shape)
                unlimited = [handle.dimensions[d].isunlimited() for d in var.dimensions]
            if len(data.gshape) != len(var_shape):
                # dim-count mismatch: netCDF broadcast semantics need the whole value
                value = data.numpy()  # collective — uniform decision from the file
                if _is_writer():
                    with nc.Dataset(path, "r+") as handle:
                        handle.variables[variable][file_slices] = value
                _writer_barrier(f"save_netcdf:{path}")
                return
            ranges = _compose_netcdf_slices(file_slices, data.gshape, var_shape, unlimited)
            if ranges is None:
                raise ValueError(
                    f"file_slices {file_slices!r} do not address the data extent "
                    f"{data.gshape} within the variable's dimensions"
                )

            def write_my_shards():
                with nc.Dataset(path, "r+") as handle:
                    _shard_writes(handle, ranges)

            _serialized_shard_write(f"save_netcdf:{path}", write_my_shards)
            return

        def write_single_controller() -> None:
            with nc.Dataset(path, mode) as handle:
                var = _ensure_variable(handle)
                unlimited = [handle.dimensions[d].isunlimited() for d in var.dimensions]
                ranges = _compose_netcdf_slices(file_slices, data.gshape, var.shape, unlimited)
                if fancy or len(data.gshape) != len(var.shape):
                    # fancy keys or netCDF broadcast across a dim-count mismatch:
                    # one whole-variable write of the logical value
                    var[file_slices] = data.numpy()
                elif ranges is None:
                    # plain slices that don't address the data: same error as the
                    # multi-controller path (never a silent broadcast)
                    raise ValueError(
                        f"file_slices {file_slices!r} do not address the data extent "
                        f"{data.gshape} within the variable's dimensions"
                    )
                elif data.split is None:
                    var[tuple(slice(r.start, r.stop, r.step) for r in ranges)] = (
                        np.asarray(data.larray)
                    )
                else:
                    _shard_writes(handle, ranges)

        _guarded_write("io.save_netcdf", write_single_controller)


def load_csv(
    path: str,
    header_lines: int = 0,
    sep: str = ",",
    dtype=types.float32,
    encoding: str = "utf-8",
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Load a CSV file with byte-offset chunked parsing (reference ``io.py:723``).

    A binary newline scan over an ``mmap`` of the file (no resident copy — the OS pages
    the scan through) indexes the row offsets; each shard's rows are then located by
    the canonical :meth:`Communication.chunk` rule and only that byte range is decoded
    and parsed — parsing, the dominant cost, happens per-shard like the HDF5
    hyperslab reads.
    """
    import mmap

    if not isinstance(path, str):
        raise TypeError(f"path must be str, not {type(path)}")
    if not isinstance(sep, str):
        raise TypeError(f"separator must be str, not {type(sep)}")
    if not isinstance(header_lines, int):
        raise TypeError(f"header_lines must be int, not {type(header_lines)}")
    comm = sanitize_comm(comm)
    dtype = types.canonical_heat_type(dtype)
    np_dtype = np.dtype(dtype.jax_type())

    # pass 1: index line start offsets (binary newline scan, no parsing)
    with open(path, "rb") as fh:
        try:
            # POSIX: the mapping outlives the closed descriptor
            blob = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ)
        except ValueError:  # empty file cannot be mmapped
            blob = b""
    offsets = [0]
    pos = blob.find(b"\n")
    while pos != -1:
        offsets.append(pos + 1)
        pos = blob.find(b"\n", pos + 1)
    if offsets[-1] >= len(blob):  # trailing newline → no final partial row
        offsets.pop()
    offsets.append(len(blob))
    # data rows: skip headers, drop blank lines anywhere (np.genfromtxt semantics)
    row_starts, row_ends = [], []
    for s, e in zip(offsets[header_lines:-1], offsets[header_lines + 1 :]):
        if blob[s:e].strip():
            row_starts.append(s)
            row_ends.append(e)
    nrows = len(row_starts)
    if nrows == 0:
        return factories.array(
            np.empty((0,), dtype=np_dtype), dtype=dtype, split=split, device=device, comm=comm
        )

    def parse_rows(lo: int, hi: int) -> np.ndarray:
        chunk = blob[row_starts[lo] : row_ends[hi - 1]].decode(encoding)
        fields = [line.split(sep) for line in chunk.splitlines() if line.strip()]
        return np.asarray(fields, dtype=np_dtype)

    ncols = len(blob[row_starts[0] : row_ends[0]].decode(encoding).split(sep))
    gshape: Tuple[int, ...] = (nrows,) if ncols == 1 else (nrows, ncols)

    if split != 0 or comm.size == 1:
        arr = parse_rows(0, nrows).reshape(gshape)
        return factories.array(arr, dtype=dtype, split=split, device=device, comm=comm)

    # split=0: each shard decodes+parses only its own byte range, straight into its
    # device buffer (reference io.py:780-905) — host memory stays O(local rows)
    class _RowReader:
        def __getitem__(self, idx):
            row_sl = idx[0]
            lo, hi = row_sl.start or 0, row_sl.stop if row_sl.stop is not None else nrows
            block = parse_rows(lo, hi).reshape((hi - lo,) + gshape[1:])
            return block[(slice(None),) + tuple(idx[1:])]

    value = _sharded_read(_RowReader(), gshape, np_dtype, 0, comm)
    return factories.array(value, dtype=dtype, split=split, device=device, comm=comm)


def save_csv(
    data: DNDarray,
    path: str,
    header_lines: Optional[List[str]] = None,
    sep: str = ",",
    decimals: int = -1,
    truncate: bool = True,
    **kwargs,
) -> None:
    """Save to CSV (reference ``io.py:949``)."""
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be a DNDarray, not {type(data)}")
    if data.ndim > 2:
        raise ValueError("CSV can only store 1-D or 2-D arrays")
    arr = data.numpy()
    if _is_writer():
        if decimals >= 0:
            fmt = f"%.{decimals}f"
        elif np.issubdtype(arr.dtype, np.integer):
            fmt = "%d"
        else:
            fmt = "%.18e"
        header = "\n".join(header_lines) if header_lines else ""
        resilience.atomic_write(
            path,
            lambda tmp: np.savetxt(
                tmp, arr.reshape(arr.shape[0], -1), delimiter=sep, fmt=fmt,
                header=header, comments="",
            ),
            site="io.save_csv",
        )
    _writer_barrier(f"save_csv:{path}")


def load_npy(path: str, dtype=None, split: Optional[int] = None, device=None, comm=None) -> DNDarray:
    """Load a .npy file (reference ``load_npy_from_path`` ``io.py:612``)."""
    arr = np.load(path)
    return factories.array(arr, dtype=dtype, split=split, device=device, comm=comm)


def save_npy(data: DNDarray, path: str) -> None:
    """Save to a .npy file (atomic: temp + fsync + rename, policy-retried)."""
    arr = data.numpy()
    if _is_writer():

        def write(tmp: str) -> None:
            # np.save(path) would append ".npy" to the temp name; write the
            # stream through an explicit handle so the rename target is exact
            with open(tmp, "wb") as fh:
                np.save(fh, arr)

        resilience.atomic_write(path, write, site="io.save_npy")
    _writer_barrier(f"save_npy:{path}")


def load(path: str, *args, **kwargs) -> DNDarray:
    """Load by file extension (reference ``io.py:672``)."""
    if not isinstance(path, str):
        raise TypeError(f"expected path to be str, but was {type(path)}")
    extension = os.path.splitext(path)[-1].strip().lower()
    if extension in (".h5", ".hdf5"):
        if not supports_hdf5():
            raise RuntimeError(f"hdf5 is required for file extension {extension}")
        return load_hdf5(path, *args, **kwargs)
    if extension in (".nc", ".nc4", ".netcdf"):
        if not supports_netcdf():
            raise RuntimeError(f"netcdf is required for file extension {extension}")
        return load_netcdf(path, *args, **kwargs)
    if extension in (".csv", ".txt"):
        return load_csv(path, *args, **kwargs)
    if extension == ".npy":
        return load_npy(path, *args, **kwargs)
    if extension == ".zarr":
        if not supports_zarr():
            raise RuntimeError(f"tensorstore is required for file extension {extension}")
        return load_zarr(path, *args, **kwargs)
    raise ValueError(f"unsupported file extension {extension}")


def save(data: DNDarray, path: str, *args, **kwargs) -> None:
    """Save by file extension (reference ``io.py:1083``)."""
    if not isinstance(path, str):
        raise TypeError(f"expected path to be str, but was {type(path)}")
    extension = os.path.splitext(path)[-1].strip().lower()
    if extension in (".h5", ".hdf5"):
        if not supports_hdf5():
            raise RuntimeError(f"hdf5 is required for file extension {extension}")
        return save_hdf5(data, path, *args, **kwargs)
    if extension in (".nc", ".nc4", ".netcdf"):
        if not supports_netcdf():
            raise RuntimeError(f"netcdf is required for file extension {extension}")
        return save_netcdf(data, path, *args, **kwargs)
    if extension in (".csv", ".txt"):
        return save_csv(data, path, *args, **kwargs)
    if extension == ".npy":
        return save_npy(data, path)
    if extension == ".zarr":
        if not supports_zarr():
            raise RuntimeError(f"tensorstore is required for file extension {extension}")
        return save_zarr(data, path, *args, **kwargs)
    raise ValueError(f"unsupported file extension {extension}")
