"""Pallas TPU kernels for hot ops where fusion beyond XLA's defaults pays.

Each kernel ships with a pure-jnp reference implementation used (a) as the fallback on
non-TPU backends and (b) by the tests to validate the kernel in interpreter mode.
"""

from .kmeans import fused_assign_update, fused_assign_update_reference

__all__ = ["fused_assign_update", "fused_assign_update_reference"]
