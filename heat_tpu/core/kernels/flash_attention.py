"""Flash-attention Pallas kernel (TPU).

The XLA blockwise path in ``heat_tpu/nn/attention.py`` materialises the (T, T)
score matrix in HBM — at T=4096, B·H=128 that is ~8 GB of f32 traffic and the op
runs HBM-bound at a few TFLOP/s. This kernel streams k/v through VMEM with the
standard online-softmax recurrence: for each query block the k/v blocks are visited
sequentially, the (bq, bk) score tile lives only in VMEM, and the rescaled output
accumulator is written to HBM once. Causal masking skips whole k-blocks above the
diagonal (the loop's trip count is data-independent per q-block, so the causal
kernel does ~half the work instead of masking all of it).

Backward: ``flash_attention`` carries a ``jax.custom_vjp`` whose backward pass
recomputes attention with the XLA dense path and differentiates that — numerically
identical gradients (both are exact softmax attention), with the forward getting
the flash memory profile. (A fused Pallas backward is a further optimisation, not
a semantics change.)

No reference counterpart: the reference has no attention at all (SURVEY §2.4);
this is TPU-first machinery for the long-context story.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["flash_attention", "flash_attention_reference"]

_NEG_INF = float(jnp.finfo(jnp.float32).min)

_BQ = 512
_BK = 512


def flash_attention_reference(q, k, v, causal: bool = False, scale=None):
    """Pure-jnp exact attention (f32 accumulation) — the parity oracle."""
    d = q.shape[-1]
    s = (1.0 / math.sqrt(d)) if scale is None else scale
    scores = jnp.einsum("...qd,...kd->...qk", q, k, preferred_element_type=jnp.float32)
    scores = scores * jnp.float32(s)
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        scores = jnp.where(mask, scores, _NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("...qk,...kd->...qd", p, v, preferred_element_type=jnp.float32)
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def _kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, causal: bool, bk: int,
            compute_dtype=None):
    import jax.experimental.pallas as pl

    iq = pl.program_id(1)
    bq, d = q_ref.shape[1], q_ref.shape[2]
    tk = k_ref.shape[1]
    nkb = tk // bk

    cdt = compute_dtype or q_ref.dtype
    q = q_ref[0].astype(cdt)  # (bq, d)
    q_row0 = iq * bq

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)

    def body(j, carry):
        acc, m, l = carry
        kb = k_ref[0, pl.ds(j * bk, bk), :].astype(cdt)  # (bk, d)
        vb = v_ref[0, pl.ds(j * bk, bk), :].astype(cdt)
        s = (
            lax.dot_general(q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
            * scale
        )  # (bq, bk) f32
        if causal:
            rows = q_row0 + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_blk = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        # probabilities ride the MXU in the value dtype (standard flash practice;
        # p ∈ [0,1] so the bf16 round-off is bounded), accumulation stays f32
        acc_new = acc * corr + lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    # causal: only k-blocks intersecting [0, q_row0 + bq) contribute; the trip
    # count depends only on the grid position, so whole above-diagonal blocks
    # are skipped rather than masked
    upper = jnp.minimum((q_row0 + bq + bk - 1) // bk, nkb) if causal else nkb
    acc, m, l = lax.fori_loop(0, upper, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "bq", "bk", "interpret", "compute_dtype")
)
def _flash_pallas(q, k, v, causal: bool, scale: float, bq: int, bk: int,
                  interpret: bool = False, compute_dtype=None):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    with jax.enable_x64(False):
        *batch, tq, d = q.shape
        tk = k.shape[-2]
        bh = math.prod(batch) if batch else 1
        qr = q.reshape(bh, tq, d)
        kr = k.reshape(bh, tk, d)
        vr = v.reshape(bh, tk, d)

        out = pl.pallas_call(
            functools.partial(_kernel, scale=scale, causal=causal, bk=bk,
                              compute_dtype=compute_dtype),
            grid=(bh, tq // bq),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec(
                (1, bq, d), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM
            ),
            out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            interpret=interpret,
        )(qr, kr, vr)
        return out.reshape(*batch, tq, d)


def _fits(q, k, bq: int, bk: int) -> bool:
    """VMEM gate: resident = q/o blocks (f32) + full k and v (input dtype) +
    score/prob tiles. Shapes must also tile evenly (pad upstream if not)."""
    tq, d = q.shape[-2], q.shape[-1]
    tk = k.shape[-2]
    if tq % bq or tk % bk:
        return False
    itemsize = jnp.dtype(q.dtype).itemsize
    resident = 4 * (3 * bq * d + 3 * bq * bk) + 2 * tk * d * itemsize
    return resident <= 10 * 2**20


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = False, scale=None):
    """Exact attention with the flash (streaming-VMEM) forward on TPU.

    q: (..., Tq, D), k/v: (..., Tk, D); Tq/Tk must be multiples of the 512-block
    (callers fall back to the XLA path otherwise via :func:`use_flash`).
    """
    s = (1.0 / math.sqrt(q.shape[-1])) if scale is None else scale
    # f32 compute wins on this shape class: at head_dim 64 the kernel is VPU-bound
    # (exp + rescale on (bq,bk) tiles), and bf16 MXU passes don't pay for the extra
    # relayouts (measured 17.3 vs 15.0 TFLOP/s at b8·h16·t4096·d64 on v5e, 3× the
    # jax.experimental.pallas.ops.tpu library kernel on the same workload)
    return _flash_pallas(q, k, v, causal, float(s), _BQ, _BK, compute_dtype=jnp.float32)


def _fwd(q, k, v, causal, scale):
    return flash_attention(q, k, v, causal, scale), (q, k, v)


def _bwd(causal, scale, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: flash_attention_reference(q_, k_, v_, causal, scale), q, k, v
    )
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)


def use_flash(q, k, v, mask, interpret: bool = False) -> bool:
    """True when the Pallas forward applies: TPU backend, no explicit mask, a
    Mosaic-supported dtype, and shapes that fit the VMEM budget/tiling."""
    if mask is not None:
        return False
    # f64 inputs (legal framework-wide: x64 is enabled globally) must take the XLA
    # path — the kernel computes under enable_x64(False) and can't store to an f64 ref
    supported = (jnp.float32, jnp.bfloat16, jnp.float16)
    if any(t.dtype not in supported for t in (q, k, v)):
        return False
    if not interpret and jax.default_backend() != "tpu":
        return False
    return _fits(q, k, _BQ, _BK)
