"""Flash-attention Pallas kernel (TPU).

The XLA blockwise path in ``heat_tpu/nn/attention.py`` materialises the (T, T)
score matrix in HBM — at T=4096, B·H=128 that is ~8 GB of f32 traffic and the op
runs HBM-bound at a few TFLOP/s. This kernel streams k/v through VMEM with the
standard online-softmax recurrence: for each query block the k/v blocks are visited
sequentially, the (bq, bk) score tile lives only in VMEM, and the rescaled output
accumulator is written to HBM once. Causal masking skips whole k-blocks above the
diagonal (the loop's trip count is data-independent per q-block, so the causal
kernel does ~half the work instead of masking all of it).

Backward: the ``jax.custom_vjp`` backward is also Pallas — the forward saves the
(O, LSE) residuals, ``_dq_kernel`` streams k/v per query block and ``_dkv_kernel``
streams q/dO per key block, each recomputing its probability tile from the LSE
(the standard flash backward). Neither direction materialises the (T, T) matrix
in HBM.

No reference counterpart: the reference has no attention at all (SURVEY §2.4);
this is TPU-first machinery for the long-context story.
"""

from __future__ import annotations

import functools
import os
import math

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["flash_attention", "flash_attention_reference"]

_NEG_INF = float(jnp.finfo(jnp.float32).min)

# Forward tile-size preference, per input itemsize: the (bq, bk) score/probability
# tiles are f32 regardless of input dtype (2 × 4·bq·bk bytes resident), so f32
# inputs take a smaller tile. Measured on v5e at b8·h16·t4096·d64: larger bk
# amortizes the per-step softmax-state update — (1024, 1024) bf16 is ~1.6× faster
# than (512, 512). Shapes that only divide 512 fall back to 512-blocks rather than
# losing the flash path entirely.
_FWD_BLOCK_PREFS = {
    2: ((1024, 1024), (512, 1024), (1024, 512), (512, 512)),
    4: ((512, 1024), (512, 512)),
}
_BWD_BQ = 512
_BWD_BK = 512
# scalar-prefetch schedule bound: the flattened pair list is O((T/b)²) int32
# entries shipped to SMEM — cap it well below SMEM capacity
_MAX_PAIRS = 8192


def _env_vmem_limit():
    """HEAT_TPU_FLASH_VMEM_LIMIT in bytes, or None when unset, malformed, or not
    positive (graceful degradation, like _env_blocks — a bad value must not take
    down every attention dispatch)."""

    raw = os.environ.get("HEAT_TPU_FLASH_VMEM_LIMIT")  # ht: ignore[trace-env-read] -- documented trace-time tuning knob (see docstring): kernel block geometry is necessarily a compile-time constant; re-tune in a fresh process
    if not raw:
        return None
    try:
        v = int(raw.strip())
    except ValueError:
        return None
    return v if v > 0 else None


def _compiler_params(pltpu):
    """Mosaic params shared by all three kernels: the batch·head grid dim is
    embarrassingly parallel (no state crosses it), the pair dim is a sequential
    sweep (softmax/accumulator state carries across it). Marking them lets the
    compiler reorder/parallelise batch steps instead of assuming a serial grid.
    ``HEAT_TPU_FLASH_VMEM_LIMIT`` (bytes) lifts the VMEM budget for block-size
    experiments on real hardware."""
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "arbitrary"),
        vmem_limit_bytes=_env_vmem_limit(),
    )


def _env_blocks(default_bq: int, default_bk: int):
    """Block-size override for on-chip tuning: HEAT_TPU_FLASH_BLOCKS=\"bq,bk\".

    Read at TRACE time: jit caches by shape/dtype, so changing the env between
    same-shape calls in one process reuses the first compilation — run each
    config in a fresh process (or clear jax caches) when sweeping."""

    spec = os.environ.get("HEAT_TPU_FLASH_BLOCKS")  # ht: ignore[trace-env-read] -- documented trace-time tuning knob (see docstring): kernel block geometry is necessarily a compile-time constant; re-tune in a fresh process
    if not spec:
        return default_bq, default_bk
    try:
        bq, bk = (int(x) for x in spec.split(","))
    except ValueError:
        return default_bq, default_bk
    if bq <= 0 or bk <= 0:
        return default_bq, default_bk
    return bq, bk


def _fwd_blocks(dtype, tq: int, tk: int, with_bias: bool = False) -> tuple:
    """Largest preferred (bq, bk) that tiles (tq, tk) evenly, else the smallest
    preference (whose divisibility _fits re-checks and may reject). A streamed
    bias adds a double-buffered f32 (bq, bk) block, so biased bf16 runs use the
    smaller f32 tile preferences."""
    size = 4 if (with_bias or _pipeline_enabled()) else jnp.dtype(dtype).itemsize
    # (the pipelined kernel keeps an extra f32 (bq, bk) score buffer resident, so
    # it takes the smaller-tile preference table like biased runs do)
    prefs = _FWD_BLOCK_PREFS.get(size, ((512, 512),))
    ebq, ebk = _env_blocks(0, 0)
    if ebq and tq % ebq == 0 and tk % ebk == 0:  # on-chip tuning override
        return ebq, ebk
    for bq, bk in prefs:
        if tq % bq == 0 and tk % bk == 0:
            return bq, bk
    return prefs[-1]


def flash_attention_reference(q, k, v, causal: bool = False, scale=None):
    """Pure-jnp exact attention (f32 accumulation) — the parity oracle."""
    d = q.shape[-1]
    s = (1.0 / math.sqrt(d)) if scale is None else scale
    scores = jnp.einsum("...qd,...kd->...qk", q, k, preferred_element_type=jnp.float32)
    scores = scores * jnp.float32(s)
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        scores = jnp.where(mask, scores, _NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("...qk,...kd->...qd", p, v, preferred_element_type=jnp.float32)
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def _online_softmax_update(s, vb, acc_ref, m_ref, l_ref, has_bias: bool):
    """One tile of the online-softmax recurrence, shared by the plain and
    pipelined forward kernels (a numerical change here reaches both)."""
    m = m_ref[...]
    m_blk = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m, m_blk)
    # a bias can mask a whole row of the block (all -inf): keep the exps finite —
    # the row's l stays 0 and its output finalizes to 0 like the dense path
    m_safe = jnp.maximum(m_new, _NEG_INF / 2) if has_bias else m_new
    p_tile = jnp.exp(s - m_safe)
    corr = jnp.exp(m - m_safe)
    l_ref[...] = l_ref[...] * corr + jnp.sum(p_tile, axis=1, keepdims=True)
    # probabilities ride the MXU in the value dtype (standard flash practice;
    # p ∈ [0,1] so the bf16 round-off is bounded), accumulation stays f32
    acc_ref[...] = acc_ref[...] * corr + lax.dot_general(
        p_tile.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[...] = m_new


def _kernel(im_ref, jm_ref, flags_ref, q_ref, k_ref, v_ref, *refs,
            scale: float, bq: int, bk: int, has_bias: bool = False):
    """One (q-block, k-block) tile of the online-softmax recurrence.

    The grid is the *flattened list of contributing (i, j) pairs* (splash-style):
    for causal attention the blocks strictly above the diagonal are not idle grid
    steps — they simply aren't in the list, so the causal kernel really does half
    the steps. Scalar-prefetched maps give each step its (i, j); flags mark the
    first/last step of each q-row sweep (init / finalize) and whether the block
    straddles the diagonal (only those pay the iota/where mask — fully-below
    blocks skip it).

    Pallas double-buffers the k/v block DMA against compute because the kv pair
    index advances with the grid. MXU inputs stay in the input dtype (bf16 runs
    at full MXU rate — forcing f32 here quarters throughput); softmax state and
    the output accumulator are f32.
    """
    import jax.experimental.pallas as pl

    if has_bias:
        bias_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    else:
        o_ref, lse_ref, acc_ref, m_ref, l_ref = refs

    p = pl.program_id(1)
    d = q_ref.shape[2]
    flags = flags_ref[p]
    is_first, is_last, needs_mask = flags & 1, flags & 2, flags & 4

    @pl.when(is_first != 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0]  # (bq, d), input dtype
    kb = k_ref[0]
    vb = v_ref[0]
    s = (
        lax.dot_general(q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        * scale
    )  # (bq, bk) f32
    if has_bias:
        s = s + bias_ref[...]

    def _update(s):
        _online_softmax_update(s, vb, acc_ref, m_ref, l_ref, has_bias)

    # only diagonal-straddling blocks pay the iota/where mask; fully-below
    # blocks take the plain branch — pl.when predication, not a lane-wise select,
    # so the mask cost really is skipped for them
    @pl.when(needs_mask != 0)
    def _masked():
        rows = im_ref[p] * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = jm_ref[p] * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        _update(jnp.where(rows >= cols, s, _NEG_INF))

    @pl.when(needs_mask == 0)
    def _plain():
        _update(s)

    @pl.when(is_last != 0)
    def _finalize():
        l = l_ref[...]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # log-sum-exp residual for the backward pass: L = m + log(l); the clamp
        # keeps fully-masked rows finite so the backward's exp(s - L) is 0, not NaN
        lse_ref[0] = jnp.maximum(m_ref[...], _NEG_INF / 2) + jnp.log(jnp.maximum(l, 1e-30))


def _pipeline_enabled() -> bool:
    """HEAT_TPU_FLASH_PIPELINE=1 selects the one-step-skewed forward kernel: each
    grid step computes QK for pair p while running exp/PV for pair p−1 — the two
    chains share no data, so Mosaic's scheduler can issue the VPU exp pass
    concurrently with the MXU matmuls instead of serialising them (the overlap
    the ceiling analysis in doc/source/flash_attention_perf.rst identifies as the
    gap between the ~33 and ~49 TFLOP/s bounds). Off by default until measured
    on hardware; read at trace time (same caveat as _env_blocks)."""

    return os.environ.get("HEAT_TPU_FLASH_PIPELINE") == "1"  # ht: ignore[trace-env-read] -- documented trace-time tuning knob (see docstring): kernel block geometry is necessarily a compile-time constant; re-tune in a fresh process


def _kernel_pipelined(im_ref, jm_ref, flags_ref, q_ref, k_ref, v_ref, *refs,
                      scale: float, bq: int, bk: int, has_bias: bool = False):
    """One-step software pipeline over the flattened pair grid.

    Step p holds TWO independent chains: (a) exp + rescale + PV for the score
    tile the previous step left in ``s_ref`` (consumes the LAGGED v block the
    index map streams), and (b) the QK matmul for pair p, written to ``s_ref``
    afterwards. A flush step (flag bit 8) per q-row consumes the row's final
    tile and finalizes — it has no QK phase, so every step needs only one v
    block. ``s_prev`` is loaded before (b) overwrites the buffer."""
    import jax.experimental.pallas as pl

    if has_bias:
        bias_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, s_ref = refs
    else:
        o_ref, lse_ref, acc_ref, m_ref, l_ref, s_ref = refs

    p = pl.program_id(1)
    flags = flags_ref[p]
    is_first, is_last, is_flush = flags & 1, flags & 2, flags & 8
    p_prev = jnp.maximum(p - 1, 0)
    prev_mask = flags_ref[p_prev] & 4

    @pl.when(is_first != 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    s_prev = s_ref[...]  # loaded before this step's QK overwrites the buffer
    vb = v_ref[0]  # v block of the PREVIOUS pair (lagged index map)

    def _update(s):
        _online_softmax_update(s, vb, acc_ref, m_ref, l_ref, has_bias)

    @pl.when((is_first == 0) & (prev_mask != 0))
    def _prev_masked():
        rows = im_ref[p_prev] * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = jm_ref[p_prev] * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        _update(jnp.where(rows >= cols, s_prev, _NEG_INF))

    @pl.when((is_first == 0) & (prev_mask == 0))
    def _prev_plain():
        _update(s_prev)

    @pl.when(is_flush == 0)
    def _qk():
        s = (
            lax.dot_general(
                q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        if has_bias:
            s = s + bias_ref[...]
        s_ref[...] = s

    @pl.when(is_last != 0)
    def _finalize():
        l = l_ref[...]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse_ref[0] = jnp.maximum(m_ref[...], _NEG_INF / 2) + jnp.log(jnp.maximum(l, 1e-30))


def _pair_schedule_pipelined(nq: int, nk: int, bq: int, bk: int, causal: bool):
    """Derived from :func:`_pair_schedule` (single-sourced pair set): finalize
    (bit 2) moves off the real pairs onto one flush step (bits 2|8) appended per
    q-row. The flush's (i, j) repeats the row's last pair so the k/v index maps
    stay in range."""
    import numpy as np

    im, jm, flags = _pair_schedule(nq, nk, bq, bk, causal)
    out_im, out_jm, out_fl = [], [], []
    for i, j, f in zip(im.tolist(), jm.tolist(), flags.tolist()):
        out_im.append(i)
        out_jm.append(j)
        out_fl.append(f & ~2)
        if f & 2:  # last pair of the row: append its flush step
            out_im.append(i)
            out_jm.append(j)
            out_fl.append(2 | 8)
    return (
        np.asarray(out_im, np.int32),
        np.asarray(out_jm, np.int32),
        np.asarray(out_fl, np.int32),
    )


def _pair_schedule(nq: int, nk: int, bq: int, bk: int, causal: bool):
    """Flattened (i, j) visit list + per-step flag bits (1=first of row sweep,
    2=last of row sweep, 4=diagonal-straddling → mask). Causal keeps only blocks
    with any (row ≥ col); mask is needed only when the block's last col exceeds
    the block's first row."""
    im, jm, flags = [], [], []
    for i in range(nq):
        js = [
            j for j in range(nk)
            if not causal or j * bk <= i * bq + bq - 1
        ]
        for idx, j in enumerate(js):
            f = (1 if idx == 0 else 0) | (2 if idx == len(js) - 1 else 0)
            if causal and (j * bk + bk - 1 > i * bq):
                f |= 4
            im.append(i)
            jm.append(j)
            flags.append(f)
    import numpy as np

    return np.asarray(im, np.int32), np.asarray(jm, np.int32), np.asarray(flags, np.int32)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "bq", "bk", "interpret", "pipelined")
)
def _flash_pallas(q, k, v, causal: bool, scale: float, bq: int, bk: int,
                  interpret: bool = False, bias=None, pipelined: bool = False):
    import jax.experimental.pallas as pl  # ht: ignore[trace-lazy-import] -- pallas imports deferred so CPU-only processes never pay them; runs once per compile, imports nothing of heat_tpu
    from jax.experimental.pallas import tpu as pltpu  # ht: ignore[trace-lazy-import] -- pallas imports deferred so CPU-only processes never pay them; runs once per compile, imports nothing of heat_tpu

    with jax.enable_x64(False):
        *batch, tq, d = q.shape
        tk = k.shape[-2]
        bh = math.prod(batch) if batch else 1
        qr = q.reshape(bh, tq, d)
        kr = k.reshape(bh, tk, d)
        vr = v.reshape(bh, tk, d)
        has_bias = bias is not None

        schedule = _pair_schedule_pipelined if pipelined else _pair_schedule
        im, jm, flags = schedule(tq // bq, tk // bk, bq, bk, causal)
        npairs = len(im)

        if pipelined:
            # the exp/PV chain consumes the PREVIOUS pair's v block
            v_spec = pl.BlockSpec(
                (1, bk, d), lambda b, p, im, jm, fl: (b, jm[jnp.maximum(p - 1, 0)], 0)
            )
        else:
            v_spec = pl.BlockSpec((1, bk, d), lambda b, p, im, jm, fl: (b, jm[p], 0))
        in_specs = [
            pl.BlockSpec((1, bq, d), lambda b, p, im, jm, fl: (b, im[p], 0)),
            pl.BlockSpec((1, bk, d), lambda b, p, im, jm, fl: (b, jm[p], 0)),
            v_spec,
        ]
        inputs = [qr, kr, vr]
        if has_bias:
            # (Tq, Tk) additive bias, broadcast over batch/heads: one (bq, bk)
            # block streams per pair, like k/v
            in_specs.append(
                pl.BlockSpec((bq, bk), lambda b, p, im, jm, fl: (im[p], jm[p]))
            )
            inputs.append(bias.astype(jnp.float32))
        scratch_shapes = [
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ]
        if pipelined:
            scratch_shapes.append(pltpu.VMEM((bq, bk), jnp.float32))  # skewed scores
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(bh, npairs),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, bq, d), lambda b, p, im, jm, fl: (b, im[p], 0)),
                pl.BlockSpec((1, bq, 1), lambda b, p, im, jm, fl: (b, im[p], 0)),
            ],
            scratch_shapes=scratch_shapes,
        )
        kern = _kernel_pipelined if pipelined else _kernel
        out, lse = pl.pallas_call(
            functools.partial(kern, scale=scale, bq=bq, bk=bk, has_bias=has_bias),
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
                jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
            ],
            interpret=interpret,
            compiler_params=None if interpret else _compiler_params(pltpu),
        )(jnp.asarray(im), jnp.asarray(jm), jnp.asarray(flags), *inputs)
        return out.reshape(*batch, tq, d), lse.reshape(*batch, tq)


def _dq_kernel(im_ref, jm_ref, flags_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
               dd_ref, *refs, scale: float, bq: int, bk: int, has_bias: bool = False):
    """dq_i = Σ_j dS_ij · k_j · scale with dS = P ∘ (dO·Vᵀ − D).

    Streams k/v blocks over the same flattened (i, j) pair grid as the forward;
    the dq accumulator lives in VMEM scratch across each row sweep, so only
    O(bq·bk) is resident regardless of T."""
    import jax.experimental.pallas as pl

    if has_bias:
        bias_ref, dq_ref, acc_ref = refs
    else:
        dq_ref, acc_ref = refs

    p = pl.program_id(1)
    flags = flags_ref[p]
    is_first, is_last, needs_mask = flags & 1, flags & 2, flags & 4

    @pl.when(is_first != 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0]
    kb = k_ref[0]
    vb = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]  # (bq, 1)
    dd = dd_ref[0]
    s = (
        lax.dot_general(q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        * scale
    )
    if has_bias:
        s = s + bias_ref[...]

    def _update(s):
        p_tile = jnp.exp(s - lse)  # exact probabilities via the saved LSE
        dp = lax.dot_general(do, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = (p_tile * (dp - dd)).astype(kb.dtype)
        acc_ref[...] = acc_ref[...] + lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(needs_mask != 0)
    def _masked():
        rows = im_ref[p] * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = jm_ref[p] * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        _update(jnp.where(rows >= cols, s, _NEG_INF))

    @pl.when(needs_mask == 0)
    def _plain():
        _update(s)

    @pl.when(is_last != 0)
    def _finalize():
        dq_ref[0] = (acc_ref[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(jm_ref, im_ref, flags_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                dd_ref, *refs, scale: float, bq: int, bk: int,
                has_bias: bool = False):
    """dk_j = Σ_i dSᵀ_ij · q_i · scale,  dv_j = Σ_i Pᵀ_ij · dO_i.

    Streams q/dO/LSE blocks over a kv-major flattened (j, i) pair grid with the
    dk/dv accumulators in VMEM scratch — no full-panel residency."""
    import jax.experimental.pallas as pl

    if has_bias:
        bias_ref, dk_ref, dv_ref, dk_acc_ref, dv_acc_ref = refs
    else:
        dk_ref, dv_ref, dk_acc_ref, dv_acc_ref = refs

    p = pl.program_id(1)
    flags = flags_ref[p]
    is_first, is_last, needs_mask = flags & 1, flags & 2, flags & 4
    is_zero = flags & 8  # causal, Tk > Tq: no query attends this k-block

    @pl.when(is_first != 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    qb = q_ref[0]
    kb = k_ref[0]
    vb = v_ref[0]
    dob = do_ref[0]
    lse = lse_ref[0]  # (bq, 1)
    dd = dd_ref[0]
    s = (
        lax.dot_general(qb, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        * scale
    )
    if has_bias:
        s = s + bias_ref[...]

    def _update(s):
        p_tile = jnp.exp(s - lse)
        dp = lax.dot_general(dob, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = (p_tile * (dp - dd)).astype(qb.dtype)
        dk_acc_ref[...] = dk_acc_ref[...] + lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dv_acc_ref[...] = dv_acc_ref[...] + lax.dot_general(
            p_tile.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(needs_mask != 0)
    def _masked():
        rows = im_ref[p] * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = jm_ref[p] * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        _update(jnp.where(rows >= cols, s, _NEG_INF))

    @pl.when((needs_mask == 0) & (is_zero == 0))
    def _plain():
        _update(s)

    @pl.when(is_last != 0)
    def _finalize():
        dk_ref[0] = (dk_acc_ref[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _pair_schedule_kv(nq: int, nk: int, bq: int, bk: int, causal: bool):
    """kv-major visit list for the dk/dv kernel: for each k-block j, the q-blocks
    i that attend to it (all of them when not causal; those at or beyond the
    diagonal otherwise). Same flag bits as :func:`_pair_schedule`, plus bit 8 =
    no query attends this k-block (causal with Tk > Tq): the step only writes
    zero gradients — without it those output blocks would hold uninitialized
    memory, since an unvisited grid block is never written."""
    jm, im, flags = [], [], []
    for j in range(nk):
        is_ = [
            i for i in range(nq)
            if not causal or i * bq + bq - 1 >= j * bk
        ]
        if not is_:
            jm.append(j)
            im.append(0)
            flags.append(1 | 2 | 8)
            continue
        for idx, i in enumerate(is_):
            f = (1 if idx == 0 else 0) | (2 if idx == len(is_) - 1 else 0)
            if causal and (j * bk + bk - 1 > i * bq):
                f |= 4
            jm.append(j)
            im.append(i)
            flags.append(f)
    import numpy as np

    return np.asarray(jm, np.int32), np.asarray(im, np.int32), np.asarray(flags, np.int32)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "bq", "bk", "interpret")
)
def _flash_bwd_pallas(q, k, v, o, do, lse, causal: bool, scale: float, bq: int,
                      bk: int, interpret: bool = False, bias=None):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    with jax.enable_x64(False):
        *batch, tq, d = q.shape
        tk = k.shape[-2]
        bh = math.prod(batch) if batch else 1
        qr = q.reshape(bh, tq, d)
        kr = k.reshape(bh, tk, d)
        vr = v.reshape(bh, tk, d)
        dor = do.reshape(bh, tq, d)
        lser = lse.reshape(bh, tq, 1).astype(jnp.float32)
        # D_i = rowsum(dO ∘ O), one fused elementwise pass over the saved output
        dd = jnp.sum(
            dor.astype(jnp.float32) * o.reshape(bh, tq, d).astype(jnp.float32),
            axis=-1, keepdims=True,
        )

        has_bias = bias is not None
        bias_f32 = bias.astype(jnp.float32) if has_bias else None

        im, jm, flags = _pair_schedule(tq // bq, tk // bk, bq, bk, causal)
        dq_in_specs = [
            pl.BlockSpec((1, bq, d), lambda b, p, im, jm, fl: (b, im[p], 0)),
            pl.BlockSpec((1, bk, d), lambda b, p, im, jm, fl: (b, jm[p], 0)),
            pl.BlockSpec((1, bk, d), lambda b, p, im, jm, fl: (b, jm[p], 0)),
            pl.BlockSpec((1, bq, d), lambda b, p, im, jm, fl: (b, im[p], 0)),
            pl.BlockSpec((1, bq, 1), lambda b, p, im, jm, fl: (b, im[p], 0)),
            pl.BlockSpec((1, bq, 1), lambda b, p, im, jm, fl: (b, im[p], 0)),
        ]
        dq_inputs = [qr, kr, vr, dor, lser, dd]
        if has_bias:
            dq_in_specs.append(
                pl.BlockSpec((bq, bk), lambda b, p, im, jm, fl: (im[p], jm[p]))
            )
            dq_inputs.append(bias_f32)
        dq_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(bh, len(im)),
            in_specs=dq_in_specs,
            out_specs=pl.BlockSpec((1, bq, d), lambda b, p, im, jm, fl: (b, im[p], 0)),
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        )
        dq = pl.pallas_call(
            functools.partial(_dq_kernel, scale=scale, bq=bq, bk=bk, has_bias=has_bias),
            grid_spec=dq_spec,
            out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            interpret=interpret,
            compiler_params=None if interpret else _compiler_params(pltpu),
        )(jnp.asarray(im), jnp.asarray(jm), jnp.asarray(flags), *dq_inputs)

        jm2, im2, flags2 = _pair_schedule_kv(tq // bq, tk // bk, bq, bk, causal)
        dkv_in_specs = [
            pl.BlockSpec((1, bq, d), lambda b, p, jm, im, fl: (b, im[p], 0)),
            pl.BlockSpec((1, bk, d), lambda b, p, jm, im, fl: (b, jm[p], 0)),
            pl.BlockSpec((1, bk, d), lambda b, p, jm, im, fl: (b, jm[p], 0)),
            pl.BlockSpec((1, bq, d), lambda b, p, jm, im, fl: (b, im[p], 0)),
            pl.BlockSpec((1, bq, 1), lambda b, p, jm, im, fl: (b, im[p], 0)),
            pl.BlockSpec((1, bq, 1), lambda b, p, jm, im, fl: (b, im[p], 0)),
        ]
        dkv_inputs = [qr, kr, vr, dor, lser, dd]
        if has_bias:
            dkv_in_specs.append(
                pl.BlockSpec((bq, bk), lambda b, p, jm, im, fl: (im[p], jm[p]))
            )
            dkv_inputs.append(bias_f32)
        dkv_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(bh, len(jm2)),
            in_specs=dkv_in_specs,
            out_specs=[
                pl.BlockSpec((1, bk, d), lambda b, p, jm, im, fl: (b, jm[p], 0)),
                pl.BlockSpec((1, bk, d), lambda b, p, jm, im, fl: (b, jm[p], 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, d), jnp.float32),
                pltpu.VMEM((bk, d), jnp.float32),
            ],
        )
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel, scale=scale, bq=bq, bk=bk, has_bias=has_bias),
            grid_spec=dkv_spec,
            out_shape=[
                jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
                jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
            ],
            interpret=interpret,
            compiler_params=None if interpret else _compiler_params(pltpu),
        )(jnp.asarray(jm2), jnp.asarray(im2), jnp.asarray(flags2), *dkv_inputs)
        return (
            dq.reshape(*batch, tq, d),
            dk.reshape(*batch, tk, d),
            dv.reshape(*batch, tk, d),
        )


def _fits(q, k, bq: int, bk: int, with_bias: bool = False) -> bool:
    """VMEM gate: forward and backward all stream blocks through the grid now, so
    residency is O(bq·bk) regardless of T — the gate only enforces even tiling
    and a sane per-step footprint."""
    tq, d = q.shape[-2], q.shape[-1]
    tk = k.shape[-2]
    if tq % bq or tk % bk:
        return False
    if tq % _BWD_BQ or tk % _BWD_BK:
        return False
    # the flattened pair schedules are O((T/b)²) int32 scalar-prefetch entries
    # living in SMEM — bound them (bwd uses the fixed _BWD blocks, check both);
    # the pipelined schedule appends one flush step per q-row
    fwd_steps = (tq // bq) * (tk // bk)
    if _pipeline_enabled():
        fwd_steps += tq // bq
    if fwd_steps > _MAX_PAIRS:
        return False
    if (tq // _BWD_BQ) * (tk // _BWD_BK) > _MAX_PAIRS:
        return False
    itemsize = jnp.dtype(q.dtype).itemsize
    # per-step residency: s + p tiles (f32), accumulator, double-buffered blocks,
    # plus a double-buffered f32 bias block when a mask streams through
    bias_fwd = 8 * bq * bk if with_bias else 0
    if _pipeline_enabled():
        bias_fwd += 4 * bq * bk  # the skewed score buffer stays resident
    bias_bwd = 8 * _BWD_BQ * _BWD_BK if with_bias else 0
    fwd = 8 * bq * bk + 4 * bq * d + 2 * (bq + 2 * bk) * d * itemsize * 2 + bias_fwd
    bwd = 8 * _BWD_BQ * _BWD_BK + 8 * _BWD_BK * d \
        + 2 * (_BWD_BQ + 2 * _BWD_BK) * d * itemsize * 2 + bias_bwd
    # the same knob _compiler_params forwards to Mosaic, so block-size
    # experiments that lift the VMEM budget actually reach the flash path
    limit = _env_vmem_limit() or 12 * 2**20
    return max(fwd, bwd) <= limit


def _as_bias(mask):
    """Normalize a (Tq, Tk) mask to an additive f32 bias: boolean True = attend
    (the dense-path convention in nn/attention.py), floats pass through."""
    if mask is None:
        return None
    if mask.dtype == jnp.bool_:
        return jnp.where(mask, jnp.float32(0), jnp.float32(_NEG_INF))
    return mask.astype(jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = False, scale=None, mask=None):
    """Exact attention with the flash (streaming-VMEM) forward on TPU.

    q: (..., Tq, D), k/v: (..., Tk, D); Tq/Tk must be multiples of the block
    sizes (callers fall back to the XLA path otherwise via :func:`use_flash`).
    ``mask`` is an optional exact-shape (Tq, Tk) boolean (True = attend) or
    additive float bias, shared across batch/heads and streamed blockwise like
    k/v. Float biases are NOT differentiated on this path (grad raises; use the
    XLA path for a learned bias). The backward is the flash backward (two Pallas
    kernels over the saved (O, LSE) residuals). All three kernels stream blocks
    through a flattened pair grid, so VMEM residency is O(block²) regardless of
    T — arbitrarily long sequences fit, and the (T, T) matrix never exists in
    HBM.
    """
    s = (1.0 / math.sqrt(q.shape[-1])) if scale is None else scale
    bias = _as_bias(mask)
    blocks = _fwd_blocks(q.dtype, q.shape[-2], k.shape[-2], with_bias=bias is not None)
    out, _ = _flash_pallas(q, k, v, causal, float(s), *blocks, bias=bias,
                            pipelined=_pipeline_enabled())
    return out


def _fwd(q, k, v, causal, scale, mask):
    s = (1.0 / math.sqrt(q.shape[-1])) if scale is None else scale
    bias = _as_bias(mask)
    blocks = _fwd_blocks(q.dtype, q.shape[-2], k.shape[-2], with_bias=bias is not None)
    out, lse = _flash_pallas(q, k, v, causal, float(s), *blocks, bias=bias,
                              pipelined=_pipeline_enabled())
    return out, (q, k, v, out, lse, mask)


def _bwd(causal, scale, res, g):
    q, k, v, out, lse, mask = res
    if mask is not None and mask.dtype != jnp.bool_:
        # a float bias has a real gradient (Σ_{b,h} dS) that this backward does not
        # compute — fail loudly rather than silently training the bias to nothing.
        # use_flash only routes BOOL masks here; differentiable biases belong on
        # the XLA path, which differentiates scores + bias normally.
        raise NotImplementedError(
            "gradient through a float attention bias is not implemented on the "
            "flash path; boolean masks are gradient-free and fine — use the XLA "
            "attention path for a learned additive bias"
        )
    s = (1.0 / math.sqrt(q.shape[-1])) if scale is None else scale
    dq, dk, dv = _flash_bwd_pallas(
        q, k, v, out, g, lse, causal, float(s), _BWD_BQ, _BWD_BK, bias=_as_bias(mask)
    )
    # boolean masks have no tangent space; the zero cotangent is exact
    dmask = None if mask is None else jnp.zeros_like(mask, dtype=jnp.float32)
    return dq, dk, dv, dmask


flash_attention.defvjp(_fwd, _bwd)


def use_flash(q, k, v, mask, scale=None, interpret: bool = False) -> bool:
    """True when the Pallas forward applies: TPU backend, a static (or default)
    scale, a Mosaic-supported dtype, shapes that fit the VMEM budget/tiling, and
    a mask that is either absent or an exact-shape (Tq, Tk) BOOLEAN shared across
    batch/heads. Per-batch masks (e.g. (B, 1, 1, Tk) padding forms) and float
    biases take the XLA path — the former aren't streamable as one 2-D block,
    the latter have a bias gradient only the XLA path computes."""
    with_bias = mask is not None
    if with_bias and (
        mask.ndim != 2
        or mask.shape != (q.shape[-2], k.shape[-2])
        or mask.dtype != jnp.bool_
    ):
        return False
    if scale is not None and not isinstance(scale, (int, float)):
        # a traced scale can't become the kernel's static parameter; XLA path handles it
        return False
    # f64 inputs (legal framework-wide: x64 is enabled globally) must take the XLA
    # path — the kernel computes under enable_x64(False) and can't store to an f64 ref
    supported = (jnp.float32, jnp.bfloat16, jnp.float16)
    if any(t.dtype not in supported for t in (q, k, v)):
        return False
    if not interpret and jax.default_backend() != "tpu":
        return False
    return _fits(q, k, *_fwd_blocks(q.dtype, q.shape[-2], k.shape[-2], with_bias))
