"""Flash-attention Pallas kernel (TPU).

The XLA blockwise path in ``heat_tpu/nn/attention.py`` materialises the (T, T)
score matrix in HBM — at T=4096, B·H=128 that is ~8 GB of f32 traffic and the op
runs HBM-bound at a few TFLOP/s. This kernel streams k/v through VMEM with the
standard online-softmax recurrence: for each query block the k/v blocks are visited
sequentially, the (bq, bk) score tile lives only in VMEM, and the rescaled output
accumulator is written to HBM once. Causal masking skips whole k-blocks above the
diagonal (the loop's trip count is data-independent per q-block, so the causal
kernel does ~half the work instead of masking all of it).

Backward: the ``jax.custom_vjp`` backward is also Pallas — the forward saves the
(O, LSE) residuals, ``_dq_kernel`` streams k/v per query block and ``_dkv_kernel``
streams q/dO per key block, each recomputing its probability tile from the LSE
(the standard flash backward). Neither direction materialises the (T, T) matrix
in HBM.

No reference counterpart: the reference has no attention at all (SURVEY §2.4);
this is TPU-first machinery for the long-context story.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["flash_attention", "flash_attention_reference"]

_NEG_INF = float(jnp.finfo(jnp.float32).min)

_BQ = 512
_BK = 512
# the backward keeps full q/dO plus three (bq,bk) f32 tiles resident; 256-blocks
# keep the dk/dv kernel under the 16 MB VMEM ceiling at t=4096
_BWD_BQ = 256
_BWD_BK = 256


def flash_attention_reference(q, k, v, causal: bool = False, scale=None):
    """Pure-jnp exact attention (f32 accumulation) — the parity oracle."""
    d = q.shape[-1]
    s = (1.0 / math.sqrt(d)) if scale is None else scale
    scores = jnp.einsum("...qd,...kd->...qk", q, k, preferred_element_type=jnp.float32)
    scores = scores * jnp.float32(s)
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        scores = jnp.where(mask, scores, _NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("...qk,...kd->...qd", p, v, preferred_element_type=jnp.float32)
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def _kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale: float, causal: bool,
            bk: int, compute_dtype=None):
    import jax.experimental.pallas as pl

    iq = pl.program_id(1)
    bq, d = q_ref.shape[1], q_ref.shape[2]
    tk = k_ref.shape[1]
    nkb = tk // bk

    cdt = compute_dtype or q_ref.dtype
    q = q_ref[0].astype(cdt)  # (bq, d)
    q_row0 = iq * bq

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)

    def body(j, carry):
        acc, m, l = carry
        kb = k_ref[0, pl.ds(j * bk, bk), :].astype(cdt)  # (bk, d)
        vb = v_ref[0, pl.ds(j * bk, bk), :].astype(cdt)
        s = (
            lax.dot_general(q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
            * scale
        )  # (bq, bk) f32
        if causal:
            rows = q_row0 + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_blk = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        # probabilities ride the MXU in the value dtype (standard flash practice;
        # p ∈ [0,1] so the bf16 round-off is bounded), accumulation stays f32
        acc_new = acc * corr + lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    # causal: only k-blocks intersecting [0, q_row0 + bq) contribute; the trip
    # count depends only on the grid position, so whole above-diagonal blocks
    # are skipped rather than masked
    upper = jnp.minimum((q_row0 + bq + bk - 1) // bk, nkb) if causal else nkb
    acc, m, l = lax.fori_loop(0, upper, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # log-sum-exp residual for the backward pass: L = m + log(l)
    lse_ref[0] = m + jnp.log(jnp.maximum(l, 1e-30))


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "bq", "bk", "interpret", "compute_dtype")
)
def _flash_pallas(q, k, v, causal: bool, scale: float, bq: int, bk: int,
                  interpret: bool = False, compute_dtype=None):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    with jax.enable_x64(False):
        *batch, tq, d = q.shape
        tk = k.shape[-2]
        bh = math.prod(batch) if batch else 1
        qr = q.reshape(bh, tq, d)
        kr = k.reshape(bh, tk, d)
        vr = v.reshape(bh, tk, d)

        out, lse = pl.pallas_call(
            functools.partial(_kernel, scale=scale, causal=causal, bk=bk,
                              compute_dtype=compute_dtype),
            grid=(bh, tq // bq),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
                jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
            ],
            interpret=interpret,
        )(qr, kr, vr)
        return out.reshape(*batch, tq, d), lse.reshape(*batch, tq)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, dq_ref, *,
               scale: float, causal: bool, bk: int):
    """dq_i = Σ_j dS_ij · k_j · scale with dS = P ∘ (dO·Vᵀ − D)."""
    import jax.experimental.pallas as pl

    iq = pl.program_id(1)
    bq, d = q_ref.shape[1], q_ref.shape[2]
    tk = k_ref.shape[1]
    nkb = tk // bk

    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]  # (bq, 1)
    dd = dd_ref[0]
    q_row0 = iq * bq

    def body(j, dq):
        kb = k_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = (
            lax.dot_general(q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
            * scale
        )
        if causal:
            rows = q_row0 + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)  # (bq, bk), exact probabilities via the saved LSE
        dp = lax.dot_general(do, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - dd)
        return dq + lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    upper = jnp.minimum((q_row0 + bq + bk - 1) // bk, nkb) if causal else nkb
    dq = lax.fori_loop(0, upper, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, dk_ref, dv_ref, *,
                scale: float, causal: bool, bq: int):
    """dk_j = Σ_i dSᵀ_ij · q_i · scale,  dv_j = Σ_i Pᵀ_ij · dO_i."""
    import jax.experimental.pallas as pl

    jk = pl.program_id(1)
    bk, d = k_ref.shape[1], k_ref.shape[2]
    tq = q_ref.shape[1]
    nqb = tq // bq

    kb = k_ref[0].astype(jnp.float32)
    vb = v_ref[0].astype(jnp.float32)
    k_row0 = jk * bk

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * bq, bq), :].astype(jnp.float32)
        dob = do_ref[0, pl.ds(i * bq, bq), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * bq, bq), :]  # (bq, 1)
        dd = dd_ref[0, pl.ds(i * bq, bq), :]
        s = (
            lax.dot_general(qb, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
            * scale
        )
        if causal:
            rows = i * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = k_row0 + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = lax.dot_general(dob, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - dd)
        dk_new = dk + lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dv_new = dv + lax.dot_general(
            p, dob, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk_new, dv_new

    # causal: only q-blocks at or below this k-block's first row contribute
    lower = (k_row0 // bq) if causal else 0
    dk, dv = lax.fori_loop(
        lower, nqb, body, (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32))
    )
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "bq", "bk", "interpret")
)
def _flash_bwd_pallas(q, k, v, o, do, lse, causal: bool, scale: float, bq: int,
                      bk: int, interpret: bool = False):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    with jax.enable_x64(False):
        *batch, tq, d = q.shape
        tk = k.shape[-2]
        bh = math.prod(batch) if batch else 1
        qr = q.reshape(bh, tq, d)
        kr = k.reshape(bh, tk, d)
        vr = v.reshape(bh, tk, d)
        dor = do.reshape(bh, tq, d)
        lser = lse.reshape(bh, tq, 1).astype(jnp.float32)
        # D_i = rowsum(dO ∘ O), one fused elementwise pass over the saved output
        dd = jnp.sum(
            dor.astype(jnp.float32) * o.reshape(bh, tq, d).astype(jnp.float32),
            axis=-1, keepdims=True,
        )

        common = dict(interpret=interpret)
        dq = pl.pallas_call(
            functools.partial(_dq_kernel, scale=scale, causal=causal, bk=bk),
            grid=(bh, tq // bq),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            **common,
        )(qr, kr, vr, dor, lser, dd)
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel, scale=scale, causal=causal, bq=bq),
            grid=(bh, tk // bk),
            in_specs=[
                pl.BlockSpec((1, tq, d), lambda b, j: (b, 0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, tq, d), lambda b, j: (b, 0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, tq, 1), lambda b, j: (b, 0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, tq, 1), lambda b, j: (b, 0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0), memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
                jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
            ],
            **common,
        )(qr, kr, vr, dor, lser, dd)
        return (
            dq.reshape(*batch, tq, d),
            dk.reshape(*batch, tk, d),
            dv.reshape(*batch, tk, d),
        )


def _fits(q, k, bq: int, bk: int) -> bool:
    """VMEM gate: the worst-resident kernel is the dk/dv backward, which keeps the
    full q and dO (plus k/v blocks and score tiles) in VMEM. Shapes must also tile
    evenly (pad upstream if not)."""
    tq, d = q.shape[-2], q.shape[-1]
    tk = k.shape[-2]
    if tq % bq or tk % bk:
        return False
    if tq % _BWD_BQ or tk % _BWD_BK:
        return False
    itemsize = jnp.dtype(q.dtype).itemsize
    fwd = 4 * (3 * bq * d + 3 * bq * bk) + 2 * tk * d * itemsize
    bwd = (
        4 * (4 * _BWD_BQ * d + 3 * _BWD_BQ * _BWD_BK)
        + 4 * max(tk, tq) * d * itemsize  # full q + dO resident in the dk/dv kernel
    )
    return max(fwd, bwd) <= 10 * 2**20


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = False, scale=None):
    """Exact attention with the flash (streaming-VMEM) forward on TPU.

    q: (..., Tq, D), k/v: (..., Tk, D); Tq/Tk must be multiples of the 512-block
    (callers fall back to the XLA path otherwise via :func:`use_flash`). The
    backward is the flash backward (two Pallas kernels over the saved (O, LSE)
    residuals) — neither direction ever materializes the (T, T) matrix in HBM.
    """
    s = (1.0 / math.sqrt(q.shape[-1])) if scale is None else scale
    # f32 compute wins on this shape class: at head_dim 64 the kernel is VPU-bound
    # (exp + rescale on (bq,bk) tiles), and bf16 MXU passes don't pay for the extra
    # relayouts (measured 17.3 vs 15.0 TFLOP/s at b8·h16·t4096·d64 on v5e, 3× the
    # jax.experimental.pallas.ops.tpu library kernel on the same workload)
    out, _ = _flash_pallas(q, k, v, causal, float(s), _BQ, _BK, compute_dtype=jnp.float32)
    return out


def _fwd(q, k, v, causal, scale):
    s = (1.0 / math.sqrt(q.shape[-1])) if scale is None else scale
    out, lse = _flash_pallas(q, k, v, causal, float(s), _BQ, _BK, compute_dtype=jnp.float32)
    return out, (q, k, v, out, lse)


def _bwd(causal, scale, res, g):
    q, k, v, out, lse = res
    s = (1.0 / math.sqrt(q.shape[-1])) if scale is None else scale
    return _flash_bwd_pallas(q, k, v, out, g, lse, causal, float(s), _BWD_BQ, _BWD_BK)


flash_attention.defvjp(_fwd, _bwd)


def use_flash(q, k, v, mask, scale=None, interpret: bool = False) -> bool:
    """True when the Pallas forward applies: TPU backend, no explicit mask, a
    static (or default) scale, a Mosaic-supported dtype, and shapes that fit the
    VMEM budget/tiling."""
    if mask is not None:
        return False
    if scale is not None and not isinstance(scale, (int, float)):
        # a traced scale can't become the kernel's static parameter; XLA path handles it
        return False
    # f64 inputs (legal framework-wide: x64 is enabled globally) must take the XLA
    # path — the kernel computes under enable_x64(False) and can't store to an f64 ref
    supported = (jnp.float32, jnp.bfloat16, jnp.float16)
    if any(t.dtype not in supported for t in (q, k, v)):
        return False
    if not interpret and jax.default_backend() != "tpu":
        return False
    return _fits(q, k, _BQ, _BK)
