"""Fused KMeans assignment + centroid-accumulate Pallas kernel.

The jnp Lloyd body materialises the (n, k) distance matrix in HBM, reads it back for
the argmin, then reads x again for the segment-sum update — three HBM passes over
O(n·k + n·d) bytes per iteration. This kernel streams x through VMEM once per
iteration: each (BN, d) block computes its distance tile on the MXU, takes the argmin,
and accumulates the per-cluster sums/counts and the min-distance² total in VMEM/SMEM
accumulators. HBM traffic per iteration drops to one read of x plus O(k·d) outputs —
the op becomes memory-bound at the streaming rate of x.

Reference workload: KMeans 10M×64 (north-star #3, reference heat/cluster/kmeans.py).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["fused_assign_update", "fused_assign_update_reference"]

# one block size shared by the kernel launcher and the VMEM gate
_DEFAULT_BLOCK_N = 1024


def fused_assign_update_reference(
    xv: jax.Array, centers: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Pure-jnp reference: (labels, sums, counts, sse) of nearest-centroid assignment."""
    xx = jnp.sum(xv * xv, axis=1, keepdims=True)
    cc = jnp.sum(centers * centers, axis=1)[None, :]
    d2 = xx + cc - 2.0 * jnp.matmul(xv, centers.T, precision=jax.lax.Precision.HIGHEST)
    d2 = jnp.maximum(d2, 0.0)
    labels = jnp.argmin(d2, axis=1).astype(jnp.int32)
    k = centers.shape[0]
    sums = jnp.zeros_like(centers).at[labels].add(xv)
    counts = jnp.zeros((k,), xv.dtype).at[labels].add(1.0)
    sse = jnp.sum(jnp.min(d2, axis=1))
    return labels, sums, counts, sse


def _kernel(nvalid_ref, x_ref, c_ref, labels_ref, sums_ref, counts_ref, sse_ref):
    import jax.experimental.pallas as pl  # ht: ignore[trace-lazy-import] -- pallas imports deferred so CPU-only processes never pay them; runs once per compile, imports nothing of heat_tpu

    i = pl.program_id(0)
    bn = x_ref.shape[0]
    k = c_ref.shape[0]

    @pl.when(i == 0)
    def _():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)
        sse_ref[0, 0] = jnp.float32(0.0)

    x = x_ref[:]  # (BN, d)
    c = c_ref[:]  # (k, d)
    xx = jnp.sum(x * x, axis=1, keepdims=True)  # (BN, 1)
    cc = jnp.sum(c * c, axis=1, keepdims=True).T  # (1, k)
    # (BN, k) distance tile on the MXU. The quadratic expansion cancels
    # catastrophically for near points, so the cross term needs full input
    # precision (same rationale as spatial._pairwise).
    xc = jax.lax.dot_general(
        x,
        c,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    d2 = jnp.maximum(xx + cc - 2.0 * xc, 0.0)
    # explicit int32 argmin (Mosaic's reduce-index only lowers int32; the framework
    # runs with x64 enabled): first index attaining the row minimum, numpy tie rule.
    # Everything stays 2-D — Mosaic relayouts of 1-D vectors are restricted.
    col = jax.lax.broadcasted_iota(jnp.int32, (bn, k), 1)
    mind = jnp.min(d2, axis=1, keepdims=True)  # (BN, 1)
    labels = jnp.min(
        jnp.where(d2 == mind, col, jnp.int32(k)), axis=1, keepdims=True
    )  # (BN, 1)
    labels_ref[:] = labels.reshape(-1)  # 1-D block: lane-dim-only tiling constraint

    rows = i * bn + jax.lax.broadcasted_iota(jnp.int32, (bn, 1), 0)
    valid = rows < nvalid_ref[0]  # (BN, 1)
    onehot = jnp.where(
        jnp.logical_and(labels == col, valid), jnp.float32(1.0), jnp.float32(0.0)
    )  # (BN, k)
    # per-cluster partial sums: (k, BN) @ (BN, d) on the MXU; full input precision —
    # bf16-rounded x would put ~0.5% noise on every accumulated coordinate
    sums_ref[:] += jax.lax.dot_general(
        onehot,
        x,
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    counts_ref[:] += jnp.sum(onehot, axis=0, keepdims=True)
    sse_ref[0, 0] += jnp.sum(jnp.where(valid, mind, 0.0))


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def _fused_pallas(xv, centers, block_n: int = _DEFAULT_BLOCK_N, interpret: bool = False):
    import jax.experimental.pallas as pl  # ht: ignore[trace-lazy-import] -- pallas imports deferred so CPU-only processes never pay them; runs once per compile, imports nothing of heat_tpu
    from jax.experimental.pallas import tpu as pltpu  # ht: ignore[trace-lazy-import] -- pallas imports deferred so CPU-only processes never pay them; runs once per compile, imports nothing of heat_tpu

    # the framework enables x64 globally; Mosaic only legalizes i32 scalars, so the
    # kernel (all-i32/f32 by construction) is traced with x64 off
    with jax.enable_x64(False):
        return _fused_pallas_body(xv, centers, pl, pltpu, block_n, interpret)


def _fused_pallas_body(xv, centers, pl, pltpu, block_n: int, interpret: bool):
    n, d = xv.shape
    k = centers.shape[0]
    bn = min(block_n, max(128, -(-n // 128) * 128))
    n_pad = -(-n // bn) * bn
    if n_pad != n:
        xv = jnp.pad(xv, ((0, n_pad - n), (0, 0)))
    grid = n_pad // bn

    labels, sums, counts, sse = pl.pallas_call(
        _kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # nvalid scalar
            pl.BlockSpec((bn, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bn,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad,), jnp.int32),
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(jnp.asarray([n], jnp.int32), xv.astype(jnp.float32), centers.astype(jnp.float32))
    return labels[:n], sums, counts[0], sse[0, 0]


def fused_assign_update(
    xv: jax.Array, centers: jax.Array, interpret: bool = False
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """(labels, sums, counts, sse) in one streaming pass over ``xv``.

    Uses the Pallas TPU kernel on TPU backends (or ``interpret=True`` anywhere);
    falls back to the jnp reference otherwise.
    """
    if not interpret and jax.default_backend() != "tpu":
        return fused_assign_update_reference(xv, centers)
    # interpret mode has no Mosaic VMEM limit — only gate real compilations
    if not interpret and not _fits_vmem(xv.shape[1], centers.shape[0], _DEFAULT_BLOCK_N):
        return fused_assign_update_reference(xv, centers)
    return _fused_pallas(xv, centers, interpret=interpret)


def _fits_vmem(d: int, k: int, block_n: int = _DEFAULT_BLOCK_N, budget_bytes: int = 8 * 2**20) -> bool:
    """Conservative VMEM gate: the kernel keeps the (bn,d) x block, (k,d) centers +
    sums, the (bn,k) distance/one-hot tiles, and working copies resident; wide or
    many-cluster inputs must fall back to the jnp path instead of failing Mosaic
    compilation with a VMEM-exceeded error."""
    resident = 4 * (2 * block_n * d + 3 * k * d + 3 * block_n * k)
    return resident <= budget_bytes
