"""Distributed linear algebra (reference heat/core/linalg/)."""

from . import basics, solver, svdtools
from . import qr as _qr_mod
from . import svd as _svd_mod

from .basics import *
from .qr import *
from .solver import *
from .svd import *
from .svdtools import *

__all__ = (
    list(basics.__all__) + list(_qr_mod.__all__) + list(solver.__all__)
    + list(_svd_mod.__all__) + list(svdtools.__all__)
)
