"""Distributed linear algebra (reference heat/core/linalg/)."""

from .basics import *
from . import basics

__all__ = list(basics.__all__)
