"""Basic linear algebra (reference heat/core/linalg/basics.py, 2404 LoC).

The reference's ``matmul`` (``basics.py:422-1100``) is a 700-line block-cyclic SUMMA with
hand-written Isend/Irecv pipelines per (a.split, b.split) case. On TPU the entire case
analysis collapses: ``jnp.matmul`` on sharded global arrays is partitioned by XLA SPMD,
which emits exactly the SUMMA-style collectives (all-gathers of panels, reduce-scatters /
all-reduces of partials) scheduled onto the MXU with overlap — this is the reference's
hot path made compiler-generated. Only the *output split bookkeeping* survives here.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp

from .. import _operations, factories, sanitation, types
from ..communication import get_comm
from ..dndarray import DNDarray
from ..stride_tricks import sanitize_axis
from . import comm_plan

__all__ = [
    "PARITY_PRECISION",
    "cross",
    "det",
    "dot",
    "inv",
    "matmul",
    "matrix_norm",
    "norm",
    "outer",
    "projection",
    "trace",
    "transpose",
    "tril",
    "triu",
    "vdot",
    "vecdot",
    "vector_norm",
]


# Per-op precision for numerics-parity-critical matmuls (the reference computes in
# full fp32/fp64 via torch). The MXU's bf16-input default is kept for the bulk
# compute path; decompositions and cancellation-prone kernels opt up to this.
PARITY_PRECISION = jax.lax.Precision.HIGHEST


def _contraction_precision(precision, *operands) -> Optional[jax.lax.Precision]:
    """Dtype-aware default precision for user-facing MXU contractions.

    An explicit ``precision`` always wins. Otherwise float32 operands get the
    full-f32 multi-pass MXU schedule so ``ht.matmul(f32, f32)`` matches numpy/torch
    to ~1e-7 like the reference (torch matmul is exact f32, ``basics.py:422``) —
    the MXU's native single-pass default would silently round inputs to bf16
    (~1e-2 error on unit-scale data). bf16/f16 inputs keep the fast native path;
    f64 is exact under any setting.
    """
    if precision is not None:
        return precision
    for o in operands:
        value = o.larray if isinstance(o, DNDarray) else o
        if getattr(value, "dtype", None) == jnp.float32:
            return jax.lax.Precision.HIGHEST
    return None


def _wrap_like(value: jax.Array, proto: DNDarray, split: Optional[int]) -> DNDarray:
    if split is not None and (split >= value.ndim or split < 0):
        split = None
    gshape = tuple(value.shape)
    value = proto.comm.shard(value, split)
    return DNDarray(
        value, gshape, types.canonical_heat_type(value.dtype), split, proto.device, proto.comm, True
    )


def matmul(
    a: DNDarray, b: DNDarray, allow_resplit: bool = False, precision=None
) -> DNDarray:
    """Matrix multiplication of distributed operands (reference ``basics.py:422``).

    Output split rule: a row-split ``a`` yields a row-split product; a column-split ``b``
    yields a column-split product; contraction-dim splits all-reduce away to ``None``;
    batch-dim splits are preserved (``HEAT_TPU_LINALG_PLAN=rs`` opts contraction-dim
    splits into a reduce-scatter with a ``split=0`` product instead).

    The data movement is chosen per call by the communication planner
    (:mod:`.comm_plan`): 2-D both-split pairs take the ring collective matmul
    (one panel in flight over ``ppermute``, the gathered operand never
    materialised); everything else defers to XLA SPMD's default (typically
    all-gather of the smaller panel riding ICI). ``HEAT_TPU_LINALG_PLAN``
    forces a plan; the choice is recorded as ``linalg.plan.*`` diagnostics.

    ``precision`` passes through to ``jnp.matmul`` — ``None`` picks a dtype-aware
    default (:func:`_contraction_precision`): full-f32 passes for float32 operands,
    the MXU-native fast path for bf16/f16.
    """
    sanitation.sanitize_in(a)
    sanitation.sanitize_in(b)
    precision = _contraction_precision(precision, a, b)
    planned = comm_plan.try_matmul(a, b, precision)
    if planned is not NotImplemented:
        return planned
    result = jnp.matmul(a.larray, b.larray, precision=precision)
    nd_out = result.ndim
    # position of a's row dim / b's col dim in the output (absent for 1-D operands)
    row_dim = nd_out - (2 if b.ndim >= 2 else 1) if a.ndim >= 2 else None
    col_dim = nd_out - 1 if b.ndim >= 2 else None
    split = None
    if a.ndim >= 2 and a.split == a.ndim - 2 and row_dim is not None and row_dim >= 0:
        split = row_dim
    elif b.ndim >= 2 and b.split == b.ndim - 1 and col_dim is not None and col_dim >= 0:
        split = col_dim
    elif a.split is not None and a.ndim >= 2 and a.split < a.ndim - 2:
        split = a.split  # batch dim
    elif b.split is not None and b.ndim >= 2 and b.split < b.ndim - 2:
        split = b.split
    if nd_out == 0:
        split = None
    return _wrap_like(result, a, split)


def dot(
    a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None, precision=None
) -> Union[DNDarray, float]:
    """Dot product (reference ``basics.py:245``): inner product for 1-D, matmul for 2-D."""
    if isinstance(a, (int, float)) or isinstance(b, (int, float)) or a.ndim == 0 or b.ndim == 0:
        from .. import arithmetics

        return arithmetics.mul(a, b)
    if a.ndim == 1 and b.ndim == 1:
        result = jnp.dot(a.larray, b.larray, precision=_contraction_precision(precision, a, b))
        res = _wrap_like(result, a, None)
        return _operations.handle_out(res, out, a)
    ret = matmul(a, b, precision=precision)
    return _operations.handle_out(ret, out, a)


def vecdot(x1: DNDarray, x2: DNDarray, axis: Optional[int] = None, keepdims: bool = False) -> DNDarray:
    """Vector dot along an axis (reference ``basics.py`` vecdot)."""
    from .. import arithmetics

    m = arithmetics.mul(x1, x2)
    if axis is None:
        axis = m.ndim - 1
    return arithmetics.sum(m, axis=axis, keepdims=keepdims)


def vdot(x1: DNDarray, x2: DNDarray) -> DNDarray:
    """Conjugate dot of flattened inputs (reference ``basics.py`` vdot)."""
    result = jnp.vdot(x1.larray, x2.larray, precision=_contraction_precision(None, x1, x2))
    return _wrap_like(result, x1, None)


def outer(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None, split: Optional[int] = None) -> DNDarray:
    """Outer product (reference ``basics.py:1391`` — a ring algorithm there; a sharded
    broadcast-multiply here)."""
    sanitation.sanitize_in(a)
    sanitation.sanitize_in(b)
    result = jnp.outer(a.larray, b.larray)
    if split is None:
        split = 0 if a.split is not None else (1 if b.split is not None else None)
    res = _wrap_like(result, a, split)
    return _operations.handle_out(res, out, a)


def cross(
    a: DNDarray, b: DNDarray, axisa: int = -1, axisb: int = -1, axisc: int = -1, axis: int = -1
) -> DNDarray:
    """Cross product (reference ``basics.py`` cross)."""
    result = jnp.cross(a.larray, b.larray, axisa=axisa, axisb=axisb, axisc=axisc, axis=axis)
    return _wrap_like(result, a, a.split)


def det(a: DNDarray) -> DNDarray:
    """Determinant (reference ``basics.py:159`` — distributed LU there; XLA's LU here)."""
    sanitation.sanitize_in(a)
    if a.ndim < 2 or a.gshape[-1] != a.gshape[-2]:
        raise ValueError(f"last two dimensions must be square, got {a.gshape}")
    result = jnp.linalg.det(a.larray)
    return _wrap_like(result, a, None)


def inv(a: DNDarray) -> DNDarray:
    """Matrix inverse (reference ``basics.py:311`` — distributed Gauss-Jordan with Bcast;
    XLA's blocked LU-based inverse here, SPMD-partitioned over the mesh)."""
    sanitation.sanitize_in(a)
    if a.ndim < 2 or a.gshape[-1] != a.gshape[-2]:
        raise ValueError(f"last two dimensions must be square, got {a.gshape}")
    result = jnp.linalg.inv(a.larray)
    return _wrap_like(result, a, a.split)


def trace(a: DNDarray, offset: int = 0, axis1: int = 0, axis2: int = 1, dtype=None, out=None) -> Union[DNDarray, float]:
    """Sum along diagonals (reference ``basics.py:1642``)."""
    sanitation.sanitize_in(a)
    result = jnp.trace(a.larray, offset=offset, axis1=axis1, axis2=axis2)
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype).jax_type())
    res = _wrap_like(result, a, None)
    if out is not None:
        out.larray = res.larray
        return out
    if res.ndim == 0:
        return res.item()
    return res


def transpose(a: DNDarray, axes: Optional[Sequence[int]] = None) -> DNDarray:
    """Permute dimensions (reference ``basics.py:2057``): local permute + split remap."""
    sanitation.sanitize_in(a)
    if axes is None:
        axes = tuple(reversed(range(a.ndim)))
    else:
        axes = tuple(int(ax) + a.ndim if ax < 0 else int(ax) for ax in axes)
        if sorted(axes) != list(range(a.ndim)):
            raise ValueError(f"axes do not match tensor of dimension {a.ndim}")
    result = jnp.transpose(a.larray, axes)
    split = axes.index(a.split) if a.split is not None else None
    return _wrap_like(result, a, split)


def _tri_op(a: DNDarray, k: int, op) -> DNDarray:
    """Shared triangle logic (reference ``__tri_op`` ``basics.py:2127``)."""
    sanitation.sanitize_in(a)
    if a.ndim == 1:
        result = op(jnp.broadcast_to(a.larray, (a.gshape[0], a.gshape[0])), k=k)
        return _wrap_like(result, a, 0 if a.split is not None else None)
    return _operations.local_op(op, a, k=k)


def tril(m: DNDarray, k: int = 0) -> DNDarray:
    """Lower triangle (reference ``basics.py:2197``)."""
    return _tri_op(m, k, jnp.tril)


def triu(m: DNDarray, k: int = 0) -> DNDarray:
    """Upper triangle (reference ``basics.py:2220``)."""
    return _tri_op(m, k, jnp.triu)


def vector_norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Vector norm (reference ``basics.py:2315``)."""
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.gshape, axis)
    result = jnp.linalg.vector_norm(x.larray, axis=axis, keepdims=keepdims, ord=ord if ord is not None else 2)
    split = _operations._out_split_reduce(x, axis if axis is not None else None, keepdims)
    if axis is None:
        split = None
    return _wrap_like(result, x, split)


def matrix_norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Matrix norm (reference ``basics.py:1114``)."""
    sanitation.sanitize_in(x)
    if axis is None:
        if x.ndim < 2:
            raise ValueError("matrix_norm requires at least 2 dimensions")
        axis = (x.ndim - 2, x.ndim - 1)
    result = jnp.linalg.matrix_norm(x.larray, keepdims=keepdims, ord=ord if ord is not None else "fro")
    return _wrap_like(result, x, None)


def norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Unified norm entry (reference ``basics.py:1242``)."""
    sanitation.sanitize_in(x)
    if axis is None and ord is None:
        result = jnp.linalg.norm(x.larray.reshape(-1))
        return _wrap_like(result, x, None)
    axis = sanitize_axis(x.gshape, axis)
    if isinstance(axis, (tuple, list)) and len(axis) == 2:
        result = jnp.linalg.norm(x.larray, ord=ord, axis=tuple(axis), keepdims=keepdims)
        return _wrap_like(result, x, None)
    result = jnp.linalg.norm(x.larray, ord=ord, axis=axis, keepdims=keepdims)
    split = _operations._out_split_reduce(x, axis, keepdims) if axis is not None else None
    return _wrap_like(result, x, split)


def projection(a: DNDarray, b: DNDarray) -> DNDarray:
    """Projection of a onto b (reference ``basics.py`` projection)."""
    if a.ndim != 1 or b.ndim != 1:
        raise RuntimeError(f"projection gets 1-D vectors, got {a.ndim}-D and {b.ndim}-D")
    from .. import arithmetics

    scale = dot(a, b) / dot(b, b)
    return arithmetics.mul(scale, b)
