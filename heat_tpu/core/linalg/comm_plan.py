"""Communication planner for distributed contractions (ISSUE 20).

``linalg.matmul`` historically delegated every byte of data movement to XLA
SPMD's default strategy — typically an all-gather of one full operand
(O(global) logical bytes on the wire, O(n·k) per-device peak memory for the
gathered panel) or, for contraction-dim splits, an all-reduce of the full
replicated product. This module adds a small cost model that picks, per call,
among four plans:

``xla``
    Today's behaviour: ``jnp.matmul`` on the sharded global arrays, movement
    chosen by the partitioner. Modeled wire bytes: replicating every split
    operand, ``(P−1)·|operand|`` each (the gather-both fallback), or
    ``2(P−1)·|C|`` for the contraction-split all-reduce.
``ring``
    SUMMA-style ring collective matmul (van de Geijn & Watts; the TPU
    collective-matmul decomposition of Wang et al., ASPLOS 2023): one panel of
    the rotating operand in flight via ``MeshCommunication.ring_shift``
    (a ``ppermute``) inside a single ``shard_map``'d program, partial-product
    accumulation overlapped with the next panel's shift. Per-device peak
    memory is O(n²/P + one panel) — the gathered operand is never
    materialised — and total wire bytes are ``(P−1)·|rotating operand|``,
    i.e. each device receives ``(P−1)/P`` of it.
``rs``
    Reduce-scatter contraction for contraction-dim splits: the local partial
    product is combined with ``psum_scatter`` straight into a ``split=0``
    result — ``(P−1)·|C|`` wire bytes, half the all-reduce's ``2(P−1)·|C|``,
    and the replicated result buffer is never allocated. Because this changes
    the result split (``None`` → ``0``), it is **never** chosen by ``auto``;
    consumers that keep the product sharded opt in with
    ``HEAT_TPU_LINALG_PLAN=rs``.
``resplit``
    ``all_to_all`` resplit for split→split layout changes
    (:meth:`~..dndarray.DNDarray.resplit_`): each device exchanges only the
    ``1/P`` tile every peer needs — ``(P−1)/P·|array|`` total wire bytes
    instead of the gather-based path's ``(P−1)·|array|``.

Plan selection honours the memoised ``HEAT_TPU_LINALG_PLAN`` knob
(:func:`.._executor.linalg_plan`; ``auto``/``xla``/``ring``/``rs``), the
chosen plan is recorded through ht.diagnostics (``linalg.plan.<kind>``
counters plus modeled ``linalg.bytes.<kind>`` wire bytes — recorded per call
at dispatch time, unlike the trace-time per-collective records), and every
staged body rides the signature-cached executor (compile-cache/AOT-warmup
family ``"mm"`` included). The bodies are pure functions of their operands —
knob reads and counter writes stay in the host-side wrappers.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .. import _executor, diagnostics, types
from ..communication import compat_shard_map
from ..dndarray import DNDarray

__all__ = ["Plan", "plan_matmul", "try_matmul", "try_resplit"]


class Plan(NamedTuple):
    """One planned contraction: the chosen ``kind`` (``xla``/``ring``/``rs``),
    the execution ``variant`` within it, and the modeled total wire bytes of
    the plan (``nbytes``) and of the gather-both-operands fallback
    (``baseline``)."""

    kind: str
    variant: str
    nbytes: int
    baseline: int


# ring variants by (a.split, b.split): which operand rotates and how the
# product is assembled. rs variants by the same key: where the local partial
# comes from before the reduce-scatter.
_RING_VARIANTS = {(0, 0): "rA", (1, 1): "rB", (0, 1): "rC"}
_RS_VARIANTS = {(1, 0): "s10", (None, 0): "sN0", (1, None): "s1N"}


def _phys_bytes(comm, gshape, split, dtype) -> int:
    """Padded-physical bytes of one global operand."""
    size = 1
    for extent in comm.padded_shape(gshape, split):
        size *= int(extent)
    return size * int(np.dtype(dtype).itemsize)


def _plannable_dtype(x: DNDarray) -> bool:
    dt = np.dtype(x.dtype.jax_type() if hasattr(x.dtype, "jax_type") else x.dtype)
    return (
        np.issubdtype(dt, np.floating) or np.issubdtype(dt, np.integer)
    ) and not np.issubdtype(dt, np.bool_)


def _structural(a, b):
    """The shared communicator when (a, b) is a plannable distributed 2-D
    contraction — single-controller, single mesh axis, size > 1, real/integer
    dtypes, conformable shapes — else ``None``."""
    if not (isinstance(a, DNDarray) and isinstance(b, DNDarray)):
        return None
    if a.ndim != 2 or b.ndim != 2:
        return None
    comm = a.comm
    if comm is not b.comm or comm.size <= 1 or len(comm.axis_names) != 1:
        return None
    if not _executor.executor_enabled():
        return None
    if a.gshape[1] != b.gshape[0]:
        return None
    if not (_plannable_dtype(a) and _plannable_dtype(b)):
        return None
    return comm


def plan_matmul(a: DNDarray, b: DNDarray) -> Optional[Plan]:
    """The communication plan for ``matmul(a, b)``, or ``None`` when the pair
    is not a plannable distributed contraction (the caller takes the XLA path
    without recording a plan).

    Policy: ``auto`` picks ``ring`` whenever a ring variant applies (both
    operands split along a non-contraction-compatible pair) and ``xla``
    otherwise; ``ring``/``rs`` force their plan where eligible, falling back
    to ``xla``; ``xla`` always defers to the partitioner. ``rs`` is never
    chosen by ``auto`` because it changes the result split (``None`` → ``0``).
    """
    comm = _structural(a, b)
    if comm is None:
        return None
    if a.split is None and b.split is None:
        return None  # purely local: nothing to plan, nothing to record
    P = comm.size
    baseline = 0
    if a.split is not None:
        baseline += (P - 1) * _phys_bytes(comm, a.gshape, a.split, a.dtype.jax_type())
    if b.split is not None:
        baseline += (P - 1) * _phys_bytes(comm, b.gshape, b.split, b.dtype.jax_type())
    knob = _executor.linalg_plan()
    key = (a.split, b.split)

    ring_variant = _RING_VARIANTS.get(key)
    if ring_variant is not None and knob in ("auto", "ring"):
        rot_op = a if ring_variant == "rB" else b
        nbytes = (P - 1) * _phys_bytes(
            comm, rot_op.gshape, rot_op.split, rot_op.dtype.jax_type()
        )
        return Plan("ring", ring_variant, nbytes, baseline)

    rs_variant = _RS_VARIANTS.get(key)
    if rs_variant is not None and knob == "rs":
        m, n = a.gshape[0], b.gshape[1]
        out_dt = np.promote_types(
            np.dtype(a.dtype.jax_type()), np.dtype(b.dtype.jax_type())
        )
        c_bytes = comm.padded_dim(m) * n * int(out_dt.itemsize)
        return Plan("rs", rs_variant, (P - 1) * c_bytes, baseline)

    return Plan("xla", "", _xla_bytes(comm, a, b, baseline), baseline)


def _xla_bytes(comm, a: DNDarray, b: DNDarray, baseline: int) -> int:
    """Modeled wire bytes of the partitioner's default: the contraction-split
    all-reduce (``2(P−1)·|C|``) when both splits land on the contraction pair,
    the gather-both fallback otherwise."""
    if (a.split, b.split) in _RS_VARIANTS:
        P = comm.size
        out_dt = np.promote_types(
            np.dtype(a.dtype.jax_type()), np.dtype(b.dtype.jax_type())
        )
        return 2 * (P - 1) * a.gshape[0] * b.gshape[1] * int(out_dt.itemsize)
    return baseline


def _record(plan: Plan) -> None:
    """Count the executed plan: ``linalg.plan.<kind>`` occurrences plus the
    modeled wire bytes of the plan and of the gather-both fallback. Host-side
    and per call — cached program replays count too, unlike the trace-time
    ``record_collective`` entries."""
    if not diagnostics._enabled:
        return
    diagnostics.counter(f"linalg.plan.{plan.kind}")
    diagnostics.counter(f"linalg.bytes.{plan.kind}", plan.nbytes)
    diagnostics.counter("linalg.bytes.gather_baseline", plan.baseline)


def try_matmul(a: DNDarray, b: DNDarray, precision) -> Any:
    """Plan and, when the plan is ``ring``/``rs``, execute ``matmul(a, b)``
    through the staged executor. Returns the result DNDarray, or
    ``NotImplemented`` for the caller's XLA-SPMD path (plan ``xla``, an
    unplannable pair, or a staged path still warming up / quarantined —
    the executed plan is what gets recorded)."""
    plan = plan_matmul(a, b)
    if plan is None:
        return NotImplemented
    if plan.kind != "xla":
        res = _execute(plan, a, b, precision)
        if res is not NotImplemented:
            _record(plan)
            return res
        plan = Plan("xla", "", _xla_bytes(a.comm, a, b, plan.baseline), plan.baseline)
    _record(plan)
    return NotImplemented


# ------------------------------------------------------------- staged bodies
def _pad_to(x, target: int, axis: int):
    """Zero-pad local axis ``axis`` up to ``target`` (a no-op when already
    there) — keeps every panel slice aligned with the peer's padded extent."""
    extent = x.shape[axis]
    if extent == target:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, target - extent)
    return jnp.pad(x, pads)


def _ring_body(variant: str, comm, agshape, bgshape, precision):
    """The shard_map'd ring program: P−1 ``ring_shift`` hops of one panel of
    the rotating operand, each overlapped with the accumulation of the
    previous panel's partial product; the last panel is consumed without a
    wasted hop (the ``_ring_pairwise`` idiom in spatial/distance.py)."""
    P = comm.size
    ax = comm.axis_name
    m, k = agshape
    n = bgshape[1]
    kp = comm.padded_dim(k)
    ck = kp // P
    np_p = comm.padded_dim(n)
    cn = np_p // P

    if variant == "rA":
        # a split 0, b split 0 (contraction dim): rotate b's row panels.
        def block(al, bl):
            idx = jax.lax.axis_index(ax)
            al = _pad_to(al, kp, 1)
            out0 = jnp.zeros((al.shape[0], bl.shape[1]), jnp.result_type(al, bl))

            def contrib(i, bblk, out):
                src = (idx - i) % P
                panel = jax.lax.dynamic_slice_in_dim(al, src * ck, ck, axis=1)
                return out + jnp.matmul(panel, bblk, precision=precision)

            def step(i, carry):
                bblk, out = carry
                out = contrib(i, bblk, out)
                return comm.ring_shift(bblk, 1, axis_name=ax), out

            bblk, out = jax.lax.fori_loop(0, P - 1, step, (bl, out0))
            return contrib(P - 1, bblk, out)

        in_splits, out_split = (0, 0), 0
    elif variant == "rB":
        # a split 1 (contraction dim), b split 1: rotate a's column panels.
        def block(al, bl):
            idx = jax.lax.axis_index(ax)
            bl = _pad_to(bl, kp, 0)
            out0 = jnp.zeros((al.shape[0], bl.shape[1]), jnp.result_type(al, bl))

            def contrib(i, ablk, out):
                src = (idx - i) % P
                rows = jax.lax.dynamic_slice_in_dim(bl, src * ck, ck, axis=0)
                return out + jnp.matmul(ablk, rows, precision=precision)

            def step(i, carry):
                ablk, out = carry
                out = contrib(i, ablk, out)
                return comm.ring_shift(ablk, 1, axis_name=ax), out

            ablk, out = jax.lax.fori_loop(0, P - 1, step, (al, out0))
            return contrib(P - 1, ablk, out)

        in_splits, out_split = (1, 1), 1
    elif variant == "rC":
        # a split 0, b split 1: rotate b's column panels into their output slot.
        def block(al, bl):
            idx = jax.lax.axis_index(ax)
            out0 = jnp.zeros((al.shape[0], np_p), jnp.result_type(al, bl))

            def contrib(i, bblk, out):
                src = (idx - i) % P
                d = jnp.matmul(al, bblk, precision=precision)
                col0 = (src * cn).astype(jnp.int32)
                return jax.lax.dynamic_update_slice(out, d, (jnp.int32(0), col0))

            def step(i, carry):
                bblk, out = carry
                out = contrib(i, bblk, out)
                return comm.ring_shift(bblk, 1, axis_name=ax), out

            bblk, out = jax.lax.fori_loop(0, P - 1, step, (bl, out0))
            return contrib(P - 1, bblk, out)[:, :n]

        in_splits, out_split = (0, 1), 0
    else:  # pragma: no cover - planner only emits the three variants above
        raise ValueError(f"unknown ring variant {variant!r}")

    def body(pa, pb):
        return compat_shard_map(
            block, comm.mesh,
            in_specs=(comm.spec(2, in_splits[0]), comm.spec(2, in_splits[1])),
            out_specs=comm.spec(2, out_split),
        )(pa, pb)

    return body, out_split


def _rs_body(variant: str, comm, agshape, bgshape, precision):
    """The reduce-scatter contraction: the device-local partial product of one
    contraction-dim tile, ``psum_scatter``'d straight into a ``split=0``
    result — the replicated product is never allocated."""
    P = comm.size
    ax = comm.axis_name
    m, k = agshape
    n = bgshape[1]
    kp = comm.padded_dim(k)
    ck = kp // P
    mp = comm.padded_dim(m)
    a_split = {"s10": 1, "sN0": None, "s1N": 1}[variant]
    b_split = {"s10": 0, "sN0": 0, "s1N": None}[variant]

    def block(al, bl):
        idx = jax.lax.axis_index(ax)
        if variant == "sN0":
            al = jax.lax.dynamic_slice_in_dim(_pad_to(al, kp, 1), idx * ck, ck, axis=1)
        elif variant == "s1N":
            bl = jax.lax.dynamic_slice_in_dim(_pad_to(bl, kp, 0), idx * ck, ck, axis=0)
        part = jnp.matmul(al, bl, precision=precision)
        part = _pad_to(part, mp, 0)
        return comm.psum_scatter(part, scatter_axis=0, axis_name=ax)

    def body(pa, pb):
        return compat_shard_map(
            block, comm.mesh,
            in_specs=(comm.spec(2, a_split), comm.spec(2, b_split)),
            out_specs=comm.spec(2, 0),
        )(pa, pb)

    return body, 0


def _prec_name(precision) -> Optional[str]:
    return None if precision is None else precision.name


def _mesh_spec(comm) -> dict:
    return {
        "shape": list(comm.mesh.devices.shape),
        "axes": list(comm.mesh.axis_names),
    }


def _execute(plan: Plan, a: DNDarray, b: DNDarray, precision) -> Any:
    """Run the planned ``ring``/``rs`` program through the staged executor.
    ``NotImplemented`` when the signature is still under the jit threshold,
    unsupported, or quarantined after a failure — the caller falls back to
    the XLA path (and records plan ``xla``)."""
    comm = a.comm
    pa, pb = a.parray, b.parray
    pname = _prec_name(precision)
    key = (
        "mm", plan.kind, plan.variant, a.gshape, b.gshape, comm.mesh,
        _executor.operand_sig(pa), _executor.operand_sig(pb), pname,
    )
    maker = _ring_body if plan.kind == "ring" else _rs_body

    def build():
        body, out_split = maker(plan.variant, comm, a.gshape, b.gshape, precision)
        return body, comm.sharding(2, out_split), None, None

    def spec():
        return {
            "family": "mm", "kind": plan.kind, "variant": plan.variant,
            "a_gshape": list(a.gshape), "a_split": a.split,
            "a_dtype": np.dtype(pa.dtype).str, "a_phys": list(pa.shape),
            "b_gshape": list(b.gshape), "b_split": b.split,
            "b_dtype": np.dtype(pb.dtype).str, "b_phys": list(pb.shape),
            "precision": pname, "mesh": _mesh_spec(comm),
        }

    prog = _executor.lookup(key, build, label=f"mm.{plan.kind}.{plan.variant}", spec=spec)
    if prog is None:
        return NotImplemented
    try:
        value = prog(pa, pb)
    except Exception as exc:  # noqa: BLE001 - accounted, then replayed or re-raised
        if not _executor.fallback_after_failure(key, prog, exc):
            raise
        return NotImplemented
    _, out_split = maker(plan.variant, comm, a.gshape, b.gshape, precision)
    out_gshape = (a.gshape[0], b.gshape[1])
    return DNDarray(
        value, out_gshape, types.canonical_heat_type(value.dtype),
        out_split, a.device, comm, True,
    )


# --------------------------------------------------------- all_to_all resplit
def resplit_eligible(x: DNDarray, axis: Optional[int]) -> bool:
    """Whether the split→split layout change ``x.resplit(axis)`` can ride the
    ``all_to_all`` program instead of the gather-based path."""
    return (
        isinstance(x, DNDarray)
        and axis is not None
        and x.split is not None
        and axis != x.split
        and x.comm.size > 1
        and len(x.comm.axis_names) == 1
        and _executor.executor_enabled()
        and _plannable_dtype(x)
        and _executor.linalg_plan() != "xla"
    )


def try_resplit(x: DNDarray, axis: int) -> Any:
    """The physical array of ``x`` re-laid-out from ``split=x.split`` to
    ``split=axis`` via one ``all_to_all`` — each device exchanges only the
    tiles its peers need, ``(P−1)/P·|array|`` total wire bytes vs the
    gather-based path's ``(P−1)·|array|``. Returns the padded-physical
    ``jax.Array`` for the new split, or ``NotImplemented`` for the caller's
    gather-based fallback."""
    if not resplit_eligible(x, axis):
        return NotImplemented
    comm = x.comm
    src, dst = x.split, axis
    gshape = x.gshape
    pv = x.parray
    nd = len(gshape)
    dst_p = comm.padded_dim(gshape[dst])
    src_extent = gshape[src]

    def build():
        def block(lv):
            lv = _pad_to(lv, dst_p, dst)
            out = comm.all_to_all(lv, split_axis=dst, concat_axis=src)
            if out.shape[src] != src_extent:
                out = jax.lax.slice_in_dim(out, 0, src_extent, axis=src)
            return out

        def body(val):
            return compat_shard_map(
                block, comm.mesh,
                in_specs=(comm.spec(nd, src),),
                out_specs=comm.spec(nd, dst),
            )(val)

        return body, comm.sharding(nd, dst), None, None

    def spec():
        return {
            "family": "mm", "kind": "resplit",
            "gshape": list(gshape), "split": src, "dst": dst,
            "dtype": np.dtype(pv.dtype).str, "phys": list(pv.shape),
            "mesh": _mesh_spec(comm),
        }

    key = ("mm", "resplit", gshape, src, dst, comm.mesh, _executor.operand_sig(pv))
    prog = _executor.lookup(key, build, label=f"mm.resplit.{src}->{dst}", spec=spec)
    if prog is None:
        return NotImplemented
    try:
        value = prog(pv)
    except Exception as exc:  # noqa: BLE001 - accounted, then replayed or re-raised
        if not _executor.fallback_after_failure(key, prog, exc):
            raise
        return NotImplemented
    if diagnostics._enabled:
        P = comm.size
        phys = _phys_bytes(comm, gshape, src, x.dtype.jax_type())
        diagnostics.counter("linalg.plan.resplit")
        diagnostics.counter("linalg.bytes.resplit", (P - 1) * phys // P)
        diagnostics.counter("linalg.bytes.resplit_gather_baseline", (P - 1) * phys)
    return value


# ------------------------------------------------------------- warmup replay
def replay_warmup(spec: dict, zeros_dnd) -> bool:
    """Re-enter the recorded family-``"mm"`` program over zero-filled operands
    of the recorded signature (the AOT-warmup tier of the persistent compile
    cache). ``zeros_dnd(gshape, split, dtype_str)`` is
    ``_compile_cache._zeros_dnd``. False when the recorded physical layout no
    longer matches this topology."""
    if spec.get("kind") == "resplit":
        x = zeros_dnd(spec["gshape"], spec["split"], spec["dtype"])
        if list(x.parray.shape) != list(spec["phys"]):
            return False
        return try_resplit(x, spec["dst"]) is not NotImplemented
    a = zeros_dnd(spec["a_gshape"], spec["a_split"], spec["a_dtype"])
    b = zeros_dnd(spec["b_gshape"], spec["b_split"], spec["b_dtype"])
    if list(a.parray.shape) != list(spec["a_phys"]) or list(b.parray.shape) != list(spec["b_phys"]):
        return False
    pname = spec.get("precision")
    precision = None if pname is None else jax.lax.Precision[pname]
    plan = Plan(spec["kind"], spec["variant"], 0, 0)
    return _execute(plan, a, b, precision) is not NotImplemented
