"""QR decomposition (reference heat/core/linalg/qr.py, 1042 LoC).

The reference implements tiled CAQR over ``SquareDiagTiles`` with hand-scheduled
Isend/Irecv merges per tile column (``qr.py:322-865``). The TPU design keeps the
*algorithmic* idea — TSQR: independent panel QRs followed by a reduction QR of the
stacked R factors — but expresses it as a handful of batched XLA ops on the global
array: the per-shard panel QRs are one batched ``jnp.linalg.qr`` (each panel resident
on its device), the R-stack reduction is a single small QR, and the final
``Q = Q_panel @ Q_reduce`` is a batched matmul on the MXU. No tile scheduler survives
because XLA's partitioner is the scheduler.
"""

from __future__ import annotations

import collections
from typing import Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from .. import types
from ..dndarray import DNDarray

__all__ = ["qr"]

QR_t = collections.namedtuple("QR", "Q, R")


def qr(
    a: DNDarray,
    tiles_per_proc: int = 2,
    calc_q: bool = True,
    overwrite_a: bool = False,
) -> Tuple[Optional[DNDarray], DNDarray]:
    """QR decomposition of a 2-D DNDarray; returns ``QR(Q, R)`` (reference ``qr.py:19``).

    ``tiles_per_proc`` keeps the reference's meaning — how many row panels each shard
    contributes (reference builds a ``SquareDiagTiles`` with it, ``qr.py:130``): the
    split=0 TSQR uses ``tiles.tile_rows`` panels, so larger values trade panel-QR size
    for R-stack size. split=1/None lower to XLA's blocked householder QR.
    """
    if not isinstance(a, DNDarray):
        raise TypeError(f"'a' must be a DNDarray, got {type(a)}")
    if a.ndim != 2:
        raise ValueError(f"qr requires a 2-D array, got {a.ndim}-D")
    if not isinstance(tiles_per_proc, int) or tiles_per_proc < 1:
        raise ValueError(f"tiles_per_proc must be a positive int, got {tiles_per_proc}")
    if not types.issubdtype(a.dtype, types.floating):
        a = a.astype(types.promote_types(a.dtype, types.float32))

    m, n = a.gshape
    nproc = a.comm.size

    if a.split == 0 and a.is_distributed() and m >= n * nproc:
        from ..tiling import SquareDiagTiles

        # the reference's tile decomposition fixes the panel schedule (qr.py:130);
        # every tile row is one TSQR level-1 panel
        tiles = SquareDiagTiles(a, tiles_per_proc=tiles_per_proc)
        nblocks = tiles.tile_rows if m >= n * tiles.tile_rows else nproc
        q_val, r_val = _tsqr(a.larray, nblocks, calc_q=calc_q)
    elif calc_q:
        # split=1 / None / short-fat: XLA's QR on the global value (the reference's
        # split=1 path is a panel loop with Bcast, qr.py:866 — subsumed by SPMD)
        q_val, r_val = jnp.linalg.qr(a.larray, mode="reduced")
    else:
        q_val, r_val = None, jnp.linalg.qr(a.larray, mode="r")

    r_split = a.split if a.split is not None and a.split < 2 else None
    if a.split == 0:
        r_split = None  # R is k x n with k = min(m, n); rows live on the merge root
    r = DNDarray(
        a.comm.shard(r_val, r_split), tuple(r_val.shape),
        types.canonical_heat_type(r_val.dtype), r_split, a.device, a.comm, True,
    )
    if overwrite_a:
        a._rebind(r)
    if not calc_q:
        return QR_t(None, r)
    q_split = a.split
    q = DNDarray(
        a.comm.shard(q_val, q_split), tuple(q_val.shape),
        types.canonical_heat_type(q_val.dtype), q_split, a.device, a.comm, True,
    )
    return QR_t(q, r)


def _tsqr(x: jax.Array, nblocks: int, calc_q: bool = True) -> Tuple[Optional[jax.Array], jax.Array]:
    """Two-level TSQR of a tall-skinny (m, n) array split into ``nblocks`` row panels.

    Level 1: batched QR of the panels (runs shard-local under SPMD).
    Level 2: QR of the (nblocks*n, n) R-stack — small, replicated.
    Combine: Q = blockdiag(Q_i) @ Q2, computed as a batched matmul.
    With ``calc_q=False`` only the R factors are formed (mode='r'), skipping the
    dominant Q-assembly cost.
    """
    m, n = x.shape
    rows = -(-m // nblocks)  # canonical ceil-division chunk, matching the sharding
    pad = rows * nblocks - m
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, n), x.dtype)], axis=0)
    panels = x.reshape(nblocks, rows, n)
    if not calc_q:
        r1 = jnp.linalg.qr(panels, mode="r")
        r = jnp.linalg.qr(r1.reshape(nblocks * r1.shape[1], n), mode="r")
        return None, r
    q1, r1 = jnp.linalg.qr(panels, mode="reduced")  # (B, rows, k), (B, k, n)
    k = r1.shape[1]
    q2, r = jnp.linalg.qr(r1.reshape(nblocks * k, n), mode="reduced")
    q2 = q2.reshape(nblocks, k, q2.shape[1])
    # full-precision combine: orthogonality of Q must hold to f32, not bf16-input, ulp
    q = jnp.einsum(
        "bik,bkj->bij", q1, q2, precision=jax.lax.Precision.HIGHEST
    ).reshape(nblocks * rows, q2.shape[2])
    if pad:
        q = q[:m]
    return q, r
