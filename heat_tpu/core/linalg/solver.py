"""Iterative solvers (reference heat/core/linalg/solver.py, 272 LoC).

``cg`` and ``lanczos`` each compile to ONE jitted program — matvec, line search /
reorthogonalization, and the convergence test all run on device inside
``lax.while_loop``/``fori_loop`` (the reference drives every iteration from the host
over MPI collectives; a host loop costs one dispatch round-trip per op). Cross-shard
reductions become ``psum`` on the mesh via XLA's partitioner.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import factories, types
from ..dndarray import DNDarray

__all__ = ["cg", "lanczos"]


def cg(A: DNDarray, b: DNDarray, x0: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """Conjugate gradients for SPD ``A x = b`` (reference ``solver.py:13``)."""
    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray) or not isinstance(x0, DNDarray):
        raise TypeError(f"A, b, x0 need to be DNDarrays, but were {type(A)}, {type(b)}, {type(x0)}")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    if b.ndim != 1:
        raise RuntimeError("b needs to be a 1D vector")
    if x0.ndim != 1:
        raise RuntimeError("x0 needs to be a 1D vector")

    # the whole iteration (matvec, line search, convergence) is one jitted
    # lax.while_loop — the reference's host loop syncs once per iteration.
    # Promote to the widest operand dtype (at least f32) like the old DNDarray-op
    # path did; a silent downcast would also leave the 1e-10 tolerance unreachable.
    dt = types.promote_types(
        types.promote_types(A.dtype, b.dtype),
        types.promote_types(x0.dtype, types.float32),
    ).jax_type()
    x_val = _cg_run(A.larray.astype(dt), b.larray.astype(dt), x0.larray.astype(dt))
    x = factories.array(x_val, split=b.split, device=b.device, comm=b.comm)
    if out is not None:
        out._rebind_physical(out.comm.shard(x.larray.astype(out.larray.dtype), out.split))
        return out
    return x


def _cg_run_impl(a, b, x0):
    hp = jax.lax.Precision.HIGHEST

    def mv(v):
        return jnp.einsum("ij,j->i", a, v, precision=hp)

    r0 = b - mv(x0)
    state0 = (x0, r0, r0, jnp.dot(r0, r0, precision=hp), jnp.int32(0))
    n = b.shape[0]

    def cond(state):
        _, _, _, rsold, it = state
        return jnp.logical_and(it < n, jnp.sqrt(rsold) >= 1e-10)

    def body(state):
        x, r, p, rsold, it = state
        Ap = mv(p)
        alpha = rsold / jnp.dot(p, Ap, precision=hp)
        x = x + alpha * p
        r = r - alpha * Ap
        rsnew = jnp.dot(r, r, precision=hp)
        p = r + (rsnew / rsold) * p
        return x, r, p, rsnew, it + 1

    x, _, _, _, _ = jax.lax.while_loop(cond, body, state0)
    return x


_cg_run = jax.jit(_cg_run_impl)


def _lanczos_device(a, m: int, v_init=None):
    """The whole Lanczos iteration as ONE jitted ``lax.fori_loop`` (the reference — and
    the first TPU port — drove each of the ~5 ops per iteration from the host, which
    costs hundreds of dispatch round-trips; on-device the m=30 run is one launch).

    Returns ``(V_rows, T)`` with ``V_rows`` (m, n): row i is the i-th Lanczos vector.
    Full reorthogonalization per step as two masked matvecs; a vanishing beta restarts
    with a counter-derived random vector (reference ``solver.py:142-156``).
    """
    if v_init is None:
        v_init = jnp.ones((a.shape[0],), a.dtype)
    else:
        v_init = v_init.astype(a.dtype)
    return _lanczos_run(a, v_init, m)


def _lanczos_run_impl(a, v0, m_):
    n = a.shape[0]
    dt = a.dtype
    eps = jnp.asarray(1e-10, dt)
    key = jax.random.key(17)

    def reorth(V, vr, i):
        # project out rows < i: two matvecs instead of a Python loop of dots
        coef = jnp.einsum(
            "mn,n->m", V, vr, precision=jax.lax.Precision.HIGHEST
        ) * (jnp.arange(m_) < i)
        return vr - jnp.einsum(
            "mn,m->n", V, coef, precision=jax.lax.Precision.HIGHEST
        )

    def matvec(x):
        return jnp.einsum("ij,j->i", a, x, precision=jax.lax.Precision.HIGHEST)

    v = v0 / jnp.linalg.norm(v0)
    w0 = matvec(v)
    alpha0 = jnp.dot(w0, v, precision=jax.lax.Precision.HIGHEST)
    V = jnp.zeros((m_, n), dt).at[0].set(v)
    T = jnp.zeros((m_, m_), dt).at[0, 0].set(alpha0)
    w = w0 - alpha0 * v

    def body(i, carry):
        V, T, w = carry
        beta = jnp.linalg.norm(w)
        good = beta > eps
        restart = jax.random.normal(jax.random.fold_in(key, i), (n,), dt)
        vr = jnp.where(good, w / jnp.where(good, beta, 1.0), restart)
        vr = reorth(V, vr, i)
        nrm = jnp.linalg.norm(vr)
        vr = jnp.where(nrm > 0, vr / jnp.where(nrm > 0, nrm, 1.0), vr)
        wn = matvec(vr)
        alpha = jnp.dot(wn, vr, precision=jax.lax.Precision.HIGHEST)
        beta_eff = jnp.where(good, beta, jnp.asarray(0.0, dt))
        wn = wn - alpha * vr - beta_eff * V[i - 1]
        V = V.at[i].set(vr)
        T = T.at[i, i].set(alpha).at[i - 1, i].set(beta_eff).at[i, i - 1].set(beta_eff)
        return V, T, wn

    V, T, _ = jax.lax.fori_loop(1, m_, body, (V, T, w))
    return V, T


# module-level jit: repeated lanczos calls hit the trace cache (a closure-local jit
# would re-trace and re-compile on every invocation)
_lanczos_run = jax.jit(_lanczos_run_impl, static_argnames=("m_",))


def lanczos(
    A: DNDarray,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
) -> Tuple[DNDarray, DNDarray]:
    """Lanczos tridiagonalization of a symmetric/Hermitian matrix
    (reference ``solver.py:69``): returns ``(V, T)`` with ``V`` n×m orthonormal-ish and
    ``T`` m×m tridiagonal."""
    if not isinstance(A, DNDarray):
        raise TypeError(f"A needs to be a DNDarray, got {type(A)}")
    if not isinstance(m, (int, float)):
        raise TypeError(f"m must be int, got {type(m)}")
    n, column = A.gshape
    if n != column:
        raise TypeError("A needs to be a square matrix")
    if v0 is not None and v0.split is not None:
        v0 = v0.resplit(None)
    m = int(m)

    out_dtype = A.dtype if A.dtype is types.float64 else types.float32
    v_init = None if v0 is None else v0.larray
    V_rows, T_val = _lanczos_device(
        A.larray.astype(np.dtype(out_dtype.jax_type())), m, v_init
    )

    T = factories.array(T_val, dtype=out_dtype, split=None, device=A.device, comm=A.comm)
    V_dnd = factories.array(
        V_rows.T, dtype=out_dtype, split=None, device=A.device, comm=A.comm
    )
    if V_out is not None:
        V_out._rebind_physical(V_out.comm.shard(V_dnd.larray.astype(V_out.larray.dtype), V_out.split))
        V_dnd = V_out
    if T_out is not None:
        T_out._rebind_physical(T_out.comm.shard(T.larray.astype(T_out.larray.dtype), T_out.split))
        return V_dnd, T_out
    return V_dnd, T
