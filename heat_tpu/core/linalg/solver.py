"""Iterative solvers (reference heat/core/linalg/solver.py, 272 LoC).

``cg`` and ``lanczos`` are expressed entirely in DNDarray ops — matvecs, dots, norms —
so every iteration is a handful of XLA programs whose cross-shard reductions become
``psum`` on the mesh. The iteration control stays on host (data-dependent convergence),
exactly like the reference's Python loop over MPI collectives.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from functools import partial

from .. import factories, types
from ..dndarray import DNDarray
from .basics import PARITY_PRECISION, norm, transpose
from .basics import dot as _dot
from .basics import matmul as _matmul

__all__ = ["cg", "lanczos"]

# iterative solvers accumulate rounding across iterations: full fp32 matvecs/dots
matmul = partial(_matmul, precision=PARITY_PRECISION)
dot = partial(_dot, precision=PARITY_PRECISION)


def cg(A: DNDarray, b: DNDarray, x0: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """Conjugate gradients for SPD ``A x = b`` (reference ``solver.py:13``)."""
    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray) or not isinstance(x0, DNDarray):
        raise TypeError(f"A, b, x0 need to be DNDarrays, but were {type(A)}, {type(b)}, {type(x0)}")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    if b.ndim != 1:
        raise RuntimeError("b needs to be a 1D vector")
    if x0.ndim != 1:
        raise RuntimeError("x0 needs to be a 1D vector")

    r = b - matmul(A, x0)
    p = r
    rsold = dot(r, r)
    x = x0

    for _ in range(len(b)):
        Ap = matmul(A, p)
        alpha = rsold / dot(p, Ap)
        x = x + alpha * p
        r = r - alpha * Ap
        rsnew = dot(r, r)
        if float(rsnew.item() if isinstance(rsnew, DNDarray) else rsnew) ** 0.5 < 1e-10:
            if out is not None:
                out.larray = out.comm.shard(x.larray.astype(out.larray.dtype), out.split)
                return out
            return x
        p = r + (rsnew / rsold) * p
        rsold = rsnew

    if out is not None:
        out.larray = out.comm.shard(x.larray.astype(out.larray.dtype), out.split)
        return out
    return x


def lanczos(
    A: DNDarray,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
) -> Tuple[DNDarray, DNDarray]:
    """Lanczos tridiagonalization of a symmetric/Hermitian matrix
    (reference ``solver.py:69``): returns ``(V, T)`` with ``V`` n×m orthonormal-ish and
    ``T`` m×m tridiagonal."""
    if not isinstance(A, DNDarray):
        raise TypeError(f"A needs to be a DNDarray, got {type(A)}")
    if not isinstance(m, (int, float)):
        raise TypeError(f"m must be int, got {type(m)}")
    n, column = A.gshape
    if n != column:
        raise TypeError("A needs to be a square matrix")
    if v0 is not None and v0.split is not None:
        v0 = v0.resplit(None)
    m = int(m)

    T = factories.zeros((m, m), dtype=A.dtype if A.dtype is types.float64 else types.float32, comm=A.comm)
    if A.split == 0:
        v = factories.ones((n,), split=0, dtype=A.dtype, comm=A.comm) if v0 is None else v0
    else:
        v = factories.ones((n,), split=None, dtype=A.dtype, comm=A.comm) if v0 is None else v0
    if v0 is None:
        v = v / norm(v)
    vr = v

    # first iteration
    w = matmul(A, vr)
    alpha = float(dot(w, vr).item())
    w = w - alpha * vr
    T[0, 0] = alpha
    V = [vr]
    for i in range(1, m):
        beta = float(norm(w).item())
        if abs(beta) < 1e-10:
            # restart with a random orthogonalized vector (reference solver.py:142-156)
            from .. import random as ht_random

            vr = ht_random.rand(n, dtype=v.dtype, split=v.split, comm=A.comm)
            for vi in V:
                vr = vr - dot(vi, vr) * vi
            vr = vr / norm(vr)
        else:
            vr = w / beta
            # full reorthogonalization for numerical stability (reference does the same
            # via projections when it detects drift)
            for vi in V:
                vr = vr - dot(vi, vr) * vi
            nrm = float(norm(vr).item())
            if nrm > 0:
                vr = vr / nrm
        w = matmul(A, vr)
        alpha = float(dot(w, vr).item())
        w = w - alpha * vr - (beta if abs(beta) >= 1e-10 else 0.0) * V[i - 1]
        T[i, i] = alpha
        T[i - 1, i] = beta
        T[i, i - 1] = beta
        V.append(vr)

    from ..manipulations import stack

    V_dnd = transpose(stack(V, axis=0), None)
    if V_out is not None:
        V_out.larray = V_out.comm.shard(V_dnd.larray.astype(V_out.larray.dtype), V_out.split)
        V_dnd = V_out
    if T_out is not None:
        T_out.larray = T_out.comm.shard(T.larray.astype(T_out.larray.dtype), T_out.split)
        return V_dnd, T_out
    return V_dnd, T
