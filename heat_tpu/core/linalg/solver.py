"""Iterative solvers (reference heat/core/linalg/solver.py, 272 LoC).

``cg`` and ``lanczos`` are expressed entirely in DNDarray ops — matvecs, dots, norms —
so every iteration is a handful of XLA programs whose cross-shard reductions become
``psum`` on the mesh. The iteration control stays on host (data-dependent convergence),
exactly like the reference's Python loop over MPI collectives.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from functools import partial

import jax
import jax.numpy as jnp

from .. import factories, types
from ..dndarray import DNDarray
from .basics import PARITY_PRECISION, norm, transpose
from .basics import dot as _dot
from .basics import matmul as _matmul

__all__ = ["cg", "lanczos"]

# iterative solvers accumulate rounding across iterations: full fp32 matvecs/dots
matmul = partial(_matmul, precision=PARITY_PRECISION)
dot = partial(_dot, precision=PARITY_PRECISION)


def cg(A: DNDarray, b: DNDarray, x0: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """Conjugate gradients for SPD ``A x = b`` (reference ``solver.py:13``)."""
    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray) or not isinstance(x0, DNDarray):
        raise TypeError(f"A, b, x0 need to be DNDarrays, but were {type(A)}, {type(b)}, {type(x0)}")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    if b.ndim != 1:
        raise RuntimeError("b needs to be a 1D vector")
    if x0.ndim != 1:
        raise RuntimeError("x0 needs to be a 1D vector")

    r = b - matmul(A, x0)
    p = r
    rsold = dot(r, r)
    x = x0

    for _ in range(len(b)):
        Ap = matmul(A, p)
        alpha = rsold / dot(p, Ap)
        x = x + alpha * p
        r = r - alpha * Ap
        rsnew = dot(r, r)
        if float(rsnew.item() if isinstance(rsnew, DNDarray) else rsnew) ** 0.5 < 1e-10:
            if out is not None:
                out.larray = out.comm.shard(x.larray.astype(out.larray.dtype), out.split)
                return out
            return x
        p = r + (rsnew / rsold) * p
        rsold = rsnew

    if out is not None:
        out.larray = out.comm.shard(x.larray.astype(out.larray.dtype), out.split)
        return out
    return x


def _lanczos_device(a, m: int, v_init=None):
    """The whole Lanczos iteration as ONE jitted ``lax.fori_loop`` (the reference — and
    the first TPU port — drove each of the ~5 ops per iteration from the host, which
    costs hundreds of dispatch round-trips; on-device the m=30 run is one launch).

    Returns ``(V_rows, T)`` with ``V_rows`` (m, n): row i is the i-th Lanczos vector.
    Full reorthogonalization per step as two masked matvecs; a vanishing beta restarts
    with a counter-derived random vector (reference ``solver.py:142-156``).
    """
    if v_init is None:
        v_init = jnp.ones((a.shape[0],), a.dtype)
    else:
        v_init = v_init.astype(a.dtype)
    return _lanczos_run(a, v_init, m)


def _lanczos_run_impl(a, v0, m_):
    n = a.shape[0]
    dt = a.dtype
    eps = jnp.asarray(1e-10, dt)
    key = jax.random.key(17)

    def reorth(V, vr, i):
        # project out rows < i: two matvecs instead of a Python loop of dots
        coef = jnp.einsum(
            "mn,n->m", V, vr, precision=jax.lax.Precision.HIGHEST
        ) * (jnp.arange(m_) < i)
        return vr - jnp.einsum(
            "mn,m->n", V, coef, precision=jax.lax.Precision.HIGHEST
        )

    def matvec(x):
        return jnp.einsum("ij,j->i", a, x, precision=jax.lax.Precision.HIGHEST)

    v = v0 / jnp.linalg.norm(v0)
    w0 = matvec(v)
    alpha0 = jnp.dot(w0, v, precision=jax.lax.Precision.HIGHEST)
    V = jnp.zeros((m_, n), dt).at[0].set(v)
    T = jnp.zeros((m_, m_), dt).at[0, 0].set(alpha0)
    w = w0 - alpha0 * v

    def body(i, carry):
        V, T, w = carry
        beta = jnp.linalg.norm(w)
        good = beta > eps
        restart = jax.random.normal(jax.random.fold_in(key, i), (n,), dt)
        vr = jnp.where(good, w / jnp.where(good, beta, 1.0), restart)
        vr = reorth(V, vr, i)
        nrm = jnp.linalg.norm(vr)
        vr = jnp.where(nrm > 0, vr / jnp.where(nrm > 0, nrm, 1.0), vr)
        wn = matvec(vr)
        alpha = jnp.dot(wn, vr, precision=jax.lax.Precision.HIGHEST)
        beta_eff = jnp.where(good, beta, jnp.asarray(0.0, dt))
        wn = wn - alpha * vr - beta_eff * V[i - 1]
        V = V.at[i].set(vr)
        T = T.at[i, i].set(alpha).at[i - 1, i].set(beta_eff).at[i, i - 1].set(beta_eff)
        return V, T, wn

    V, T, _ = jax.lax.fori_loop(1, m_, body, (V, T, w))
    return V, T


# module-level jit: repeated lanczos calls hit the trace cache (a closure-local jit
# would re-trace and re-compile on every invocation)
_lanczos_run = jax.jit(_lanczos_run_impl, static_argnames=("m_",))


def lanczos(
    A: DNDarray,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
) -> Tuple[DNDarray, DNDarray]:
    """Lanczos tridiagonalization of a symmetric/Hermitian matrix
    (reference ``solver.py:69``): returns ``(V, T)`` with ``V`` n×m orthonormal-ish and
    ``T`` m×m tridiagonal."""
    if not isinstance(A, DNDarray):
        raise TypeError(f"A needs to be a DNDarray, got {type(A)}")
    if not isinstance(m, (int, float)):
        raise TypeError(f"m must be int, got {type(m)}")
    n, column = A.gshape
    if n != column:
        raise TypeError("A needs to be a square matrix")
    if v0 is not None and v0.split is not None:
        v0 = v0.resplit(None)
    m = int(m)

    out_dtype = A.dtype if A.dtype is types.float64 else types.float32
    v_init = None if v0 is None else v0.larray
    V_rows, T_val = _lanczos_device(
        A.larray.astype(np.dtype(out_dtype.jax_type())), m, v_init
    )

    from ..dndarray import DNDarray as _D

    T = _D(
        A.comm.shard(T_val, None), (m, m), out_dtype, None, A.device, A.comm, True
    )
    V_dnd = _D(
        A.comm.shard(V_rows.T, None), (n, m), out_dtype, None, A.device, A.comm, True
    )
    if V_out is not None:
        V_out.larray = V_out.comm.shard(V_dnd.larray.astype(V_out.larray.dtype), V_out.split)
        V_dnd = V_out
    if T_out is not None:
        T_out.larray = T_out.comm.shard(T.larray.astype(T_out.larray.dtype), T_out.split)
        return V_dnd, T_out
    return V_dnd, T
