"""Full SVD (reference heat/core/linalg/svd.py, 17 LoC).

The reference intentionally raises: "Full SVD computation is not supported in heat. Please
use heat.linalg.hsvd_rank or heat.linalg.hsvd_rtol" (``svd.py:15``). Kept for parity —
the truncated hierarchical SVD in :mod:`.svdtools` is the supported path.
"""

from ..dndarray import DNDarray

__all__ = ["svd"]


def svd(A: DNDarray):
    """Raises NotImplementedError, matching the reference (``svd.py:15``)."""
    raise NotImplementedError(
        "Full SVD computation is not supported. "
        "Please use hsvd_rank or hsvd_rtol to compute an approximate truncated SVD."
    )
