"""Full SVD (reference heat/core/linalg/svd.py, 17 LoC).

The reference intentionally raises: "Full SVD computation is not supported in heat.
Please use heat.linalg.hsvd_rank or heat.linalg.hsvd_rtol" (``svd.py:15``). The TPU
build goes beyond parity and implements it: for a tall-skinny split-0 array the
factorization rides the existing TSQR — ``A = QR``, small local ``R = U_r Σ Vᴴ``,
``U = Q U_r`` (a batched MXU matmul) — so the only non-local math is the
reduction QR the framework already has. Short-fat arrays factor their transpose and
swap the roles of U and V; replicated arrays lower straight to XLA's SVD.

Exactness: this is the exact reduced SVD (rank min(m, n)), not the truncated
hierarchical approximation of :mod:`.svdtools` — use ``hsvd_rank``/``hsvd_rtol``
when an approximation at lower cost is acceptable.
"""

from __future__ import annotations

import collections
from typing import Optional

import jax
import jax.numpy as jnp

from .. import types
from ..dndarray import DNDarray
from .svdtools import guarded_svd

__all__ = ["svd", "pinv", "matrix_rank", "cond"]

SVD_t = collections.namedtuple("SVD", "U, S, Vh")


def _wrap(A: DNDarray, value: jax.Array, split):
    return DNDarray(
        A.comm.shard(value, split), tuple(value.shape),
        types.canonical_heat_type(value.dtype), split, A.device, A.comm, True,
    )


def svd(A: DNDarray, full_matrices: bool = False, compute_uv: bool = True):
    """Reduced SVD of a 2-D DNDarray: ``A = U @ diag(S) @ Vh``.

    Returns the namedtuple ``SVD(U, S, Vh)`` (torch.linalg.svd naming), with U
    keeping A's row distribution and S/Vh replicated; with ``compute_uv=False``
    returns only the singular values. ``full_matrices=True`` is not supported —
    the reduced factorization is the distributed-friendly one (the reference
    offers no full SVD at all, ``svd.py:15``).
    """
    if not isinstance(A, DNDarray):
        raise TypeError(f"'A' must be a DNDarray, got {type(A)}")
    if A.ndim != 2:
        raise ValueError(f"svd requires a 2-D array, got {A.ndim}-D")
    if full_matrices:
        raise NotImplementedError(
            "full_matrices=True is not supported; the reduced SVD is"
        )
    if not types.issubdtype(A.dtype, types.floating):
        A = A.astype(types.promote_types(A.dtype, types.float32))

    m, n = A.gshape

    if m < n:
        # A = U Σ Vᴴ  ⇔  Aᵀ = V Σ Uᴴ: factor the (tall) transpose and swap roles
        res = svd(A.T, compute_uv=compute_uv)
        if not compute_uv:
            return res
        u_t, s, vh_t = res
        return SVD_t(vh_t.T, s, u_t.T)

    from .qr import qr as _qr

    if A.split == 0 and A.is_distributed() and m >= n * A.comm.size:
        # TSQR path: panel QRs + small-R SVD; U = Q @ U_r stays row-distributed
        if not compute_uv:
            _, r = _qr(A, calc_q=False)
            return _wrap(A, guarded_svd(r.larray, compute_uv=False), None)
        q, r = _qr(A, calc_q=True)
        u_r, s_val, vh_val = guarded_svd(r.larray)
        u_val = jnp.matmul(q.larray, u_r, precision=jax.lax.Precision.HIGHEST)
    else:
        if not compute_uv:
            return _wrap(A, guarded_svd(A.larray, compute_uv=False), None)
        u_val, s_val, vh_val = guarded_svd(A.larray)

    u_split = A.split if A.split == 0 else None
    return SVD_t(
        _wrap(A, u_val, u_split), _wrap(A, s_val, None), _wrap(A, vh_val, None)
    )


def pinv(A: DNDarray, rtol: float = 1e-15) -> DNDarray:
    """Moore–Penrose pseudo-inverse via the reduced SVD (numpy.linalg.pinv
    semantics; not in the reference, which has no full SVD to build it on).

    Singular values below ``rtol * max(s)`` are treated as zero. The result of a
    split-0 tall input is split along its columns (the transpose of U's rows).
    """
    u, s, vh = svd(A)
    sv = s.larray
    cutoff = rtol * jnp.max(sv)
    inv_s = jnp.where(sv > cutoff, 1.0 / jnp.where(sv > cutoff, sv, 1.0), 0.0)
    # A⁺ = V Σ⁺ Uᴴ — one einsum so XLA fuses the diagonal scale into the matmul
    value = jnp.einsum(
        "ij,j,kj->ik", jnp.conj(vh.larray).T, inv_s, jnp.conj(u.larray),
        precision=jax.lax.Precision.HIGHEST,
    )
    split = 1 if (A.split == 0 and A.gshape[0] >= A.gshape[1]) else (
        0 if A.split == 1 else None
    )
    return _wrap(A, value, split)


def matrix_rank(A: DNDarray, tol: Optional[float] = None) -> DNDarray:
    """Rank from the singular values (numpy.linalg.matrix_rank semantics:
    default tol = max(s) * max(m, n) * eps)."""
    s = svd(A, compute_uv=False)
    sv = s.larray
    if tol is None:
        eps = jnp.finfo(sv.dtype).eps
        tol_val = jnp.max(sv) * max(A.gshape) * eps
    else:
        tol_val = tol
    value = jnp.sum(sv > tol_val).astype(jnp.int64)
    return _wrap(A, value, None)


def cond(A: DNDarray) -> DNDarray:
    """2-norm condition number σ_max / σ_min (numpy.linalg.cond(p=2))."""
    s = svd(A, compute_uv=False)
    sv = s.larray
    value = jnp.max(sv) / jnp.min(sv)
    return _wrap(A, value, None)
