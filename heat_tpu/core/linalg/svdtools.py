"""Hierarchical SVD (reference heat/core/linalg/svdtools.py, 531 LoC).

The reference's hSVD is the framework's north-star workload: per-rank truncated SVDs of
the local column blocks, then a tree reduction where "loser" ranks ``Send`` their
``U·diag(sigma)`` to "winner" ranks that concatenate and re-truncate
(``svdtools.py:260-470``), with a merge-budget scheduler (``:357-382``) deciding the tree
arity under a memory cap.

The TPU build keeps the identical mathematical tree — local truncation, pairwise/k-way
merge, error accumulation ``err² = Σ err_i² + err_merge²`` — but the "ranks" are column
blocks of one global sharded array: each level is a few jnp ops (batched where shapes
agree) and the Sends are XLA data movement. The merge scheduling survives as plain host
logic between device steps, exactly as SURVEY.md prescribes for data-dependent comm
schedules.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from .. import factories, types
from ..dndarray import DNDarray
from .basics import PARITY_PRECISION, matmul, vector_norm

__all__ = ["hsvd", "hsvd_rank", "hsvd_rtol"]


def guarded_svd(x, full_matrices: bool = False, compute_uv: bool = True):
    """``jnp.linalg.svd`` with the TPU x64 guard, shared by hsvd and the full
    :func:`heat_tpu.linalg.svd`: the float32 SVD lowering SIGABRTs the TPU
    compiler when global x64 mode is on (int64 index types), so the op is traced
    in x32 scope there."""
    if jax.default_backend() != "cpu" and x.dtype == jnp.float32:
        with jax.enable_x64(False):
            return jnp.linalg.svd(x, full_matrices=full_matrices, compute_uv=compute_uv)
    return jnp.linalg.svd(x, full_matrices=full_matrices, compute_uv=compute_uv)


def hsvd_rank(
    A: DNDarray,
    maxrank: int,
    compute_sv: bool = False,
    maxmergedim: Optional[int] = None,
    safetyshift: int = 5,
    silent: bool = True,
):
    """Hierarchical SVD truncated to ``maxrank`` (reference ``svdtools.py:32``)."""
    if A.ndim != 2:
        raise RuntimeError(f"hsvd_rank requires a 2-D array, got {A.ndim}-D")
    A_local_size = max(int(np.ceil(s / max(A.comm.size, 1))) for s in A.gshape)
    if maxmergedim is None:
        maxmergedim = max(A_local_size + 1, 2 * (maxrank + safetyshift) + 1)
    return hsvd(
        A,
        maxrank=maxrank,
        maxmergedim=maxmergedim,
        safetyshift=safetyshift,
        compute_sv=compute_sv,
        silent=silent,
        warnings_off=True,
    )


def hsvd_rtol(
    A: DNDarray,
    rtol: float,
    compute_sv: bool = False,
    maxrank: Optional[int] = None,
    maxmergedim: Optional[int] = None,
    safetyshift: int = 5,
    no_of_merges: Optional[int] = None,
    silent: bool = True,
):
    """Hierarchical SVD truncated to a relative reconstruction-error bound
    (reference ``svdtools.py:125``)."""
    if A.ndim != 2:
        raise RuntimeError(f"hsvd_rtol requires a 2-D array, got {A.ndim}-D")
    return hsvd(
        A,
        rtol=rtol,
        maxrank=maxrank,
        maxmergedim=maxmergedim,
        safetyshift=safetyshift,
        no_of_merges=no_of_merges or 2,
        compute_sv=compute_sv,
        silent=silent,
        warnings_off=True,
    )


def hsvd(
    A: DNDarray,
    maxrank: Optional[int] = None,
    maxmergedim: Optional[int] = None,
    rtol: Optional[float] = None,
    safetyshift: int = 0,
    no_of_merges: Optional[int] = 2,
    compute_sv: bool = False,
    silent: bool = True,
    warnings_off: bool = False,
):
    """Low-level hierarchical SVD (reference ``svdtools.py:260``).

    Returns ``(U, sigma, V, rel_error_estimate)`` if ``compute_sv`` else
    ``(U, rel_error_estimate)``.
    """
    if A.ndim != 2:
        raise RuntimeError(f"hsvd requires a 2-D array, got {A.ndim}-D")
    if A.dtype not in (types.float32, types.float64):
        raise TypeError(f"hsvd requires float32/float64, got {A.dtype}")
    if maxrank is None and rtol is None:
        raise ValueError("at least one of maxrank and rtol must be given")

    # split=0 → run on A.T so the distributed axis is the column axis
    # (reference svdtools.py:316-319)
    transposeflag = A.split == 0
    work = A.T if transposeflag else A

    Anorm = float(vector_norm(work).item())
    x = work.larray
    m, n = x.shape
    nblocks = work.comm.size if work.split == 1 and work.is_distributed() else 1
    if maxrank is None:
        maxrank = min(m, n)

    # per-level absolute tolerance (reference: rtol * ||A|| / sqrt(2*nblocks-1))
    loc_atol = None if rtol is None else rtol * Anorm / np.sqrt(2 * nblocks - 1)

    # level 0: truncated SVD of each shard's column block (whole array if replicated).
    # All blocks of a level go through ONE batched SVD (zero-padded to a common
    # width — zero columns add exact-zero singular values, removed by truncation)
    # and ONE host readback of the singular values for the truncation decisions;
    # the reference runs P sequential device round-trips here (svdtools.py:341).
    # The stack is built by a sharding-preserving reshape (device i already holds
    # exactly column block i under the canonical ceil-division chunking), so each
    # device only ever materialises its own (m, n/P) block — matching the strictly
    # local property of the reference's per-rank SVD (svdtools.py:478) — and the
    # batched SVD runs embarrassingly parallel over the mesh.
    level = 0
    if nblocks == 1:
        outs = _batched_truncated_svd(level, [x], maxrank, loc_atol, safetyshift, silent)
    else:
        stacked = _stack_column_blocks(x, nblocks, work.comm)
        outs = _truncate_stacked(level, stacked, maxrank, loc_atol, safetyshift, silent)
    nodes = [u * s for u, s, _ in outs]  # carry U·diag(sigma) into the merges
    err_squared = [e for _, _, e in outs]
    sigmas = [s for _, s, _ in outs]

    arity = no_of_merges or 2
    while len(nodes) > 1:
        level += 1
        # merge-budget scheduling (reference svdtools.py:357-382) needs only the node
        # *widths*, which are static shapes — pure host logic, no device sync
        groups, keep = [], []
        i = 0
        while i < len(nodes):
            group_idx = [i]
            width = nodes[i].shape[1]
            j = i + 1
            while (
                j < len(nodes)
                and len(group_idx) < arity
                and (maxmergedim is None or width + nodes[j].shape[1] <= maxmergedim)
            ):
                group_idx.append(j)
                width += nodes[j].shape[1]
                j += 1
            (groups if len(group_idx) > 1 else keep).append(group_idx)
            i = j
        cats = [jnp.concatenate([nodes[k] for k in g], axis=1) for g in groups]
        outs = (
            _batched_truncated_svd(level, cats, maxrank, loc_atol, safetyshift, silent)
            if cats
            else []
        )
        merged = {}
        for g, (u, s, e) in zip(groups, outs):
            merged[g[0]] = (u * s, sum(err_squared[k] for k in g) + e, s)
        for g in keep:
            k = g[0]
            merged[k] = (nodes[k], err_squared[k], sigmas[k])
        order = sorted(merged)
        nodes = [merged[k][0] for k in order]
        err_squared = [merged[k][1] for k in order]
        sigmas = [merged[k][2] for k in order]

    # final truncation removes the safetyshift (reference svdtools.py:419-421)
    final_u, final_sigma, final_err = _local_truncated_svd(
        level + 1, 0, nodes[0], maxrank, loc_atol, 0, silent
    )
    total_err_squared = sum(err_squared) + final_err
    rel_err = float(np.sqrt(total_err_squared)) / Anorm if Anorm > 0 else 0.0

    U = factories.array(final_u, split=None, device=A.device, comm=A.comm)
    rel_error_estimate = factories.array(
        np.asarray(rel_err, dtype=np.dtype(final_u.dtype)), device=A.device, comm=A.comm
    )

    # postprocessing (reference svdtools.py:457-470)
    if transposeflag or compute_sv:
        work_dnd = A.T if transposeflag else A
        V = matmul(work_dnd.T, U, precision=PARITY_PRECISION)
        sigma = vector_norm(V, axis=0)
        if float(vector_norm(sigma).item()) > 0:
            from ..manipulations import diag

            V = matmul(V, diag(1.0 / sigma), precision=PARITY_PRECISION)
        if transposeflag:
            if compute_sv:
                return V, sigma, U, rel_error_estimate
            return V, rel_error_estimate
        return U, sigma, V, rel_error_estimate
    return U, rel_error_estimate


# jit cache for the level-0 block stacker, keyed by mesh/shape/dtype (compiles once
# per hsvd configuration; on the real chip a fresh trace costs tens of seconds).
_stack_cache: dict = {}


def _stack_column_blocks(x: jax.Array, nblocks: int, comm) -> jax.Array:
    """Restack the column-sharded ``(m, n)`` array as ``(nblocks, m, w)`` column
    blocks, block ``i`` = ``x[:, i*w:(i+1)*w]`` with ``w = ceil(n / nblocks)`` (the
    canonical ceil-division chunk, :meth:`MeshCommunication.chunk`), zero-padding the
    last block.

    The leading block axis carries the mesh axis (``P('d', None, None)``): device ``i``
    already owns exactly column block ``i`` of a split-1 array, so the pad + reshape +
    transpose is pure local relabeling — the compiled program contains no collectives
    (verified: no all-to-all/all-gather/collective-permute in the HLO) and each device
    holds only its own ``m × w`` block, unlike a ``jnp.stack`` of global slices which
    replicates every block everywhere."""
    m, n = x.shape
    w = -(-n // nblocks)
    pad = w * nblocks - n
    target = comm.sharding(3, 0)
    key = (target, nblocks, m, n, str(x.dtype))  # NamedSharding hashes mesh + devices
    fn = _stack_cache.get(key)
    if fn is None:

        def f(v):
            vp = jnp.pad(v, ((0, 0), (0, pad)))
            st = vp.reshape(m, nblocks, w).transpose(1, 0, 2)
            return jax.lax.with_sharding_constraint(st, target)

        fn = jax.jit(f)
        _stack_cache[key] = fn
    return fn(x)


def _batched_truncated_svd(
    level: int,
    blocks: List[jax.Array],
    maxrank: int,
    loc_atol: Optional[float],
    safetyshift: int,
    silent: bool = True,
) -> List[Tuple[jax.Array, jax.Array, float]]:
    """Truncated SVDs of a list of node blocks: zero-pad to a common width, stack,
    and delegate to :func:`_truncate_stacked`. Used for the merge levels (node widths
    are small, ≤ ``maxrank + safetyshift`` columns each) and the final root
    truncation; level 0 builds its stack sharding-preservingly via
    :func:`_stack_column_blocks` instead."""
    wmax = max(b.shape[1] for b in blocks)
    stacked = jnp.stack(
        [
            jnp.pad(b, ((0, 0), (0, wmax - b.shape[1]))) if b.shape[1] < wmax else b
            for b in blocks
        ]
    )
    return _truncate_stacked(level, stacked, maxrank, loc_atol, safetyshift, silent)


def _truncate_stacked(
    level: int,
    stacked: jax.Array,
    maxrank: int,
    loc_atol: Optional[float],
    safetyshift: int,
    silent: bool = True,
) -> List[Tuple[jax.Array, jax.Array, float]]:
    """Truncated SVDs of one whole tree level from a pre-stacked ``(B, m, w)`` operand
    (reference runs ``compute_local_truncated_svd`` ``svdtools.py:478`` per node, each
    with its own host sync): ONE batched ``jnp.linalg.svd`` — shard-local when the
    stack's block axis is sharded — and the singular values cross to host in ONE
    transfer for the noise-floor / rank / atol truncation decisions. Per node, returns
    ``(U_trunc, sigma_trunc, err²_dropped)``."""
    u, s, _ = guarded_svd(stacked)
    noiselevel = 1e-14 if stacked.dtype == jnp.float64 else 1e-7
    # the level's single host sync; under multiple controllers the blocks live on
    # other hosts too, so the (tiny) singular-value matrix is allgathered so every
    # controller makes identical truncation decisions (reference allgathers the
    # local rank dims the same way, svdtools.py:349)
    if isinstance(s, jax.Array) and not s.is_fully_addressable:
        from jax.experimental import multihost_utils

        s_all = np.asarray(multihost_utils.process_allgather(s, tiled=True))
    else:
        s_all = np.asarray(s)

    results: List[Tuple[jax.Array, jax.Array, float]] = []
    for node_id in range(stacked.shape[0]):
        s_np = s_all[node_id]
        above = np.nonzero(s_np >= noiselevel)[0]
        if len(above) == 0:
            err = float(np.linalg.norm(s_np) ** 2)
            results.append(
                (
                    jnp.zeros((stacked.shape[1], 1), stacked.dtype),
                    jnp.zeros((1,), stacked.dtype),
                    err,
                )
            )
            continue
        cut_noise_rank = int(above.max()) + 1
        if loc_atol is None:
            trunc = min(maxrank, cut_noise_rank)
        else:
            tails = np.array(
                [np.linalg.norm(s_np[k:]) ** 2 for k in range(len(s_np) + 1)]
            )
            ideal = int(np.nonzero(tails < loc_atol**2)[0].min())
            trunc = min(maxrank, ideal, cut_noise_rank)
            if trunc != ideal and not silent:
                print(
                    f"in hSVD (level {level}, node {node_id}): atol requires rank "
                    f"{ideal}, but maxrank={maxrank}. Loss of desired precision likely!"
                )
        trunc = min(len(s_np), trunc + safetyshift)
        # squared energy actually discarded at this node. The reference charges the
        # kept safety-shift columns too (``sigma_loc[loc_trunc_rank - safetyshift:]``,
        # svdtools.py:525), double-counting them against the final truncation; counting
        # only the dropped tail keeps the estimate an upper bound and makes it tight.
        err = float(np.linalg.norm(s_np[trunc:]) ** 2)
        results.append((u[node_id, :, :trunc], s[node_id, :trunc], err))
    return results


def _local_truncated_svd(
    level: int,
    node_id: int,
    x: jax.Array,
    maxrank: int,
    loc_atol: Optional[float],
    safetyshift: int,
    silent: bool = True,
) -> Tuple[jax.Array, jax.Array, float]:
    """Single-node wrapper over :func:`_batched_truncated_svd` (kept for the final
    root truncation and for direct testing)."""
    return _batched_truncated_svd(level, [x], maxrank, loc_atol, safetyshift, silent)[0]
