"""Hierarchical SVD (reference heat/core/linalg/svdtools.py, 531 LoC).

The reference's hSVD is the framework's north-star workload: per-rank truncated SVDs of
the local column blocks, then a tree reduction where "loser" ranks ``Send`` their
``U·diag(sigma)`` to "winner" ranks that concatenate and re-truncate
(``svdtools.py:260-470``), with a merge-budget scheduler (``:357-382``) deciding the tree
arity under a memory cap.

The TPU build keeps the identical mathematical tree — local truncation, pairwise/k-way
merge, error accumulation ``err² = Σ err_i² + err_merge²`` — but the "ranks" are column
blocks of one global sharded array: each level is a few jnp ops (batched where shapes
agree) and the Sends are XLA data movement. The merge scheduling survives as plain host
logic between device steps, exactly as SURVEY.md prescribes for data-dependent comm
schedules.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from .. import factories, types
from ..dndarray import DNDarray
from .basics import matmul, vector_norm

__all__ = ["hsvd", "hsvd_rank", "hsvd_rtol"]


def hsvd_rank(
    A: DNDarray,
    maxrank: int,
    compute_sv: bool = False,
    maxmergedim: Optional[int] = None,
    safetyshift: int = 5,
    silent: bool = True,
):
    """Hierarchical SVD truncated to ``maxrank`` (reference ``svdtools.py:32``)."""
    if A.ndim != 2:
        raise RuntimeError(f"hsvd_rank requires a 2-D array, got {A.ndim}-D")
    A_local_size = max(int(np.ceil(s / max(A.comm.size, 1))) for s in A.gshape)
    if maxmergedim is None:
        maxmergedim = max(A_local_size + 1, 2 * (maxrank + safetyshift) + 1)
    return hsvd(
        A,
        maxrank=maxrank,
        maxmergedim=maxmergedim,
        safetyshift=safetyshift,
        compute_sv=compute_sv,
        silent=silent,
        warnings_off=True,
    )


def hsvd_rtol(
    A: DNDarray,
    rtol: float,
    compute_sv: bool = False,
    maxrank: Optional[int] = None,
    maxmergedim: Optional[int] = None,
    safetyshift: int = 5,
    no_of_merges: Optional[int] = None,
    silent: bool = True,
):
    """Hierarchical SVD truncated to a relative reconstruction-error bound
    (reference ``svdtools.py:125``)."""
    if A.ndim != 2:
        raise RuntimeError(f"hsvd_rtol requires a 2-D array, got {A.ndim}-D")
    return hsvd(
        A,
        rtol=rtol,
        maxrank=maxrank,
        maxmergedim=maxmergedim,
        safetyshift=safetyshift,
        no_of_merges=no_of_merges or 2,
        compute_sv=compute_sv,
        silent=silent,
        warnings_off=True,
    )


def hsvd(
    A: DNDarray,
    maxrank: Optional[int] = None,
    maxmergedim: Optional[int] = None,
    rtol: Optional[float] = None,
    safetyshift: int = 0,
    no_of_merges: Optional[int] = 2,
    compute_sv: bool = False,
    silent: bool = True,
    warnings_off: bool = False,
):
    """Low-level hierarchical SVD (reference ``svdtools.py:260``).

    Returns ``(U, sigma, V, rel_error_estimate)`` if ``compute_sv`` else
    ``(U, rel_error_estimate)``.
    """
    if A.ndim != 2:
        raise RuntimeError(f"hsvd requires a 2-D array, got {A.ndim}-D")
    if A.dtype not in (types.float32, types.float64):
        raise TypeError(f"hsvd requires float32/float64, got {A.dtype}")
    if maxrank is None and rtol is None:
        raise ValueError("at least one of maxrank and rtol must be given")

    # split=0 → run on A.T so the distributed axis is the column axis
    # (reference svdtools.py:316-319)
    transposeflag = A.split == 0
    work = A.T if transposeflag else A

    Anorm = float(vector_norm(work).item())
    x = work.larray
    m, n = x.shape
    nblocks = work.comm.size if work.split == 1 and work.is_distributed() else 1
    if maxrank is None:
        maxrank = min(m, n)

    # per-level absolute tolerance (reference: rtol * ||A|| / sqrt(2*nblocks-1))
    loc_atol = None if rtol is None else rtol * Anorm / np.sqrt(2 * nblocks - 1)

    # level 0: truncated SVD of each rank's column block (whole array if replicated)
    if nblocks == 1:
        nodes: List[jax.Array] = [x]
    else:
        bounds = [work.comm.chunk((m, n), 1, rank=r)[2][1] for r in range(nblocks)]
        nodes = [x[:, sl] for sl in bounds]
    level = 0
    err_squared = [0.0] * len(nodes)
    sigmas: List[jax.Array] = [None] * len(nodes)
    new_nodes, new_err, new_sig = [], [], []
    for i, blk in enumerate(nodes):
        u, s, e = _local_truncated_svd(level, i, blk, maxrank, loc_atol, safetyshift, silent)
        new_nodes.append(u * s)  # carry U·diag(sigma) into the merges, like the Sends
        new_err.append(e)
        new_sig.append(s)
    nodes, err_squared, sigmas = new_nodes, new_err, new_sig

    arity = no_of_merges or 2
    while len(nodes) > 1:
        level += 1
        merged_nodes, merged_err, merged_sig = [], [], []
        i = 0
        while i < len(nodes):
            group = [nodes[i]]
            group_err = err_squared[i]
            width = nodes[i].shape[1]
            j = i + 1
            # merge-budget scheduling (reference svdtools.py:357-382): grow the group
            # while the concatenation stays under maxmergedim and the arity cap
            while (
                j < len(nodes)
                and len(group) < arity
                and (maxmergedim is None or width + nodes[j].shape[1] <= maxmergedim)
            ):
                group.append(nodes[j])
                group_err += err_squared[j]
                width += nodes[j].shape[1]
                j += 1
            if len(group) == 1:
                merged_nodes.append(group[0])
                merged_err.append(group_err)
                merged_sig.append(sigmas[i])
            else:
                cat = jnp.concatenate(group, axis=1)
                u, s, e = _local_truncated_svd(level, i, cat, maxrank, loc_atol, safetyshift, silent)
                merged_nodes.append(u * s)
                merged_err.append(group_err + e)
                merged_sig.append(s)
            i = j
        nodes, err_squared, sigmas = merged_nodes, merged_err, merged_sig

    # final truncation removes the safetyshift (reference svdtools.py:419-421)
    final_u, final_sigma, final_err = _local_truncated_svd(
        level + 1, 0, nodes[0], maxrank, loc_atol, 0, silent
    )
    total_err_squared = sum(err_squared) + final_err
    rel_err = float(np.sqrt(total_err_squared)) / Anorm if Anorm > 0 else 0.0

    U = factories.array(final_u, split=None, device=A.device, comm=A.comm)
    rel_error_estimate = factories.array(
        np.asarray(rel_err, dtype=np.dtype(final_u.dtype)), device=A.device, comm=A.comm
    )

    # postprocessing (reference svdtools.py:457-470)
    if transposeflag or compute_sv:
        work_dnd = A.T if transposeflag else A
        V = matmul(work_dnd.T, U)
        sigma = vector_norm(V, axis=0)
        if float(vector_norm(sigma).item()) > 0:
            from ..manipulations import diag

            V = matmul(V, diag(1.0 / sigma))
        if transposeflag:
            if compute_sv:
                return V, sigma, U, rel_error_estimate
            return V, rel_error_estimate
        return U, sigma, V, rel_error_estimate
    return U, rel_error_estimate


def _local_truncated_svd(
    level: int,
    node_id: int,
    x: jax.Array,
    maxrank: int,
    loc_atol: Optional[float],
    safetyshift: int,
    silent: bool = True,
) -> Tuple[jax.Array, jax.Array, float]:
    """Truncated SVD of one tree node (reference ``compute_local_truncated_svd``
    ``svdtools.py:478``): noise-floor cut, rank/atol truncation, safety shift, and the
    squared truncation error of what was dropped."""
    if jax.default_backend() != "cpu" and x.dtype == jnp.float32:
        # TPU workaround: the float32 SVD lowering SIGABRTs the TPU compiler when
        # global x64 mode is on (int64 index types); trace this op in x32 scope
        with jax.enable_x64(False):
            u, s, _ = jnp.linalg.svd(x, full_matrices=False)
    else:
        u, s, _ = jnp.linalg.svd(x, full_matrices=False)
    noiselevel = 1e-14 if x.dtype == jnp.float64 else 1e-7
    s_np = np.asarray(s)
    above = np.nonzero(s_np >= noiselevel)[0]
    if len(above) == 0:
        err = float(np.linalg.norm(s_np) ** 2)
        return (
            jnp.zeros((x.shape[0], 1), x.dtype),
            jnp.zeros((1,), x.dtype),
            err,
        )
    cut_noise_rank = int(above.max()) + 1
    if loc_atol is None:
        trunc = min(maxrank, cut_noise_rank)
    else:
        tails = np.array([np.linalg.norm(s_np[k:]) ** 2 for k in range(len(s_np) + 1)])
        ideal = int(np.nonzero(tails < loc_atol**2)[0].min())
        trunc = min(maxrank, ideal, cut_noise_rank)
        if trunc != ideal and not silent:
            print(
                f"in hSVD (level {level}, node {node_id}): atol requires rank {ideal}, "
                f"but maxrank={maxrank}. Loss of desired precision likely!"
            )
    trunc = min(len(s_np), trunc + safetyshift)
    # squared energy actually discarded at this node. The reference charges the kept
    # safety-shift columns too (``sigma_loc[loc_trunc_rank - safetyshift:]``,
    # svdtools.py:525), double-counting them against the final truncation; counting only
    # the dropped tail keeps the estimate an upper bound and makes it tight.
    err = float(np.linalg.norm(s_np[trunc:]) ** 2)
    return u[:, :trunc], s[:trunc], err
