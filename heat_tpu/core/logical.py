"""Logical operations (reference heat/core/logical.py, 557 LoC, 14 exports)."""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from . import _operations, types
from .dndarray import DNDarray

__all__ = [
    "all",
    "allclose",
    "any",
    "isclose",
    "isfinite",
    "isinf",
    "isnan",
    "isneginf",
    "isposinf",
    "logical_and",
    "logical_not",
    "logical_or",
    "logical_xor",
    "signbit",
]


def all(x: DNDarray, axis=None, out=None, keepdims=False) -> DNDarray:  # noqa: A001
    """Whether all elements evaluate True (reference ``logical.py`` all → ``__reduce_op``
    with ``MPI.LAND``; here a jnp.all whose cross-shard and-reduce XLA emits)."""
    return _operations.reduce_op(jnp.all, x, axis, out, keepdims)


def allclose(x, y, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> bool:
    """Collective scalar closeness verdict (reference ``logical.py:109``)."""
    from . import factories

    a = x if isinstance(x, DNDarray) else factories.array(x)
    b = y if isinstance(y, DNDarray) else factories.array(y)
    return bool(jnp.allclose(a.larray, b.larray, rtol=rtol, atol=atol, equal_nan=equal_nan))


def any(x: DNDarray, axis=None, out=None, keepdims=False) -> DNDarray:  # noqa: A001
    """Whether any element evaluates True (reference ``logical.py`` any, ``MPI.LOR``)."""
    return _operations.reduce_op(jnp.any, x, axis, out, keepdims)


def isclose(x, y, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> DNDarray:
    """Element-wise closeness (reference ``logical.py:229``)."""
    return _operations.binary_op(
        jnp.isclose, x, y, fn_kwargs=dict(rtol=rtol, atol=atol, equal_nan=equal_nan)
    )


def isfinite(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.isfinite, x, out)


def isinf(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.isinf, x, out)


def isnan(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.isnan, x, out)


def isneginf(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.isneginf, x, out)


def isposinf(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.isposinf, x, out)


def logical_and(x, y) -> DNDarray:
    return _operations.binary_op(jnp.logical_and, x, y)


def logical_not(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.logical_not, x, out)


def logical_or(x, y) -> DNDarray:
    return _operations.binary_op(jnp.logical_or, x, y)


def logical_xor(x, y) -> DNDarray:
    return _operations.binary_op(jnp.logical_xor, x, y)


def signbit(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.signbit, x, out)
