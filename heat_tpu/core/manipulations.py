"""Array manipulations (reference heat/core/manipulations.py, 4180 LoC).

The reference is the comm-heaviest layer in Heat: ``reshape`` is an Alltoallv pipeline
(``manipulations.py:1995``), ``sort`` a distributed sample-sort (``:2429``), ``unique`` a
merge of per-rank partials (``:3203``), ``concatenate`` a split-matching resplit dance
(``:391``). On TPU every payload is a single global ``jax.Array``, so each of these is one
jnp call — XLA emits the all-to-alls for the layout changes — plus split bookkeeping
deciding which output dimension keeps the mesh axis.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import _operations, sanitation, stride_tricks, types
from .communication import get_comm
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis

__all__ = [
    "balance",
    "broadcast_arrays",
    "broadcast_to",
    "collect",
    "column_stack",
    "concatenate",
    "diag",
    "diagonal",
    "dsplit",
    "expand_dims",
    "flatten",
    "flip",
    "fliplr",
    "flipud",
    "hsplit",
    "hstack",
    "moveaxis",
    "pad",
    "ravel",
    "redistribute",
    "repeat",
    "reshape",
    "resplit",
    "roll",
    "rot90",
    "row_stack",
    "shape",
    "sort",
    "split",
    "squeeze",
    "stack",
    "swapaxes",
    "tile",
    "topk",
    "unique",
    "vsplit",
    "vstack",
]


_wrap = _operations.wrap_result
_handle_out = _operations.handle_out


def _ensure(x) -> DNDarray:
    from . import factories

    return x if isinstance(x, DNDarray) else factories.array(x)


def balance(array: DNDarray, copy: bool = False) -> DNDarray:
    """Out-of-place balance (reference ``manipulations.py:37``). XLA layouts are always
    the canonical chunks, so this is a copy at most."""
    sanitation.sanitize_in(array)
    if copy:
        from . import memory

        return memory.copy(array)
    return array.balance_()


def broadcast_arrays(*arrays: DNDarray) -> List[DNDarray]:
    """Broadcast arrays against each other (reference ``manipulations.py:76``)."""
    arrays = [_ensure(a) for a in arrays]
    shapes = [a.gshape for a in arrays]
    out_shape = stride_tricks.broadcast_shapes(*shapes) if len(shapes) > 1 else shapes[0]
    return [broadcast_to(a, out_shape) for a in arrays]


def broadcast_to(x: DNDarray, shape: Sequence[int]) -> DNDarray:
    """Broadcast to a new shape (reference ``manipulations.py:130``)."""
    sanitation.sanitize_in(x)
    shape = tuple(int(s) for s in shape)
    result = jnp.broadcast_to(x.larray, shape)
    split = None if x.split is None else x.split + (len(shape) - x.ndim)
    return _wrap(result, x, split)


def collect(arr: DNDarray, target_rank: int = 0) -> DNDarray:
    """Out-of-place collect to one rank ≙ replicate (reference ``manipulations.py:180``)."""
    sanitation.sanitize_in(arr)
    return arr.resplit(None)


def column_stack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack 1-D/2-D arrays as columns of a 2-D array (reference ``manipulations.py:225``)."""
    arrays = [_ensure(a) for a in arrays]
    proto = arrays[0]
    locs = [a.larray if a.ndim > 1 else a.larray.reshape(-1, 1) for a in arrays]
    result = jnp.concatenate(locs, axis=1)
    split = next((a.split for a in arrays if a.split is not None), None)
    return _wrap(result, proto, split)


def concatenate(arrays: Sequence[DNDarray], axis: int = 0) -> DNDarray:
    """Join arrays along an existing axis (reference ``manipulations.py:391``; the
    split-matching resplit machinery there is handled by XLA's layout solver)."""
    if not isinstance(arrays, (tuple, list)):
        raise TypeError("concatenate requires a sequence of DNDarrays")
    if len(arrays) == 0:
        raise ValueError("need at least one array to concatenate")
    arrays = [_ensure(a) for a in arrays]
    proto = arrays[0]
    axis = sanitize_axis(proto.gshape, axis)
    dt = types.result_type(*arrays)
    locs = [a.larray.astype(dt.jax_type()) for a in arrays]
    result = jnp.concatenate(locs, axis=axis)
    split = next((a.split for a in arrays if a.split is not None), None)
    return _wrap(result, proto, split)


def diag(a: DNDarray, offset: int = 0) -> DNDarray:
    """Extract a diagonal or construct a diagonal matrix (reference ``manipulations.py:529``)."""
    sanitation.sanitize_in(a)
    if a.ndim == 1:
        result = jnp.diag(a.larray, k=offset)
        return _wrap(result, a, a.split)
    return diagonal(a, offset=offset)


def diagonal(a: DNDarray, offset: int = 0, dim1: int = 0, dim2: int = 1) -> DNDarray:
    """Return specified diagonals (reference ``manipulations.py:610``)."""
    sanitation.sanitize_in(a)
    if a.ndim < 2:
        raise ValueError("diagonal requires at least 2 dimensions")
    dim1 = sanitize_axis(a.gshape, dim1)
    dim2 = sanitize_axis(a.gshape, dim2)
    if dim1 == dim2:
        raise ValueError("dim1 and dim2 must be different")
    result = jnp.diagonal(a.larray, offset=offset, axis1=dim1, axis2=dim2)
    # surviving dims keep relative order; diagonal appended last
    if a.split is None or a.split in (dim1, dim2):
        split = None
    else:
        split = a.split - sum(1 for d in (dim1, dim2) if d < a.split)
    return _wrap(result, a, split)


def dsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split along the third axis (reference ``manipulations.py:676``)."""
    return split(x, indices_or_sections, axis=2)


def expand_dims(a: DNDarray, axis: int) -> DNDarray:
    """Insert a new axis (reference ``manipulations.py:718``)."""
    sanitation.sanitize_in(a)
    axis = sanitize_axis(a.gshape + (1,), axis)
    result = jnp.expand_dims(a.larray, axis)
    split = a.split if a.split is None or a.split < axis else a.split + 1
    return _wrap(result, a, split)


def flatten(a: DNDarray) -> DNDarray:
    """Flatten to 1-D (reference ``manipulations.py:770``)."""
    sanitation.sanitize_in(a)
    result = a.larray.reshape(-1)
    return _wrap(result, a, None if a.split is None else 0)


def flip(a: DNDarray, axis: Optional[Union[int, Tuple[int, ...]]] = None) -> DNDarray:
    """Reverse element order along axis (reference ``manipulations.py:823``)."""
    sanitation.sanitize_in(a)
    axis = sanitize_axis(a.gshape, axis) if axis is not None else None
    result = jnp.flip(a.larray, axis=axis)
    return _wrap(result, a, a.split)


def fliplr(a: DNDarray) -> DNDarray:
    """Flip along axis 1 (reference ``manipulations.py:877``)."""
    if a.ndim < 2:
        raise IndexError("fliplr requires at least 2 dimensions")
    return flip(a, 1)


def flipud(a: DNDarray) -> DNDarray:
    """Flip along axis 0 (reference ``manipulations.py:905``)."""
    return flip(a, 0)


def hsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split along the second axis (1-D: axis 0; reference ``manipulations.py:931``)."""
    if x.ndim < 2:
        return split(x, indices_or_sections, axis=0)
    return split(x, indices_or_sections, axis=1)


def hstack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack horizontally (reference ``manipulations.py:976``)."""
    arrays = [_ensure(a) for a in arrays]
    axis = 0 if all(a.ndim == 1 for a in arrays) else 1
    return concatenate(arrays, axis=axis)


def moveaxis(
    x: DNDarray, source: Union[int, Sequence[int]], destination: Union[int, Sequence[int]]
) -> DNDarray:
    """Move axes to new positions (reference ``manipulations.py:1023``)."""
    sanitation.sanitize_in(x)
    if isinstance(source, int):
        source = (source,)
    if isinstance(destination, int):
        destination = (destination,)
    source = tuple(sanitize_axis(x.gshape, s) for s in source)
    destination = tuple(sanitize_axis(x.gshape, d) for d in destination)
    if len(source) != len(destination):
        raise ValueError("source and destination must have the same number of elements")
    order = [n for n in range(x.ndim) if n not in source]
    for dest, src in sorted(zip(destination, source)):
        order.insert(dest, src)
    from .linalg import transpose

    return transpose(x, order)


def pad(array: DNDarray, pad_width, mode: str = "constant", constant_values=0) -> DNDarray:
    """Pad an array (reference ``manipulations.py:1329``; numpy-compatible widths)."""
    sanitation.sanitize_in(array)
    if isinstance(pad_width, int):
        np_width = pad_width
    else:
        np_width = tuple(tuple(p) if isinstance(p, (tuple, list)) else p for p in pad_width) \
            if isinstance(pad_width, (tuple, list)) else pad_width
    kwargs = {"constant_values": constant_values} if mode == "constant" else {}
    result = jnp.pad(array.larray, np_width, mode=mode, **kwargs)
    return _wrap(result, array, array.split)


def ravel(a: DNDarray) -> DNDarray:
    """Flatten view (reference ``manipulations.py:1672``)."""
    return flatten(a)


def redistribute(arr: DNDarray, lshape_map=None, target_map=None) -> DNDarray:
    """Out-of-place redistribute (reference ``manipulations.py:1707``)."""
    from . import memory

    out = memory.copy(arr)
    out.redistribute_(lshape_map, target_map)
    return out


def repeat(a: DNDarray, repeats, axis: Optional[int] = None) -> DNDarray:
    """Repeat elements (reference ``manipulations.py:1764``)."""
    a = _ensure(a)
    r = repeats.larray if isinstance(repeats, DNDarray) else repeats
    if axis is not None:
        axis = sanitize_axis(a.gshape, axis)
    result = jnp.repeat(a.larray, r, axis=axis)
    split = (None if a.split is None else 0) if axis is None else a.split
    return _wrap(result, a, split)


def reshape(a: DNDarray, *shape, **kwargs) -> DNDarray:
    """Reshape with optional ``new_split`` (reference ``manipulations.py:1995``; the
    reference's Alltoallv pipeline is XLA's relayout)."""
    sanitation.sanitize_in(a)
    new_split = kwargs.pop("new_split", None)
    if kwargs:
        raise TypeError(f"unexpected kwargs {tuple(kwargs)}")
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    shape = tuple(int(s) for s in shape)
    # resolve -1
    if any(s == -1 for s in shape):
        known = int(np.prod([s for s in shape if s != -1]))
        missing = a.size // known if known else 0
        shape = tuple(missing if s == -1 else s for s in shape)
    if int(np.prod(shape)) != a.size:
        raise ValueError(f"cannot reshape array of size {a.size} into shape {shape}")
    result = a.larray.reshape(shape)
    if new_split is None:
        new_split = a.split if a.split is not None and a.split < len(shape) else (
            None if a.split is None else len(shape) - 1
        )
    else:
        new_split = sanitize_axis(shape, new_split)
    return _wrap(result, a, new_split)


def resplit(arr: DNDarray, axis: Optional[int] = None) -> DNDarray:
    """Out-of-place resplit (reference ``manipulations.py:3480``)."""
    sanitation.sanitize_in(arr)
    return arr.resplit(axis)


def roll(x: DNDarray, shift, axis=None) -> DNDarray:
    """Roll elements along axis (reference ``manipulations.py:2157``; the reference's
    Isend ring is a collective-permute emitted by XLA)."""
    sanitation.sanitize_in(x)
    if axis is not None:
        axis = (
            tuple(sanitize_axis(x.gshape, ax) for ax in axis)
            if isinstance(axis, (tuple, list))
            else sanitize_axis(x.gshape, axis)
        )
    result = jnp.roll(x.larray, shift, axis=axis)
    return _wrap(result, x, x.split)


def rot90(m: DNDarray, k: int = 1, axes: Sequence[int] = (0, 1)) -> DNDarray:
    """Rotate by 90° in the plane of ``axes`` (reference ``manipulations.py:2277``)."""
    sanitation.sanitize_in(m)
    axes = tuple(sanitize_axis(m.gshape, ax) for ax in axes)
    if len(axes) != 2 or axes[0] == axes[1]:
        raise ValueError("len(axes) must be 2 with distinct entries")
    result = jnp.rot90(m.larray, k=k, axes=axes)
    split = m.split
    if split in axes and (k % 4) in (1, 3):
        split = axes[1] if split == axes[0] else axes[0]
    return _wrap(result, m, split)


def row_stack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack row-wise (reference ``manipulations.py:2369``)."""
    arrays = [_ensure(a) for a in arrays]
    locs = [a.larray if a.ndim > 1 else a.larray.reshape(1, -1) for a in arrays]
    result = jnp.concatenate(locs, axis=0)
    proto = arrays[0]
    split = next((a.split for a in arrays if a.split is not None), None)
    return _wrap(result, proto, split)


def shape(a: DNDarray) -> Tuple[int, ...]:
    """Global shape (reference ``manipulations.py:2415``)."""
    return a.gshape


def sort(a: DNDarray, axis: int = -1, descending: bool = False, out=None):
    """Sort along axis; returns ``(values, indices)`` (reference ``manipulations.py:2429``).

    Along the split axis this runs the distributed merge-split sorting network
    (:mod:`heat_tpu.core.dist_sort`) — the TPU-native form of the reference's
    sample-sort, O(n/P) memory per device. Other axes are embarrassingly parallel:
    one local argsort per shard, no communication."""
    from . import dist_sort

    sanitation.sanitize_in(a)
    axis = sanitize_axis(a.gshape, axis)
    comm = a.comm
    if dist_sort.can_distribute_sort(comm, a.gshape, a.split, axis, a.parray.dtype):
        # padded-physical in, padded-physical out: O(n/P) per device end to end
        values, indices = dist_sort.distributed_sort(
            comm, comm.shard(a.parray, a.split), axis, descending,
            logical_n=a.gshape[axis],
        )
        indices = indices.astype(jnp.int64)
        if values.shape[axis] != a.gshape[axis]:
            # distributed_sort pads with sort sentinels (NaN / dtype extrema); the
            # DNDarray layout contract is zero pads (guards probe parray directly)
            values = _operations._zero_pads(values, a.gshape, a.split)
            indices = _operations._zero_pads(indices, a.gshape, a.split)
        v = DNDarray(values, a.gshape, types.canonical_heat_type(values.dtype),
                     a.split, a.device, a.comm, True)
        i = DNDarray(indices, a.gshape, types.canonical_heat_type(indices.dtype),
                     a.split, a.device, a.comm, True)
        return _handle_out(v, out, a), i
    else:
        indices = jnp.argsort(
            a.larray, axis=axis, descending=descending, stable=True
        ).astype(jnp.int64)
        values = jnp.take_along_axis(a.larray, indices, axis=axis)
    v = _wrap(values, a, a.split)
    i = _wrap(indices, a, a.split)
    return _handle_out(v, out, a), i


def split(x: DNDarray, indices_or_sections, axis: int = 0) -> List[DNDarray]:
    """Split into sub-arrays (reference ``manipulations.py:2555``)."""
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.gshape, axis)
    if isinstance(indices_or_sections, DNDarray):
        indices_or_sections = indices_or_sections.numpy().tolist()
    elif isinstance(indices_or_sections, np.ndarray):
        indices_or_sections = indices_or_sections.tolist()
    parts = jnp.split(x.larray, indices_or_sections, axis=axis)
    return [_wrap(p, x, x.split if x.split != axis else None) for p in parts]


def squeeze(x: DNDarray, axis: Optional[Union[int, Tuple[int, ...]]] = None) -> DNDarray:
    """Remove size-1 dimensions (reference ``manipulations.py:2682``)."""
    sanitation.sanitize_in(x)
    if axis is None:
        removed = tuple(i for i, s in enumerate(x.gshape) if s == 1)
    else:
        axes = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
        removed = tuple(sanitize_axis(x.gshape, ax) for ax in axes)
        for ax in removed:
            if x.gshape[ax] != 1:
                raise ValueError(f"cannot squeeze axis {ax} with size {x.gshape[ax]}")
    result = jnp.squeeze(x.larray, axis=removed if removed else None)
    split = x.split
    if split is not None:
        if split in removed:
            split = None
        else:
            split -= sum(1 for ax in removed if ax < split)
    return _wrap(result, x, split)


def stack(arrays: Sequence[DNDarray], axis: int = 0, out=None) -> DNDarray:
    """Join along a new axis (reference ``manipulations.py:2778``)."""
    arrays = [_ensure(a) for a in arrays]
    proto = arrays[0]
    for a in arrays[1:]:
        if a.gshape != proto.gshape:
            raise ValueError("all input arrays must have the same shape")
    axis = sanitize_axis(proto.gshape + (1,), axis)
    result = jnp.stack([a.larray for a in arrays], axis=axis)
    base_split = next((a.split for a in arrays if a.split is not None), None)
    split = None if base_split is None else (base_split if base_split < axis else base_split + 1)
    return _handle_out(_wrap(result, proto, split), out, proto)


def swapaxes(x: DNDarray, axis1: int, axis2: int) -> DNDarray:
    """Interchange two axes (reference ``manipulations.py:2890``)."""
    sanitation.sanitize_in(x)
    axis1 = sanitize_axis(x.gshape, axis1)
    axis2 = sanitize_axis(x.gshape, axis2)
    axes = list(range(x.ndim))
    axes[axis1], axes[axis2] = axes[axis2], axes[axis1]
    from .linalg import transpose

    return transpose(x, axes)


def tile(x: DNDarray, reps: Sequence[int]) -> DNDarray:
    """Construct by repeating (reference ``manipulations.py:2933``)."""
    sanitation.sanitize_in(x)
    if isinstance(reps, int):
        reps = (reps,)
    reps = tuple(int(r) for r in reps)
    result = jnp.tile(x.larray, reps)
    split = None if x.split is None else x.split + (result.ndim - x.ndim)
    return _wrap(result, x, split)


def _topk_split(a: DNDarray, k: int, dim: int, largest: bool):
    """Distributed top-k along the split axis (reference ``mpi_topk``
    ``manipulations.py:4137``): each shard selects its own k candidates locally
    (O(n/P)), a tiled all-gather moves only P·k candidates (k·P ≪ n), and the final
    k are chosen from those — the reference's candidate-reduction scheme on XLA
    collectives. Smallest-k avoids negation (INT_MIN/unsigned-safe) via a plain
    ascending argsort of the shard, mirroring the global fallback path.

    Tie order matches the global path (lowest global index wins): per-shard
    selections are index-ascending among equal values, the gather is shard-major,
    and the final stable argsort preserves that order.
    """
    comm = a.comm
    phys = a.parray
    c = phys.shape[dim] // comm.size
    n = a.gshape[dim]
    nd = phys.ndim
    last = nd - 1

    def block(x):
        r = jax.lax.axis_index(comm.axis_name)
        xm = jnp.moveaxis(x, dim, -1)
        gidx = r * c + jnp.arange(c)
        valid = gidx < n  # exclude layout-padding slots from candidacy
        if largest:
            info = jnp.iinfo(xm.dtype) if jnp.issubdtype(xm.dtype, jnp.integer) else None
            sent = info.min if info else -jnp.inf
            xv = jnp.where(valid, xm, jnp.asarray(sent, xm.dtype))
            vals, li = jax.lax.top_k(xv, k)
        else:
            info = jnp.iinfo(xm.dtype) if jnp.issubdtype(xm.dtype, jnp.integer) else None
            sent = info.max if info else jnp.inf
            xv = jnp.where(valid, xm, jnp.asarray(sent, xm.dtype))
            li = jnp.argsort(xv, axis=-1)[..., :k]
            vals = jnp.take_along_axis(xv, li, axis=-1)
        gi = li + r * c
        cv = comm.all_gather(vals, axis=last)
        ci = comm.all_gather(gi, axis=last)
        sel = jnp.argsort(cv, axis=-1, descending=largest, stable=True)[..., :k]
        fv = jnp.take_along_axis(cv, sel, axis=-1)
        fi = jnp.take_along_axis(ci, sel, axis=-1)
        return jnp.moveaxis(fv, -1, dim), jnp.moveaxis(fi, -1, dim)

    from jax.sharding import PartitionSpec

    rep = PartitionSpec(*([None] * nd))
    values, idx = jax.shard_map(
        block, mesh=comm.mesh, in_specs=(comm.spec(nd, dim),), out_specs=(rep, rep),
        check_vma=False,  # outputs ARE replicated (post-all_gather) but the static
        # varying-manual-axes analysis cannot see through the final take_along_axis
    )(phys)
    return values, idx


def topk(
    a: DNDarray,
    k: int,
    dim: int = -1,
    largest: bool = True,
    sorted: bool = True,
    out=None,
):
    """k largest/smallest entries along ``dim``; returns ``(values, indices)``
    (reference ``manipulations.py:3982``). Along a split ``dim`` this is the
    candidate-reduction scheme of the reference's ``mpi_topk`` (per-shard top-k +
    P·k-candidate gather — O(n/P + k·P) per device); otherwise a global top-k XLA
    lowers directly."""
    sanitation.sanitize_in(a)
    dim = sanitize_axis(a.gshape, dim)
    if k > a.gshape[dim]:
        raise ValueError(f"selected index k={k} out of range for dimension of size {a.gshape[dim]}")
    if (
        a.split == dim
        and a.comm.is_distributed()
        and len(a.comm.axis_names) == 1
        and a.comm.size > 1
        and k <= a.parray.shape[dim] // a.comm.size
        and jnp.issubdtype(a.parray.dtype, jnp.number)
        and not jnp.issubdtype(a.parray.dtype, jnp.complexfloating)
    ):
        values, idx = _topk_split(a, k, dim, largest)
        # DEVIATION (doc/source/deviations.rst): the replicated candidate-reduction
        # result is returned with split=None, whereas the reference re-creates the
        # output with split=a.split when dim == split (reference
        # manipulations.py:4105-4112); resplit explicitly for that layout
        split = None
        v = _wrap(values, a, split)
        i = _wrap(idx.astype(jnp.int64), a, split)
        if out is not None:
            out_v, out_i = out
            return _handle_out(v, out_v, a), _handle_out(i, out_i, a)
        return v, i
    x = jnp.moveaxis(a.larray, dim, -1)
    if largest:
        values, idx = jax.lax.top_k(x, k)
    else:
        # negation overflows INT_MIN and wraps unsigned dtypes; an ascending argsort is
        # always order-correct for the smallest-k path
        idx = jnp.argsort(x, axis=-1)[..., :k]
        values = jnp.take_along_axis(x, idx, axis=-1)
    values = jnp.moveaxis(values, -1, dim)
    idx = jnp.moveaxis(idx.astype(jnp.int64), -1, dim)
    split = a.split if a.split != dim else None
    v, i = _wrap(values, a, split), _wrap(idx, a, split)
    if out is not None:
        out_v, out_i = out
        return _handle_out(v, out_v, a), _handle_out(i, out_i, a)
    return v, i


def _partial_unique_values(a: DNDarray) -> np.ndarray:
    """Merge of per-shard partial uniques (reference ``manipulations.py:3203``).

    Each device computes the unique set of its own shard (O(n/P) device memory); only
    those partials — at most the shard size, typically far smaller — leave the device
    and are merged on host. The full data is never gathered, matching the reference's
    per-rank-partials-then-merge scheme rather than its worst case."""
    import jax as _jax

    # iter_shards trims layout padding and yields device-local shard values
    parts = [np.asarray(jnp.unique(data)) for _, data in a.iter_shards()]
    np_dtype = np.dtype(a.dtype.jax_type())
    local = (
        np.unique(np.concatenate(parts)) if parts else np.empty(0, np_dtype)
    )
    if _jax.process_count() > 1:
        from jax.experimental import multihost_utils

        counts = np.asarray(
            multihost_utils.process_allgather(np.array([local.size], np.int64))
        ).reshape(-1)
        mx = int(counts.max()) if counts.size else 0
        padded = np.zeros(mx, np_dtype)
        padded[: local.size] = local
        gathered = np.asarray(multihost_utils.process_allgather(padded))
        local = np.unique(
            np.concatenate([gathered[p, : int(counts[p])] for p in range(len(counts))])
        ) if mx else local
    return local


def unique(a: DNDarray, sorted: bool = False, return_inverse: bool = False, axis: Optional[int] = None):
    """Unique elements (reference ``manipulations.py:3203``).

    A flat unique over a split array runs as per-shard partial uniques merged across
    shards — O(n/P) device memory; the result is replicated like the reference's
    final gather. The ``axis`` form and unsplit arrays are one global jnp.unique."""
    sanitation.sanitize_in(a)
    if axis is not None:
        axis = sanitize_axis(a.gshape, axis)
    use_partials = (
        axis is None
        and a.split is not None
        and a.comm.is_distributed()
        and a.size >= a.comm.size
    )
    if use_partials and jnp.issubdtype(a.parray.dtype, jnp.floating):
        # NaN != NaN breaks the searchsorted inverse and partial-merge dedup; route
        # arrays containing NaNs through the global path. The probe runs on the
        # padded physical value (pad slots are zero, never NaN) so it stays O(n/P).
        use_partials = not bool(jnp.isnan(a.parray).any())
    if use_partials:
        result = jnp.asarray(_partial_unique_values(a))
        if return_inverse:
            # searchsorted on the padded physical keeps the inverse O(n/P); it
            # inherits the input's split like the reference's local inverses
            inverse = jnp.searchsorted(result, a.parray).astype(jnp.int64)
            if a._is_padded():
                inverse = _operations._zero_pads(inverse, a.gshape, a.split)
            inv = DNDarray(
                a.comm.shard(inverse, a.split), a.gshape,
                types.canonical_heat_type(inverse.dtype), a.split, a.device, a.comm, True,
            )
            return _wrap(result, a, None), inv
        return _wrap(result, a, None)
    if return_inverse:
        result, inverse = jnp.unique(a.larray, return_inverse=True, axis=axis)
        return _wrap(result, a, None), _wrap(inverse.astype(jnp.int64), a, None)
    result = jnp.unique(a.larray, axis=axis)
    return _wrap(result, a, None)


def vsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split along the first axis (reference ``manipulations.py:4091``)."""
    return split(x, indices_or_sections, axis=0)


def vstack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack vertically (reference ``manipulations.py:4033``)."""
    return row_stack(arrays)
