"""Memory helpers (reference heat/core/memory.py:1-96)."""

from __future__ import annotations

import jax.numpy as jnp

from .dndarray import DNDarray

__all__ = ["copy", "sanitize_memory_layout"]


def copy(x: DNDarray) -> DNDarray:
    """A (logical) copy of the array (reference ``memory.py:14``). jax.Arrays are
    immutable, so this is a metadata-fresh wrapper over the same buffers."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"input needs to be a DNDarray, got {type(x)}")
    # parray, not larray: slicing a ragged array's padding off resolves to a
    # replicated value — the copy must keep the 1/P padded physical layout.
    # Source and copy share one immutable buffer object; the dispatch executor's
    # out= donation stays safe because sanitation.sanitize_donation's refcount
    # guard sees the sibling's reference for as long as it is alive.
    return DNDarray(x.parray, x.gshape, x.dtype, x.split, x.device, x.comm, x.balanced)


def sanitize_memory_layout(x, order: str = "C"):
    """Memory layout normalisation (reference ``memory.py:40``). XLA owns physical
    layouts on TPU (tiled HBM layouts, not strided C/F order), so only 'C' is accepted
    and the call is the identity."""
    if order == "K":
        raise NotImplementedError("Internal usage of torch.clone() means losing original memory layout for now.")
    if order not in ("C",):
        raise ValueError(f"only row-major ('C') layout is supported on TPU, got {order!r}")
    return x
