"""``ht.ops`` — the live operations plane: continuous metrics export, cluster
health beats, and per-tenant SLO burn-rate alerts.

Every observability surface before this one is post-hoc: :mod:`diagnostics` /
:mod:`profiler` dump on exit, ``telemetry merge`` folds shards offline, and the
flight recorder fires only after a fault. This module is the *live* half — the
signal plane an autoscaler (or a human watching a dashboard) consumes while
traffic is in flight — built from four coupled parts:

- **Continuous sampler.** An opt-in daemon (``HEAT_TPU_OPS=1``, cadence
  ``HEAT_TPU_OPS_INTERVAL_S``, default 5 s) snapshots ``executor_stats()``
  (admission / shed / expiry ledger, per-shard pressure EWMAs, result- and
  compile-cache counters), ``resilience.breakers()``, the supervision abort
  state, and the per-tenant ``request.<tag>`` latency histograms into a
  bounded ring of **windowed deltas**: each ring entry is the difference
  between two cumulative snapshots (counters subtract exactly; histograms via
  :meth:`profiler.Histogram.delta`), so rates — rps, shed rate, cache hit
  rate, per-shard queue-depth EWMA — are first-class values, not cumulative
  counters a consumer has to differentiate. A mid-run stats reset makes the
  previous snapshot a non-prefix; the sampler detects the ``ValueError``,
  marks the sample ``delta_reset`` and re-baselines instead of exporting
  garbage negative rates.

- **Exporter.** :func:`render_openmetrics` emits a strict OpenMetrics text
  page from the latest sample (``# TYPE``/``# HELP`` metadata per family,
  counter samples suffixed ``_total``, escaped label values, terminating
  ``# EOF``); :func:`parse_openmetrics` is the matching strict parser the
  tests and CI gates validate the page with. ``HEAT_TPU_OPS_PORT`` starts a
  localhost-only stdlib ``http.server`` on a daemon thread serving
  ``/metrics`` (the page) and ``/healthz`` (JSON; 503 while draining, while
  any circuit breaker is open, or while a supervision abort sentinel is up —
  exactly the states a load balancer must route around).
  ``HEAT_TPU_OPS_SCRAPE`` additionally writes the page to a file via
  ``resilience.atomic_write`` every sample, and ``HEAT_TPU_OPS_BEAT_DIR``
  writes the compact beat as ``ops-beat-r<rank>.json`` (the file-mode input
  of ``python -m heat_tpu.telemetry top --dir`` and ``merge --from-ops``).

- **Cluster health beats.** When the supervision plane is armed, every
  monitor tick also publishes this rank's compact beat under
  ``<monitor.ns>/ops/<rank>`` on the jax.distributed coordination KV channel
  — piggybacking the existing heartbeat cadence: no new collectives, no
  thread, nothing in XLA. Keys sit strictly *under* the prefix (the
  ``get_dir`` directory-semantics contract), so :func:`cluster_snapshot`
  folds all ranks with ONE non-blocking KV sweep — a rank that is mid-drain
  or dead simply has a stale/absent beat; nothing waits on it. ``python -m
  heat_tpu.telemetry top`` renders the fold as a per-rank / per-tenant
  terminal table.

- **SLO trackers.** :func:`set_slo` declares per-tenant objectives
  (``p99_ms`` and/or ``success_ratio``); the sampler computes multi-window
  (1 m / 5 m) **burn rates** from the ring's windowed deltas — burn =
  bad-fraction ÷ error-budget, the standard SRE form, where a p99 objective
  budgets 1% of requests over the threshold (counted bucket-exactly via
  :meth:`profiler.Histogram.count_over`) and a success objective budgets
  ``1 - success_ratio`` of requests failing (shed + expired + cancelled from
  the exact lifecycle ledger). The alert is up while BOTH windows burn above
  1.0 (the fast window trips quickly, the slow window keeps one spike from
  paging); the OFF->ON transition is a typed ``slo-burn`` event on the
  always-on resilience stream — which auto-dumps a flight-recorder
  post-mortem carrying the offending window's per-shard pressure breakdown —
  and every burn is exported as the ``ht_slo_burn_rate{tenant,window}``
  series.

Zero-cost contract (same discipline as every sibling plane)
-----------------------------------------------------------
This module adds **no hook to any dispatch or compute path**: the sampler
reads the same cross-module report surfaces the end-of-run dumps read, on its
own daemon thread, at human cadence. Idle (the default) nothing runs at all;
armed, the only foreign code touched per sample is ``executor_stats()`` et
al. — host-side report folds that never enter a traced body, so compiled HLO
is byte-identical with the plane off, armed, or never imported (gated by the
parity test in ``tests/test_ops.py``). The supervision beat piggyback is one
relaxed ``ops._armed`` attribute read per monitor tick.

Thread-safety
-------------
All module state (the ring, the previous cumulative snapshot, SLO/alert
tables, server/thread handles) mutates under the one module ``_lock``, which
is a strict LEAF of the lock graph: cross-module snapshots are gathered
*before* taking it and alert events are emitted *after* releasing it, so no
code ever holds ``ops._lock`` while calling into another locking module.
``_armed`` is the relaxed observer gate, read bare like
``diagnostics._enabled``.

Env knobs (read by :func:`reload`, chained from
``_executor.reload_env_knobs``)
------------------------------------------------------------------------
- ``HEAT_TPU_OPS=1``             — arm the plane at import (sampler daemon).
- ``HEAT_TPU_OPS_INTERVAL_S=F``  — sample cadence, seconds (default 5).
- ``HEAT_TPU_OPS_PORT=N``        — serve ``/metrics`` + ``/healthz`` on
  localhost:N (0 picks a free port; see :func:`http_address`).
- ``HEAT_TPU_OPS_SCRAPE=path``   — write the OpenMetrics page here each
  sample (atomic; for file-based scrapers).
- ``HEAT_TPU_OPS_BEAT_DIR=dir``  — write ``ops-beat-r<rank>.json`` here each
  sample (for ``telemetry top --dir`` / ``merge --from-ops``).
- ``HEAT_TPU_OPS_RING=N``        — ring capacity in samples (default 256 —
  comfortably past the 5 m burn window at the default cadence).
- ``HEAT_TPU_OPS_SLO=spec``      — declare objectives without code changes:
  ``tenantA:p99_ms=50,success_ratio=0.999;tenantB:p99_ms=10`` (applied at
  :func:`arm`; malformed entries are skipped, never fatal).

Stdlib-only at module load (like diagnostics/profiler/telemetry): the
executor is imported lazily inside the sampler, so the exporter/parser half
runs in tooling that never touches the JAX backend.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

try:
    from . import (diagnostics, forensics, profiler, resilience, supervision,
                   telemetry)
except ImportError:  # standalone file-path load (no parent package): the
    # exporter/parser surface still works; live sampling degrades to None
    diagnostics = forensics = profiler = resilience = supervision = None
    telemetry = None

__all__ = [
    "SCHEMA",
    "BEAT_SCHEMA",
    "BEAT_PREFIX",
    "arm",
    "disarm",
    "armed",
    "reload",
    "sample_once",
    "latest_sample",
    "samples",
    "set_slo",
    "clear_slo",
    "slo_status",
    "render_openmetrics",
    "parse_openmetrics",
    "healthz",
    "http_address",
    "cluster_snapshot",
    "write_beat_file",
    "write_scrape_file",
    "ops_stats",
    "reset",
]

SCHEMA = "heat-tpu-ops/1"
BEAT_SCHEMA = "heat-tpu-ops-beat/1"

#: filename prefix of per-rank beat files inside a beat directory (the
#: file-mode input of ``telemetry top --dir`` and ``merge --from-ops``)
BEAT_PREFIX = "ops-beat-r"

# Observer gate, read bare (``ops._armed``) by the supervision beat tee and
# the sampler loop: one attribute load + branch when off.
_armed: bool = False

# LEAF lock: everything below mutates under it; nothing called while holding
# it may take another module's lock (cross-module snapshots are gathered
# before acquiring, events emitted after releasing).
_lock = threading.RLock()

_DEFAULT_INTERVAL_S = 5.0
_DEFAULT_RING = 256
_BURN_WINDOWS: Tuple[Tuple[str, float], ...] = (("1m", 60.0), ("5m", 300.0))
#: floor on an error budget so a 100% success objective cannot divide by zero
_MIN_BUDGET = 1e-4


def _parse_slo_spec(spec: str) -> Dict[str, Dict[str, float]]:
    """Parse ``HEAT_TPU_OPS_SLO`` — objectives declared from the environment
    so CI can arm SLO tracking on an unmodified workload. Grammar:
    ``tenant:p99_ms=50,success_ratio=0.999;tenant2:p99_ms=10`` (semicolons
    between tenants, commas between objectives). Malformed entries are
    skipped, never fatal: a typo'd knob degrades to fewer objectives, it
    must not take down the process it observes."""
    out: Dict[str, Dict[str, float]] = {}
    for entry in spec.split(";"):
        entry = entry.strip()
        tenant, sep, body = entry.partition(":")
        if not sep or not tenant.strip():
            continue
        objectives: Dict[str, float] = {}
        for pair in body.split(","):
            key, eq, value = pair.strip().partition("=")
            if eq and key in ("p99_ms", "success_ratio"):
                try:
                    objectives[key] = float(value)
                except ValueError:
                    pass
        if objectives:
            out[tenant.strip()] = objectives
    return out


class _Knobs:
    """Memoised ``HEAT_TPU_OPS*`` env knobs (the executor's ``_EnvKnobs``
    pattern): read once at import and on every :func:`reload`."""

    __slots__ = ("enabled", "interval_s", "port", "scrape_path", "beat_dir",
                 "ring", "slos")

    def reload(self) -> None:
        env = os.environ
        self.enabled = env.get("HEAT_TPU_OPS") == "1"
        self.slos = _parse_slo_spec(env.get("HEAT_TPU_OPS_SLO", ""))
        try:
            self.interval_s = max(
                0.05, float(env.get("HEAT_TPU_OPS_INTERVAL_S", "")
                            or _DEFAULT_INTERVAL_S))
        except ValueError:
            self.interval_s = _DEFAULT_INTERVAL_S
        try:
            self.port = (int(env["HEAT_TPU_OPS_PORT"])
                         if "HEAT_TPU_OPS_PORT" in env else None)
        except ValueError:
            self.port = None
        self.scrape_path = env.get("HEAT_TPU_OPS_SCRAPE") or None
        self.beat_dir = env.get("HEAT_TPU_OPS_BEAT_DIR") or None
        try:
            self.ring = max(8, int(env.get("HEAT_TPU_OPS_RING", "")
                                   or _DEFAULT_RING))
        except ValueError:
            self.ring = _DEFAULT_RING


_knobs = _Knobs()
_knobs.reload()

# the sample ring (windowed deltas), the previous cumulative snapshot the
# next delta subtracts against, and the lifetime tallies
_ring: "deque[dict]" = deque(maxlen=_knobs.ring)
_prev_cum: Optional[dict] = None
_samples_total: int = 0
_delta_resets: int = 0

# per-tenant SLOs and the current alert state machine
_slos: Dict[str, Dict[str, float]] = {}
_alerts: Dict[str, Dict[str, Any]] = {}

# daemon handles
_thread: Optional[threading.Thread] = None
_thread_stop: Optional[threading.Event] = None
_server: Optional[Any] = None
_server_thread: Optional[threading.Thread] = None


def _utcnow() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _record_degrade(site: str, exc: BaseException) -> None:
    """Account one degraded sampler leg (never raises; the plane observes,
    it must not fail the workload it observes)."""
    if diagnostics is not None:
        diagnostics.record_fallback(site, f"{type(exc).__name__}: {exc}")


# ------------------------------------------------------------------ gathering
def _collect_cumulative() -> dict:
    """One cumulative cross-plane snapshot, gathered OUTSIDE ``_lock`` (every
    callee takes its own module's lock; ops holds none of them).

    ``admitted`` / ``shed`` / ``failed`` are the exact executor ledger:
    admitted = inline + queued dispatches, shed = typed ``Shed`` rejections,
    failed = deadline expiries + cancellations — the same cells the serving
    gate asserts on, so the exported totals reconcile against it exactly."""
    cum: Dict[str, Any] = {
        "mono": time.monotonic(),
        "t": _utcnow(),
        "admitted": 0,
        "shed": 0,
        "failed": 0,
        "cache_hits": 0,
        "cache_misses": 0,
        "compile_hits": 0,
        "compile_misses": 0,
        "queue_depth": 0,
        "draining": False,
        "pressure": {"per_shard": [], "service_ewma_s": {}},
        "tenant_lifecycle": {},
        "tenant_cost": {},
        "request_hists": {},
        "breakers": {},
        "supervision": {"armed": False, "aborted": None},
    }
    try:
        from . import _executor
        ex = _executor.executor_stats()
    except Exception as exc:  # ht: ignore[silent-except] -- accounted via diagnostics.record_fallback (_record_degrade); a sampler tick must degrade, not kill the plane, when the executor half is absent (standalone load) or mid-teardown
        _record_degrade("ops.sample.executor", exc)
        ex = None
    if ex is not None:
        cum["admitted"] = (ex.get("inline_dispatches", 0)
                           + ex.get("queued_dispatches", 0))
        cum["shed"] = ex.get("shed_requests", 0)
        cum["failed"] = (ex.get("expired_requests", 0)
                         + ex.get("cancelled_requests", 0))
        cum["cache_hits"] = ex.get("cache_hits", 0)
        cum["cache_misses"] = ex.get("cache_misses", 0)
        cum["compile_hits"] = ex.get("hits", 0)
        cum["compile_misses"] = ex.get("misses", 0)
        cum["draining"] = bool(ex.get("draining", False))
        cum["pressure"] = ex.get("pressure",
                                 {"per_shard": [], "service_ewma_s": {}})
        cum["queue_depth"] = sum(
            s.get("queue_depth", 0) for s in cum["pressure"]["per_shard"])
        cum["tenant_lifecycle"] = ex.get("lifecycle_by_tenant", {})
        # forensics cost meters are CUMULATIVE cells (device seconds, flops,
        # logical collective bytes, cache bytes saved) — exported as counters,
        # never folded into the windowed deltas (the merge disjointness rule)
        cum["tenant_cost"] = ex.get("tenant_cost", {})
    if profiler is not None:
        hists = profiler.histogram_snapshots()
        cum["request_hists"] = {
            name[len("request."):]: snap
            for name, snap in hists.items() if name.startswith("request.")
        }
    if resilience is not None:
        cum["breakers"] = {
            site: snap.get("state", "closed")
            for site, snap in resilience.breakers().items()
        }
    if supervision is not None:
        cum["supervision"] = {
            "armed": supervision._armed,
            "aborted": supervision.aborted(),
        }
    return cum


def _tenant_window(cum: dict, prev: dict) -> Dict[str, dict]:
    """Per-tenant windowed delta cells: completed-request count, requests over
    each tenant's p99 threshold (bucket-exact via ``Histogram.count_over``),
    lifecycle failures, and the window's p50/p99. Pure computation on
    snapshots — no foreign locks. Raises ``ValueError`` when ``prev`` is not
    a prefix (a mid-run reset); the caller re-baselines."""
    out: Dict[str, dict] = {}
    tenants = set(cum["request_hists"]) | set(cum["tenant_lifecycle"])
    tenants |= set(prev.get("tenant_lifecycle", {}))
    for tenant in sorted(tenants):
        cell = {"count": 0, "over": 0, "bad": 0, "p50_ms": None, "p99_ms": None}
        snap = cum["request_hists"].get(tenant)
        if snap is not None and profiler is not None:
            h = profiler.Histogram.from_snapshot(snap)
            prev_snap = prev.get("request_hists", {}).get(tenant)
            d = h.delta(prev_snap) if prev_snap is not None else h
            cell["count"] = d.count
            if d.count:
                cell["p50_ms"] = round(d.percentile(0.50) * 1e3, 6)
                cell["p99_ms"] = round(d.percentile(0.99) * 1e3, 6)
            slo = _slos.get(tenant)
            if slo and slo.get("p99_ms") is not None:
                cell["over"] = d.count_over(slo["p99_ms"] / 1e3)
        cur_lc = cum["tenant_lifecycle"].get(tenant, {})
        prev_lc = prev.get("tenant_lifecycle", {}).get(tenant, {})
        bad = 0
        for kind in ("shed", "deadline_expired", "cancelled"):
            diff = cur_lc.get(kind, 0) - prev_lc.get(kind, 0)
            if diff < 0:
                raise ValueError(
                    f"lifecycle ledger went backwards for {tenant!r}/{kind}")
            bad += diff
        cell["bad"] = bad
        out[tenant] = cell
    return out


def _rate(delta: float, window_s: float) -> float:
    return round(delta / window_s, 6) if window_s > 0 else 0.0


def _burn_for(tenant: str, slo: Dict[str, float],
              window_samples: List[dict]) -> float:
    """One window's burn rate for ``tenant``: observed bad fraction divided
    by the SLO's error budget (>1.0 means the budget is being spent faster
    than it accrues). When both objectives are declared the worse burn wins —
    an alert must not hide behind the healthier objective."""
    count = over = bad = 0
    for s in window_samples:
        cell = s["tenants"].get(tenant)
        if cell is None:
            continue
        count += cell["count"]
        over += cell["over"]
        bad += cell["bad"]
    burns = []
    if slo.get("p99_ms") is not None:
        frac = (over / count) if count else 0.0
        burns.append(frac / 0.01)  # p99 objective: 1% of requests may exceed
    if slo.get("success_ratio") is not None:
        total = count + bad
        frac = (bad / total) if total else 0.0
        budget = max(_MIN_BUDGET, 1.0 - slo["success_ratio"])
        burns.append(frac / budget)
    return round(max(burns), 6) if burns else 0.0


def sample_once() -> Optional[dict]:
    """Take one sample NOW (the daemon's tick; public so tests and
    ``bench.py`` drive windows deterministically): gather the cumulative
    cross-plane snapshot, delta it against the previous one, evaluate SLO
    burn rates over the ring, append to the ring, then emit any alert
    transitions / beat / scrape **after** releasing the lock. Returns the
    sample, or None when no previous snapshot existed yet (the first call
    only establishes the baseline)."""
    global _prev_cum, _samples_total, _delta_resets
    cum = _collect_cumulative()
    # (tenant, kind, detail-dict): details stay dicts until after `_lock` is
    # released so the slo-burn case can attach forensics exemplar refs without
    # calling into another locking module from under the leaf lock
    transitions: List[Tuple[str, str, Dict[str, Any]]] = []
    with _lock:
        prev = _prev_cum
        _prev_cum = cum
        if prev is None:
            return None
        window_s = max(1e-9, cum["mono"] - prev["mono"])
        sample: Dict[str, Any] = {
            "schema": SCHEMA,
            "t": cum["t"],
            "mono": cum["mono"],
            "window_s": round(window_s, 6),
            "delta_reset": False,
            "totals": {k: cum[k] for k in
                       ("admitted", "shed", "failed",
                        "cache_hits", "cache_misses")},
            "queue_depth": cum["queue_depth"],
            "pressure": cum["pressure"],
            "breakers": cum["breakers"],
            "draining": cum["draining"],
            "supervision": cum["supervision"],
            "tenant_cost": cum.get("tenant_cost", {}),
        }
        try:
            deltas = {}
            for k in ("admitted", "shed", "failed",
                      "cache_hits", "cache_misses"):
                d = cum[k] - prev[k]
                if d < 0:
                    raise ValueError(f"counter {k!r} went backwards")
                deltas[k] = d
            sample["tenants"] = _tenant_window(cum, prev)
        except ValueError:
            # a mid-run stats reset: the old snapshot is not a prefix of the
            # new stream — re-baseline instead of exporting negative rates
            _delta_resets += 1
            sample["delta_reset"] = True
            deltas = {k: 0 for k in ("admitted", "shed", "failed",
                                     "cache_hits", "cache_misses")}
            sample["tenants"] = {}
        sample["deltas"] = deltas
        lookups = deltas["cache_hits"] + deltas["cache_misses"]
        sample["rates"] = {
            "rps": _rate(deltas["admitted"], window_s),
            "shed_rate": _rate(deltas["shed"], window_s),
            "failure_rate": _rate(deltas["failed"], window_s),
            "cache_hit_rate": (round(deltas["cache_hits"] / lookups, 6)
                               if lookups else None),
        }
        # ---- SLO burn rates over the ring (this sample included)
        history = list(_ring) + [sample]
        slo_out: Dict[str, dict] = {}
        for tenant, slo in sorted(_slos.items()):
            burns = {}
            for name, span in _BURN_WINDOWS:
                in_window = [s for s in history
                             if cum["mono"] - s["mono"] <= span]
                burns[name] = _burn_for(tenant, slo, in_window)
            alerting = all(b > 1.0 for b in burns.values())
            state = _alerts.setdefault(
                tenant, {"active": False, "since": None, "transitions": 0})
            if alerting and not state["active"]:
                state.update(active=True, since=cum["t"])
                state["transitions"] += 1
                transitions.append((tenant, "slo-burn", {
                    "tenant": tenant, "burn": burns,
                    "window_s": sample["window_s"],
                    "tenant_window": sample["tenants"].get(tenant),
                    "per_shard": cum["pressure"]["per_shard"],
                }))
            elif not alerting and state["active"]:
                state.update(active=False, since=cum["t"])
                transitions.append((tenant, "slo-burn-cleared",
                                    {"tenant": tenant, "burn": burns}))
            slo_out[tenant] = {
                "objectives": dict(slo),
                "burn": burns,
                "alert": state["active"],
            }
        sample["slo"] = slo_out
        _ring.append(sample)
        _samples_total += 1
    # ---- event emission OUTSIDE the leaf lock (telemetry/diagnostics lock)
    for tenant, kind, body in transitions:
        site = f"ops.slo.{tenant}"
        if kind == "slo-burn":
            # reference the tenant's slowest-K forensic exemplars so the
            # post-mortem names the concrete requests that burned the budget
            # (forensics takes its own leaf lock — hence after `_lock`)
            body["exemplars"] = (
                forensics.exemplar_refs(tenant, 3)
                if forensics is not None and forensics._enabled else [])
        detail = json.dumps(body, sort_keys=True)
        if kind == "slo-burn" and diagnostics is not None:
            # the typed event on the always-on resilience stream; its
            # telemetry tee BOTH lands it on the flight ring and auto-dumps
            # the `slo-burn` post-mortem (telemetry's _AUTO_DUMP_KINDS),
            # per-shard breakdown riding in the detail — so exactly one ring
            # event and one dump per OFF->ON transition
            diagnostics.record_resilience_event(site, kind, detail)
        elif telemetry is not None:
            # `slo-burn-cleared` (and the standalone-load fallback): ring
            # only, no dump — recovery is worth a breadcrumb, not a pager
            telemetry.flight_record("ops", site, detail, kind=kind)
    return sample


# ------------------------------------------------------------------ ring views
def latest_sample() -> Optional[dict]:
    """The newest windowed sample, or None before two ticks have happened."""
    with _lock:
        return _ring[-1] if _ring else None


def samples() -> List[dict]:
    """The current ring contents, oldest first."""
    with _lock:
        return list(_ring)


# ------------------------------------------------------------------ SLOs
def set_slo(tenant: str, *, p99_ms: Optional[float] = None,
            success_ratio: Optional[float] = None) -> None:
    """Declare (or replace) ``tenant``'s objectives: ``p99_ms`` — at most 1%
    of a window's requests may exceed this latency; ``success_ratio`` — at
    least this fraction must not be shed/expired/cancelled. At least one
    objective is required."""
    if p99_ms is None and success_ratio is None:
        raise ValueError("an SLO needs p99_ms and/or success_ratio")
    if p99_ms is not None and p99_ms <= 0:
        raise ValueError(f"p99_ms must be positive, got {p99_ms}")
    if success_ratio is not None and not (0.0 < success_ratio <= 1.0):
        raise ValueError(
            f"success_ratio must be in (0, 1], got {success_ratio}")
    slo: Dict[str, float] = {}
    if p99_ms is not None:
        slo["p99_ms"] = float(p99_ms)
    if success_ratio is not None:
        slo["success_ratio"] = float(success_ratio)
    with _lock:
        _slos[str(tenant)] = slo


def clear_slo(tenant: str) -> None:
    """Drop ``tenant``'s objectives (and its alert state)."""
    with _lock:
        _slos.pop(str(tenant), None)
        _alerts.pop(str(tenant), None)


def slo_status() -> Dict[str, dict]:
    """``{tenant: {objectives, burn, alert, since}}`` — the declared SLOs
    with their latest burn rates and alert states."""
    with _lock:
        latest = _ring[-1] if _ring else None
        out: Dict[str, dict] = {}
        for tenant, slo in sorted(_slos.items()):
            entry = (latest or {}).get("slo", {}).get(tenant, {})
            state = _alerts.get(tenant, {})
            out[tenant] = {
                "objectives": dict(slo),
                "burn": dict(entry.get("burn", {})),
                "alert": bool(state.get("active", False)),
                "since": state.get("since"),
            }
        return out


# ------------------------------------------------------------------ exporter
def _escape_label(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _fmt(value: Any) -> str:
    if value is None or (isinstance(value, float) and math.isnan(value)):
        return "NaN"
    if isinstance(value, bool):
        return "1" if value else "0"
    f = float(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Family:
    __slots__ = ("name", "type", "help", "rows")

    def __init__(self, name: str, mtype: str, help_text: str):
        self.name, self.type, self.help = name, mtype, help_text
        self.rows: List[Tuple[Dict[str, str], Any]] = []

    def add(self, value: Any, **labels: str) -> "_Family":
        self.rows.append((labels, value))
        return self

    def render(self) -> List[str]:
        lines = [f"# TYPE {self.name} {self.type}",
                 f"# HELP {self.name} {self.help}"]
        suffix = "_total" if self.type == "counter" else ""
        for labels, value in self.rows:
            lbl = ""
            if labels:
                inner = ",".join(
                    f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items()))
                lbl = "{" + inner + "}"
            lines.append(f"{self.name}{suffix}{lbl} {_fmt(value)}")
        return lines


def render_openmetrics() -> str:
    """The OpenMetrics text page for the latest sample: strict metadata
    (``# TYPE`` then ``# HELP`` per family), counter samples suffixed
    ``_total``, escaped label values, and the mandatory terminating
    ``# EOF``. Counters come from the CUMULATIVE totals (monotone across
    pages — the scrape contract); rates/gauges from the latest window.
    Always well-formed, even before the first sample."""
    with _lock:
        sample = _ring[-1] if _ring else None
        samples_total = _samples_total
        resets = _delta_resets
    fams: List[_Family] = []
    fams.append(_Family(
        "ht_samples", "counter",
        "ops samples taken since arm").add(samples_total))
    fams.append(_Family(
        "ht_delta_resets", "counter",
        "samples re-baselined after a mid-run stats reset").add(resets))
    if sample is not None:
        fams.append(_Family(
            "ht_sample_window_seconds", "gauge",
            "width of the latest sample window").add(sample["window_s"]))
        totals = sample["totals"]
        fams.append(_Family(
            "ht_requests_admitted", "counter",
            "dispatches admitted (inline + queued)").add(totals["admitted"]))
        fams.append(_Family(
            "ht_requests_shed", "counter",
            "requests rejected typed by admission control").add(totals["shed"]))
        fams.append(_Family(
            "ht_requests_failed", "counter",
            "requests deadline-expired or cancelled").add(totals["failed"]))
        rates = sample["rates"]
        fams.append(_Family(
            "ht_rps", "gauge",
            "admitted requests per second over the window").add(rates["rps"]))
        fams.append(_Family(
            "ht_shed_rate", "gauge",
            "shed requests per second over the window").add(rates["shed_rate"]))
        hit = _Family("ht_cache_hit_rate", "gauge",
                      "result-cache hit fraction over the window")
        hit.add(rates["cache_hit_rate"]
                if rates["cache_hit_rate"] is not None else float("nan"))
        fams.append(hit)
        depth = _Family("ht_queue_depth", "gauge",
                        "instantaneous queue depth per shard")
        d_ewma = _Family("ht_queue_depth_ewma", "gauge",
                         "queue-depth EWMA per shard (alpha 0.25)")
        s_ewma = _Family("ht_shed_rate_ewma", "gauge",
                         "shed-rate EWMA per shard (1.0 = all sheds)")
        for shard in sample["pressure"]["per_shard"]:
            idx = str(shard.get("shard", shard.get("index", "?")))
            depth.add(shard.get("queue_depth", 0), shard=idx)
            d_ewma.add(shard.get("depth_ewma", 0.0), shard=idx)
            s_ewma.add(shard.get("shed_rate_ewma", 0.0), shard=idx)
        fams.extend((depth, d_ewma, s_ewma))
        svc = _Family("ht_service_ewma_seconds", "gauge",
                      "service-time EWMA per hot signature")
        for label, ewma in sorted(
                sample["pressure"].get("service_ewma_s", {}).items()):
            svc.add(ewma, signature=label)
        if svc.rows:
            fams.append(svc)
        brk = _Family("ht_breaker_open", "gauge",
                      "1 while the site's circuit breaker is open")
        for site, state in sorted(sample["breakers"].items()):
            brk.add(1 if state == "open" else 0, site=site)
        if brk.rows:
            fams.append(brk)
        fams.append(_Family(
            "ht_draining", "gauge",
            "1 while dispatch admission is closed").add(sample["draining"]))
        p99 = _Family("ht_tenant_p99_seconds", "gauge",
                      "per-tenant p99 latency over the window")
        for tenant, cell in sorted(sample.get("tenants", {}).items()):
            if cell.get("p99_ms") is not None:
                p99.add(cell["p99_ms"] / 1e3, tenant=tenant)
        if p99.rows:
            fams.append(p99)
        burn = _Family("ht_slo_burn_rate", "gauge",
                       "error-budget burn rate per tenant and window")
        alert = _Family("ht_slo_alert", "gauge",
                        "1 while the tenant's burn alert is up")
        for tenant, entry in sorted(sample.get("slo", {}).items()):
            for window, b in sorted(entry["burn"].items()):
                burn.add(b, tenant=tenant, window=window)
            alert.add(entry["alert"], tenant=tenant)
        if burn.rows:
            fams.extend((burn, alert))
        # ---- forensics cost meters: cumulative counters per tenant, plus
        # the tenant's lifetime stage time-share as a gauge family
        cost = sample.get("tenant_cost", {})
        if cost:
            dev = _Family("ht_tenant_device_seconds", "counter",
                          "attributed device execute time per tenant")
            flops = _Family("ht_tenant_flops", "counter",
                            "attributed device FLOPs per tenant")
            cbytes = _Family("ht_tenant_collective_bytes", "counter",
                             "logical collective bytes attributed per tenant")
            saved = _Family("ht_tenant_cache_bytes_saved", "counter",
                            "result-cache bytes served per tenant")
            share = _Family("ht_tenant_stage_share", "gauge",
                            "fraction of the tenant's request time per stage")
            for tenant, cell in sorted(cost.items()):
                dev.add(cell.get("device_seconds", 0.0), tenant=tenant)
                flops.add(cell.get("flops", 0.0), tenant=tenant)
                cbytes.add(cell.get("collective_bytes", 0.0), tenant=tenant)
                saved.add(cell.get("cache_bytes_saved", 0.0), tenant=tenant)
                stages = cell.get("stage_seconds", {})
                total = sum(stages.values())
                if total > 0:
                    for stage, secs in sorted(stages.items()):
                        share.add(round(secs / total, 6),
                                  tenant=tenant, stage=stage)
            fams.extend((dev, flops, cbytes, saved))
            if share.rows:
                fams.append(share)
    lines: List[str] = []
    for fam in fams:
        lines.extend(fam.render())
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def parse_openmetrics(text: str) -> Dict[str, dict]:
    """Strict in-repo OpenMetrics parser (the test/CI gate twin of
    :func:`render_openmetrics`): returns ``{family: {"type", "help",
    "samples": [(name, labels, value)]}}`` and raises ``ValueError`` on a
    malformed page — missing ``# EOF``, data after ``# EOF``, a sample
    before its ``# TYPE``, a counter sample not suffixed ``_total``, bad
    label syntax, an unescaped quote, or a non-numeric value."""
    families: Dict[str, dict] = {}
    lines = text.split("\n")
    if lines and lines[-1] == "":
        lines.pop()
    if not lines or lines[-1] != "# EOF":
        raise ValueError("page must terminate with '# EOF'")
    current: Optional[str] = None
    for lineno, line in enumerate(lines[:-1], 1):
        if line == "# EOF":
            raise ValueError(f"line {lineno}: data after '# EOF'")
        if not line:
            raise ValueError(f"line {lineno}: blank line inside page")
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) < 4 or parts[0] != "#" or parts[1] not in (
                    "TYPE", "HELP"):
                raise ValueError(f"line {lineno}: bad metadata: {line!r}")
            _, keyword, name, rest = parts
            if keyword == "TYPE":
                if rest not in ("counter", "gauge", "histogram", "summary",
                                "info", "stateset", "unknown"):
                    raise ValueError(f"line {lineno}: bad type {rest!r}")
                if name in families:
                    raise ValueError(f"line {lineno}: duplicate TYPE {name}")
                families[name] = {"type": rest, "help": None, "samples": []}
                current = name
            else:
                if name not in families:
                    raise ValueError(f"line {lineno}: HELP before TYPE {name}")
                families[name]["help"] = rest
            continue
        sample_name, _, rest = line.partition("{")
        labels: Dict[str, str] = {}
        if rest:
            body, close, tail = rest.partition("}")
            if not close or not tail.startswith(" "):
                raise ValueError(f"line {lineno}: bad label block: {line!r}")
            labels = _parse_labels(body, lineno)
            value_str = tail[1:]
        else:
            try:
                sample_name, value_str = line.split(" ", 1)
            except ValueError:
                raise ValueError(f"line {lineno}: no value: {line!r}")
        fam = current
        if fam is None or not sample_name.startswith(fam):
            fam = next((f for f in families if sample_name.startswith(f)
                        and sample_name[len(f):] in ("", "_total")), None)
        if fam is None:
            raise ValueError(
                f"line {lineno}: sample {sample_name!r} before its TYPE")
        expected = fam + ("_total" if families[fam]["type"] == "counter"
                          else "")
        if sample_name != expected:
            raise ValueError(
                f"line {lineno}: sample {sample_name!r} for "
                f"{families[fam]['type']} family {fam!r} (want {expected!r})")
        try:
            value = float(value_str)
        except ValueError:
            raise ValueError(f"line {lineno}: bad value {value_str!r}")
        families[fam]["samples"].append((sample_name, labels, value))
    return families


def _parse_labels(body: str, lineno: int) -> Dict[str, str]:
    labels: Dict[str, str] = {}
    i = 0
    while i < len(body):
        eq = body.find('="', i)
        if eq < 0:
            raise ValueError(f"line {lineno}: bad label syntax: {body!r}")
        key = body[i:eq]
        if not key.replace("_", "").isalnum():
            raise ValueError(f"line {lineno}: bad label name {key!r}")
        j = eq + 2
        out: List[str] = []
        while j < len(body):
            c = body[j]
            if c == "\\":
                if j + 1 >= len(body) or body[j + 1] not in ('\\', '"', 'n'):
                    raise ValueError(f"line {lineno}: bad escape in {body!r}")
                out.append({"\\": "\\", '"': '"', "n": "\n"}[body[j + 1]])
                j += 2
            elif c == '"':
                break
            else:
                out.append(c)
                j += 1
        else:
            raise ValueError(f"line {lineno}: unterminated label in {body!r}")
        labels[key] = "".join(out)
        i = j + 1
        if i < len(body):
            if body[i] != ",":
                raise ValueError(f"line {lineno}: bad label separator")
            i += 1
    return labels


# ------------------------------------------------------------------ health
def healthz() -> Tuple[bool, dict]:
    """``(healthy, payload)``: healthy is False while dispatch admission is
    draining, while any circuit breaker is OPEN, or while a supervision abort
    sentinel (peer-dead / collective-timeout / ...) is installed — the states
    a load balancer must route around. Reads only the latest cumulative
    facts; never blocks on the coordination channel."""
    draining = False
    try:
        from . import _executor
        sched = _executor._dispatch_scheduler
        draining = bool(sched is not None and sched.draining())
    except Exception as exc:  # ht: ignore[silent-except] -- accounted via diagnostics.record_fallback (_record_degrade); a health probe must answer from what it CAN read, not 500 because the executor half is absent
        _record_degrade("ops.healthz", exc)
    open_breakers = []
    if resilience is not None:
        open_breakers = sorted(
            site for site, snap in resilience.breakers().items()
            if snap.get("state") == "open")
    abort = supervision.aborted() if supervision is not None else None
    ok = not draining and not open_breakers and abort is None
    return ok, {
        "ok": ok,
        "draining": draining,
        "open_breakers": open_breakers,
        "abort": abort,
        "armed": _armed,
        "generated_at": _utcnow(),
    }


# ------------------------------------------------------------------ HTTP
def _make_server(port: int):
    import http.server

    class _Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - stdlib handler contract
            if self.path == "/metrics":
                body = render_openmetrics().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "application/openmetrics-text; version=1.0.0; "
                    "charset=utf-8")
            elif self.path == "/healthz":
                ok, payload = healthz()
                body = (json.dumps(payload, sort_keys=True) + "\n").encode()
                self.send_response(200 if ok else 503)
                self.send_header("Content-Type", "application/json")
            else:
                body = b"not found\n"
                self.send_response(404)
                self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # silence per-request stderr noise
            pass

    # localhost ONLY: an operations endpoint must never bind a routable
    # interface by default
    return http.server.ThreadingHTTPServer(("127.0.0.1", port), _Handler)


def http_address() -> Optional[Tuple[str, int]]:
    """The live ``(host, port)`` of the metrics endpoint, or None. With
    ``HEAT_TPU_OPS_PORT=0`` the OS picks the port; tests read it here."""
    with _lock:
        return _server.server_address[:2] if _server is not None else None


# ------------------------------------------------------------------ beats
def _compact_beat(rank: int) -> dict:
    """This rank's compact beat: the latest window's headline rates plus
    per-tenant SLO state — small enough for a KV value, rich enough for
    ``telemetry top``."""
    with _lock:
        sample = _ring[-1] if _ring else None
        seq = _samples_total
    beat: Dict[str, Any] = {
        "schema": BEAT_SCHEMA,
        "rank": int(rank),
        "seq": seq,
        "t": _utcnow(),
    }
    if sample is not None:
        # per-tenant rows: the window's latency/lifecycle cells joined with
        # the CUMULATIVE forensics cost meters (device_s / flops /
        # collective_bytes) — a tenant seen only by the cost meters (e.g. the
        # unattributed "-" bucket) still gets a row
        cost = sample.get("tenant_cost", {})
        tenants: Dict[str, dict] = {}
        for tenant in sorted(set(sample.get("tenants", {})) | set(cost)):
            cell = sample.get("tenants", {}).get(tenant, {})
            cc = cost.get(tenant, {})
            tenants[tenant] = {
                "p99_ms": cell.get("p99_ms"),
                "count": cell.get("count", 0),
                "bad": cell.get("bad", 0),
                "burn_1m": sample.get("slo", {}).get(tenant, {})
                .get("burn", {}).get("1m"),
                "alert": sample.get("slo", {}).get(tenant, {})
                .get("alert", False),
                "device_s": round(cc.get("device_seconds", 0.0), 6),
                "flops": cc.get("flops", 0.0),
                "collective_bytes": cc.get("collective_bytes", 0.0),
            }
        beat.update({
            "window_s": sample["window_s"],
            "rps": sample["rates"]["rps"],
            "shed_rate": sample["rates"]["shed_rate"],
            "cache_hit_rate": sample["rates"]["cache_hit_rate"],
            "queue_depth": sample["queue_depth"],
            "draining": sample["draining"],
            "tenants": tenants,
        })
    else:
        beat.update({"window_s": None, "rps": 0.0, "shed_rate": 0.0,
                     "cache_hit_rate": None, "queue_depth": 0,
                     "draining": False, "tenants": {}})
    return beat


def _beat_tee(monitor) -> None:
    """The supervision piggyback (installed as ``supervision._ops_tee``; one
    relaxed ``ops._armed`` read per monitor tick when idle): publish this
    rank's beat under ``<ns>/ops/<rank>`` on the coordination KV channel —
    strictly under the prefix, per the ``get_dir`` directory contract."""
    if not _armed:
        return
    beat = _compact_beat(monitor.rank)
    monitor.coordinator.set(
        f"{monitor.ns}/ops/{monitor.rank}",
        json.dumps(beat, sort_keys=True), True)


def publish_beat(coordinator, ns: str, rank: int) -> dict:
    """Publish this rank's beat explicitly (the tee does this automatically
    on every monitor tick while armed). Returns the beat."""
    beat = _compact_beat(rank)
    coordinator.set(f"{ns}/ops/{rank}", json.dumps(beat, sort_keys=True), True)
    return beat


def cluster_snapshot(coordinator=None, ns: Optional[str] = None) -> dict:
    """Fold every rank's latest beat with ONE non-blocking KV directory sweep
    (``get_dir(<ns>/ops/)``): no collective, no waiting — a rank that is
    mid-drain or dead simply contributes a stale or absent row, so this can
    never hang on a sick cluster. Defaults to the armed supervision monitor's
    coordinator/namespace; single-process (no monitor, no coordinator) falls
    back to this process's own beat as rank 0."""
    if coordinator is None and supervision is not None:
        mon = supervision.current_monitor()
        if mon is not None:
            coordinator, ns = mon.coordinator, mon.ns
    ranks: Dict[str, dict] = {}
    if coordinator is not None and ns is not None:
        for key, value in coordinator.get_dir(f"{ns}/ops/"):
            rank = key.rsplit("/", 1)[-1]
            try:
                ranks[rank] = json.loads(value)
            except (ValueError, TypeError):
                ranks[rank] = {"schema": BEAT_SCHEMA, "rank": rank,
                               "error": "unparseable beat"}
    if not ranks:
        local_rank = 0
        if telemetry is not None:
            local_rank = telemetry.process_info()[0]
        ranks[str(local_rank)] = _compact_beat(local_rank)
    return {
        "schema": SCHEMA,
        "generated_at": _utcnow(),
        "ranks": {k: ranks[k] for k in sorted(ranks, key=lambda r: (len(r), r))},
    }


# ------------------------------------------------------------------ files
def _atomic_text(path: str, text: str, site: str) -> None:
    def _write(tmp: str) -> None:
        with open(tmp, "w") as f:
            f.write(text)

    if resilience is not None:
        resilience.atomic_write(path, _write, site=site)
    else:  # standalone load: plain write (no breaker registry to ride)
        _write(path)


def write_scrape_file(path: str) -> str:
    """Write the OpenMetrics page to ``path`` atomically (the file-based
    scraper contract; also done every tick under ``HEAT_TPU_OPS_SCRAPE``)."""
    _atomic_text(path, render_openmetrics(), "ops.scrape")
    return path


def write_beat_file(directory: str, rank: Optional[int] = None) -> str:
    """Write this rank's beat as ``<directory>/ops-beat-r<rank>.json`` — the
    file the ``telemetry top --dir`` / ``merge --from-ops`` tooling reads on
    login nodes with no coordination channel. Returns the path."""
    if rank is None:
        rank = telemetry.process_info()[0] if telemetry is not None else 0
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"{BEAT_PREFIX}{int(rank)}.json")
    beat = _compact_beat(int(rank))
    _atomic_text(path, json.dumps(beat, indent=2, sort_keys=True) + "\n",
                 "ops.beat")
    return path


# ------------------------------------------------------------------ daemon
def _export_tick() -> None:
    if _knobs.scrape_path:
        try:
            write_scrape_file(_knobs.scrape_path)
        except OSError as exc:
            _record_degrade("ops.scrape", exc)
    if _knobs.beat_dir:
        try:
            write_beat_file(_knobs.beat_dir)
        except OSError as exc:
            _record_degrade("ops.beat", exc)


def _sampler_loop(stop: threading.Event, interval: float) -> None:
    while not stop.wait(interval):
        try:
            sample_once()
            _export_tick()
        except Exception as exc:  # ht: ignore[silent-except] -- accounted via diagnostics.record_fallback (_record_degrade); the plane observes the workload and must never kill it — a degraded tick is counted and the next tick retries
            _record_degrade("ops.sampler", exc)


def arm(interval_s: Optional[float] = None, *,
        start_thread: bool = True) -> None:
    """Arm the plane: baseline snapshot now, sampler daemon at ``interval_s``
    (default the env knob), HTTP endpoint if ``HEAT_TPU_OPS_PORT`` is set,
    and the supervision beat piggyback. Idempotent; ``start_thread=False``
    leaves ticking to the caller (tests and ``bench.py`` drive
    :func:`sample_once` with deterministic windows)."""
    global _armed, _thread, _thread_stop, _server, _server_thread, _prev_cum
    with _lock:
        if _armed:
            return
        _armed = True
    # env-declared objectives (HEAT_TPU_OPS_SLO) land before the first
    # sample; a programmatic set_slo for the same tenant later replaces them
    for tenant, objectives in _knobs.slos.items():
        try:
            set_slo(tenant, **objectives)
        except ValueError as exc:
            _record_degrade("ops.slo-env", exc)
    # the baseline (outside _lock: it reads foreign report surfaces)
    baseline = _collect_cumulative()
    with _lock:
        if _prev_cum is None:
            _prev_cum = baseline
    interval = float(interval_s if interval_s is not None
                     else _knobs.interval_s)
    if _knobs.port is not None:
        try:
            server = _make_server(_knobs.port)
        except OSError as exc:
            _record_degrade("ops.http", exc)
            server = None
        if server is not None:
            t = threading.Thread(target=server.serve_forever,
                                 name="heat-tpu-ops-http", daemon=True)
            with _lock:
                _server, _server_thread = server, t
            t.start()
    if start_thread:
        stop = threading.Event()
        t = threading.Thread(target=_sampler_loop, args=(stop, interval),
                             name="heat-tpu-ops-sampler", daemon=True)
        with _lock:
            _thread, _thread_stop = t, stop
        t.start()
    if diagnostics is not None:
        diagnostics.record_resilience_event(
            "ops.plane", "ops-armed",
            f"interval {interval:.3f}s, port {_knobs.port}, "
            f"ring {_knobs.ring}")


def disarm() -> None:
    """Stop the sampler daemon and the HTTP endpoint; the ring, SLOs and
    alert states are kept (post-mortem reads must still work)."""
    global _armed, _thread, _thread_stop, _server, _server_thread
    with _lock:
        if not _armed:
            return
        _armed = False
        stop, thread = _thread_stop, _thread
        server, server_thread = _server, _server_thread
        _thread = _thread_stop = _server = _server_thread = None
    if stop is not None:
        stop.set()
    if thread is not None:
        thread.join(timeout=5.0)
    if server is not None:
        server.shutdown()
        server.server_close()
    if server_thread is not None:
        server_thread.join(timeout=5.0)


def armed() -> bool:
    return _armed


def reload() -> None:
    """Re-read the ``HEAT_TPU_OPS*`` knobs (chained from
    ``_executor.reload_env_knobs``). Ring capacity applies to new samples;
    the enable knob only governs import-time auto-arm."""
    global _ring
    _knobs.reload()
    with _lock:
        if _ring.maxlen != _knobs.ring:
            _ring = deque(_ring, maxlen=_knobs.ring)


def reset() -> None:
    """Drop the ring, baselines, SLOs and alert states (tests)."""
    global _prev_cum, _samples_total, _delta_resets
    with _lock:
        _ring.clear()
        _prev_cum = None
        _samples_total = 0
        _delta_resets = 0
        _slos.clear()
        _alerts.clear()


# ------------------------------------------------------------------ reporting
def ops_stats() -> dict:
    """The ``ops`` section of ``ht.diagnostics.report()``: armed state,
    sample tallies, knobs, SLO/alert summary."""
    with _lock:
        latest = _ring[-1] if _ring else None
        return {
            "schema": SCHEMA,
            "armed": _armed,
            "samples": _samples_total,
            "ring": len(_ring),
            "ring_cap": _ring.maxlen,
            "delta_resets": _delta_resets,
            "interval_s": _knobs.interval_s,
            "http": (_server.server_address[:2] if _server is not None
                     else None),
            "slos": {t: dict(s) for t, s in sorted(_slos.items())},
            "alerts": {
                t: {"active": a["active"], "since": a["since"],
                    "transitions": a["transitions"]}
                for t, a in sorted(_alerts.items())
            },
            "last_window_s": latest["window_s"] if latest else None,
        }


# ------------------------------------------------------------------ wiring
if diagnostics is not None:
    diagnostics.register_provider("ops", ops_stats)

if supervision is not None:
    # the beat piggyback: Monitor.step reads this bare; idle cost is one
    # `ops._armed` attribute load per monitor tick
    supervision._ops_tee = _beat_tee

# Env bootstrap: armed from the start (serving/chaos CI jobs).
if _knobs.enabled:
    arm()
