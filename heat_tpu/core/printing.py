"""Printing of distributed arrays (reference heat/core/printing.py:30-308).

The reference gathers shards to rank 0 (with summarisation for large arrays) and prints
there. A global ``jax.Array`` already exposes the global value on every controller, so
"global printing" is direct; ``local_printing`` switches to per-shard display.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "get_printoptions",
    "global_printing",
    "local_printing",
    "print0",
    "set_printoptions",
]

# summarisation thresholds mirroring the reference/torch defaults
__PRINT_OPTIONS = dict(precision=4, threshold=1000, edgeitems=3, linewidth=120, sci_mode=None)
__LOCAL_PRINTING = False


def get_printoptions() -> dict:
    """View of the current print options (reference ``printing.py:21``)."""
    return dict(__PRINT_OPTIONS)


def global_printing() -> None:
    """Print global values (default; reference ``printing.py:62``)."""
    global __LOCAL_PRINTING
    __LOCAL_PRINTING = False


def local_printing() -> None:
    """Print each process's local shards only (reference ``printing.py:30``)."""
    global __LOCAL_PRINTING
    __LOCAL_PRINTING = True


def print0(*args, **kwargs) -> None:
    """Print on (process) rank 0 only (reference ``printing.py:100``)."""
    import jax

    if jax.process_index() == 0:
        print(*args, **kwargs)


def set_printoptions(precision=None, threshold=None, edgeitems=None, linewidth=None, profile=None, sci_mode=None):
    """Configure printing (reference ``printing.py:150``)."""
    if profile == "default":
        __PRINT_OPTIONS.update(precision=4, threshold=1000, edgeitems=3, linewidth=120)
    elif profile == "short":
        __PRINT_OPTIONS.update(precision=2, threshold=1000, edgeitems=2, linewidth=120)
    elif profile == "full":
        __PRINT_OPTIONS.update(precision=4, threshold=np.inf, edgeitems=3, linewidth=120)
    for k, v in dict(
        precision=precision, threshold=threshold, edgeitems=edgeitems, linewidth=linewidth, sci_mode=sci_mode
    ).items():
        if v is not None:
            __PRINT_OPTIONS[k] = v


def _edge_data(dndarray, edgeitems: int) -> np.ndarray:
    """Host array holding ONLY the edgeitem slices of each summarised dimension
    (reference ``_torch_data``, ``printing.py:208-263``, which gathers just the
    edge slices to rank 0). Per dimension over ``2*edgeitems+1`` elements, the
    device-side take keeps ``edgeitems`` per side plus one never-displayed filler,
    so host transfer and host memory are O(edgeitems**ndim), not O(n). Runs on the
    padded physical value — a ragged split never materialises its replicated trim.
    """
    value = dndarray.parray
    for d, s in enumerate(dndarray.gshape):
        if s > 2 * edgeitems + 1:
            idx = jnp.concatenate([
                jnp.arange(edgeitems),
                jnp.asarray([edgeitems]),  # filler: hidden by summarisation, keeps
                jnp.arange(s - edgeitems, s),  # the extent at 2e+1 so '...' appears
            ])
            value = jnp.take(value, idx, axis=d)
        elif value.shape[d] != s:  # ragged split dim small enough to show: trim pads
            value = jnp.take(value, jnp.arange(s), axis=d)
    if getattr(value, "is_fully_addressable", True):
        return np.asarray(jax.device_get(value))
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(value, tiled=True))


def __str__(dndarray) -> str:
    """Render a DNDarray (reference ``printing.py:184``).

    Large arrays (``size > threshold``) never materialise the global value: only
    edgeitem slices are fetched (reference ``printing.py:208-263``), then numpy's
    own summarisation renders them with the identical ``...`` layout it would use
    on the full array (its formatter is built from the edge slices either way).
    Small arrays route through ``DNDarray.numpy()`` — the process_allgather-aware
    path — so multi-controller repr of a non-addressable array works too."""
    opts = __PRINT_OPTIONS
    if __LOCAL_PRINTING:
        shards = "\n".join(
            f"  device {i}: {np.array2string(np.asarray(s), precision=opts['precision'])}"
            for i, s in enumerate(dndarray.lshards)
        )
        return (
            f"DNDarray(local shards, gshape={dndarray.gshape}, split={dndarray.split}):\n{shards}"
        )
    summarize = dndarray.size > opts["threshold"] and dndarray.ndim > 0
    if summarize:
        value = _edge_data(dndarray, opts["edgeitems"])
        threshold = 0  # the gathered corners must summarise like the full array would
    else:
        value = dndarray.numpy()
        threshold = opts["threshold"]
    body = np.array2string(
        value,
        precision=opts["precision"],
        threshold=threshold,
        edgeitems=opts["edgeitems"],
        max_line_width=opts["linewidth"],
        separator=", ",
    )
    return f"DNDarray({body}, dtype=ht.{dndarray.dtype}, device={dndarray.device}, split={dndarray.split})"
