"""Printing of distributed arrays (reference heat/core/printing.py:30-308).

The reference gathers shards to rank 0 (with summarisation for large arrays) and prints
there. A global ``jax.Array`` already exposes the global value on every controller, so
"global printing" is direct; ``local_printing`` switches to per-shard display.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "get_printoptions",
    "global_printing",
    "local_printing",
    "print0",
    "set_printoptions",
]

# summarisation thresholds mirroring the reference/torch defaults
__PRINT_OPTIONS = dict(precision=4, threshold=1000, edgeitems=3, linewidth=120, sci_mode=None)
__LOCAL_PRINTING = False


def get_printoptions() -> dict:
    """View of the current print options (reference ``printing.py:21``)."""
    return dict(__PRINT_OPTIONS)


def global_printing() -> None:
    """Print global values (default; reference ``printing.py:62``)."""
    global __LOCAL_PRINTING
    __LOCAL_PRINTING = False


def local_printing() -> None:
    """Print each process's local shards only (reference ``printing.py:30``)."""
    global __LOCAL_PRINTING
    __LOCAL_PRINTING = True


def print0(*args, **kwargs) -> None:
    """Print on (process) rank 0 only (reference ``printing.py:100``)."""
    import jax

    if jax.process_index() == 0:
        print(*args, **kwargs)


def set_printoptions(precision=None, threshold=None, edgeitems=None, linewidth=None, profile=None, sci_mode=None):
    """Configure printing (reference ``printing.py:150``)."""
    if profile == "default":
        __PRINT_OPTIONS.update(precision=4, threshold=1000, edgeitems=3, linewidth=120)
    elif profile == "short":
        __PRINT_OPTIONS.update(precision=2, threshold=1000, edgeitems=2, linewidth=120)
    elif profile == "full":
        __PRINT_OPTIONS.update(precision=4, threshold=np.inf, edgeitems=3, linewidth=120)
    for k, v in dict(
        precision=precision, threshold=threshold, edgeitems=edgeitems, linewidth=linewidth, sci_mode=sci_mode
    ).items():
        if v is not None:
            __PRINT_OPTIONS[k] = v


def __str__(dndarray) -> str:
    """Render a DNDarray (reference ``printing.py:184``)."""
    opts = __PRINT_OPTIONS
    if __LOCAL_PRINTING:
        shards = "\n".join(
            f"  device {i}: {np.array2string(np.asarray(s), precision=opts['precision'])}"
            for i, s in enumerate(dndarray.lshards)
        )
        return (
            f"DNDarray(local shards, gshape={dndarray.gshape}, split={dndarray.split}):\n{shards}"
        )
    value = np.asarray(dndarray.larray)
    body = np.array2string(
        value,
        precision=opts["precision"],
        threshold=opts["threshold"],
        edgeitems=opts["edgeitems"],
        max_line_width=opts["linewidth"],
        separator=", ",
    )
    return f"DNDarray({body}, dtype=ht.{dndarray.dtype}, device={dndarray.device}, split={dndarray.split})"
