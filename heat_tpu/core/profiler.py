"""``ht.profiler`` — request-level latency histograms, per-request span trees,
and Chrome-trace/Perfetto timeline export.

:mod:`diagnostics` (PR 3) answers *"what ran, how many times, over how many
bytes"* — aggregates. This module answers the serving questions aggregates
cannot: *"what is my p99?"*, *"which request was slow, and where did its time
go?"*, *"what does the timeline of 32 concurrent requests look like?"*. It is
the proof instrument for the ROADMAP's serving north star and the metric
source for ``benchmarks/serving/``:

- **Latency histograms** (:class:`Histogram`) — streaming, log-bucketed,
  bounded-memory, *mergeable* (bucket counts add; two harness shards can fold
  their histograms into one), with p50/p95/p99/max snapshots in
  :func:`report`. Every :func:`request` scope observes its wall latency into
  the ``request.<tag>`` histogram; :func:`observe` feeds arbitrary ones.
- **Per-request span trees** — ``with profiler.request("kmeans"):`` opens a
  contextvar-scoped request id that the dispatch wrappers
  (:mod:`_operations`), the deferred-graph force and program calls
  (:mod:`_executor`), and every ``MeshCommunication`` collective
  (:mod:`communication`) pick up, so the slices of 32 concurrent requests
  attribute to the right request even when they interleave on a thread pool.
  A :class:`~._executor.Deferred` node additionally *captures* the ambient
  request id at defer time, so a chain built inside a request scope but forced
  later — from another thread, after the scope closed — still attributes its
  force to the request that built it.
- **Chrome-trace export** (:func:`dump_trace`) — the recorded slices as
  trace-event JSON loadable in Perfetto / ``chrome://tracing``: one track
  (pid) per request with its tag as the process name, nested B/E slices for
  ``dispatch`` → ``compile``/``execute`` → ``collective`` (collectives record
  at trace time, so they nest inside the compile slice that traced them), and
  counter tracks for pad-waste fractions, cumulative donated bytes, and
  force-boundary memory samples.
- **Device-memory gauges** — at every deferred-graph force boundary the
  executor samples the *logical* bytes the force touched (leaf inputs +
  emitted outputs): ``report()["memory"]`` keeps the last and peak sample.
  This is a host-side estimate of live working set at force boundaries, not
  an XLA allocator readout — it tracks the framework's view of buffer
  traffic, which is the quantity the donation and memoisation machinery
  manage.

Zero-cost contract (same as diagnostics/resilience)
---------------------------------------------------
Disabled (the default), every hook is one module-attribute read
(``profiler._active``) and a branch not taken. Nothing is EVER injected into
traced program bodies — all timing is host-side, around tracing/dispatch — so
compiled HLO is byte-identical with the profiler enabled, disabled, or never
touched (``tests/test_profiler.py::TestHLOParity``), and the dispatch ops/s
baseline gates keep enforcing the idle cost in CI.

Request deadlines (ISSUE 10)
----------------------------
``request(tag, deadline_s=...)`` additionally arms a wall-clock deadline in a
second contextvar scoped exactly like the request id: ``current_deadline()``
reads it, ``Deferred`` nodes capture it at defer time, and the async
executor's lifecycle checkpoints (admission, pre-dispatch, batch formation,
eager replay) act on it. Deadlines are a lifecycle contract rather than
telemetry, so they are armed even while the profiler is disabled; the
``_deadline_seen`` module attribute (set once, never cleared, deliberately
relaxed like ``_active``) lets a process that never uses deadlines skip even
the contextvar read.

Thread-safety
-------------
All registries mutate under one module lock; the current request id is a
``contextvars.ContextVar`` (per-thread by default, correctly inherited by
``contextvars.copy_context`` based pools). Slices are stored as *complete*
(start, end) records and only serialised to B/E pairs at dump time, so a
record evicted from the bounded deque removes both its B and its E — the
exported trace always has matched pairs.

Env knobs (read once at import)
-------------------------------
- ``HEAT_TPU_PROFILE=1``          — start with the profiler enabled.
- ``HEAT_TPU_PROFILE_TRACE=path`` — dump the Chrome trace to ``path`` at
  interpreter exit (the serving CI artifact).

Stdlib-only at module load (like :mod:`diagnostics`): the serving harness and
driver tooling can load it before touching the JAX backend.
"""

from __future__ import annotations

import atexit
import contextlib
import contextvars
import itertools
import json
import math
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

try:
    from . import diagnostics  # registers the report provider below
except ImportError:  # loaded standalone by file path (no parent package)
    diagnostics = None

try:
    from . import resilience  # atomic trace dumps (crash-safe artifacts)
except ImportError:  # loaded standalone by file path (no parent package)
    resilience = None

__all__ = [
    "Histogram",
    "enable",
    "disable",
    "active",
    "reset",
    "request",
    "current_request",
    "current_request_tag",
    "current_deadline",
    "attributed",
    "attribution_active",
    "request_slices",
    "scope",
    "observe",
    "histogram_snapshots",
    "record_counter",
    "record_force_memory",
    "report",
    "dump_trace",
    "trace_snapshot",
    "trace_events",
    "SCHEMA",
    "TRACE_SCHEMA",
]

SCHEMA = "heat-tpu-profiler/1"
TRACE_SCHEMA = "heat-tpu-profiler-trace/1"

# Hot-path hooks read this module attribute directly (`profiler._active`):
# one attribute load + branch when off — the zero-cost-when-disabled contract.
_active: bool = False

_lock = threading.RLock()

# Bounded stores, same policy as diagnostics: evict OLDEST on overflow so the
# dump holds the most recent tail of the run. Slices are (rid, tid, cat, name,
# t0_us, t1_us) tuples — complete records, so eviction never orphans a B or E.
_MAX_SLICES = 65_536
_MAX_COUNTER_EVENTS = 16_384
_MAX_REQUESTS = 8_192

_slices: "deque[tuple]" = deque(maxlen=_MAX_SLICES)
_counter_events: "deque[tuple]" = deque(maxlen=_MAX_COUNTER_EVENTS)
# rid -> {"tag", "t0_us", "t1_us"} — insertion-ordered; evict-oldest beyond cap
_requests: "OrderedDict[int, dict]" = OrderedDict()
_hists: Dict[str, "Histogram"] = {}
_mem = {"forces": 0, "last_force_live_bytes": 0, "peak_force_live_bytes": 0}
_counters: Dict[str, float] = {}  # cumulative values behind the counter tracks

_rid_counter = itertools.count(1)
_current_request: "contextvars.ContextVar[Optional[int]]" = contextvars.ContextVar(
    "heat_tpu_profiler_request", default=None
)

# Request deadlines ride the same contextvar scoping as the request id but are
# a LIFECYCLE feature, not telemetry: `request(tag, deadline_s=...)` arms the
# ambient deadline even while the profiler is disabled, and the executor's
# deadline checkpoints act on it either way. `_deadline_seen` is the relaxed
# one-attribute-read gate (set once, never cleared) the executor's hot paths
# check before paying the contextvar lookup — a process that never sets a
# deadline never reads the contextvar at all (the deadline-off parity
# contract the dispatch ops/s baseline gates enforce).
_current_deadline: "contextvars.ContextVar[Optional[float]]" = contextvars.ContextVar(
    "heat_tpu_profiler_deadline", default=None
)
_deadline_seen: bool = False

# Late-bound forensics module (set once at `heat_tpu.core.forensics` import,
# read bare afterwards — the diagnostics tee pattern). While the forensics
# plane is armed, `request()` runs "lite-active": it allocates a request id
# and threads the contextvar (so tenant attribution and forensic records
# work) even when the profiler itself is disabled, but records no slices and
# observes no histograms. Forensics calls happen OUTSIDE `_lock`.
_forensics = None

# perf_counter origin for trace timestamps; rebased on enable() so a long-lived
# process's trace starts near zero. Microseconds, Chrome's native unit.
_t0 = time.perf_counter()


def _now_us() -> float:
    return (time.perf_counter() - _t0) * 1e6


# ------------------------------------------------------------------ histograms
class Histogram:
    """A streaming log-bucketed latency histogram: bounded memory, mergeable,
    quantile estimates with a known relative error bound.

    Values (seconds) land in geometric buckets ``[base·growth^i,
    base·growth^(i+1))``; the default ``growth=1.05`` bounds any quantile
    estimate's relative error by ~2.5% (half a bucket width, geometric
    midpoint) while covering 1 µs … >1 h in under 600 buckets. Buckets are a
    sparse dict — a workload whose latencies span three decades holds ~140
    entries, not an array of the full index range.

    ``merge`` adds bucket counts (exact, associative, commutative), takes
    min/max of extremes and sums counts/totals — two harness shards, or two
    rounds, fold into one histogram whose quantiles are identical to having
    observed the union stream (bucket counts are integers; only ``sum_s``
    is subject to float addition order)."""

    __slots__ = ("base", "growth", "_log_growth", "buckets", "count", "sum_s",
                 "min_s", "max_s")

    #: index clamp: base·growth^512 at the defaults is ≳ 19 h — anything slower
    #: is an outage, not a latency, and lands saturated in the top bucket.
    MAX_INDEX = 512

    def __init__(self, base: float = 1e-6, growth: float = 1.05):
        if not (base > 0 and growth > 1):
            raise ValueError(f"need base > 0 and growth > 1, got {base}, {growth}")
        self.base = float(base)
        self.growth = float(growth)
        self._log_growth = math.log(growth)
        self.buckets: Dict[int, int] = {}
        self.count = 0
        self.sum_s = 0.0
        self.min_s = math.inf
        self.max_s = 0.0

    def _index(self, seconds: float) -> int:
        if seconds <= self.base:
            return 0
        i = int(math.log(seconds / self.base) / self._log_growth) + 1
        return min(i, self.MAX_INDEX)

    def _bound(self, index: int) -> float:
        """Upper bound of bucket ``index`` (its quantile estimate uses the
        geometric midpoint of [bound/growth, bound])."""
        return self.base * self.growth ** index

    def observe(self, seconds: float) -> None:
        seconds = max(0.0, float(seconds))
        i = self._index(seconds)
        self.buckets[i] = self.buckets.get(i, 0) + 1
        self.count += 1
        self.sum_s += seconds
        if seconds < self.min_s:
            self.min_s = seconds
        if seconds > self.max_s:
            self.max_s = seconds

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold ``other`` into self (in place; returns self). Bucket configs
        must match — merging histograms of different resolutions would silently
        corrupt the quantiles."""
        if (other.base, other.growth) != (self.base, self.growth):
            raise ValueError(
                f"cannot merge histograms with different bucket configs: "
                f"({self.base}, {self.growth}) vs ({other.base}, {other.growth})"
            )
        for i, c in other.buckets.items():
            self.buckets[i] = self.buckets.get(i, 0) + c
        self.count += other.count
        self.sum_s += other.sum_s
        self.min_s = min(self.min_s, other.min_s)
        self.max_s = max(self.max_s, other.max_s)
        return self

    def percentile(self, q: float) -> Optional[float]:
        """Estimated ``q``-quantile (``q`` in [0, 1]): geometric midpoint of
        the bucket where the cumulative count crosses ``q·count``, clamped to
        the observed min/max so tiny histograms never report an estimate
        outside the data. None when empty."""
        if self.count == 0:
            return None
        target = q * self.count
        cum = 0
        for i in sorted(self.buckets):
            cum += self.buckets[i]
            if cum >= target:
                hi = self._bound(i)
                est = hi / math.sqrt(self.growth) if i > 0 else hi / 2.0
                return min(max(est, self.min_s), self.max_s)
        return self.max_s

    def count_over(self, threshold_s: float) -> int:
        """How many observed samples exceeded ``threshold_s`` — the SLO
        burn-rate numerator (``ht.ops`` counts a window's requests over the
        tenant's p99 objective with this). Bucket-resolution: a whole bucket
        counts as over when its LOWER bound is at or above the threshold, so
        the answer is exact whenever the threshold lands on a bucket boundary
        and otherwise errs by at most the one straddling bucket (under — the
        conservative direction for alerting on latency)."""
        threshold_s = max(0.0, float(threshold_s))
        total = 0
        for i, c in self.buckets.items():
            lower = self._bound(i - 1) if i > 0 else 0.0
            if lower >= threshold_s:
                total += c
        return total

    def snapshot(self) -> dict:
        """A JSON-able summary: counts, extremes, p50/p95/p99, and the sparse
        bucket table (``[[index, count], …]`` with the bucket config) so a
        downstream consumer can re-merge snapshots offline."""
        return {
            "count": self.count,
            "sum_s": round(self.sum_s, 9),
            "min_s": round(self.min_s, 9) if self.count else None,
            "max_s": round(self.max_s, 9) if self.count else None,
            "p50_s": _round_opt(self.percentile(0.50)),
            "p95_s": _round_opt(self.percentile(0.95)),
            "p99_s": _round_opt(self.percentile(0.99)),
            "bucket_base": self.base,
            "bucket_growth": self.growth,
            "buckets": [[i, self.buckets[i]] for i in sorted(self.buckets)],
        }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "Histogram":
        """Rebuild a mergeable histogram from :meth:`snapshot` output (the
        offline half of mergeability — fold BENCH rounds without the process
        that recorded them)."""
        h = cls(base=snap["bucket_base"], growth=snap["bucket_growth"])
        for i, c in snap["buckets"]:
            h.buckets[int(i)] = int(c)
        h.count = int(snap["count"])
        h.sum_s = float(snap["sum_s"])
        h.min_s = float(snap["min_s"]) if snap.get("min_s") is not None else math.inf
        h.max_s = float(snap["max_s"]) if snap.get("max_s") is not None else 0.0
        return h

    def delta(self, prev) -> "Histogram":
        """The *windowed* histogram between an earlier :meth:`snapshot` of this
        same stream (``prev`` — a snapshot dict or a Histogram) and now: bucket
        counts subtract exactly, so interval p50/p99 between two dumps come out
        of cumulative snapshots without any per-window state. Raises
        ``ValueError`` when ``prev`` is not a prefix of this stream (some
        bucket would go negative — the histograms are from different streams
        or the stream was reset between the snapshots).

        The window's true ``min_s``/``max_s`` are not recoverable from
        cumulative counts; the delta clamps them to the occupied buckets'
        bounds, so quantile estimates keep their usual half-bucket error
        but the extremes are bucket-resolution, not sample-resolution.
        ``merge(prev, delta)`` reproduces the cumulative bucket table exactly
        (the round-trip the telemetry tests gate)."""
        if isinstance(prev, dict):
            prev = Histogram.from_snapshot(prev)
        if (prev.base, prev.growth) != (self.base, self.growth):
            raise ValueError(
                f"cannot delta histograms with different bucket configs: "
                f"({self.base}, {self.growth}) vs ({prev.base}, {prev.growth})"
            )
        h = Histogram(base=self.base, growth=self.growth)
        for i in set(self.buckets) | set(prev.buckets):
            c = self.buckets.get(i, 0) - prev.buckets.get(i, 0)
            if c < 0:
                raise ValueError(
                    f"snapshot is not a prefix of this histogram: bucket {i} "
                    f"has {self.buckets.get(i, 0)} < prior {prev.buckets.get(i, 0)}"
                )
            if c:
                h.buckets[i] = c
        h.count = self.count - prev.count
        if h.count < 0 or h.count != sum(h.buckets.values()):
            raise ValueError(
                "snapshot is not a prefix of this histogram (count mismatch)"
            )
        h.sum_s = max(0.0, self.sum_s - prev.sum_s)
        if h.buckets:
            lo, hi = min(h.buckets), max(h.buckets)
            h.min_s = self._bound(lo - 1) if lo > 0 else 0.0
            h.max_s = min(self._bound(hi), self.max_s)
            h.min_s = min(h.min_s, h.max_s)
        return h


def _round_opt(v: Optional[float]) -> Optional[float]:
    return round(v, 9) if v is not None else None


# ------------------------------------------------------------------ switches
def enable() -> None:
    """Turn the profiler on. On a fresh (or :func:`reset`) profiler the
    timestamp origin rebases to now, so the exported trace starts near t=0;
    when collected data exists the origin is KEPT — slices from before and
    after a disable/enable cycle must share one timeline or the exported
    B/E stream would interleave two origins."""
    global _active, _t0
    with _lock:
        if not _active and not _slices and not _requests and not _counter_events:
            _t0 = time.perf_counter()
        _active = True


def disable() -> None:
    """Stop collecting. Collected data is kept — :func:`report` and
    :func:`dump_trace` still work; :func:`reset` clears."""
    global _active
    _active = False


def active() -> bool:
    """Whether the profiler is currently collecting."""
    return _active


def reset() -> None:
    """Drop every collected slice, request, histogram, counter and memory
    sample. The enabled switch is kept."""
    with _lock:
        _slices.clear()
        _counter_events.clear()
        _requests.clear()
        _hists.clear()
        _counters.clear()
        _mem["forces"] = 0
        _mem["last_force_live_bytes"] = 0
        _mem["peak_force_live_bytes"] = 0


# ------------------------------------------------------------------ requests & scopes
def current_request() -> Optional[int]:
    """The ambient request id (inside a :func:`request` scope on this
    thread/context), or None."""
    return _current_request.get()


def attribution_active() -> bool:
    """True while request attribution is flowing: the profiler is enabled, or
    the forensics plane is armed (its lifecycle records ride the same request
    contextvar). Hot paths that only need a tenant/request id gate on this
    instead of ``_active`` — still just relaxed attribute reads."""
    f = _forensics
    return _active or (f is not None and f._enabled)


def request_slices(rid: int) -> List[dict]:
    """Every recorded slice attributed to request ``rid``, as ``{cat, name,
    t0_us, t1_us}`` dicts in recording order. Used by the forensics plane to
    attach a span tree to a tail exemplar at capture time."""
    with _lock:
        return [
            {"cat": c, "name": n, "t0_us": t0, "t1_us": t1}
            for (r, _tid, c, n, t0, t1) in _slices
            if r == rid
        ]


def current_request_tag() -> Optional[str]:
    """The ambient request's *tag* (the string passed to :func:`request`), or
    None outside a request scope / while disabled. The async executor uses
    this as the tenant key for its fair dispatch queue: requests sharing a tag
    share one round-robin slot."""
    rid = _current_request.get()
    if rid is None:
        return None
    with _lock:
        entry = _requests.get(rid)
        return entry["tag"] if entry is not None else None


@contextlib.contextmanager
def attributed(req: Optional[int]):
    """Make ``req`` the ambient request for this thread for the duration of
    the block (no-op for ``None`` or while disabled). The dispatch scheduler
    wraps queued executions in this so program-call and collective slices
    running on the scheduler thread still attribute to the request that
    planned the force. Also threads while only the forensics plane is armed —
    its records attribute through the same contextvar."""
    if req is None or not attribution_active():
        yield
        return
    token = _current_request.set(req)
    try:
        yield
    finally:
        _current_request.reset(token)


def current_deadline() -> Optional[float]:
    """The ambient request's absolute wall-clock deadline (a
    ``time.monotonic()`` instant), or None when no deadline is armed. Set by
    ``request(tag, deadline_s=...)``; captured by ``Deferred`` nodes at defer
    time and acted on at the executor's lifecycle checkpoints."""
    return _current_deadline.get()


@contextlib.contextmanager
def request(tag: str, deadline_s: Optional[float] = None):
    """Scope one serving request: allocates a request id, makes it the ambient
    request for every profiler hook on this thread (dispatch, force, program
    call, collective), records the request as a top-level slice on its own
    trace track, and observes its wall latency into the ``request.<tag>``
    histogram. Yields the request id. No-op (yields None) while disabled.

    ``deadline_s`` arms a wall-clock deadline ``deadline_s`` seconds from now
    for everything scoped under this request — deferred nodes capture it at
    defer time (like the request id), the async executor refuses/cancels work
    that cannot meet it, and readers get a typed
    ``ht.resilience.DeadlineExceeded`` instead of late results. The deadline
    is a lifecycle contract, not telemetry: it is armed even while the
    profiler is disabled.

    While the forensics plane is armed the scope runs "lite-active" even with
    the profiler disabled: a request id is allocated and threaded (so tenant
    attribution and the lifecycle record work) and the forensic record is
    opened/closed around the body, but no slices or histograms are recorded."""
    global _deadline_seen
    dtoken = None
    if deadline_s is not None:
        _deadline_seen = True
        dtoken = _current_deadline.set(time.monotonic() + float(deadline_s))
    f = _forensics
    fon = f is not None and f._enabled
    if not _active and not fon:
        try:
            yield None
        finally:
            if dtoken is not None:
                _current_deadline.reset(dtoken)
        return
    rid = next(_rid_counter)
    t0 = _now_us()
    with _lock:
        _requests[rid] = {"tag": str(tag), "t0_us": t0, "t1_us": None}
        while len(_requests) > _MAX_REQUESTS:
            _requests.popitem(last=False)
    token = _current_request.set(rid)
    if fon:
        f.begin_request(rid, str(tag), _current_deadline.get())
    try:
        yield rid
    finally:
        _current_request.reset(token)
        if dtoken is not None:
            _current_deadline.reset(dtoken)
        t1 = _now_us()
        with _lock:
            entry = _requests.get(rid)
            if entry is not None:
                entry["t1_us"] = t1
            if _active:
                _slices.append((rid, threading.get_ident(), "request", str(tag), t0, t1))
                _hist_locked(f"request.{tag}").observe((t1 - t0) / 1e6)
        if fon:
            f.finish_request(rid, (t1 - t0) / 1e6)


@contextlib.contextmanager
def scope(cat: str, name: str, req: Optional[int] = None):
    """Record one timed slice of category ``cat`` (``dispatch`` / ``compile``
    / ``execute`` / ``collective`` / ``force`` / user categories), attributed
    to the ambient request. ``req`` is a *fallback* attribution: when no
    request scope is ambient (a deferred chain forced outside the scope that
    built it) the slice — and everything nested under it — attributes to
    ``req`` instead. Callers on hot paths gate on ``profiler._active``
    themselves; this guard is for direct users.

    Yields a control handle: setting ``handle["keep"] = False`` before the
    block exits discards the slice. The executor uses this for a force that
    lost the plan race and had nothing to execute — recording it would put a
    phantom empty ``force`` on the timeline."""
    if not _active:
        yield {"keep": True}
        return
    token = None
    if req is not None and _current_request.get() is None:
        token = _current_request.set(req)
    rid = _current_request.get()
    ctl = {"keep": True}
    t0 = _now_us()
    try:
        yield ctl
    finally:
        t1 = _now_us()
        if ctl["keep"]:
            with _lock:
                _slices.append((rid, threading.get_ident(), str(cat), str(name), t0, t1))
        if token is not None:
            _current_request.reset(token)


# ------------------------------------------------------------------ metrics feeds
def _hist_locked(name: str) -> Histogram:
    h = _hists.get(name)
    if h is None:
        h = _hists[name] = Histogram()
    return h


def observe(name: str, seconds: float) -> None:
    """Observe one latency sample into the named histogram (no-op while
    disabled)."""
    if not _active:
        return
    with _lock:
        _hist_locked(name).observe(seconds)


def histogram_snapshots() -> Dict[str, dict]:
    """``{name: snapshot}`` for every histogram (works while disabled — the
    data survives :func:`disable` until :func:`reset`)."""
    with _lock:
        return {name: h.snapshot() for name, h in sorted(_hists.items())}


def record_counter(name: str, value: float) -> None:
    """One sample of a cumulative/gauge series, exported as a Chrome counter
    track (``ph: "C"``). The executor feeds ``donated_bytes`` (cumulative) and
    ``pad_waste_fraction`` (gauge) through here; callers gate on
    ``profiler._active``."""
    if not _active:
        return
    with _lock:
        _counters[name] = float(value)
        _counter_events.append((str(name), _now_us(), float(value)))


def record_force_memory(live_bytes: int) -> None:
    """Sample the logical bytes a deferred-graph force touched (leaf inputs +
    emitted outputs) — the force-boundary memory gauge. Callers gate on
    ``profiler._active``."""
    if not _active:
        return
    live_bytes = int(live_bytes)
    with _lock:
        _mem["forces"] += 1
        _mem["last_force_live_bytes"] = live_bytes
        if live_bytes > _mem["peak_force_live_bytes"]:
            _mem["peak_force_live_bytes"] = live_bytes
        _counter_events.append(("force_live_bytes", _now_us(), float(live_bytes)))


# ------------------------------------------------------------------ reporting
def report() -> dict:
    """The structured profiler snapshot (also registered as the ``profiler``
    section of ``ht.diagnostics.report()``)."""
    with _lock:
        reqs = [
            {"id": rid, "tag": e["tag"],
             "latency_s": round((e["t1_us"] - e["t0_us"]) / 1e6, 9)
             if e["t1_us"] is not None else None}
            for rid, e in list(_requests.items())[-64:]
        ]
        return {
            "schema": SCHEMA,
            "active": _active,
            "histograms": {name: h.snapshot() for name, h in sorted(_hists.items())},
            "requests_total": _requests_total(),
            "recent_requests": reqs,
            "memory": dict(_mem),
            "counters": dict(_counters),
            "slices_recorded": len(_slices),
        }


def _requests_total() -> int:
    # request.<tag> histogram counts are the durable tally (the _requests
    # table is evict-oldest); summing them counts every completed request
    return sum(h.count for name, h in _hists.items() if name.startswith("request."))


def _snapshot_locked() -> Dict[str, list]:
    # callers hold _lock (the _locked-suffix convention)
    return {
        "requests": [
            {"id": rid, "tag": e["tag"], "t0_us": e["t0_us"],
             "t1_us": e["t1_us"]}
            for rid, e in _requests.items()
        ],
        "slices": [list(s) for s in _slices],
        "counter_events": [list(c) for c in _counter_events],
    }


def trace_snapshot() -> Dict[str, list]:
    """The raw timeline data — requests, complete slices, counter samples —
    as JSON-able lists. This is the per-process export ``ht.telemetry`` ships
    inside a telemetry shard so ``telemetry.merge`` can rebuild ONE
    cross-process trace with per-process track groups (``trace_events``
    re-serialises a snapshot into Chrome trace events)."""
    with _lock:
        return _snapshot_locked()


def trace_events(snapshot: Dict[str, list], *, pid_offset: int = 0,
                 ts_shift_us: float = 0.0,
                 process_label: Optional[str] = None) -> List[dict]:
    """Serialise a :func:`trace_snapshot` into Chrome trace events.

    ``pid_offset`` namespaces every track pid (request tracks become
    ``pid_offset + rid``, the unattributed and counter tracks sit at
    ``pid_offset`` itself) — the telemetry merger gives each process its own
    disjoint pid range so two processes' request id 3 cannot collide on one
    track, and cumulative counters from different ranks land on separate
    tracks instead of summing into nonsense. ``ts_shift_us`` is added to every
    timestamp (the merger's clock alignment); ``process_label`` prefixes the
    track metadata names (``p1/request 3: kmeans``)."""
    prefix = f"{process_label}/" if process_label else ""
    events: List[dict] = []
    # one track (pid) per request, its tag as the process name; the offset
    # base pid is the unattributed track (framework work outside any request)
    events.append({"name": "process_name", "ph": "M", "pid": pid_offset,
                   "tid": 0, "args": {"name": f"{prefix}unattributed"}})
    events.append({"name": "process_sort_index", "ph": "M", "pid": pid_offset,
                   "tid": 0, "args": {"sort_index": pid_offset}})
    for entry in snapshot.get("requests", ()):
        rid = entry["id"]
        events.append({
            "name": "process_name", "ph": "M", "pid": pid_offset + rid,
            "tid": 0, "args": {"name": f"{prefix}request {rid}: {entry['tag']}"},
        })
        events.append({
            "name": "process_sort_index", "ph": "M", "pid": pid_offset + rid,
            "tid": 0, "args": {"sort_index": pid_offset + rid},
        })
    be: List[tuple] = []
    for seq, (rid, tid, cat, name, t0, t1) in enumerate(snapshot.get("slices", ())):
        pid = pid_offset + (rid if rid is not None else 0)
        t0 += ts_shift_us
        t1 += ts_shift_us
        t1 = max(t1, t0 + 1e-3)  # floor at 1 ns: a zero-length slice must
        dur = t1 - t0            # still emit its B strictly before its E
        be.append((t0, 1, -dur, -seq, {"name": name, "cat": cat, "ph": "B",
                                       "pid": pid, "tid": tid, "ts": round(t0, 3)}))
        be.append((t1, 0, dur, seq, {"name": name, "cat": cat, "ph": "E",
                                     "pid": pid, "tid": tid, "ts": round(t1, 3)}))
    # nesting-stable order: at equal ts an E closes before a B opens (sibling
    # slices), an enclosing B opens before its co-timed child (-dur sorts the
    # longer slice first, and a parent's larger append seq breaks exact ties —
    # children exit scopes, and so append, before their parents), and a child
    # E closes before its co-timed parent E (dur, then seq ascending)
    be.sort(key=lambda e: (e[0], e[1], e[2], e[3]))
    events.extend(e[-1] for e in be)
    for name, ts, value in snapshot.get("counter_events", ()):
        events.append({"name": name, "cat": "counter", "ph": "C",
                       "pid": pid_offset, "tid": 0,
                       "ts": round(ts + ts_shift_us, 3), "args": {name: value}})
    return events


def _trace_events_locked() -> List[dict]:
    # callers hold _lock (the _locked-suffix convention)
    return trace_events(_snapshot_locked())


def dump_trace(path: str) -> dict:
    """Write the recorded timeline as Chrome trace-event JSON (the object
    format: ``{"traceEvents": [...]}``) loadable in Perfetto /
    ``chrome://tracing``. Returns the written object (tests schema-check it
    without re-reading the file).

    The write goes through ``resilience.atomic_write`` (site
    ``profiler.trace``): a crash mid-dump leaves the previous artifact (or
    nothing), never a torn half-JSON that a downstream ``telemetry.merge``
    would choke on."""
    with _lock:
        obj = {
            "schema": TRACE_SCHEMA,
            "displayTimeUnit": "ms",
            "traceEvents": _trace_events_locked(),
        }

    def _write(target: str) -> None:
        with open(target, "w") as f:
            json.dump(obj, f)
            f.write("\n")

    if resilience is not None:
        resilience.atomic_write(path, _write, site="profiler.trace")
    else:  # standalone file-path load: no resilience instance to route through
        _write(path)
    return obj


# The profiler's section of ht.diagnostics.report(): histograms + memory +
# recent requests ride along with the aggregate telemetry in one artifact.
# (None only under a standalone file-path load, where there is no shared
# diagnostics instance to report into.)
if diagnostics is not None:
    diagnostics.register_provider("profiler", report)


# ------------------------------------------------------------------ env bootstrap
if os.environ.get("HEAT_TPU_PROFILE") == "1":
    enable()

_trace_path = os.environ.get("HEAT_TPU_PROFILE_TRACE")
if _trace_path and __package__:

    @atexit.register
    def _dump_trace_at_exit(path: str = _trace_path) -> None:  # pragma: no cover - exit hook
        try:
            dump_trace(path)
        except Exception:  # ht: ignore[silent-except] -- atexit hook: raising here would mask the process's real exit status
            pass
