"""Random number generation (reference heat/core/random.py, 1076 LoC).

The reference hand-implements a counter-based stateless Threefry-2x32/64 pRNG
(``random.py:875,977``) so that streams are reproducible regardless of process count:
a global (seed, counter) pair is advanced by the *global* number of elements drawn, and
each rank generates only its chunk of the counter sequence (``__counter_sequence``
``random.py:56``). JAX's native RNG **is** this design — threefry2x32 keyed by
``jax.random.key(seed)`` — so the TPU build keeps a (seed, counter) module state for API
parity and derives a fresh fold of the key per call: identical devices-count-independent
streams, no mass-generation kernel needed (XLA fuses the threefry rounds).

``normal``/``randn`` use true inverse-CDF gaussians from ``jax.random.normal`` rather
than the reference's Kundu-transform approximation (``random.py:247``) — numerics are
*better* than parity, and the distribution contract (mean/std) is identical.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import types
from .communication import get_comm, sanitize_comm
from .devices import get_device, sanitize_device
from .dndarray import DNDarray
from .stride_tricks import sanitize_shape

__all__ = [
    "get_state",
    "normal",
    "permutation",
    "rand",
    "ranf",
    "randint",
    "random_integer",
    "randn",
    "random",
    "random_sample",
    "randperm",
    "sample",
    "seed",
    "set_state",
    "standard_normal",
]

# Global (seed, counter) state, mirroring the reference's module state (random.py:40-44).
__seed: int = 0
__counter: int = 0
# The counter advance is a read-modify-write: under the async executor two
# serving threads drawing concurrently could reserve the SAME counter range
# and emit duplicate streams. Every access to the (seed, counter) PAIR is
# atomic under this lock — a draw must snapshot the seed its reserved range
# belongs to (a concurrent reseed between the two reads would pair the new
# seed with a stale counter and reproduce a later draw's key exactly). The
# key derivation itself stays outside — it is pure in (seed, base).
_state_lock = threading.Lock()


def _next_key(nelem: int) -> jax.Array:
    """Derive the key for the next draw and advance the counter by the *global* element
    count — the property that makes streams independent of the device count
    (reference ``__counter_sequence`` ``random.py:56``)."""
    global __counter
    with _state_lock:
        sd = __seed
        base = __counter
        __counter = base + int(nelem)
    # fold the counter in 32-bit limbs so the stream never wraps (the reference's
    # Threefry counter is effectively 128-bit, random.py:56)
    lo = base & 0xFFFFFFFF
    hi = (base >> 32) & 0xFFFFFFFF
    return jax.random.fold_in(jax.random.fold_in(jax.random.key(sd), hi), lo)


def _wrap(value: jax.Array, dtype, split, device, comm) -> DNDarray:
    comm = sanitize_comm(comm)
    device = sanitize_device(device)
    gshape = tuple(value.shape)
    value = comm.shard(value, split)
    return DNDarray(value, gshape, dtype, split, device, comm, True)


def get_state() -> Tuple[str, int, int, int, float]:
    """Return the internal state of the generator (reference ``random.py:202``)."""
    with _state_lock:
        return ("Threefry", __seed, __counter, 0, 0.0)


def set_state(state: Tuple[str, int, int, int, float]) -> None:
    """Set the internal state (reference ``random.py:789``)."""
    if state[0] != "Threefry":
        raise ValueError(f"random state must be of type Threefry, got {state[0]}")
    global __seed, __counter
    with _state_lock:
        __seed = int(state[1])
        __counter = int(state[2])


def seed(seed: Optional[int] = None) -> None:
    """Seed the generator (reference ``random.py:771``)."""
    global __seed, __counter
    if seed is None:
        seed = np.random.SeedSequence().entropy % (2**32)
    with _state_lock:
        __seed = int(seed)
        __counter = 0


def normal(
    mean: Union[float, DNDarray] = 0.0,
    std: Union[float, DNDarray] = 1.0,
    shape: Optional[Tuple[int, ...]] = None,
    dtype=types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Normal distribution with given mean/std (reference ``random.py:267``)."""
    if shape is None:
        shape = getattr(mean, "gshape", None) or getattr(std, "gshape", None) or ()
    s = standard_normal(shape, dtype=dtype, split=split, device=device, comm=comm)
    from . import arithmetics

    return arithmetics.add(arithmetics.mul(s, std), mean)


def permutation(x: Union[int, DNDarray], **kwargs) -> DNDarray:
    """Randomly permute a sequence (reference ``random.py:325``; the split-0 p2p shuffle
    there is one global permutation XLA reshards)."""
    from . import factories

    if isinstance(x, int):
        return randperm(x, **kwargs)
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected int or DNDarray, got {type(x)}")
    if kwargs:
        raise TypeError(f"unexpected kwargs {tuple(kwargs)} for DNDarray input")
    key = _next_key(x.gshape[0])
    perm = jax.random.permutation(key, x.gshape[0])
    result = jnp.take(x.larray, perm, axis=0)
    return _wrap(result, x.dtype, x.split, x.device, x.comm)


def rand(
    *d: int,
    dtype=types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Uniform [0, 1) samples (reference ``random.py:403``)."""
    shape = sanitize_shape(d if d else ())
    dtype = types.canonical_heat_type(dtype)
    if dtype not in (types.float32, types.float64):
        raise ValueError(f"Unsupported dtype {dtype} for rand")
    nelem = int(np.prod(shape)) if shape else 1
    key = _next_key(nelem)
    value = jax.random.uniform(key, shape, dtype=dtype.jax_type())
    return _wrap(value, dtype, split, device, comm)


def randint(
    low: int,
    high: Optional[int] = None,
    size: Optional[Union[int, Tuple[int, ...]]] = None,
    dtype=types.int32,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Random integers in [low, high) (reference ``random.py:480``)."""
    if high is None:
        low, high = 0, low
    if size is None:
        size = ()
    if isinstance(size, int):
        size = (size,)
    size = sanitize_shape(size)
    if low >= high:
        raise ValueError(f"low >= high ({low} >= {high})")
    dtype = types.canonical_heat_type(dtype)
    if dtype not in (types.int32, types.int64):
        raise ValueError(f"Unsupported dtype {dtype} for randint")
    nelem = int(np.prod(size)) if size else 1
    key = _next_key(nelem)
    value = jax.random.randint(key, size, low, high, dtype=dtype.jax_type())
    return _wrap(value, dtype, split, device, comm)


def random_integer(
    low: int,
    high: Optional[int] = None,
    size: Optional[Union[int, Tuple[int, ...]]] = None,
    dtype=types.int32,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Alias of :func:`randint` (reference ``random.py:576``)."""
    return randint(low, high, size, dtype, split, device, comm)


def randn(
    *d: int,
    dtype=types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Standard-normal samples (reference ``random.py:591``)."""
    return standard_normal(sanitize_shape(d if d else ()), dtype, split, device, comm)


def randperm(
    n: int,
    dtype=types.int64,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Random permutation of ``arange(n)`` (reference ``random.py:648``)."""
    if not isinstance(n, int):
        raise TypeError(f"n must be an int, got {type(n)}")
    dtype = types.canonical_heat_type(dtype)
    key = _next_key(n)
    value = jax.random.permutation(key, n).astype(dtype.jax_type())
    return _wrap(value, dtype, split, device, comm)


def random(
    shape: Optional[Tuple[int, ...]] = None,
    dtype=types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Uniform [0, 1) samples in the given shape (reference ``random.py:692``)."""
    shape = sanitize_shape(shape) if shape is not None else ()
    return rand(*shape, dtype=dtype, split=split, device=device, comm=comm)


def ranf(*args, **kwargs) -> DNDarray:
    """Alias of :func:`random` (reference ``random.py:732``)."""
    return random(*args, **kwargs)


def random_sample(*args, **kwargs) -> DNDarray:
    """Alias of :func:`random` (reference ``random.py:745``)."""
    return random(*args, **kwargs)


def sample(*args, **kwargs) -> DNDarray:
    """Alias of :func:`random` (reference ``random.py:758``)."""
    return random(*args, **kwargs)


def standard_normal(
    shape: Optional[Tuple[int, ...]] = None,
    dtype=types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Standard-normal samples (reference ``random.py:826``)."""
    shape = sanitize_shape(shape) if shape is not None else ()
    dtype = types.canonical_heat_type(dtype)
    if dtype not in (types.float32, types.float64):
        raise ValueError(f"Unsupported dtype {dtype} for standard_normal")
    nelem = int(np.prod(shape)) if shape else 1
    key = _next_key(nelem)
    value = jax.random.normal(key, shape, dtype=dtype.jax_type())
    return _wrap(value, dtype, split, device, comm)


# initialise with a fixed default seed like the reference (random.py:1066-1076 seeds from
# time; a fixed default keeps single-program runs reproducible — call seed() for entropy)
seed(ord("h") + ord("e") + ord("a") + ord("t"))
