"""Relational operations (reference heat/core/relational.py, 12 exports)."""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = ["eq", "equal", "ge", "greater", "greater_equal", "gt", "le", "less", "less_equal", "lt", "ne", "not_equal"]


def eq(x, y) -> DNDarray:
    """Element-wise ``==`` (reference ``relational.py`` eq)."""
    return _operations.binary_op(jnp.equal, x, y)


def equal(x, y) -> bool:
    """True iff all elements equal — a collective scalar verdict (reference
    ``relational.py`` equal, which Allreduces the local verdicts)."""
    from . import factories

    a = x if isinstance(x, DNDarray) else factories.array(x)
    b = y if isinstance(y, DNDarray) else factories.array(y)
    try:
        return bool(jnp.array_equal(a.larray, b.larray))
    except (TypeError, ValueError):
        return False


def ge(x, y) -> DNDarray:
    return _operations.binary_op(jnp.greater_equal, x, y)


greater_equal = ge


def gt(x, y) -> DNDarray:
    return _operations.binary_op(jnp.greater, x, y)


greater = gt


def le(x, y) -> DNDarray:
    return _operations.binary_op(jnp.less_equal, x, y)


less_equal = le


def lt(x, y) -> DNDarray:
    return _operations.binary_op(jnp.less, x, y)


less = lt


def ne(x, y) -> DNDarray:
    return _operations.binary_op(jnp.not_equal, x, y)


not_equal = ne
