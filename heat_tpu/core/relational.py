"""Relational operations (reference heat/core/relational.py, 12 exports)."""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = ["eq", "equal", "ge", "greater", "greater_equal", "gt", "le", "less", "less_equal", "lt", "ne", "not_equal"]


def eq(t1, t2) -> DNDarray:
    """Element-wise ``==`` (reference ``relational.py`` eq)."""
    return _operations.binary_op(jnp.equal, t1, t2)


def equal(t1, t2) -> bool:
    """True iff all elements equal — a collective scalar verdict (reference
    ``relational.py`` equal, which Allreduces the local verdicts)."""
    from . import factories

    a = t1 if isinstance(t1, DNDarray) else factories.array(t1)
    b = t2 if isinstance(t2, DNDarray) else factories.array(t2)
    try:
        return bool(jnp.array_equal(a.larray, b.larray))
    except (TypeError, ValueError):
        return False


def ge(t1, t2) -> DNDarray:
    return _operations.binary_op(jnp.greater_equal, t1, t2)


greater_equal = ge


def gt(t1, t2) -> DNDarray:
    return _operations.binary_op(jnp.greater, t1, t2)


greater = gt


def le(t1, t2) -> DNDarray:
    return _operations.binary_op(jnp.less_equal, t1, t2)


less_equal = le


def lt(t1, t2) -> DNDarray:
    return _operations.binary_op(jnp.less, t1, t2)


less = lt


def ne(t1, t2) -> DNDarray:
    return _operations.binary_op(jnp.not_equal, t1, t2)


not_equal = ne
