"""``ht.resilience`` — retry/backoff policies, circuit breakers, deterministic
fault injection, and atomic write primitives.

The framework has four failure domains that used to be defended by
independently-invented ad-hoc loops: the accelerator relay (bench.py probes and
``__graft_entry__``'s dryrun re-probe), the backend capability probe
(``devices.py``'s killable subprocess), the dispatch executor's compiled
programs, and the checkpoint/save writers. None of that recovery code was
testable, because nothing could make a collective, a compile, or a checkpoint
write fail on demand. This module centralises all of it:

- :class:`Policy` — ``max_attempts`` × exponential backoff (``backoff_base *
  2**(attempt-1)``) with optional ``jitter`` fraction, ``max_delay_s`` cap and
  a wall-clock ``deadline_s``. ``Policy.run(site, fn)`` re-raises the failing
  call's exception unchanged on exhaustion (the final attempt's — never a
  policy wrapper), so call sites stay transparent. Every
  retry and exhaustion is recorded via :func:`diagnostics.record_resilience_event`
  and (when metrics are on) a ``resilience.retry.<site>`` counter.
- :class:`CircuitBreaker` — per-site closed → open → half-open. ``failure_threshold``
  consecutive failures open the circuit; while open, :meth:`CircuitBreaker.allows`
  returns False so callers short-circuit to their cached negative answer
  (``devices.py`` stops re-paying the 90 s probe-subprocess timeout); after
  ``cooldown_s`` the breaker half-opens and one real trial closes or re-opens
  it. Transitions are recorded via diagnostics.
- **Deterministic fault injection** — ``HEAT_TPU_FAULT_PLAN=<json>`` (or
  :func:`arm_fault_plan`) loads a list of entries, each naming a ``site``, a
  fire-on-Nth-call trigger (``on_call``, optional ``count`` for a window) and a
  fault ``kind``: ``"raise"``, ``"timeout"``, ``"backend-down"`` or
  ``"torn-write"`` (the last truncates an :func:`atomic_write` payload before
  the rename, simulating silent corruption). Sites count calls under a lock,
  so chaos tests replay exact failure sequences with zero flakiness.
- :func:`atomic_write` — write-to-temp + fsync + ``os.replace`` with
  policy-driven retry, the primitive behind the checkpoint manifest writer and
  the whole-file ``ht.save`` paths.

The async executor's bounded dispatch queue (ISSUE 8) resolves queue-full
backpressure through this module: a refused submit retries under the
``executor.queue`` site policy (register an override with
``set_policy("executor.queue", Policy(...))`` to tune a deployment's
backpressure; the executor's built-in default is a few-millisecond ladder)
and, exhausted, executes inline — retries and exhaustions land in the
resilience event stream like every other site's.

This module also defines the **request-lifecycle** error vocabulary the
executor's deadline/cancellation/shedding machinery (ISSUE 10) delivers
through dispatch-done futures: :class:`DeadlineExceeded` (the request's
wall-clock deadline passed — never retried by a :class:`Policy`),
:class:`Shed` (``HEAT_TPU_SHED=1`` admission control rejected infeasible or
queue-full work without attempting it), :class:`RequestCancelled`
(``DispatchScheduler.cancel(tag)``), and :class:`DrainTimeout`
(``DispatchScheduler.drain(timeout)`` could not flush — raised to the caller
AND delivered to every still-queued future so nothing blocks forever). The
``deadline-exceeded`` fault kind injects :class:`InjectedDeadlineExceeded`
so chaos plans can fire expiries inside queued and batched executions.

Zero-cost contract (same discipline as ``ht.diagnostics`` and
``HEAT_TPU_TRACE``): instrumented sites gate on the module attributes
``resilience._armed`` (a fault plan is loaded) / ``resilience._active``
(a plan is loaded OR a site policy is registered) — one attribute read and a
branch not taken when idle — and nothing is ever injected into traced program
bodies, so compiled HLO is byte-identical whether or not a plan is armed
(``tests/test_resilience.py::TestHLOByteParity``).

This module imports only the stdlib at top level (the ``diagnostics`` import
degrades to ``None`` under a standalone file-path load) so the driver entry
points (``bench.py``, ``__graft_entry__.py``) can load it via
``_diag_bootstrap.load_resilience()`` *before* anything touches the JAX
backend.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

try:  # standalone file-path load (driver entry points): the bootstrap injects
    from . import diagnostics  # its own diagnostics instance after exec_module
except ImportError:  # pragma: no cover - exercised via _diag_bootstrap
    diagnostics = None

__all__ = [
    "Policy",
    "CircuitBreaker",
    "CLOSED",
    "OPEN",
    "HALF_OPEN",
    "breaker",
    "breakers",
    "relay_breaker",
    "RELAY_SITE",
    "get_policy",
    "site_policy",
    "set_policy",
    "guard",
    "arm_fault_plan",
    "disarm_fault_plan",
    "fault_plan",
    "fault_signal",
    "maybe_fault",
    "atomic_write",
    "fsync_dir",
    "resilience_stats",
    "reset",
    "FaultInjected",
    "InjectedTimeout",
    "InjectedBackendDown",
    "InjectedDeadlineExceeded",
    "CircuitOpen",
    "DeadlineExceeded",
    "Shed",
    "RequestCancelled",
    "DrainTimeout",
    "SwapFailed",
    "PeerFailed",
    "CollectiveTimeout",
    "CoordinationTimeout",
    "set_fault_rank",
    "run_supervised",
]

# Hot-path gates, read as ``resilience._armed`` / ``resilience._active`` by the
# instrumented sites (one attribute load + branch when idle — the zero-cost
# contract). ``_armed``: a fault plan is loaded. ``_active``: a plan is loaded
# or at least one site policy was registered (guard() then applies retries).
_armed: bool = False
_active: bool = False

_lock = threading.RLock()

FAULT_KINDS = (
    "raise", "timeout", "backend-down", "torn-write", "deadline-exceeded",
    "peer-dead",
)


class FaultInjected(RuntimeError):
    """An injected fault fired (``HEAT_TPU_FAULT_PLAN``); never raised by real
    failures, so tests can tell injection from genuine breakage."""


class InjectedTimeout(FaultInjected, TimeoutError):
    """Injected ``timeout`` fault — also a ``TimeoutError`` so probe code that
    special-cases timeouts treats it identically to a real one."""


class InjectedBackendDown(FaultInjected):
    """Injected ``backend-down`` fault — probe sites treat it as an unreachable
    relay without paying their subprocess timeout."""


class CircuitOpen(RuntimeError):
    """A call was short-circuited because the site's circuit breaker is open."""

    def __init__(self, site: str):
        super().__init__(f"circuit breaker for site {site!r} is open")
        self.site = site


# ------------------------------------------------------------- request lifecycle
class DeadlineExceeded(RuntimeError):
    """A request's wall-clock deadline passed before (or while) its work could
    run: the executor delivers this instead of late results — at queue
    admission, when an expired queued item is cancelled pre-dispatch, and
    between ops of an eager replay. Never retried by a :class:`Policy`
    (retrying cannot un-expire a deadline)."""


class Shed(RuntimeError):
    """The load-shedding admission control (``HEAT_TPU_SHED=1``) rejected this
    request instead of executing it: its deadline was infeasible per the
    per-signature service-time estimate, or the dispatch queue stayed full
    through backpressure. The work was NOT attempted — retrying later (or
    without a deadline) is safe and side-effect-free."""


class RequestCancelled(RuntimeError):
    """Queued work was cancelled by an explicit lifecycle verb
    (``DispatchScheduler.cancel(tag)``) before it dispatched."""


class DrainTimeout(RuntimeError):
    """``DispatchScheduler.drain(timeout)`` could not flush the queue in time.
    Every still-queued item's future was failed with this same exception (so
    nothing is left blocked); ``undelivered`` names them, and ``in_flight``
    counts executions that were still running when the timeout struck (their
    futures are fulfilled by the executing thread when it finishes)."""

    def __init__(self, timeout_s: float, undelivered, in_flight: int = 0):
        self.timeout_s = timeout_s
        self.undelivered = list(undelivered)
        self.in_flight = int(in_flight)
        names = ", ".join(self.undelivered) or "<none>"
        super().__init__(
            f"scheduler drain did not settle within {timeout_s:.3f}s: "
            f"{len(self.undelivered)} queued item(s) shed with this error "
            f"({names}); {self.in_flight} execution(s) still in flight"
        )


class SwapFailed(RuntimeError):
    """A zero-downtime model swap (``ht.serving.swap_state``) failed and was
    rolled back to the previous generation: staging the new state raised
    (verification/IO — serving was never touched), the drain timed out, or the
    rebind itself failed. ``stage`` names the step; serving continues on the
    old generation either way — a failed swap is an incident, never an
    outage."""

    def __init__(self, stage: str, pool: str, detail: str):
        self.stage = stage
        self.pool = pool
        super().__init__(
            f"model swap for pool {pool!r} failed at the {stage!r} step and "
            f"was rolled back: {detail}"
        )


class InjectedDeadlineExceeded(FaultInjected, DeadlineExceeded):
    """Injected ``deadline-exceeded`` fault — also a :class:`DeadlineExceeded`
    so the executor's lifecycle paths (typed delivery, no eager replay of
    over-deadline work, no quarantine) treat it exactly like a real expiry."""


# ------------------------------------------------------------- supervision
class PeerFailed(RuntimeError):
    """A peer process stopped heartbeating past ``HEAT_TPU_PEER_TIMEOUT_S``
    (or the coordination runtime reported it dead): the supervision plane
    (``ht.supervision``) posts a cluster-wide abort sentinel and EVERY
    survivor raises this at its next chokepoint — collective invocation,
    scheduler dispatch, or supervised coordination wait — instead of hanging.
    ``rank`` is the failed peer (-1 when unknown), ``last_seen_s`` how long
    it had been silent when declared dead, ``detected_by`` the rank whose
    monitor posted the sentinel. Never retried by a :class:`Policy` — the
    recovery path is ``run_supervised``'s elastic restart."""

    def __init__(self, rank: int, last_seen_s: float, *, detected_by: int = -1):
        self.rank = int(rank)
        self.last_seen_s = float(last_seen_s)
        self.detected_by = int(detected_by)
        super().__init__(
            f"peer rank {self.rank} failed (no heartbeat for "
            f"{self.last_seen_s:.3f}s; detected by rank {self.detected_by}); "
            "all survivors abort typed at their next supervision chokepoint"
        )


class CollectiveTimeout(RuntimeError):
    """The collective watchdog (``HEAT_TPU_COLLECTIVE_TIMEOUT_S``) found a
    ``MeshCommunication._guarded`` invocation window stuck past its deadline:
    a flight-recorder post-mortem was dumped (trigger kind
    ``supervision.watchdog``), the abort sentinel posted, and this raised on
    every survivor — the stuck rank itself raises the moment its call
    unblocks. ``site`` is the guarded call site, ``elapsed_s`` how long the
    window had been open when flagged."""

    def __init__(self, site: str, elapsed_s: float, *, detected_by: int = -1):
        self.site = str(site)
        self.elapsed_s = float(elapsed_s)
        self.detected_by = int(detected_by)
        super().__init__(
            f"collective at {self.site!r} exceeded its watchdog deadline "
            f"({self.elapsed_s:.3f}s elapsed; detected by rank "
            f"{self.detected_by})"
        )


class CoordinationTimeout(RuntimeError):
    """A supervised coordination-channel wait (``supervision.kv_wait`` /
    ``kv_barrier``) exhausted its ``HEAT_TPU_COORD_TIMEOUT_MS`` budget: the
    typed replacement for the raw KV/barrier backend errors the old
    hardcoded handshake/checkpoint timeouts surfaced. ``key`` names the
    coordination key waited on; ``waiting_on`` lists the ranks that never
    arrived (barriers); ``detail`` carries the last backend error text."""

    def __init__(self, site: str, *, key: str = "", timeout_ms: int = 0,
                 waiting_on=(), detail: str = ""):
        self.site = str(site)
        self.key = str(key)
        self.timeout_ms = int(timeout_ms)
        self.waiting_on = [int(r) for r in waiting_on]
        self.detail = str(detail)
        ranks = (f"; ranks not arrived: {self.waiting_on}"
                 if self.waiting_on else "")
        extra = f"; last error: {self.detail}" if self.detail else ""
        super().__init__(
            f"coordination wait at {self.site!r} for key {self.key!r} "
            f"exceeded {self.timeout_ms}ms{ranks}{extra}"
        )


def _record_event(site: str, kind: str, detail: str = "") -> None:
    """Resilience events (retries, breaker transitions, fault firings) are rare
    and explicit — recorded always-on like backend-health events. Metric
    counters stay gated on ``diagnostics.enabled()`` as usual."""
    if diagnostics is not None:
        diagnostics.record_resilience_event(site, kind, detail)


def _count(name: str) -> None:
    if diagnostics is not None:
        diagnostics.counter(name)


# ------------------------------------------------------------------ policy engine
class Policy:
    """A retry/backoff policy: ``max_attempts`` tries with exponential backoff.

    ``max_attempts=None`` retries until ``deadline_s`` (which is then required).
    ``backoff_base`` seconds doubles per attempt, capped at ``max_delay_s``;
    ``jitter`` is a ± fraction applied from a module-seeded RNG (leave 0 for
    fully deterministic schedules — the chaos tests do). ``retry_on`` bounds
    which exception types are retried; anything else propagates immediately.

    :meth:`run` re-raises the failing call's exception UNCHANGED when attempts
    or the deadline are exhausted (the final attempt's exception — earlier
    attempts' errors are in the recorded retry events) — callers keep their
    existing ``except`` semantics and the policy stays an invisible wrapper.
    """

    __slots__ = (
        "max_attempts", "backoff_base", "jitter", "deadline_s", "max_delay_s",
        "retry_on",
    )

    def __init__(
        self,
        max_attempts: Optional[int] = 3,
        backoff_base: float = 0.5,
        jitter: float = 0.0,
        deadline_s: Optional[float] = None,
        max_delay_s: Optional[float] = None,
        retry_on: Tuple[type, ...] = (Exception,),
    ):
        if max_attempts is None and deadline_s is None:
            raise ValueError("max_attempts=None (unbounded) requires deadline_s")
        if max_attempts is not None and max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = max_attempts
        self.backoff_base = float(backoff_base)
        self.jitter = float(jitter)
        self.deadline_s = deadline_s
        self.max_delay_s = max_delay_s
        self.retry_on = retry_on

    def delay_s(self, attempt: int) -> float:
        """Backoff after the ``attempt``-th failure (1-based)."""
        d = self.backoff_base * (2.0 ** (attempt - 1))
        if self.max_delay_s is not None:
            d = min(d, self.max_delay_s)
        if self.jitter:
            d *= 1.0 + _jitter_rng.uniform(-self.jitter, self.jitter)
        return max(0.0, d)

    def run(
        self,
        site: str,
        fn: Callable,
        *args,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
        breaker: Optional["CircuitBreaker"] = None,
        **kwargs,
    ):
        """Call ``fn(*args, **kwargs)`` under this policy.

        When ``breaker`` is given, an open circuit raises :class:`CircuitOpen`
        before any attempt, and every attempt's outcome feeds the breaker.
        ``sleep``/``clock`` are injectable so tests run without wall time.
        """
        start = clock()
        attempt = 0
        while True:
            attempt += 1
            if breaker is not None and not breaker.allows():
                raise CircuitOpen(site)
            try:
                result = fn(*args, **kwargs)
            except self.retry_on as exc:
                if isinstance(exc, CircuitOpen):
                    raise
                if isinstance(exc, DeadlineExceeded):
                    # a deadline that has passed cannot un-pass: retrying would
                    # only burn backoff time the request no longer has. The
                    # breaker learned NOTHING about the backend from a request
                    # running out of time — release a half-open probe token so
                    # the next caller can run the real trial instead of
                    # everyone waiting out another cooldown.
                    if breaker is not None:
                        breaker.abandon_probe()
                    raise
                if breaker is not None:
                    breaker.record_failure(f"{type(exc).__name__}: {exc}")
                exhausted = (
                    self.max_attempts is not None and attempt >= self.max_attempts
                )
                delay = self.delay_s(attempt)
                if (
                    self.deadline_s is not None
                    and clock() - start + delay >= self.deadline_s
                ):
                    exhausted = True
                if exhausted:
                    _record_event(
                        site, "exhausted",
                        f"attempt {attempt}: {type(exc).__name__}: {exc}",
                    )
                    raise
                _record_event(
                    site, "retry",
                    f"attempt {attempt} failed ({type(exc).__name__}: {exc}); "
                    f"backing off {delay:.3f}s",
                )
                _count(f"resilience.retry.{site}")
                sleep(delay)
            else:
                if breaker is not None:
                    breaker.record_success()
                return result


_jitter_rng = random.Random(0x48454154)  # deterministic per process

# The fallback policy for sites without a registered override: a short, cheap
# retry ladder (three attempts, 50 ms base) — enough to absorb a transient
# fault without turning a deterministic failure into seconds of stalling.
_DEFAULT_POLICY = Policy(max_attempts=3, backoff_base=0.05, jitter=0.0)

_site_policies: Dict[str, Policy] = {}


def get_policy(site: str) -> Policy:
    """The policy for ``site``: a registered override or the module default."""
    return _site_policies.get(site, _DEFAULT_POLICY)


def site_policy(site: str) -> Optional[Policy]:
    """The registered override for ``site``, or None — lets non-idempotent
    call sites pick their own fallback instead of the retrying default."""
    return _site_policies.get(site)


def set_policy(site: str, policy: Optional[Policy]) -> None:
    """Register (or, with ``None``, remove) a per-site policy override.
    Registering any override also activates :func:`guard`-wrapped sites."""
    with _lock:
        if policy is None:
            _site_policies.pop(site, None)
        else:
            if not isinstance(policy, Policy):
                raise TypeError(f"expected a Policy, got {type(policy)}")
            _site_policies[site] = policy
        _refresh_active_locked()


def _refresh_active_locked() -> None:
    # called with _lock held (the _locked-suffix convention ht.analysis checks)
    global _active
    _active = _armed or bool(_site_policies)


def guard(site: str, fn: Callable, *args, inject: bool = True,
          policy: Optional[Policy] = None, **kwargs):
    """Run ``fn`` under ``site``'s policy, injecting planned faults per attempt.

    The retry wrapper for instrumented call sites (collective invocation, the
    executor's program calls). Callers gate on ``resilience._active`` so the
    idle cost is one attribute read; ``inject=False`` skips the per-attempt
    :func:`maybe_fault` for callees that carry their own injection hook (the
    executor's ``_Program.__call__``) — a site must count each attempt exactly
    once for fire-on-Nth-call plans to stay deterministic. ``policy``
    overrides the site lookup (non-idempotent writers pass a single-attempt
    policy so a half-applied in-place write is never blindly replayed)."""
    policy = policy or get_policy(site)
    if inject and _armed:

        def attempt():
            maybe_fault(site)
            return fn(*args, **kwargs)

        return policy.run(site, attempt)
    return policy.run(site, fn, *args, **kwargs)


# ------------------------------------------------------------------ circuit breaker
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CircuitBreaker:
    """Per-site closed → open → half-open breaker.

    ``failure_threshold`` consecutive :meth:`record_failure` calls open the
    circuit; :meth:`allows` then returns False (callers short-circuit to their
    cached negative result) until ``cooldown_s`` elapses, when the breaker
    half-opens: exactly ONE caller per half-open window is admitted as the
    trial probe — success closes the circuit, failure re-opens it (restarting
    the cooldown). Concurrent callers during the trial see the circuit as
    still open, so N threads hitting a half-open breaker cannot re-probe a
    down backend simultaneously (the thundering-herd shape the relay probe's
    90 s subprocess timeout makes expensive). A probe holder that never
    reports back (crashed caller) forfeits its token after another
    ``cooldown_s``, when a fresh window grants a new one.

    Every state transition is recorded via
    ``diagnostics.record_resilience_event(site, "breaker", "old->new")``.
    ``clock`` is injectable for deterministic tests.
    """

    __slots__ = (
        "site", "failure_threshold", "cooldown_s", "clock",
        "_state", "_failures", "_opened_at", "opens", "short_circuits",
        "_probe_taken", "_probe_at",
    )

    def __init__(
        self,
        site: str,
        failure_threshold: int = 3,
        cooldown_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError(f"failure_threshold must be >= 1, got {failure_threshold}")
        self.site = site
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self._state = CLOSED
        self._failures = 0
        self._opened_at: Optional[float] = None
        self.opens = 0
        self.short_circuits = 0
        self._probe_taken = False
        self._probe_at: Optional[float] = None

    def _transition(self, new: str, detail: str = "") -> None:
        old, self._state = self._state, new
        if old != new:
            self._probe_taken = False  # every state change opens a fresh window
            self._probe_at = None
            _record_event(
                self.site, "breaker", f"{old}->{new}" + (f": {detail}" if detail else "")
            )
            _count(f"resilience.breaker.{self.site}.{new}")

    def _poll(self) -> None:
        if self._state == OPEN and self._opened_at is not None:
            if self.clock() - self._opened_at >= self.cooldown_s:
                self._transition(HALF_OPEN, f"cooldown {self.cooldown_s:.0f}s elapsed")

    @property
    def state(self) -> str:
        with _lock:
            self._poll()
            return self._state

    def allows(self) -> bool:
        """Whether a call may proceed: True in closed; in half-open True for
        exactly ONE caller per window (the trial probe — everyone else sees the
        circuit as open until the probe reports); False while open (the caller
        should use its cached negative result)."""
        with _lock:
            self._poll()
            if self._state == HALF_OPEN:
                if self._probe_taken and self._probe_at is not None and (
                    self.clock() - self._probe_at < self.cooldown_s
                ):
                    # a trial probe is already out: admitting more would
                    # thundering-herd the backend the breaker is protecting
                    self.short_circuits += 1
                    _count(f"resilience.breaker.{self.site}.short_circuit")
                    return False
                # first caller of this window (or the previous probe holder
                # vanished for a whole cooldown): this call IS the trial
                self._probe_taken = True
                self._probe_at = self.clock()
                return True
            if self._state == OPEN:
                self.short_circuits += 1
                _count(f"resilience.breaker.{self.site}.short_circuit")
                return False
            return True

    def abandon_probe(self) -> None:
        """Release a held half-open probe token WITHOUT a verdict: the trial
        call ended for a reason that says nothing about the backend (its
        request's deadline expired). The next caller becomes the trial."""
        with _lock:
            if self._state == HALF_OPEN:
                self._probe_taken = False
                self._probe_at = None

    def record_success(self) -> None:
        with _lock:
            self._poll()
            self._failures = 0
            if self._state != CLOSED:
                self._transition(CLOSED, "probe succeeded")

    def record_failure(self, detail: str = "") -> None:
        with _lock:
            self._poll()
            self._failures += 1
            if self._state == HALF_OPEN or self._failures >= self.failure_threshold:
                if self._state != OPEN:
                    self.opens += 1
                self._opened_at = self.clock()
                self._transition(OPEN, detail or f"{self._failures} consecutive failures")

    def snapshot(self) -> dict:
        with _lock:
            self._poll()
            return {
                "site": self.site,
                "state": self._state,
                "failures": self._failures,
                "failure_threshold": self.failure_threshold,
                "cooldown_s": self.cooldown_s,
                "opens": self.opens,
                "short_circuits": self.short_circuits,
                "half_open_probe_out": self._probe_taken and self._state == HALF_OPEN,
            }


_breakers: Dict[str, CircuitBreaker] = {}

# One process may hold TWO instances of this module: the package import and the
# standalone file-path load the driver entry points use before touching JAX
# (``_diag_bootstrap.load_resilience`` registers its instance as
# ``_heat_tpu_resilience``). Breaker state — relay health! — must not split
# across them, so whichever instance loads second adopts the first one's
# registry OBJECT: ``devices.relay_breaker()`` then sees the failures the
# driver probes recorded, and vice versa.
import sys as _sys  # noqa: E402 - deliberate late import for the adoption probe

for _name in ("_heat_tpu_resilience", "heat_tpu.core.resilience"):
    _other = _sys.modules.get(_name)
    _shared = getattr(_other, "_breakers", None)
    if _shared is not None and _shared is not _breakers:
        _breakers = _shared
        break
del _sys


def breaker(site: str, **kwargs) -> CircuitBreaker:
    """The process-wide breaker for ``site``, created on first use. ``kwargs``
    (``failure_threshold`` / ``cooldown_s`` / ``clock``) apply only at creation;
    later callers share whatever the first caller configured."""
    with _lock:
        br = _breakers.get(site)
        if br is None:
            br = _breakers[site] = CircuitBreaker(site, **kwargs)
        return br


def breakers() -> Dict[str, dict]:
    """Snapshot of every registered breaker, keyed by site."""
    with _lock:
        return {site: br.snapshot() for site, br in _breakers.items()}


# The one breaker every backend/relay probe shares (bench.py, __graft_entry__,
# devices.py caps probe). Its config lives HERE — the registry applies kwargs
# only at first creation, so scattering the numbers across call sites would
# silently resolve to whichever probe ran first.
RELAY_SITE = "backend.relay"
_RELAY_FAILURE_THRESHOLD = 2
_RELAY_COOLDOWN_S = 300.0


def relay_breaker() -> CircuitBreaker:
    """The process-wide ``backend.relay`` breaker: two consecutive probe
    failures open it, a 5 min cooldown half-opens it for a real re-probe."""
    return breaker(
        RELAY_SITE,
        failure_threshold=_RELAY_FAILURE_THRESHOLD,
        cooldown_s=_RELAY_COOLDOWN_S,
    )


# ------------------------------------------------------------------ fault injection
class _FaultEntry:
    __slots__ = ("site", "kind", "on_call", "count", "fraction", "message",
                 "rank")

    def __init__(self, site, kind, on_call, count, fraction, message,
                 rank=None):
        self.site = site
        self.kind = kind
        self.on_call = on_call
        self.count = count
        self.fraction = fraction
        self.message = message
        self.rank = rank

    def as_dict(self) -> dict:
        return {
            "site": self.site, "kind": self.kind, "on_call": self.on_call,
            "count": self.count, "fraction": self.fraction,
            **({"message": self.message} if self.message else {}),
            **({"rank": self.rank} if self.rank is not None else {}),
        }


_plan: Dict[str, List[_FaultEntry]] = {}
_site_calls: Dict[str, int] = {}
_fired: int = 0


def _parse_plan(spec: Union[str, Sequence[dict]]) -> Dict[str, List[_FaultEntry]]:
    if isinstance(spec, str):
        try:
            spec = json.loads(spec)
        except ValueError as exc:
            raise ValueError(f"HEAT_TPU_FAULT_PLAN is not valid JSON: {exc}") from exc
    if not isinstance(spec, (list, tuple)):
        raise ValueError(f"fault plan must be a JSON list of entries, got {type(spec)}")
    plan: Dict[str, List[_FaultEntry]] = {}
    for i, raw in enumerate(spec):
        if not isinstance(raw, dict):
            raise ValueError(f"fault-plan entry {i} must be an object, got {type(raw)}")
        unknown = set(raw) - {
            "site", "kind", "on_call", "count", "fraction", "message", "rank",
        }
        if unknown:
            raise ValueError(f"fault-plan entry {i} has unknown keys {sorted(unknown)}")
        site = raw.get("site")
        if not isinstance(site, str) or not site:
            raise ValueError(f"fault-plan entry {i} needs a non-empty 'site'")
        kind = raw.get("kind", "raise")
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"fault-plan entry {i}: kind {kind!r} not in {FAULT_KINDS}"
            )
        on_call = int(raw.get("on_call", 1))
        count = int(raw.get("count", 1))
        if on_call < 1 or count < 1:
            raise ValueError(f"fault-plan entry {i}: on_call/count must be >= 1")
        fraction = float(raw.get("fraction", 0.5))
        if not 0.0 <= fraction < 1.0:
            raise ValueError(f"fault-plan entry {i}: fraction must be in [0, 1)")
        rank = raw.get("rank")
        if rank is not None and (not isinstance(rank, int) or rank < 0):
            raise ValueError(
                f"fault-plan entry {i}: rank must be a process index >= 0"
            )
        plan.setdefault(site, []).append(
            _FaultEntry(site, kind, on_call, count, fraction,
                        raw.get("message", ""), rank)
        )
    return plan


def arm_fault_plan(plan: Union[None, str, Sequence[dict]] = None) -> None:
    """Load a deterministic fault plan (a JSON string, a list of entry dicts, or
    ``None`` to read ``HEAT_TPU_FAULT_PLAN``) and reset every site's call
    counter, so the same plan replays the same failure sequence."""
    global _armed
    if plan is None:
        plan = os.environ.get("HEAT_TPU_FAULT_PLAN", "")
        if not plan:
            raise ValueError("no plan given and HEAT_TPU_FAULT_PLAN is unset")
    parsed = _parse_plan(plan)
    with _lock:
        _plan.clear()
        _plan.update(parsed)
        _site_calls.clear()
        _armed = bool(_plan)
        _refresh_active_locked()
    _record_event(
        "plan", "armed",
        f"{sum(len(v) for v in parsed.values())} entries at {sorted(parsed)}",
    )


def disarm_fault_plan() -> None:
    """Drop the fault plan and its call counters; sites go back to zero-cost."""
    global _armed
    with _lock:
        _plan.clear()
        _site_calls.clear()
        _armed = False
        _refresh_active_locked()


def fault_plan() -> List[dict]:
    """The armed plan as plain dicts (empty when disarmed) — introspection."""
    with _lock:
        return [e.as_dict() for entries in _plan.values() for e in entries]


def fault_signal(site: str) -> Optional[_FaultEntry]:
    """Count one call at ``site`` and return the plan entry firing on it, if
    any. The non-raising form for sites that handle kinds specially (probe
    sites map ``backend-down`` to a recorded DOWN result; :func:`atomic_write`
    maps ``torn-write`` to a truncated payload). Most sites use
    :func:`maybe_fault` instead. Entries carrying a ``rank`` fire only on the
    process whose :func:`set_fault_rank` identity matches — one env-armed
    plan can target one rank of a multi-process chaos job."""
    if not _armed:
        return None
    global _fired
    with _lock:
        n = _site_calls.get(site, 0) + 1
        _site_calls[site] = n
        for entry in _plan.get(site, ()):
            if entry.rank is not None and entry.rank != _fault_rank:
                continue
            if entry.on_call <= n < entry.on_call + entry.count:
                _fired += 1
                _record_event(site, "fault", f"{entry.kind} fired on call {n}")
                _count(f"resilience.fault.{site}")
                return entry
    return None


def maybe_fault(site: str) -> None:
    """Raise the planned fault for this call at ``site``, if one fires."""
    entry = fault_signal(site)
    if entry is not None:
        raise_entry(entry, site)


def raise_entry(entry: _FaultEntry, site: str) -> None:
    """Raise the exception form of a fired plan entry (``peer-dead`` does not
    return at all: the process exits)."""
    msg = entry.message or f"injected {entry.kind} at {site!r}"
    if entry.kind == "timeout":
        raise InjectedTimeout(msg)
    if entry.kind == "backend-down":
        raise InjectedBackendDown(msg)
    if entry.kind == "deadline-exceeded":
        raise InjectedDeadlineExceeded(msg)
    if entry.kind == "peer-dead":
        _die_as_peer(site, msg)
    raise FaultInjected(msg)


# ------------------------------------------------------- peer-dead injection
#: this process's rank for fault-plan ``rank`` targeting (stamped by the
#: communication bootstrap; None = entries without a rank match everything)
_fault_rank: Optional[int] = None

#: hook the supervision plane registers so a peer-dead firing stops this
#: process's heartbeats BEFORE exiting (the realistic crash shape: silence,
#: then absence); tests may stub it
_peer_dead_hook: Optional[Callable[[], None]] = None

#: the exit primitive — ``os._exit`` so no atexit handler (the clean-departure
#: marker above all) can soften the simulated crash; tests monkeypatch this
#: to observe the firing without dying
_peer_dead_exit: Callable[[int], None] = os._exit

#: exit status of a peer-dead firing, distinguishable in launcher logs
PEER_DEAD_EXIT_STATUS = 43


def set_fault_rank(rank: Optional[int]) -> None:
    """Stamp this process's rank for ``rank``-targeted fault-plan entries
    (the communication bootstrap calls this with ``jax.process_index()``)."""
    global _fault_rank
    with _lock:
        _fault_rank = None if rank is None else int(rank)


def _die_as_peer(site: str, msg: str) -> None:
    """The ``peer-dead`` fault kind: this rank stops heartbeating and exits
    abruptly — the deterministic stand-in for SIGKILL, so supervision paths
    are testable single-host and in chaos CI without real process murder.
    Does not return; when a test stubs ``_peer_dead_exit``, the firing
    surfaces as :class:`FaultInjected` instead of silently continuing."""
    _record_event(site, "peer-dead", msg)
    hook = _peer_dead_hook
    if hook is not None:
        hook()
    _peer_dead_exit(PEER_DEAD_EXIT_STATUS)
    raise FaultInjected(msg)


def reset(clear_breakers: bool = False) -> None:
    """Zero the site call counters (the plan itself stays armed) and, with
    ``clear_breakers=True``, drop every registered breaker — test isolation."""
    global _fired
    with _lock:
        _site_calls.clear()
        _fired = 0
        if clear_breakers:
            _breakers.clear()


# ------------------------------------------------------------------ atomic writes
_tmp_seq = 0


def fsync_dir(path: str) -> None:
    """fsync a directory so a rename inside it is durable (best-effort on
    filesystems that reject directory fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, writer: Callable[[str], Any], *, site: str = "io.write",
                 policy: Optional[Policy] = None):
    """Atomically produce ``path``: ``writer(tmp_path)`` writes the payload to a
    temp file in the same directory, which is fsynced and ``os.replace``d onto
    ``path`` (readers see the old file or the complete new one, never a torn
    middle). Returns ``writer``'s return value.

    Policy-driven retry: each attempt gets a fresh temp file; the ``site``
    policy (default: the module default) decides attempts/backoff. Fault
    injection: ``raise``/``timeout`` entries abort the attempt (and are
    retried); a ``torn-write`` entry truncates the temp payload to its
    ``fraction`` *before* the rename — the committed file is silently short,
    which is exactly what manifest-side partial-write detection must catch.
    """
    pol = policy or get_policy(site)
    directory = os.path.dirname(os.path.abspath(path)) or "."

    def attempt():
        global _tmp_seq
        torn: Optional[float] = None
        entry = fault_signal(site)
        if entry is not None:
            if entry.kind == "torn-write":
                torn = entry.fraction
            else:
                raise_entry(entry, site)
        with _lock:
            _tmp_seq += 1
            seq = _tmp_seq
        tmp = f"{path}.tmp.{os.getpid()}.{seq}"
        try:
            result = writer(tmp)
            if torn is not None:
                size = os.path.getsize(tmp)
                with open(tmp, "r+b") as fh:
                    fh.truncate(int(size * torn))
            fd = os.open(tmp, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
            os.replace(tmp, path)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        fsync_dir(directory)
        return result

    return pol.run(site, attempt)


# ------------------------------------------------------------- supervision
def run_supervised(step_fn, manager, policy=None, **kwargs):
    """Run a training loop under the supervision plane with elastic restart
    from checkpoint — the recovery half of the typed failure vocabulary
    above. Delegates to :func:`heat_tpu.core.supervision.run_supervised`
    (see there for the full contract): on :class:`PeerFailed` /
    :class:`CollectiveTimeout` / :class:`CoordinationTimeout` the harness
    drains the scheduler, re-initializes the distributed runtime at the
    surviving world size, restores the latest ``CheckpointManager`` step via
    reshard-on-restore, and resumes under ``policy``'s restart budget."""
    from . import supervision

    return supervision.run_supervised(step_fn, manager, policy, **kwargs)


# ------------------------------------------------------------------ reporting
def resilience_stats() -> dict:
    """The resilience section of ``ht.diagnostics.report()``: armed plan, site
    call counts, fault firings, registered policies and breaker snapshots."""
    with _lock:
        return {
            "armed": _armed,
            "plan": [e.as_dict() for entries in _plan.values() for e in entries],
            "site_calls": dict(_site_calls),
            "faults_fired": _fired,
            "policies": sorted(_site_policies),
            "breakers": {site: br.snapshot() for site, br in _breakers.items()},
        }


if diagnostics is not None:
    diagnostics.register_provider("resilience", resilience_stats)
    # diagnostics cannot import this module (cycle), so the atomic-dump
    # primitive is installed into it here: diagnostics.dump commits whole
    # artifacts from now on instead of risking a torn JSON mid-crash
    diagnostics._atomic_writer = atomic_write

# Env bootstrap: a plan armed by the environment applies to the whole process
# (the CI chaos job's canned plans); a malformed plan fails LOUDLY here rather
# than silently running the chaos suite fault-free.
if os.environ.get("HEAT_TPU_FAULT_PLAN"):
    arm_fault_plan(os.environ["HEAT_TPU_FAULT_PLAN"])
