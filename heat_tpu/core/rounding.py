"""Rounding operations (reference heat/core/rounding.py, 11 exports)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from . import _operations, types
from .dndarray import DNDarray

__all__ = ["abs", "absolute", "ceil", "clip", "fabs", "floor", "modf", "round", "sgn", "sign", "trunc"]


def abs(x, out=None, dtype=None) -> DNDarray:  # noqa: A001
    """Element-wise absolute value (reference ``rounding.py`` abs)."""
    if dtype is not None and not issubclass(types.canonical_heat_type(dtype), types.number):
        raise TypeError("dtype must be a heat data type")
    res = _operations.local_op(jnp.abs, x, out)
    if dtype is not None:
        res = res.astype(dtype, copy=False)
    return res


absolute = abs


def ceil(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.ceil, x, out)


def clip(x: DNDarray, min=None, max=None, out=None) -> DNDarray:
    """Clip values to [min, max] (reference ``rounding.py`` clip)."""
    if min is None and max is None:
        raise ValueError("either min or max must be set")
    lo = min.larray if isinstance(min, DNDarray) else min
    hi = max.larray if isinstance(max, DNDarray) else max
    return _operations.local_op(jnp.clip, x, out, min=lo, max=hi)


def fabs(x, out=None) -> DNDarray:
    """Float absolute value (reference ``rounding.py`` fabs)."""
    return _operations.local_op(jnp.fabs, x, out)


def floor(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.floor, x, out)


# module-level (not per-call lambdas): the dispatch executor caches compiled
# programs by operation identity, and a fresh lambda per call would never hit
def _modf_frac(v):
    return jnp.modf(v)[0]


def _modf_int(v):
    return jnp.modf(v)[1]


def _sign_real(v):
    return jnp.sign(v.real).astype(v.dtype)


def modf(x: DNDarray, out=None):
    """Fractional and integral parts (reference ``rounding.py`` modf)."""
    frac = _operations.local_op(_modf_frac, x, out[0] if out else None)
    intg = _operations.local_op(_modf_int, x, out[1] if out else None)
    return frac, intg


def round(x: DNDarray, decimals: int = 0, out=None, dtype=None) -> DNDarray:  # noqa: A001
    res = _operations.local_op(jnp.round, x, out, decimals=decimals)
    if dtype is not None:
        res = res.astype(dtype, copy=False)
    return res


def sgn(x, out=None) -> DNDarray:
    """Sign (complex: x/|x|) (reference ``rounding.py`` sgn)."""
    return _operations.local_op(jnp.sign, x, out)


def sign(x, out=None) -> DNDarray:
    """Sign; complex inputs use sign of the real part (reference ``rounding.py`` sign)."""
    if isinstance(x, DNDarray) and types.heat_type_is_complexfloating(x.dtype):
        return _operations.local_op(_sign_real, x, out)
    return _operations.local_op(jnp.sign, x, out)


def trunc(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.trunc, x, out)
