"""Input validation and distribution matching (reference heat/core/sanitation.py:32-361)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

import jax.numpy as jnp

from . import types
from .communication import MeshCommunication
from .dndarray import DNDarray

__all__ = [
    "sanitize_sequence",
    "sanitize_donation",
    "sanitize_leaf_donation",
    "sanitize_in",
    "sanitize_infinity",
    "sanitize_in_tensor",
    "sanitize_lshape",
    "sanitize_out",
    "sanitize_distribution",
    "scalar_to_1d",
]


def sanitize_in(x: object) -> None:
    """Verify ``x`` is a DNDarray (reference ``sanitation.py:159``)."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"input must be a DNDarray, is {type(x)}")


def sanitize_in_tensor(x: object) -> None:
    """Verify ``x`` is a jax.Array (reference checks torch.Tensor, ``sanitation.py:186``)."""
    import jax

    if not isinstance(x, jax.Array):
        raise TypeError(f"input must be a jax.Array, is {type(x)}")


def sanitize_infinity(x: Union[DNDarray, "jnp.ndarray"]) -> Union[int, float]:
    """Largest representable value for ``x``'s dtype (reference ``sanitation.py:140``)."""
    dtype = x.dtype.jax_type() if isinstance(x, DNDarray) else x.dtype
    if jnp.issubdtype(dtype, jnp.integer):
        return int(jnp.iinfo(dtype).max)
    return float(jnp.finfo(dtype).max)


def sanitize_lshape(array: DNDarray, tensor) -> None:
    """Verify a local tensor is a valid local shard of ``array`` (reference ``:213``)."""
    tshape = tuple(tensor.shape)
    if tshape == tuple(array.lshape) or tshape == tuple(array.gshape):
        return
    raise ValueError(f"local tensor shape {tshape} does not match chunk shape {array.lshape}")


def sanitize_out(
    out: object,
    output_shape: Sequence[int],
    output_split: Optional[int],
    output_device,
    output_comm=None,
) -> None:
    """Verify ``out`` buffer metadata (reference ``sanitation.py:255``)."""
    sanitize_in(out)
    if tuple(out.shape) != tuple(output_shape):
        raise ValueError(f"Expecting output buffer of shape {tuple(output_shape)}, got {tuple(out.shape)}")
    if out.split != output_split:
        # match the reference behaviour: resplit the out buffer to the required split
        out.resplit_(output_split)


def sanitize_donation(out: DNDarray, operand_arrays: Sequence) -> bool:
    """Whether ``out``'s physical buffer may be **donated** to a jitted ``out=``
    program (the dispatch executor's ``donate_argnums`` path).

    Donation invalidates the donated ``jax.Array`` object, so it is only safe
    when no *other* live consumer can still read it. The contract:

    - the buffer must not also be a program operand (``ht.add(a, b, out=a)``
      reads ``a`` — aliasing the read with the write is not guaranteed safe);
    - no references beyond the ``out`` array itself and this call chain may
      exist (``sys.getrefcount`` guard — a user holding ``buf = x.parray``,
      or a ``memory.copy`` sibling sharing the buffer object, keeps the buffer
      alive and undonatable for exactly as long as that holder exists).
      Callers must therefore check *before* putting the buffer into their own
      argument list.

    When this returns False the program still runs, just without the
    input/output aliasing — correctness never depends on donation.
    """
    import sys

    buf = out.parray
    if any(buf is arr for arr in operand_arrays):
        return False
    # expected references: the DNDarray's private attribute, the ``buf`` local,
    # and the getrefcount argument itself. Anything beyond that is an external
    # holder we must not invalidate.
    return sys.getrefcount(buf) <= 3


def _call_ref_overhead() -> int:
    """How many references one Python-level call layer adds to an argument —
    CPython 3.10 keeps both the caller's stack slot and the callee's frame
    local alive during the call (+2); 3.11+ consumes the stack slot into the
    frame (+1). Measured once at import so the leaf-donation refcount contract
    is exact on either convention."""
    import sys

    probe = object()

    def _measure(x):
        return sys.getrefcount(x)

    return _measure(probe) - sys.getrefcount(probe)


_LEAF_CALL_OVERHEAD = _call_ref_overhead()


def sanitize_leaf_donation(buf, plan_refs: int) -> bool:
    """Whether a fused-graph *leaf* buffer may be donated to the deferred
    executor's program (``donate_argnums`` on the leaf's argument position).

    The fused-graph form of :func:`sanitize_donation`'s contract: donation
    invalidates the buffer, so it is only safe when the forcing program is the
    buffer's last reader. ``plan_refs`` is the number of *persistent*
    references the caller accounts for — the plan's own operand-tuple slots
    plus the caller's bookkeeping containers (the executor passes its leaf
    list). On top of those, ``getrefcount``'s own argument and this call's
    argument-passing references (:func:`_call_ref_overhead`, measured at
    import) are expected; anything beyond is an external holder — a live
    ``DNDarray`` payload, a user-held ``x.parray``, or a deferred graph
    outside the forcing plan — and refuses donation.

    When this returns False the program still runs without the aliasing —
    correctness never depends on donation.
    """
    import sys

    return sys.getrefcount(buf) <= plan_refs + 1 + _LEAF_CALL_OVERHEAD


def sanitize_distribution(
    *args: DNDarray, target: DNDarray, diff_map: Optional[DNDarray] = None
) -> Union[DNDarray, List[DNDarray]]:
    """Distribute ``args`` like ``target`` (reference ``sanitation.py:32``).

    On TPU this is a pure resplit: canonical chunks mean two arrays with the same split
    are automatically aligned, so matching distribution = matching split axis.
    """
    out = []
    tsplit = target.split
    for arg in args:
        sanitize_in(arg)
        if arg.split == tsplit:
            out.append(arg)
        else:
            out.append(arg.resplit(tsplit))
    return out[0] if len(out) == 1 else out


def scalar_to_1d(x: DNDarray) -> DNDarray:
    """Turn a scalar DNDarray into a 1-element 1-D DNDarray (reference ``sanitation.py:339``)."""
    if x.ndim == 1 and x.size == 1:
        return x
    return DNDarray(
        x.larray.reshape(1),
        (1,),
        x.dtype,
        None,
        x.device,
        x.comm,
        True,
    )


def sanitize_sequence(seq):
    """Check that ``seq`` is a valid sequence and return it as a list
    (reference ``sanitation.py:314``)."""
    if isinstance(seq, list):
        return seq
    if isinstance(seq, tuple):
        return list(seq)
    from .dndarray import DNDarray

    if isinstance(seq, DNDarray):
        return seq.tolist()
    raise TypeError(f"seq must be a list, tuple or DNDarray, got {type(seq)}")
