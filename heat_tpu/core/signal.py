"""Signal processing (reference heat/core/signal.py, 211 LoC).

The reference's distributed 1-D ``convolve`` pads, computes a halo size from the kernel's
local shape, exchanges halos with neighbouring ranks (``signal.py:107-120``, via
``DNDarray.get_halo``), and runs a local ``torch.conv1d`` per rank. The TPU form of that
halo pipeline is :func:`_convolve_overlap_add`: every shard convolves its chunk locally
and the (kernel-1)-wide boundary tail rides one ``ppermute`` hop to the next shard on
the ICI ring — overlap-add, the collective-permute dual of the reference's halo
exchange. Replicated or feature-split inputs fall back to one global ``jnp.convolve``.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from . import types
from .dndarray import DNDarray

__all__ = ["convolve"]


def _convolve_overlap_add(comm, av: jax.Array, vv: jax.Array, n: int, m: int) -> jax.Array:
    """Distributed full convolution by overlap-add under ``shard_map``.

    Shard ``i`` holds ``c = n_pad/P`` samples and computes a local full convolution
    (length ``c+m-1``). The trailing ``m-1`` values overlap shard ``i+1``'s head: they
    are sent one hop down the ring (reference halo Isend/Irecv, ``dndarray.py:387-455``)
    and added. The global result is the shards' bodies back-to-back plus the last
    shard's tail — total length ``n+m-1`` after unpadding.
    """
    axis = comm.axis_name
    nproc = comm.size
    c = -(-n // nproc)
    n_pad = c * nproc
    if n_pad != n:
        av = jnp.pad(av, (0, n_pad - n))
    av = comm.shard(av, 0)

    def body(al, vl):
        y = jnp.convolve(al.reshape(-1), vl.reshape(-1), mode="full")  # c+m-1
        tail = y[c:]  # my halo into the next shard's head
        recv = comm.ppermute(
            tail, [(i, i + 1) for i in range(nproc - 1)], axis_name=axis
        )
        out = y[:c].at[: m - 1].add(recv)
        return out, tail

    out, tails = jax.shard_map(
        body,
        mesh=comm.mesh,
        in_specs=(PartitionSpec(axis), PartitionSpec()),
        out_specs=(PartitionSpec(axis), PartitionSpec(axis)),
    )(av, vv)
    # bodies cover [0, n_pad); the final m-1 values come from the last shard's tail
    return jnp.concatenate([out, tails[-(m - 1) :]])[: n + m - 1]


def convolve(a, v, mode: str = "full") -> DNDarray:
    """Discrete linear convolution of two 1-D arrays (reference ``signal.py:16``)."""
    from . import factories

    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if not isinstance(v, DNDarray):
        v = factories.array(v, comm=a.comm)
    if a.ndim != 1 or v.ndim != 1:
        raise ValueError("convolve requires 1-D inputs")
    if mode not in ("full", "same", "valid"):
        raise ValueError(f"unsupported mode {mode!r}")
    if mode == "same" and v.gshape[0] % 2 == 0:
        raise ValueError("mode 'same' is not supported for even-sized filter weights")
    if a.gshape[0] < v.gshape[0]:
        a, v = v, a
    dt = types.promote_types(a.dtype, v.dtype)
    av = a.larray.astype(dt.jax_type())
    vv = v.larray.astype(dt.jax_type())
    n, m = a.gshape[0], v.gshape[0]
    if a.split == 0 and a.is_distributed() and m >= 2 and m - 1 <= -(-n // a.comm.size):
        # distributed signal: explicit halo/overlap-add schedule on the ring
        full = _convolve_overlap_add(a.comm, av, vv, n, m)
    else:
        full = jnp.convolve(av, vv, mode="full")
    if mode == "full":
        result = full
    elif mode == "same":
        off = (m - 1) // 2
        result = full[off : off + n]
    else:  # valid
        result = full[m - 1 : n]
    split = a.split
    out = a.comm.shard(result, split)
    return DNDarray(
        out, tuple(result.shape), types.canonical_heat_type(result.dtype), split,
        a.device, a.comm, True,
    )
