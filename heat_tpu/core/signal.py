"""Signal processing (reference heat/core/signal.py, 211 LoC).

The reference's distributed 1-D ``convolve`` pads, computes a halo size from the kernel's
local shape, exchanges halos with neighbouring ranks (``signal.py:107-120``, via
``DNDarray.get_halo``), and runs a local ``torch.conv1d`` per rank. On TPU the signal is
one global sharded array: a single ``jnp.convolve`` computes the same thing and XLA emits
the boundary collective-permutes the halo exchange hand-wrote.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from . import types
from .dndarray import DNDarray

__all__ = ["convolve"]


def convolve(a, v, mode: str = "full") -> DNDarray:
    """Discrete linear convolution of two 1-D arrays (reference ``signal.py:16``)."""
    from . import factories

    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if not isinstance(v, DNDarray):
        v = factories.array(v, comm=a.comm)
    if a.ndim != 1 or v.ndim != 1:
        raise ValueError("convolve requires 1-D inputs")
    if mode not in ("full", "same", "valid"):
        raise ValueError(f"unsupported mode {mode!r}")
    if mode == "same" and v.gshape[0] % 2 == 0:
        raise ValueError("mode 'same' is not supported for even-sized filter weights")
    if a.gshape[0] < v.gshape[0]:
        a, v = v, a
    dt = types.promote_types(a.dtype, v.dtype)
    result = jnp.convolve(
        a.larray.astype(dt.jax_type()), v.larray.astype(dt.jax_type()), mode=mode
    )
    split = a.split
    out = a.comm.shard(result, split)
    return DNDarray(
        out, tuple(result.shape), types.canonical_heat_type(result.dtype), split,
        a.device, a.comm, True,
    )
