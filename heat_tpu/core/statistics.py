"""Statistical operations (reference heat/core/statistics.py, 1993 LoC).

The reference's distributed statistics lean on custom MPI reduction ops — ``MPI_ARGMAX``/
``MPI_ARGMIN`` carry (value, index) payloads through an Allreduce
(``statistics.py:1370,1405``), and ``mean``/``var`` merge per-rank moments with a
numerically-stable pairwise update (``statistics.py:893,1850``). On TPU the global value
is a single sharded ``jax.Array``: one jnp reduction computes the same result and XLA
emits the cross-shard all-reduce, so the entire custom-op machinery disappears. Only the
split bookkeeping (which output dim still carries the mesh axis) survives, shared with
:mod:`._operations`.
"""

from __future__ import annotations

from builtins import max as builtins_max
from typing import Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import _operations, sanitation, types
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis

__all__ = [
    "argmax",
    "argmin",
    "average",
    "bincount",
    "bucketize",
    "cov",
    "digitize",
    "histc",
    "histogram",
    "kurtosis",
    "max",
    "maximum",
    "mean",
    "median",
    "min",
    "minimum",
    "percentile",
    "skew",
    "std",
    "var",
]


_wrap = _operations.wrap_result
_handle_out = _operations.handle_out


def _arg_reduce(op, x: DNDarray, axis, out, keepdims: bool) -> DNDarray:
    """Shared argmax/argmin logic (reference custom MPI ops ``statistics.py:1370-1405``)."""
    sanitation.sanitize_in(x)
    if axis is None:
        result = op(x.larray.reshape(-1)).astype(jnp.int64)
        if keepdims:
            result = result.reshape((1,) * x.ndim)
        out_split = None
    else:
        axis = sanitize_axis(x.gshape, axis)
        result = op(x.larray, axis=axis).astype(jnp.int64)
        if keepdims:
            result = jnp.expand_dims(result, axis)
        out_split = _operations._out_split_reduce(x, axis, keepdims)
    return _handle_out(_wrap(result, x, out_split), out, x)


def argmax(x: DNDarray, axis: Optional[int] = None, out: Optional[DNDarray] = None, keepdims: bool = False) -> DNDarray:
    """Indices of maximum values (reference ``statistics.py:40``)."""
    return _arg_reduce(jnp.argmax, x, axis, out, keepdims)


def argmin(x: DNDarray, axis: Optional[int] = None, out: Optional[DNDarray] = None, keepdims: bool = False) -> DNDarray:
    """Indices of minimum values (reference ``statistics.py:109``)."""
    return _arg_reduce(jnp.argmin, x, axis, out, keepdims)


def average(
    x: DNDarray,
    axis: Optional[Union[int, Tuple[int, ...]]] = None,
    weights: Optional[DNDarray] = None,
    returned: bool = False,
):
    """Weighted average (reference ``statistics.py:178``)."""
    sanitation.sanitize_in(x)
    if weights is None:
        result = mean(x, axis)
        if returned:
            n = x.size if axis is None else np.prod(
                [x.gshape[a] for a in (axis if isinstance(axis, tuple) else (axis,))]
            )
            wsum = _wrap(jnp.full(result.gshape, float(n), result.larray.dtype), result, result.split)
            return result, wsum
        return result
    w = weights.larray if isinstance(weights, DNDarray) else jnp.asarray(weights)
    axis_s = sanitize_axis(x.gshape, axis) if axis is not None else None
    if tuple(w.shape) != tuple(x.gshape):
        if axis_s is None:
            raise TypeError("Axis must be specified when shapes of x and weights differ.")
        if isinstance(axis_s, tuple):
            raise TypeError("1D weights expect an integer axis.")
        if w.ndim != 1:
            raise TypeError("1D weights expected when shapes of x and weights differ.")
        if w.shape[0] != x.gshape[axis_s]:
            raise ValueError("Length of weights not compatible with specified axis.")
        shape = [1] * x.ndim
        shape[axis_s] = w.shape[0]
        wb = w.reshape(shape)
    else:
        wb = w
    num = jnp.sum(x.larray * wb, axis=axis_s)
    den = jnp.sum(jnp.broadcast_to(wb, x.gshape), axis=axis_s)
    if bool(jnp.any(den == 0)):
        raise ZeroDivisionError("Weights sum to zero, can't be normalized")
    result = num / den
    out_split = _operations._out_split_reduce(x, axis_s, False) if axis_s is not None else None
    res = _wrap(result, x, out_split)
    if returned:
        return res, _wrap(jnp.broadcast_to(den, result.shape).astype(result.dtype), x, out_split)
    return res


def bincount(x: DNDarray, weights: Optional[DNDarray] = None, minlength: int = 0) -> DNDarray:
    """Count occurrences of each value in a non-negative int array
    (reference ``statistics.py:240``)."""
    sanitation.sanitize_in(x)
    w = weights.larray if isinstance(weights, DNDarray) else weights
    if x.size and bool(jnp.any(x.larray < 0)):
        raise ValueError("bincount: input array must have no negative elements")
    length = int(jnp.max(x.larray)) + 1 if x.size else 0
    length = builtins_max(length, int(minlength))
    result = jnp.bincount(x.larray.reshape(-1), weights=None if w is None else w.reshape(-1), length=length)
    return _wrap(result, x, None)



def bucketize(input: DNDarray, boundaries, out_int32: bool = False, right: bool = False, out=None) -> DNDarray:
    """Index of the bucket each element falls into (reference ``statistics.py:289``,
    torch.bucketize semantics: boundaries are sorted bucket edges)."""
    sanitation.sanitize_in(input)
    b = boundaries.larray if isinstance(boundaries, DNDarray) else jnp.asarray(boundaries)
    side = "left" if not right else "right"
    # torch.bucketize(right=False) counts boundaries < v as numpy side='left'... torch's
    # right=False means v <= boundary ⇒ numpy searchsorted side='left'
    result = jnp.searchsorted(b, input.larray.reshape(-1), side=side).reshape(input.gshape)
    result = result.astype(jnp.int32 if out_int32 else jnp.int64)
    return _handle_out(_wrap(result, input, input.split), out, input)


def cov(m: DNDarray, y: Optional[DNDarray] = None, rowvar: bool = True, bias: bool = False, ddof: Optional[int] = None) -> DNDarray:
    """Estimate the covariance matrix (reference ``statistics.py:346``)."""
    sanitation.sanitize_in(m)
    if m.ndim > 2:
        raise ValueError("m has more than 2 dimensions")
    x = m.larray
    if x.ndim == 1:
        x = x.reshape(1, -1)
    if not rowvar and x.shape[0] != 1:
        x = x.T
    if y is not None:
        yv = y.larray if isinstance(y, DNDarray) else jnp.asarray(y)
        if yv.ndim > 2:
            raise ValueError("y has more than 2 dimensions")
        if yv.ndim == 1:
            yv = yv.reshape(1, -1)
        if not rowvar and yv.shape[0] != 1:
            yv = yv.T
        x = jnp.concatenate([x, yv], axis=0)
    if ddof is None:
        ddof = 0 if bias else 1
    n = x.shape[1]
    xm = x - jnp.mean(x, axis=1, keepdims=True)
    fact = builtins_max(n - ddof, 0)
    # full input precision: covariance entries cancel for correlated variables
    result = jnp.matmul(xm, xm.conj().T, precision=jax.lax.Precision.HIGHEST) / fact
    if result.shape == (1, 1):  # numpy returns a 0-d value for a single variable
        result = result.reshape(())
    return _wrap(result, m, None)


def digitize(x: DNDarray, bins, right: bool = False) -> DNDarray:
    """Indices of the bins each value belongs to (reference ``statistics.py:408``,
    numpy.digitize semantics)."""
    sanitation.sanitize_in(x)
    b = bins.larray if isinstance(bins, DNDarray) else jnp.asarray(bins)
    result = jnp.digitize(x.larray, b, right=right)
    return _wrap(result, x, x.split)


def histc(input: DNDarray, bins: int = 100, min: float = 0.0, max: float = 0.0, out=None) -> DNDarray:
    """Histogram with equal-width bins (reference ``statistics.py:465``, torch.histc
    semantics: min==max ⇒ use data min/max; out-of-range elements ignored)."""
    sanitation.sanitize_in(input)
    lo, hi = float(min), float(max)
    data = input.larray.reshape(-1)
    if lo == hi:
        lo, hi = float(jnp.min(data)), float(jnp.max(data))
    hist, _ = jnp.histogram(data, bins=bins, range=(lo, hi))
    result = hist.astype(input.larray.dtype)
    return _handle_out(_wrap(result, input, None), out, input)


def histogram(a: DNDarray, bins: int = 10, range=None, normed=None, weights=None, density=None):
    """numpy-compatible histogram (reference ``statistics.py:522``)."""
    sanitation.sanitize_in(a)
    if normed is not None:
        raise NotImplementedError("'normed' is deprecated; use density instead")
    w = weights.larray.reshape(-1) if isinstance(weights, DNDarray) else weights
    hist, edges = jnp.histogram(a.larray.reshape(-1), bins=bins, range=range, weights=w, density=density)
    return _wrap(hist, a, None), _wrap(edges, a, None)


def kurtosis(x: DNDarray, axis: Optional[int] = None, unbiased: bool = True, Fischer: bool = True) -> DNDarray:
    """Kurtosis (fourth central moment; reference ``statistics.py:581``)."""
    sanitation.sanitize_in(x)
    if axis is not None and not isinstance(axis, (int, np.integer)):
        raise TypeError(f"axis must be None or an int, got {type(axis)}")
    axis_s = sanitize_axis(x.gshape, axis) if axis is not None else None
    v = x.larray.astype(jnp.promote_types(x.larray.dtype, jnp.float32))
    if axis_s is None:
        v = v.reshape(-1)
        axis_s = 0
        out_split = None
        n = v.shape[0]
    else:
        out_split = _operations._out_split_reduce(x, axis_s, False)
        n = x.gshape[axis_s]
    m = jnp.mean(v, axis=axis_s, keepdims=True)
    d = v - m
    m2 = jnp.mean(d**2, axis=axis_s)
    m4 = jnp.mean(d**4, axis=axis_s)
    g2 = m4 / (m2**2)
    if unbiased:
        k = ((n - 1) / ((n - 2) * (n - 3))) * ((n + 1) * g2 - 3 * (n - 1)) + 3
    else:
        k = g2
    if Fischer:
        k = k - 3
    return _wrap(k, x, out_split)


def max(x: DNDarray, axis=None, out=None, keepdims=None) -> DNDarray:  # noqa: A001
    """Maximum along axis (reference ``statistics.py:698``)."""
    return _operations.reduce_op(jnp.max, x, axis, out, bool(keepdims))


def maximum(x1, x2, out=None) -> DNDarray:
    """Elementwise maximum (reference ``statistics.py:762``)."""
    return _operations.binary_op(jnp.maximum, x1, x2, out)


def mean(x: DNDarray, axis=None, keepdims: bool = False) -> DNDarray:
    """Arithmetic mean (reference ``statistics.py:893``; the pairwise moment-merging
    Allreduce collapses into one global jnp.mean)."""
    return _operations.reduce_op(jnp.mean, x, axis, None, keepdims)


def median(x: DNDarray, axis: Optional[int] = None, keepdims: bool = False) -> DNDarray:
    """Median (reference ``statistics.py:1019``)."""
    return percentile(x, 50.0, axis=axis, keepdims=keepdims)


def min(x: DNDarray, axis=None, out=None, keepdims=None) -> DNDarray:  # noqa: A001
    """Minimum along axis (reference ``statistics.py:1129``)."""
    return _operations.reduce_op(jnp.min, x, axis, out, bool(keepdims))


def minimum(x1, x2, out=None) -> DNDarray:
    """Elementwise minimum (reference ``statistics.py:1192``)."""
    return _operations.binary_op(jnp.minimum, x1, x2, out)


def _percentile_from_sorted(sv, q_arr, axis, method, keepdims, n=None):
    """Percentiles from already-sorted values: gather the bracketing index planes and
    interpolate — O(q) gathered planes instead of materialising the sorted global.
    ``sv`` may be the padded physical form; ``n`` is the logical extent (pad slots sit
    past it and are never gathered)."""
    n = sv.shape[axis] if n is None else n
    qshape = q_arr.shape
    pos = q_arr.reshape(-1) / 100.0 * (n - 1)
    lo = jnp.clip(jnp.floor(pos), 0, n - 1).astype(jnp.int32)
    hi = jnp.clip(jnp.ceil(pos), 0, n - 1).astype(jnp.int32)
    if method == "lower":
        r = jnp.take(sv, lo, axis=axis)
    elif method == "higher":
        r = jnp.take(sv, hi, axis=axis)
    elif method == "nearest":
        # jnp.percentile's tie rule: the LOWER bracket wins at frac == 0.5 exactly
        # (jnp.rint's round-half-even gave layout-dependent answers — ADVICE r4)
        nearest = jnp.where(pos - lo <= 0.5, lo, hi)
        r = jnp.take(sv, jnp.clip(nearest, 0, n - 1).astype(jnp.int32), axis=axis)
    elif method == "midpoint":
        r = (jnp.take(sv, lo, axis=axis) + jnp.take(sv, hi, axis=axis)) / 2
    else:  # linear
        a = jnp.take(sv, lo, axis=axis)
        b = jnp.take(sv, hi, axis=axis)
        shape = [1] * a.ndim
        shape[axis] = pos.shape[0]
        frac = (pos - lo).astype(sv.dtype).reshape(shape)
        r = a + (b - a) * frac
    r = jnp.moveaxis(r, axis, 0)  # q dim to front, matching jnp.percentile layout
    rest = r.shape[1:]
    if keepdims:
        rest = rest[:axis] + (1,) + rest[axis:]
    return r.reshape(qshape + rest)


def percentile(
    x: DNDarray,
    q,
    axis: Optional[int] = None,
    out: Optional[DNDarray] = None,
    interpolation: str = "linear",
    keepdims: bool = False,
) -> DNDarray:
    """q-th percentile (reference ``statistics.py:1408``).

    Along a split reduction axis the order statistics come from the distributed
    merge-split sort (:mod:`heat_tpu.core.dist_sort`) followed by a gather of just the
    two bracketing index planes — O(n/P) memory per device, the property the
    reference's resplit+local-sort scheme provides. Other configurations are one
    global ``jnp.percentile``."""
    from . import dist_sort

    sanitation.sanitize_in(x)
    axis_s = sanitize_axis(x.gshape, axis) if axis is not None else None
    q_arr = jnp.asarray(q, dtype=jnp.float64)
    promoted = jnp.promote_types(x.parray.dtype, jnp.float32)
    # axis=None over a 1-D split array is the same reduction with axis=0
    eff_axis = 0 if (axis_s is None and x.ndim == 1) else axis_s
    use_dist = (
        eff_axis is not None
        and interpolation in ("linear", "lower", "higher", "nearest", "midpoint")
        and dist_sort.can_distribute_sort(x.comm, x.gshape, x.split, eff_axis, promoted)
    )
    if use_dist:
        # NaN inputs must yield NaN like jnp.percentile; the sorted-order-statistics
        # path would interpolate finite planes instead, so route those globally.
        # The reduction runs on the padded physical (pad slots are finite zeros).
        use_dist = not bool(jnp.isnan(x.parray).any())
    if use_dist:
        n_log = x.gshape[eff_axis]
        work = x.comm.shard(x.parray.astype(promoted), x.split)  # stays 1/P-local
        sv, _ = dist_sort.distributed_sort(
            x.comm, work, eff_axis, logical_n=n_log
        )
        result = _percentile_from_sorted(
            sv, q_arr, eff_axis, interpolation, keepdims, n=n_log
        )
        if axis_s is None:  # scalar-q + axis=None conventions already match (ndim-1 case)
            axis_s = eff_axis
    else:
        result = jnp.percentile(
            x.larray.astype(promoted),
            q_arr,
            axis=axis_s,
            method=interpolation,
            keepdims=keepdims,
        )
    out_split = _operations._out_split_reduce(x, axis_s, keepdims) if axis_s is not None else None
    if out_split is not None and np.ndim(q):  # leading q dim shifts the split
        out_split += np.ndim(q)
    return _handle_out(_wrap(result, x, out_split), out, x)


def skew(x: DNDarray, axis: Optional[int] = None, unbiased: bool = True) -> DNDarray:
    """Skewness (third central moment; reference ``statistics.py:1676``)."""
    sanitation.sanitize_in(x)
    if axis is not None and not isinstance(axis, (int, np.integer)):
        raise TypeError(f"axis must be None or an int, got {type(axis)}")
    axis_s = sanitize_axis(x.gshape, axis) if axis is not None else None
    v = x.larray.astype(jnp.promote_types(x.larray.dtype, jnp.float32))
    if axis_s is None:
        v = v.reshape(-1)
        axis_s = 0
        out_split = None
        n = v.shape[0]
    else:
        out_split = _operations._out_split_reduce(x, axis_s, False)
        n = x.gshape[axis_s]
    m = jnp.mean(v, axis=axis_s, keepdims=True)
    d = v - m
    m2 = jnp.mean(d**2, axis=axis_s)
    m3 = jnp.mean(d**3, axis=axis_s)
    g1 = m3 / (m2**1.5)
    if unbiased:
        g1 = g1 * ((n * (n - 1)) ** 0.5) / (n - 2)
    return _wrap(g1, x, out_split)


def std(x: DNDarray, axis=None, ddof: int = 0, **kwargs) -> DNDarray:
    """Standard deviation (reference ``statistics.py:1717``)."""
    return _operations.reduce_op(jnp.std, x, axis, None, kwargs.get("keepdims", False), ddof=ddof)


def var(x: DNDarray, axis=None, ddof: int = 0, **kwargs) -> DNDarray:
    """Variance (reference ``statistics.py:1850``; the Allreduce moment merge is one
    global jnp.var)."""
    return _operations.reduce_op(jnp.var, x, axis, None, kwargs.get("keepdims", False), ddof=ddof)
