"""Shape/axis sanitation helpers (reference heat/core/stride_tricks.py:12-257)."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import numpy as np

__all__ = ["broadcast_shape", "broadcast_shapes", "sanitize_axis", "sanitize_shape", "sanitize_slice"]


def broadcast_shapes(*shapes: Sequence[int]) -> Tuple[int, ...]:
    """NumPy broadcasting of several shapes (reference ``stride_tricks.py:70``)."""
    try:
        return tuple(np.broadcast_shapes(*[tuple(s) for s in shapes]))
    except ValueError as e:
        raise ValueError(f"operands could not be broadcast, input shapes {shapes}") from e


def broadcast_shape(shape_a: Sequence[int], shape_b: Sequence[int]) -> Tuple[int, ...]:
    """Broadcast two shapes (reference ``stride_tricks.py:12``)."""
    return broadcast_shapes(shape_a, shape_b)


def sanitize_axis(
    shape: Sequence[int], axis: Optional[Union[int, Sequence[int]]]
) -> Optional[Union[int, Tuple[int, ...]]]:
    """Normalise ``axis`` to non-negative value(s) within ``len(shape)``
    (reference ``stride_tricks.py:115``)."""
    ndim = len(shape)
    if axis is None:
        return None
    if isinstance(axis, (tuple, list)):
        axes = tuple(sanitize_axis(shape, a) for a in axis)
        if len(set(axes)) != len(axes):
            raise ValueError(f"repeated axis in {axis}")
        return axes
    if not isinstance(axis, (int, np.integer)):
        raise TypeError(f"axis must be None or int or tuple of ints, got {type(axis)}")
    axis = int(axis)
    original = axis
    if ndim == 0 and axis in (-1, 0):
        return 0
    if axis < 0:
        axis += ndim
    if axis < 0 or axis >= max(ndim, 1):
        raise ValueError(f"axis {original} out of bounds for {ndim}-dimensional array")
    return axis


def sanitize_shape(shape: Union[int, Sequence[int]], lval: int = 0) -> Tuple[int, ...]:
    """Normalise a shape argument to a tuple of non-negative ints ≥ ``lval``
    (reference ``stride_tricks.py:182``)."""
    if isinstance(shape, (int, np.integer)):
        shape = (int(shape),)
    shape = tuple(int(s) for s in shape)
    for s in shape:
        if s < lval:
            raise ValueError(f"negative dimensions are not allowed, got shape {shape}")
    return shape


def sanitize_slice(sl: slice, max_dim: int) -> slice:
    """Resolve a slice against a dimension size (reference ``stride_tricks.py:227``)."""
    if not isinstance(sl, slice):
        raise TypeError("can only sanitize slices")
    return slice(*sl.indices(max_dim))
