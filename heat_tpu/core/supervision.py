"""``ht.supervision`` — the distributed supervision plane: heartbeats, a
collective watchdog, coordinated typed abort, and elastic restart.

The runtime counterpart to the static SPMD checker (``ht.analysis``'s
collective-ordering rules, PR 12): static analysis prevents *divergent*
collective sequences, but a peer that dies or wedges mid-step still strands
every other rank inside a collective (or a coordination-channel wait)
forever — the multi-controller failure mode PR 12's commit message named as
the worst one: a hang, not a crash. This module turns ANY single-process
failure into a typed error on every survivor within a bounded budget, and —
together with checkpoint v2's reshard-on-restore — into automatic recovery:

- **Heartbeats + abort sentinel.** Each process publishes a monotonic
  heartbeat over the ``jax.distributed`` coordination KV channel (the same
  no-XLA transport as the telemetry clock handshake and the checkpoint
  agreement — works on every backend, CPU meshes included). A daemon monitor
  detects a peer whose beat has not advanced for ``HEAT_TPU_PEER_TIMEOUT_S``
  and posts a cluster-wide *abort sentinel*; every rank polls the sentinel at
  the ``MeshCommunication._guarded`` chokepoint, at the scheduler's
  pre-dispatch checkpoint, and inside every supervised coordination wait —
  raising typed :class:`~.resilience.PeerFailed` on ALL survivors, never a
  silent hang. A rank that exits cleanly publishes a departure marker first,
  so normal shutdown is not a failure.

- **Collective watchdog.** :func:`watch` arms a per-collective deadline
  around every ``_guarded`` invocation window when
  ``HEAT_TPU_COLLECTIVE_TIMEOUT_S`` is set (off by default — single-process
  meshes cannot hang in a collective). A window that overruns triggers a
  flight-recorder auto-dump (trigger kind ``supervision.watchdog``), posts
  the abort sentinel, and delivers typed
  :class:`~.resilience.CollectiveTimeout` — on the survivors at their next
  sentinel poll, and on the stuck rank itself the moment its call unblocks.
  What the watchdog can catch depends on the backend: on CPU meshes every
  cross-process wait rides the coordination channel (multiprocess XLA
  computations do not exist there), so every stuck window is also an
  abortable wait; on TPU meshes a rank stuck inside an XLA collective cannot
  be interrupted — the watchdog documents the hang (post-mortem + sentinel
  for the survivors) rather than pretending to cancel it
  (``doc/source/resilience.rst`` has the matrix).

- **Supervised coordination waits.** :func:`kv_wait` / :func:`kv_barrier`
  replace every raw ``blocking_key_value_get`` / ``wait_at_barrier`` in the
  framework (the ``coord-unbounded-wait`` analysis rule bans new ones): the
  wait is chunked so the sentinel is polled while blocked, bounded by the
  unified ``HEAT_TPU_COORD_TIMEOUT_MS`` budget, and exhaustion raises typed
  :class:`~.resilience.CoordinationTimeout` naming the key and the ranks
  still missing — instead of the raw backend error the two hardcoded
  timeouts used to surface.

- **Elastic restart.** :func:`run_supervised` (also exported as
  ``ht.resilience.run_supervised``) wraps a training loop: on
  ``PeerFailed`` / ``CollectiveTimeout`` / ``CoordinationTimeout`` it drains
  the dispatch scheduler (typed), tears down the distributed runtime,
  re-initializes at the surviving world size (the caller's ``reinit`` policy
  names the new coordinator), restores the latest ``CheckpointManager`` step
  through the reshard-on-restore path (a P=8 checkpoint restores onto P=7),
  and resumes — under a bounded restart budget (an ``ht.resilience.Policy``
  plus the ``supervision.restart`` circuit breaker).

Supervised runtime bootstrap
----------------------------
XLA's own coordination service is fail-*stop*: when a task dies, the service
propagates a fatal error and the distributed client TERMINATES the surviving
processes (``client.h:80``) — exactly the opaque behaviour this module
replaces with typed delivery. :func:`bootstrap_distributed` therefore builds
the service/client pair itself (installed into
``jax._src.distributed.global_state``, so everything else in jax sees a
normally-initialized runtime) with native failure detection effectively
disabled and ``shutdown_on_destruction`` off; supervision owns failure
detection at the KV layer. On a clean exit an atexit hook performs the
ordinary shutdown barrier, preserving the default synchronized-exit
semantics; after an abort the old runtime is *abandoned* instead
(:func:`teardown_distributed`): the dead generation's service object is kept
referenced forever (destroying it would cancel surviving clients' RPCs and
kill them), the client is destroyed (safe — it owns its own threads), and the
next generation boots on a fresh coordinator address.

Zero-cost contract (the diagnostics/profiler/resilience/telemetry
discipline): idle, the one hook on a hot path — the chokepoint check in
``MeshCommunication._guarded`` — is a single module-attribute read
(``supervision._armed``) and a branch not taken. Armed, the per-collective
cost is a relaxed bool read (:func:`poll`) plus, with the watchdog on, one
dict insert/remove. Nothing is ever injected into traced program bodies, so
compiled HLO is byte-identical armed or idle
(``tests/test_supervision.py::TestHLOByteParity``).

Thread-safety: registries — the watchdog window table, the monitor's
per-peer bookkeeping, the abort payload, the graveyard — mutate under the
one module ``_lock`` (a leaf; nothing holding it calls into another locking
module). ``_armed`` and ``_aborted`` are the relaxed hot-path switches, read
bare like ``diagnostics._enabled``; the abort payload they point at is
installed before the flag flips and never mutated after.

Env knobs (memoised; re-read by :func:`reload_env_knobs`, which
``_executor.reload_env_knobs()`` calls too):

- ``HEAT_TPU_SUPERVISION=0``          — disable the plane entirely (the
  supervised bootstrap, heartbeats, and chokepoint polls).
- ``HEAT_TPU_PEER_TIMEOUT_S``         — missed-beat budget before a peer is
  declared failed (default 60).
- ``HEAT_TPU_COLLECTIVE_TIMEOUT_S``   — per-collective watchdog deadline
  (default 0 = watchdog off).
- ``HEAT_TPU_COORD_TIMEOUT_MS``       — the unified coordination-channel
  wait budget (default 600000), replacing the hardcoded
  ``communication._HANDSHAKE_TIMEOUT_MS`` / ``checkpoint._COORD_TIMEOUT_MS``.

Stdlib-only at module load (like diagnostics/profiler/resilience/_scheduler/
telemetry): jax is imported lazily inside the functions that talk to the
coordination service, so the scheduler can import this module in its
standalone file-path mode and the analysis tooling stays jax-free.
"""

from __future__ import annotations

import atexit
import contextlib
import itertools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

try:  # standalone file-path load (driver entry points): degrade like siblings
    from . import diagnostics, resilience, telemetry
except ImportError:  # pragma: no cover - exercised via tests/test_analysis.py
    diagnostics = resilience = telemetry = None

__all__ = [
    "LocalCoordinator",
    "ClientCoordinator",
    "Monitor",
    "arm",
    "disarm",
    "armed",
    "auto_arm",
    "poll",
    "abort_error",
    "aborted",
    "post_abort",
    "current_monitor",
    "forget_peer",
    "watch",
    "kv_wait",
    "kv_barrier",
    "coord_timeout_ms",
    "peer_timeout_s",
    "collective_timeout_s",
    "enabled",
    "reload_env_knobs",
    "bootstrap_distributed",
    "teardown_distributed",
    "run_supervised",
    "supervision_stats",
]

# Hot-path gates, read bare by the MeshCommunication chokepoint and the
# scheduler loop: one attribute load + branch when idle — the zero-cost
# contract. ``_armed``: a monitor is running (or a test armed the plane).
# ``_aborted``: an abort sentinel was observed; the payload in ``_abort`` is
# installed BEFORE this flips and never mutated after, so relaxed readers can
# hand it to abort_error() without the lock.
_armed: bool = False
_aborted: bool = False

_lock = threading.RLock()

_abort: Optional[dict] = None
_monitor: Optional["Monitor"] = None
_thread: Optional[threading.Thread] = None
_thread_stop: Optional[threading.Event] = None
_generation: int = 0

# Late-bound collaborator hook (the diagnostics tee pattern): ``ht.ops``
# installs its beat publisher here at ITS import so every monitor tick also
# carries the rank's compact ops beat on the same KV channel — this module
# cannot import ops (that would be a cycle). Written once, read bare; the
# tee itself gates on ``ops._armed``, so the idle cost per tick is one
# foreign attribute load + branch.
_ops_tee: Optional[Callable[["Monitor"], None]] = None

# watchdog: token -> (site, start_monotonic, deadline_monotonic); tokens the
# scan flagged overdue move to _watch_fired so the stuck rank raises typed
# the moment its call unblocks
_watch_seq = itertools.count(1)
_watch_windows: Dict[int, Tuple[str, float, float]] = {}
_watch_fired: Dict[int, float] = {}

# the dead-generation graveyard (see the module header): service objects (and
# clients we could not safely destroy) from abandoned runtimes. Entries are
# IMMORTALIZED (an extra C-level reference via Py_IncRef) so their C++
# destructors never run — not in-flight NOR at interpreter shutdown: a
# service destructor cancels every connected client's outstanding
# coordination RPC, and a cancelled error-poll trips XLA's fail-stop
# termination (client.h:80) in whatever process still holds such a client
# (pre-failure arrays keep the old backend, and with it the old client,
# reachable — their lifetime cannot be bounded here). The OS reclaims the
# leak at process exit; one service + port per elastic restart is the
# documented cost of surviving a peer death.
_graveyard: List[Any] = []


def _immortalize(obj: Any) -> None:
    import ctypes

    ctypes.pythonapi.Py_IncRef(ctypes.py_object(obj))
    with _lock:
        _graveyard.append(obj)

# process identity as armed (mirrors telemetry's, but supervision must work
# when telemetry degraded): set by arm()
_rank: int = 0
_nprocs: int = 1

_restarts: int = 0  # elastic restarts performed by this process

# the supervised bootstrap remembers whether IT built the client (then an
# abandon-teardown may destroy it; a foreign client is only graveyarded)
_owns_client: bool = False
_atexit_registered: bool = False

_CHUNK_MS = 2000  # sentinel-poll cadence inside a supervised wait


# ----------------------------------------------------------------- env knobs
class _Knobs:
    __slots__ = ("enabled", "peer_timeout_s", "collective_timeout_s",
                 "coord_timeout_ms")

    def reload(self) -> None:
        def _num(name: str, default: float, lo: float) -> float:
            try:
                return max(lo, float(os.environ.get(name, "") or default))
            except ValueError:
                return default

        self.enabled = os.environ.get("HEAT_TPU_SUPERVISION", "1") != "0"
        self.peer_timeout_s = _num("HEAT_TPU_PEER_TIMEOUT_S", 60.0, 0.1)
        self.collective_timeout_s = _num("HEAT_TPU_COLLECTIVE_TIMEOUT_S", 0.0, 0.0)
        self.coord_timeout_ms = int(_num("HEAT_TPU_COORD_TIMEOUT_MS", 600_000, 1))


_knobs = _Knobs()
_knobs.reload()


def reload_env_knobs() -> None:
    """Re-read the memoised ``HEAT_TPU_SUPERVISION`` / ``PEER_TIMEOUT_S`` /
    ``COLLECTIVE_TIMEOUT_S`` / ``COORD_TIMEOUT_MS`` knobs from ``os.environ``
    (``_executor.reload_env_knobs()`` calls this too, so one re-read point
    covers the whole framework)."""
    _knobs.reload()


def enabled() -> bool:
    """Whether the supervision plane is enabled (``HEAT_TPU_SUPERVISION``,
    default on; memoised)."""
    return _knobs.enabled


def peer_timeout_s() -> float:
    """Missed-beat budget before a peer is declared failed
    (``HEAT_TPU_PEER_TIMEOUT_S``, default 60; memoised)."""
    return _knobs.peer_timeout_s


def collective_timeout_s() -> float:
    """Per-collective watchdog deadline (``HEAT_TPU_COLLECTIVE_TIMEOUT_S``,
    default 0 = watchdog off; memoised)."""
    return _knobs.collective_timeout_s


def coord_timeout_ms() -> int:
    """The unified coordination-channel wait budget
    (``HEAT_TPU_COORD_TIMEOUT_MS``, default 600000; memoised). Replaces the
    old hardcoded handshake/checkpoint timeouts."""
    return _knobs.coord_timeout_ms


def record_resilience_event(site: str, kind: str, detail: str = "") -> None:
    """Forward one supervision event into the always-on resilience stream
    (``supervision.*`` sites; the flight-recorder tee sees every one)."""
    if diagnostics is not None:
        diagnostics.record_resilience_event(site, kind, detail)


def _count(name: str) -> None:
    if diagnostics is not None:
        diagnostics.counter(name)


# -------------------------------------------------------------- coordinators
class LocalCoordinator:
    """An in-memory KV coordinator: the single-process stand-in for the
    ``jax.distributed`` coordination service, so the heartbeat state machine,
    the watchdog, and the supervised waits are testable (and chaos-drivable)
    without real process murder. Same surface as :class:`ClientCoordinator`.

    Thread-safe: one condition variable guards the store; :meth:`wait` blocks
    on it, so a publisher wakes waiters promptly like the real service.

    The semantics deliberately MATCH the real coordination service (verified
    against jaxlib's ``DistributedRuntimeService``), so tests exercise what
    production does: :meth:`get_dir` has DIRECTORY semantics — it returns
    keys strictly *under* the prefix, never a key exactly equal to it — and
    :meth:`delete` removes the key AND its whole subtree."""

    def __init__(self):
        self._cv = threading.Condition()
        self._kv: Dict[str, str] = {}

    @staticmethod
    def _as_dir(prefix: str) -> str:
        return prefix if prefix.endswith("/") else prefix + "/"

    def set(self, key: str, value: str, overwrite: bool = True) -> None:
        with self._cv:
            if not overwrite and key in self._kv:
                raise ValueError(f"key {key!r} already set")
            self._kv[key] = value
            self._cv.notify_all()

    def get_dir(self, prefix: str) -> List[Tuple[str, str]]:
        p = self._as_dir(prefix)
        with self._cv:
            return [(k, v) for k, v in sorted(self._kv.items())
                    if k.startswith(p)]

    def wait(self, key: str, timeout_ms: int) -> str:
        with self._cv:
            ok = self._cv.wait_for(lambda: key in self._kv,
                                   timeout=max(0.0, timeout_ms / 1e3))
            if not ok:
                raise TimeoutError(f"key {key!r} not set within {timeout_ms}ms")
            return self._kv[key]

    def delete(self, key: str) -> None:
        p = self._as_dir(key)
        with self._cv:
            self._kv.pop(key, None)
            for k in [k for k in self._kv if k.startswith(p)]:
                del self._kv[k]


class ClientCoordinator:
    """The ``jax.distributed`` coordination client behind the coordinator
    surface. Built lazily (:func:`default_coordinator`) so this module stays
    stdlib-only at load."""

    def __init__(self, client):
        self._client = client

    def set(self, key: str, value: str, overwrite: bool = True) -> None:
        self._client.key_value_set(key, value, overwrite)

    def get_dir(self, prefix: str) -> List[Tuple[str, str]]:
        return list(self._client.key_value_dir_get(prefix))

    def wait(self, key: str, timeout_ms: int) -> str:
        return self._client.blocking_key_value_get(key, timeout_ms)

    def delete(self, key: str) -> None:
        self._client.key_value_delete(key)


def _distributed_client():
    """The live jax.distributed coordination client, or None (lazy jax
    import — never at module load)."""
    try:
        import jax  # noqa: F401  (ensures _src is populated)
        from jax._src import distributed as _dist
    except ImportError:
        return None
    return _dist.global_state.client


def default_coordinator() -> Optional[ClientCoordinator]:
    """A coordinator over the live jax.distributed client, or None when the
    coordination service is not initialized (single-process runs)."""
    client = _distributed_client()
    return ClientCoordinator(client) if client is not None else None


def _require_coordinator(coordinator=None):
    if coordinator is not None:
        return coordinator
    with _lock:
        if _monitor is not None:
            return _monitor.coordinator
    co = default_coordinator()
    if co is None:
        raise RuntimeError(
            "supervised coordination wait needs the jax.distributed "
            "coordination service (or an explicit coordinator)"
        )
    return co


# ------------------------------------------------------------- typed errors
def _errors():
    """The typed supervision error classes (from ht.resilience — the error
    vocabulary module). Standalone loads degrade to RuntimeError lookups."""
    if resilience is not None:
        return (resilience.PeerFailed, resilience.CollectiveTimeout,
                resilience.CoordinationTimeout)
    raise RuntimeError("supervision typed errors need ht.resilience")


def abort_error(site: str = "") -> Optional[BaseException]:
    """The typed exception for the installed abort sentinel, or None. Each
    call constructs a FRESH exception (tracebacks must not be shared across
    raising threads)."""
    if not _aborted:
        return None
    with _lock:
        payload = dict(_abort) if _abort is not None else None
    if payload is None:  # pragma: no cover - _aborted implies _abort installed
        return None
    PeerFailed, CollectiveTimeout, CoordinationTimeout = _errors()
    kind = payload.get("kind", "peer-failed")
    if kind == "collective-timeout":
        return CollectiveTimeout(
            payload.get("site", site or "<unknown>"),
            float(payload.get("elapsed_s", 0.0)),
            detected_by=int(payload.get("by", -1)),
        )
    if kind == "coordination-timeout":
        return CoordinationTimeout(
            payload.get("site", site or "<unknown>"),
            key=payload.get("key", ""),
            timeout_ms=int(payload.get("timeout_ms", 0)),
            waiting_on=payload.get("waiting_on", ()),
        )
    return PeerFailed(
        int(payload.get("rank", -1)),
        float(payload.get("last_seen_s", 0.0)),
        detected_by=int(payload.get("by", -1)),
    )


def poll(site: str = "") -> None:
    """The sentinel chokepoint: raise the typed abort error if one is
    installed, else return immediately (one relaxed bool read). Called by
    ``MeshCommunication._guarded``, the scheduler's pre-dispatch checkpoint,
    and every supervised coordination wait."""
    if _aborted:
        exc = abort_error(site)
        if exc is not None:
            raise exc


def aborted() -> Optional[dict]:
    """The installed abort-sentinel payload, or None."""
    with _lock:
        return dict(_abort) if _abort is not None else None


def _install_abort_locked(payload: dict) -> None:
    # called with _lock held (the _locked-suffix convention)
    global _abort, _aborted
    if _abort is None:
        _abort = dict(payload)
        _aborted = True


def _replace_abort_locked(payload: dict) -> None:
    # adopt a racing peer's earlier sentinel payload; with _lock held
    global _abort, _aborted
    _abort = dict(payload)
    _aborted = True


def post_abort(kind: str, *, site: str = "", coordinator=None, **fields) -> dict:
    """Post the cluster-wide abort sentinel (first poster wins — a racing
    second abort keeps the original payload) and install it locally. Returns
    the effective payload. Records a ``supervision.abort`` resilience event
    of ``kind`` — the kinds (``peer-failed`` / ``collective-timeout`` /
    ``coordination-timeout``) are flight-recorder auto-dump triggers, so
    every abort ships a post-mortem."""
    payload = {"kind": kind, "by": _rank, "site": site, **fields}
    with _lock:
        mon = _monitor
        _install_abort_locked(payload)
        effective = dict(_abort)
    co = coordinator or (mon.coordinator if mon is not None else None)
    if co is not None and mon is not None:
        try:
            co.set(mon.sentinel_key, json.dumps(effective), False)
        except Exception as exc:
            # a racing rank posted first, or the channel is already gone:
            # adopt the original payload when readable; either way the LOCAL
            # abort above already guarantees typed delivery on this rank
            record_resilience_event("supervision.abort", "post-raced",
                    f"{type(exc).__name__}: {exc}")
            try:
                found = co.get_dir(mon.abort_key)
                if found:
                    prior = json.loads(found[0][1])
                    with _lock:
                        _replace_abort_locked(prior)
                    effective = prior
            except Exception as exc2:
                record_resilience_event("supervision.abort", "sentinel-unreadable",
                        f"{type(exc2).__name__}: {exc2}")
    record_resilience_event("supervision.abort", kind, json.dumps(effective))
    _count(f"supervision.abort.{kind}")
    return effective


# ----------------------------------------------------------------- monitor
class Monitor:
    """The heartbeat + watchdog state machine, one :meth:`step` per tick.

    Deliberately thread-free: the daemon thread :func:`arm` starts just calls
    ``step(clock())`` in a loop, and tests drive the same machine with an
    injected clock and a :class:`LocalCoordinator` — the
    heartbeat/departure/detection logic is exercised without wall time or
    real processes.

    Peer liveness is judged on the OBSERVER's clock: a peer's beat value is
    tracked with the local time it last *changed*; a beat that has not
    advanced for ``peer_timeout_s`` marks the peer failed. Cross-process
    clock skew therefore never enters the decision, and a peer that died
    before its first beat is aged from this monitor's start."""

    def __init__(self, coordinator, rank: int, nprocs: int, *,
                 generation: int, peer_timeout_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.coordinator = coordinator
        self.rank = int(rank)
        self.nprocs = int(nprocs)
        self.generation = int(generation)
        self.peer_timeout_s = float(peer_timeout_s)
        self.clock = clock
        self.ns = f"heat_tpu/sup/{generation}"
        # the sentinel is STORED under the prefix (abort_key is the
        # directory, sentinel_key the one entry in it): the real service's
        # key_value_dir_get has directory semantics — a key exactly equal to
        # the prefix is never returned — so readers get_dir(abort_key) and
        # the payload must live strictly below it
        self.abort_key = f"{self.ns}/abort"
        self.sentinel_key = f"{self.ns}/abort/0"
        self._seq = 0
        started = clock()
        # rank -> (last beat value seen, local time it last changed)
        self._seen: Dict[int, Tuple[Optional[str], float]] = {
            r: (None, started) for r in range(self.nprocs) if r != self.rank
        }
        self._departed: set = set()

    # ------------------------------------------------------------ publishing
    def beat(self) -> None:
        """Publish this rank's next heartbeat (monotonic counter)."""
        self._seq += 1
        self.coordinator.set(f"{self.ns}/hb/{self.rank}", str(self._seq), True)

    def depart(self) -> None:
        """Publish the clean-departure marker: peers stop expecting beats."""
        try:
            self.coordinator.set(f"{self.ns}/bye/{self.rank}", "1", True)
        except Exception as exc:
            record_resilience_event("supervision.heartbeat", "depart-unpublished",
                    f"{type(exc).__name__}: {exc}")

    def forget(self, rank: int) -> None:
        """Stop expecting beats from ``rank``: its failure has been HANDLED
        (e.g. the serving failover shed its work typed and the pool serves
        on) — without this the next scan would re-detect the same silent
        peer and re-post the abort the handler just cleared."""
        self._departed.add(int(rank))

    # ------------------------------------------------------------- detection
    def scan(self, now: float) -> Optional[dict]:
        """One detection pass: read peers' beats and departures, age the
        silent ones, and post the abort sentinel for the first peer past the
        budget. Returns the posted payload, or None."""
        beats: Dict[int, str] = {}
        for key, value in self.coordinator.get_dir(f"{self.ns}/hb/"):
            try:
                beats[int(key.rsplit("/", 1)[-1])] = value
            except ValueError:
                continue  # foreign key under the prefix: not a beat
        for key, _ in self.coordinator.get_dir(f"{self.ns}/bye/"):
            try:
                self._departed.add(int(key.rsplit("/", 1)[-1]))
            except ValueError:
                continue
        for r, (last, changed) in list(self._seen.items()):
            if r in self._departed:
                continue
            cur = beats.get(r)
            if cur is not None and cur != last:
                self._seen[r] = (cur, now)
                continue
            age = now - changed
            if age > self.peer_timeout_s:
                record_resilience_event(
                    "supervision.heartbeat", "peer-missed",
                    f"rank {r} silent for {age:.3f}s "
                    f"(budget {self.peer_timeout_s:.3f}s)",
                )
                return post_abort(
                    "peer-failed", site="supervision.heartbeat",
                    rank=r, last_seen_s=round(age, 3),
                )
        return None

    def check_sentinel(self) -> Optional[dict]:
        """Adopt a peer-posted abort sentinel into the local abort state."""
        if _aborted:
            return aborted()
        found = self.coordinator.get_dir(self.abort_key)
        if not found:
            return None
        try:
            payload = json.loads(found[0][1])
        except ValueError:
            payload = {"kind": "peer-failed", "rank": -1, "last_seen_s": 0.0}
        with _lock:
            _install_abort_locked(payload)
        record_resilience_event("supervision.abort", "adopted", json.dumps(payload))
        return payload

    # -------------------------------------------------------------- watchdog
    def watchdog_scan(self, now: float) -> Optional[dict]:
        """Flag in-flight collective windows past their deadline: mark the
        window fired (the stuck rank raises when it unblocks), dump a
        ``supervision.watchdog`` post-mortem, and post the sentinel so every
        survivor aborts typed."""
        overdue: Optional[Tuple[int, str, float]] = None
        with _lock:
            for token, (site, start, deadline) in _watch_windows.items():
                if now >= deadline and token not in _watch_fired:
                    _watch_fired[token] = now - start
                    overdue = (token, site, now - start)
                    break
        if overdue is None:
            return None
        _token, site, elapsed = overdue
        record_resilience_event(
            "supervision.watchdog", "watchdog-fired",
            f"collective window at {site!r} stuck for {elapsed:.3f}s "
            f"(budget {collective_timeout_s():.3f}s)",
        )
        _count("supervision.watchdog.fired")
        if telemetry is not None:
            telemetry.flight_record(
                "supervision", site,
                f"stuck collective window: {elapsed:.3f}s", kind="watchdog",
            )
            telemetry.flight_dump("supervision.watchdog")
        return post_abort(
            "collective-timeout", site=site, elapsed_s=round(elapsed, 3),
        )

    def step(self, now: Optional[float] = None) -> None:
        """One monitor tick: beat, adopt/post sentinels, age peers, scan the
        watchdog. Each leg is independent; a channel error in one must not
        starve the others (it is recorded and retried next tick)."""
        now = self.clock() if now is None else now
        try:
            self.beat()
        except Exception as exc:
            record_resilience_event("supervision.heartbeat", "beat-unpublished",
                    f"{type(exc).__name__}: {exc}")
        tee = _ops_tee
        if tee is not None:
            try:
                tee(self)
            except Exception as exc:
                record_resilience_event(
                    "supervision.heartbeat", "ops-beat-unpublished",
                    f"{type(exc).__name__}: {exc}")
        try:
            self.check_sentinel()
            if not _aborted:
                self.scan(now)
        except Exception as exc:
            record_resilience_event("supervision.heartbeat", "scan-failed",
                    f"{type(exc).__name__}: {exc}")
        self.watchdog_scan(now)


# ------------------------------------------------------------ arm / disarm
def _tick_interval(timeout_s: float) -> float:
    """Monitor cadence: a few beats per peer-timeout window, bounded to stay
    responsive for test-scale budgets and cheap for production ones."""
    return min(1.0, max(0.05, timeout_s / 5.0))


def arm(coordinator=None, *, rank: Optional[int] = None,
        nprocs: Optional[int] = None, peer_timeout_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        start_thread: bool = True) -> Monitor:
    """Arm the supervision plane: start heartbeats + the monitor daemon on
    ``coordinator`` (default: the live jax.distributed client) for this
    ``rank`` of ``nprocs``. Re-arming replaces the previous monitor (a new
    generation namespace). ``start_thread=False`` leaves stepping to the
    caller — the injected-clock tests."""
    global _armed, _monitor, _thread, _thread_stop, _generation, _rank, _nprocs
    if rank is None or nprocs is None:
        if telemetry is not None:
            t_rank, t_count = telemetry.process_info()
        else:  # pragma: no cover - standalone load
            t_rank, t_count = 0, 1
        rank = t_rank if rank is None else rank
        nprocs = t_count if nprocs is None else nprocs
    co = coordinator if coordinator is not None else default_coordinator()
    if co is None:
        co = LocalCoordinator()
    disarm()
    with _lock:
        _generation += 1
        _rank, _nprocs = int(rank), int(nprocs)
        monitor = Monitor(
            co, rank, nprocs, generation=_generation,
            peer_timeout_s=(peer_timeout_s if peer_timeout_s is not None
                            else _knobs.peer_timeout_s),
            clock=clock,
        )
        _monitor = monitor
        stop = _thread_stop = threading.Event()
    _armed = True
    _register_atexit()
    if start_thread and nprocs > 1:
        interval = _tick_interval(monitor.peer_timeout_s)

        def loop() -> None:
            try:
                # first beat before any sleep: peers start aging us now; a
                # transient channel error here must not kill the daemon (the
                # next step()'s beat retries) — an armed-looking plane whose
                # thread died at birth would get this healthy rank declared
                # dead by every peer
                monitor.beat()
            except Exception as exc:
                record_resilience_event(
                    "supervision.heartbeat", "beat-unpublished",
                    f"{type(exc).__name__}: {exc}")
            while not stop.wait(interval):
                monitor.step()

        t = threading.Thread(target=loop, name="heat-tpu-supervision",
                             daemon=True)
        with _lock:
            _thread = t
        t.start()
    record_resilience_event("supervision.plane", "armed",
            f"rank {rank}/{nprocs}, peer_timeout {monitor.peer_timeout_s:.3f}s,"
            f" generation {_generation}")
    return monitor


def current_monitor() -> Optional["Monitor"]:
    """The armed :class:`Monitor`, or None — the handle ``ht.ops`` folds
    cluster beats through (``cluster_snapshot`` sweeps ``<ns>/ops/`` on its
    coordinator)."""
    with _lock:
        return _monitor


def disarm() -> None:
    """Stop the monitor daemon and return the plane to zero-cost idle. The
    abort state is kept (a typed failure must stay deliverable until
    :func:`reset_abort`); watchdog windows are cleared."""
    global _armed, _monitor, _thread, _thread_stop
    with _lock:
        thread, stop = _thread, _thread_stop
        _thread = _thread_stop = None
        _monitor = None
    _armed = False
    if stop is not None:
        stop.set()
    if thread is not None and thread.is_alive():
        thread.join(timeout=5.0)
    with _lock:
        _watch_windows.clear()
        _watch_fired.clear()


def armed() -> bool:
    """Whether the supervision plane is armed."""
    return _armed


def reset_abort() -> None:
    """Clear the installed abort sentinel (failover handled / elastic
    restart / test isolation). While a monitor is still armed — the
    single-host serving failover, where the SAME generation keeps running —
    the store copy is deleted FIRST (its ``check_sentinel`` would re-adopt a
    lingering key every tick); after a disarm the store copy belongs to the
    dead generation's namespace and is simply left behind."""
    global _abort, _aborted
    with _lock:
        mon = _monitor
    if mon is not None:
        try:
            mon.coordinator.delete(mon.abort_key)
        except Exception as exc:
            record_resilience_event("supervision.abort", "sentinel-clear-failed",
                    f"{type(exc).__name__}: {exc}")
    with _lock:
        _abort = None
        _aborted = False


def forget_peer(rank: int) -> None:
    """Tell the armed monitor that ``rank``'s failure has been handled: it
    stops expecting the dead peer's beats, so clearing the abort sentinel
    (``reset_abort``) does not just get it re-posted at the next scan. The
    single-host failover verb — ``ModelPool.on_peer_failure`` uses it; the
    multi-host elastic restart re-arms a fresh monitor at the surviving
    world size instead."""
    with _lock:
        mon = _monitor
    if mon is not None:
        mon.forget(rank)


def auto_arm() -> None:
    """Arm the plane for a multi-process job when enabled — called by the
    communication bootstrap after the runtime is up. Single-process runs (or
    ``HEAT_TPU_SUPERVISION=0``) stay zero-cost idle."""
    if not _knobs.enabled:
        return
    client = _distributed_client()
    if client is None:
        return
    try:
        import jax
        nprocs = jax.process_count()
        rank = jax.process_index()
    except Exception as exc:  # backend not initialized yet: stay idle
        record_resilience_event("supervision.plane", "arm-deferred",
                f"{type(exc).__name__}: {exc}")
        return
    if nprocs <= 1:
        return
    arm(ClientCoordinator(client), rank=rank, nprocs=nprocs)


# ------------------------------------------------------------ the watchdog
@contextlib.contextmanager
def watch(site: str):
    """Supervise one collective invocation window: poll the sentinel on
    entry and exit, and — when ``HEAT_TPU_COLLECTIVE_TIMEOUT_S`` is set —
    arm a watchdog deadline for the window. A window the watchdog flagged
    raises typed :class:`~.resilience.CollectiveTimeout` on this rank as soon
    as the call unblocks (survivors raise at their own sentinel polls)."""
    poll(site)
    budget = collective_timeout_s()
    mon = _monitor  # snapshot: a concurrent disarm() may null the global
    if budget <= 0.0 or mon is None:
        yield
        poll(site)
        return
    token = next(_watch_seq)
    start = mon.clock()
    with _lock:
        _watch_windows[token] = (site, start, start + budget)
    fired: Optional[float] = None
    try:
        yield
    finally:
        with _lock:
            _watch_windows.pop(token, None)
            fired = _watch_fired.pop(token, None)
    if fired is not None:
        PeerFailed, CollectiveTimeout, CoordinationTimeout = _errors()
        raise CollectiveTimeout(site, fired, detected_by=_rank)
    poll(site)


# ------------------------------------------------- supervised coordination
def _looks_like_timeout(exc: BaseException) -> bool:
    if isinstance(exc, TimeoutError):
        return True
    text = f"{type(exc).__name__}: {exc}".lower()
    return "deadline" in text or "timeout" in text or "timed out" in text


def kv_wait(key: str, timeout_ms: Optional[int] = None, *,
            site: str = "supervision.kv", coordinator=None) -> str:
    """A supervised ``blocking_key_value_get``: the wait is chunked so the
    abort sentinel is polled while blocked (a detected peer failure raises
    typed :class:`~.resilience.PeerFailed` MID-WAIT, not after the full
    budget), bounded by ``timeout_ms`` (default: the unified
    ``HEAT_TPU_COORD_TIMEOUT_MS``), and exhaustion raises typed
    :class:`~.resilience.CoordinationTimeout` naming the key — never the raw
    backend error. This wrapper (and :func:`kv_barrier`) is the only
    sanctioned coordination-wait form: the ``coord-unbounded-wait`` analysis
    rule flags raw waits anywhere else."""
    co = _require_coordinator(coordinator)
    budget = coord_timeout_ms() if timeout_ms is None else int(timeout_ms)
    mon = _monitor  # snapshot: a concurrent disarm() may null the global
    clock = mon.clock if mon is not None else time.monotonic
    deadline = clock() + budget / 1e3
    last: Optional[BaseException] = None
    while True:
        poll(site)
        remaining_ms = (deadline - clock()) * 1e3
        if remaining_ms <= 0.0:
            PeerFailed, CollectiveTimeout, CoordinationTimeout = _errors()
            detail = f"{type(last).__name__}: {last}" if last else ""
            raise CoordinationTimeout(
                site, key=key, timeout_ms=budget, detail=detail
            ) from last
        try:
            return co.wait(key, int(max(1.0, min(_CHUNK_MS, remaining_ms))))
        except Exception as exc:
            last = exc
            if not _looks_like_timeout(exc):
                # a genuine channel failure (service gone, connection reset):
                # typed immediately — waiting out the budget cannot fix it
                PeerFailed, CollectiveTimeout, CoordinationTimeout = _errors()
                raise CoordinationTimeout(
                    site, key=key, timeout_ms=budget,
                    detail=f"{type(exc).__name__}: {exc}",
                ) from exc
            # chunk expired: loop to poll the sentinel, then keep waiting


def kv_barrier(ns: str, *, nprocs: Optional[int] = None,
               rank: Optional[int] = None, timeout_ms: Optional[int] = None,
               site: str = "supervision.barrier", coordinator=None) -> None:
    """A supervised barrier over the KV store: every rank publishes
    ``<ns>/<rank>`` and waits for all ``nprocs`` keys. Unlike the native
    ``wait_at_barrier`` this is sentinel-abortable mid-wait, and a timeout
    raises typed :class:`~.resilience.CoordinationTimeout` NAMING the ranks
    that never arrived. The namespace must be fresh per use (callers thread
    their own sequence numbers, e.g. ``checkpoint._coord_ns``)."""
    co = _require_coordinator(coordinator)
    if nprocs is None or rank is None:
        with _lock:
            mon = _monitor
        if mon is None:
            raise ValueError("kv_barrier needs nprocs/rank when disarmed")
        nprocs = mon.nprocs if nprocs is None else nprocs
        rank = mon.rank if rank is None else rank
    budget = coord_timeout_ms() if timeout_ms is None else int(timeout_ms)
    mon = _monitor  # snapshot: a concurrent disarm() may null the global
    clock = mon.clock if mon is not None else time.monotonic
    deadline = clock() + budget / 1e3
    co.set(f"{ns}/{rank}", "1", True)
    PeerFailed, CollectiveTimeout, CoordinationTimeout = _errors()
    for r in range(int(nprocs)):
        remaining = max(1, int((deadline - clock()) * 1e3))
        try:
            kv_wait(f"{ns}/{r}", remaining, site=site, coordinator=co)
        except CoordinationTimeout as exc:
            # one directory listing of the arrived ranks (keys {ns}/{rank}
            # sit strictly under the namespace, so directory semantics
            # return them; an exact-key probe per rank would not — the real
            # service never returns a key equal to the prefix)
            arrived = set()
            try:
                for k, _v in co.get_dir(ns):
                    try:
                        arrived.add(int(k.rsplit("/", 1)[-1]))
                    except ValueError:
                        continue
            except Exception as exc2:
                # channel gone: report the timeout unadorned
                record_resilience_event(
                    "supervision.barrier", "arrived-unreadable",
                    f"{type(exc2).__name__}: {exc2}")
                arrived = None
            waiting = ([w for w in range(int(nprocs)) if w not in arrived]
                       if arrived is not None else [])
            raise CoordinationTimeout(
                site, key=f"{ns}/{r}", timeout_ms=budget, waiting_on=waiting,
                detail=exc.detail,
            ) from exc


# ------------------------------------------------- supervised jax runtime
def _service_bind_address(coordinator_address: str) -> str:
    return "[::]:" + coordinator_address.rsplit(":", 1)[1]


def bootstrap_distributed(coordinator_address: str, num_processes: int,
                          process_id: int, *,
                          init_timeout_s: Optional[int] = None) -> None:
    """Initialize the jax distributed runtime in SUPERVISED mode: same
    observable result as ``jax.distributed.initialize`` (the service/client
    pair lands in ``jax._src.distributed.global_state``), but XLA's native
    fail-stop is disabled — peer failure detection, typed delivery, and
    recovery belong to this module (see the module header). Survivors of a
    peer failure can therefore abandon this runtime and re-initialize at the
    surviving world size, which the default runtime's process-terminating
    error propagation makes impossible."""
    import jax  # noqa: F401
    from jax._src import distributed as _dist
    from jax._src.lib import xla_extension as xe

    global _owns_client
    state = _dist.global_state
    if state.client is not None:
        return  # already initialized (explicit user bootstrap): respect it
    timeout = (int(init_timeout_s) if init_timeout_s is not None
               else max(1, coord_timeout_ms() // 1000))
    if process_id == 0 and state.service is None:
        # native failure detection OFF (one beat per 10 s, a practically
        # infinite miss budget): supervision's KV heartbeats own detection,
        # and the service must never fail-stop the survivors
        state.service = xe.get_distributed_runtime_service(
            _service_bind_address(coordinator_address), num_processes,
            heartbeat_interval=10, max_missing_heartbeats=1_000_000,
        )
    client = xe.get_distributed_runtime_client(
        coordinator_address, process_id, init_timeout=timeout,
        heartbeat_interval=10, max_missing_heartbeats=1_000_000,
        shutdown_on_destruction=False, use_compression=True,
    )
    client.connect()
    state.client = client
    state.process_id = process_id
    state.num_processes = num_processes
    state.coordinator_address = coordinator_address
    with _lock:
        _owns_client = True
    _register_atexit()
    record_resilience_event("supervision.runtime", "bootstrapped",
            f"rank {process_id}/{num_processes} at {coordinator_address}")


def teardown_distributed(*, clean: Optional[bool] = None) -> None:
    """Tear the distributed runtime down. ``clean`` (default: no abort
    installed) performs the ordinary synchronized shutdown (barrier across
    all tasks — only safe when every peer is alive). Dirty teardown ABANDONS
    the runtime instead: the service object joins the graveyard (destroying
    it would cancel surviving peers' coordination RPCs and terminate them),
    the supervised client is destroyed (it owns only its own threads), a
    foreign client is graveyarded too (its destructor may run a shutdown
    barrier that can never complete), and every jax backend/topology cache is
    cleared so the next :func:`bootstrap_distributed` rebuilds the world at
    its new size."""
    import gc

    import jax
    from jax._src import distributed as _dist
    from jax._src import xla_bridge as xb

    global _owns_client
    state = _dist.global_state
    client, service = state.client, state.service
    if clean is None:
        clean = not _aborted
    state.client = None
    state.service = None
    state.preemption_sync_manager = None
    with _lock:
        owns = _owns_client
        _owns_client = False
    if clean and client is not None:
        try:
            client.shutdown()
            if service is not None:
                service.shutdown()
            client = service = None
        except Exception as exc:
            # a peer vanished between the abort check and the barrier:
            # fall through to the abandon path below
            record_resilience_event("supervision.runtime", "shutdown-degraded",
                    f"{type(exc).__name__}: {exc}")
    if service is not None:
        _immortalize(service)
    if client is not None and not owns:
        _immortalize(client)
    client = None  # a supervised client: destroying it stops its own threads
    gc.collect()
    jax.clear_caches()
    with xb._backend_lock:
        xb._backends.clear()
        xb._backend_errors.clear()
        xb._default_backend = None
    for attr in dir(xb):
        fn = getattr(xb, attr, None)
        if callable(fn) and hasattr(fn, "cache_clear"):
            fn.cache_clear()
    record_resilience_event("supervision.runtime", "teardown",
            "clean" if clean else "abandoned (graveyarded)")


def _register_atexit() -> None:
    global _atexit_registered
    with _lock:
        if _atexit_registered:
            return
        _atexit_registered = True
    atexit.register(_atexit_shutdown)


def _atexit_shutdown() -> None:
    """Process-exit hook for supervised runs: publish the clean-departure
    marker (peers must not read a normal exit as a failure), then — when this
    module built the runtime and no abort happened — perform the ordinary
    synchronized shutdown the default client would have done from its
    destructor. After an abort the runtime is abandoned instead: the
    destructors must not run (see :func:`teardown_distributed`)."""
    with _lock:
        mon = _monitor
        owns = _owns_client
    if mon is not None:
        mon.depart()
    disarm()
    if not owns:
        return
    try:
        teardown_distributed()
    except Exception as exc:
        # the process is exiting: a failed courtesy shutdown must not turn
        # a clean exit into a crash
        record_resilience_event("supervision.runtime", "atexit-degraded",
                f"{type(exc).__name__}: {exc}")


# --------------------------------------------------------- elastic restart
def _drain_scheduler(timeout_s: float) -> None:
    """Flush the dispatch scheduler before teardown: queued work is delivered
    or shed TYPED (DrainTimeout's contract), so no request future can survive
    into the new generation blocked."""
    from . import _executor

    try:
        _executor._get_scheduler().drain(timeout_s)
    except Exception as exc:
        if resilience is not None and isinstance(exc, resilience.DrainTimeout):
            return  # typed + delivered to every waiter: exactly the contract
        raise


def _reanchor_framework() -> None:
    """Rebuild every world-size-derived singleton after a re-init: the
    communicators, the executor's program/signature caches and memoised
    process-count, the checkpoint coordination counters, and the telemetry
    identity/clock handshake for the new generation."""
    import jax

    from . import _executor, checkpoint, communication

    communication.COMM_WORLD = communication.MeshCommunication()
    communication.COMM_SELF = communication.MeshCommunication(jax.devices()[:1])
    communication.use_comm(None)
    communication._pad_cache.clear()
    _executor.clear_executor_cache()
    _executor._single_controller = None
    with checkpoint._state_lock:
        checkpoint._coord_seq = 0
        checkpoint._coord_my_keys.clear()
    communication._telemetry_bootstrap()
    _executor._get_scheduler().reopen()


def elastic_restart(exc: BaseException, *, reinit=None,
                    drain_timeout_s: float = 10.0) -> dict:
    """One supervised restart: drain → disarm → teardown → (re)initialize →
    re-anchor → re-arm. ``reinit(exc)`` is the caller's elasticity policy: it
    returns ``{"coordinator_address", "num_processes", "process_id"}`` for
    the surviving world (a fresh coordinator address — the dead generation's
    port is abandoned, not reused), or None to continue single-process.
    Returns a summary dict. Used by :func:`run_supervised`; callable directly
    by serving-side failover logic."""
    global _restarts
    record_resilience_event("supervision.restart", "elastic-restart",
            f"{type(exc).__name__}: {exc}")
    _count("supervision.restart")
    _drain_scheduler(drain_timeout_s)
    disarm()
    # the abort is being HANDLED from here on: clear it before the reinit
    # policy runs, whose own supervised waits (negotiating the new
    # coordinator over the old KV store) must not re-raise it
    reset_abort()
    had_client = _distributed_client() is not None
    spec = reinit(exc) if reinit is not None else None
    if had_client:
        teardown_distributed(clean=False)
    if spec is not None:
        bootstrap_distributed(
            spec["coordinator_address"], int(spec["num_processes"]),
            int(spec["process_id"]),
        )
    if had_client or spec is not None:
        _reanchor_framework()  # ends in the telemetry bootstrap → auto_arm()
    else:
        from . import _executor

        _executor._get_scheduler().reopen()
        auto_arm()
    with _lock:
        _restarts += 1
        restarts = _restarts
    summary = {
        "cause": f"{type(exc).__name__}: {exc}",
        "world": (spec or {}).get("num_processes", 1),
        "rank": (spec or {}).get("process_id", 0),
        "restarts": restarts,
    }
    record_resilience_event("supervision.restart", "restarted", json.dumps(summary))
    return summary


def run_supervised(step_fn, manager, policy=None, *, template=None,
                   state=None, start_step: int = 0,
                   max_steps: Optional[int] = None, save_every: int = 1,
                   reinit=None, drain_timeout_s: float = 10.0,
                   restore_kwargs: Optional[dict] = None) -> dict:
    """Run a training loop under the supervision plane with elastic restart.

    ``step_fn(step, state) -> state`` is one training step;
    ``manager`` is a :class:`~.checkpoint.CheckpointManager`; ``template``
    the restore template pytree, or a CALLABLE returning one — pass a
    callable for elastic multi-process jobs, because a template's DNDarray
    leaves pin the communicator and the restore after a world-size change
    must build against the surviving world's mesh (defaults to ``state``).
    Steps where
    ``step % save_every == 0`` are checkpointed. On a typed supervision
    failure (:class:`~.resilience.PeerFailed` /
    :class:`~.resilience.CollectiveTimeout` /
    :class:`~.resilience.CoordinationTimeout`) the harness performs
    :func:`elastic_restart` — drain, teardown, re-init at the surviving world
    size per the ``reinit`` policy, restore the latest step through the
    reshard-on-restore path — and resumes, under a bounded restart budget:
    ``policy.max_attempts`` restarts (default 3) gated by the
    ``supervision.restart`` circuit breaker. An exhausted budget (or an open
    breaker) re-raises the typed failure unchanged.

    Returns ``{"state", "steps", "restarts"}``."""
    if resilience is None:  # pragma: no cover - standalone load
        raise RuntimeError("run_supervised needs the heat_tpu package")
    PeerFailed, CollectiveTimeout, CoordinationTimeout = _errors()
    pol = policy or resilience.Policy(max_attempts=3, backoff_base=0.5)
    br = resilience.breaker("supervision.restart")
    template = template if template is not None else state

    def _template():
        return template() if callable(template) else template

    restore_kwargs = dict(restore_kwargs or {})
    if state is None:
        latest = manager.latest_step
        if latest is None:
            raise ValueError("run_supervised needs an initial state or a "
                             "restorable checkpoint step")
        state = manager.restore(_template(), **restore_kwargs)
        start_step = latest + 1
    step = int(start_step)
    restarts = 0
    while max_steps is None or step < max_steps:
        try:
            poll("supervision.step")
            state = step_fn(step, state)
            if save_every and step % save_every == 0:
                manager.save(step, state)
            br.record_success()
            step += 1
        except (PeerFailed, CollectiveTimeout, CoordinationTimeout) as exc:
            restarts += 1
            br.record_failure(f"{type(exc).__name__}: {exc}")
            budget_left = (pol.max_attempts is None
                           or restarts < pol.max_attempts)
            if not budget_left or not br.allows():
                record_resilience_event(
                    "supervision.restart", "exhausted",
                    f"restart {restarts} refused "
                    f"(budget_left={budget_left}, breaker={br.state}): "
                    f"{type(exc).__name__}: {exc}",
                )
                raise
            time.sleep(pol.delay_s(restarts))
            elastic_restart(exc, reinit=reinit,
                            drain_timeout_s=drain_timeout_s)
            latest = manager.latest_step
            if latest is None:
                raise
            state = manager.restore(_template(), **restore_kwargs)
            step = latest + 1
    return {"state": state, "steps": step, "restarts": restarts}


# ------------------------------------------------------------------ stats
def supervision_stats() -> dict:
    """The supervision section of ``ht.diagnostics.report()``: armed state,
    identity, abort payload, watchdog windows, restart count."""
    with _lock:
        mon = _monitor
        return {
            "armed": _armed,
            "enabled": _knobs.enabled,
            "rank": _rank,
            "nprocs": _nprocs,
            "generation": _generation,
            "peer_timeout_s": (mon.peer_timeout_s if mon is not None
                               else _knobs.peer_timeout_s),
            "collective_timeout_s": _knobs.collective_timeout_s,
            "coord_timeout_ms": _knobs.coord_timeout_ms,
            "aborted": dict(_abort) if _abort is not None else None,
            "watch_windows": len(_watch_windows),
            "restarts": _restarts,
            "graveyard": len(_graveyard),
        }


if diagnostics is not None:
    diagnostics.register_provider("supervision", supervision_stats)

if resilience is not None:
    def _go_silent_for_peer_death() -> None:
        """The ``peer-dead`` fault hook: stop heartbeating WITHOUT the
        clean-departure marker — peers must observe a crash (silence, then
        absence), not a shutdown. The exit that follows skips atexit, so the
        marker can never leak out after this."""
        disarm()

    resilience._peer_dead_hook = _go_silent_for_peer_death
