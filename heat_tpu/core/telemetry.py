"""``ht.telemetry`` — the distributed telemetry plane: cross-process
metric/trace aggregation, collective skew & straggler attribution, and a
failure flight recorder.

Everything :mod:`diagnostics` (PR 3), :mod:`profiler` (PR 6) and the
executor/scheduler ledgers (PRs 7/9) collect is strictly per-process: a
4-process ``jax.distributed`` job yields four disjoint reports with no global
view, no cross-rank clock alignment, and no way to see which rank straggles
inside a collective — even though :class:`profiler.Histogram` was designed
mergeable for exactly this. This module closes that gap with three pillars:

- **Aggregation.** :func:`dump_shard` writes one self-describing *telemetry
  shard* per process (schema ``heat-tpu-telemetry/1``): the full diagnostics
  report (counters, spans, collectives, pad gauges, and the executor /
  profiler / resilience provider sections), the raw profiler timeline
  (:func:`profiler.trace_snapshot`), this process's collective windows, and
  the flight-recorder ring — all stamped with the process identity and the
  clock-alignment anchor. :func:`merge` (also ``python -m heat_tpu.telemetry
  merge``) folds N shards into ONE global report — counters sum exactly,
  spans fold, histogram merge is the associative bucket fold that already
  exists, per-process breakdowns are preserved — and :func:`merged_trace`
  emits ONE Perfetto trace with per-process track groups: every process gets
  a disjoint pid range (``(index + 1) * PID_STRIDE``), fixing the pid
  collision two concatenated per-process traces used to have, and every
  timestamp is aligned onto the shared clock (below).

- **Clock alignment.** At ``jax.distributed`` bootstrap,
  :mod:`communication` runs a one-shot handshake: a global barrier, then each
  process samples ``time.monotonic_ns()`` and one ``allgather`` shares the
  anchors (:func:`record_clock_anchor`). Trace timestamps are shifted so the
  barrier instant is t=0 on every track — trace-time only, HLO untouched,
  accurate to the barrier's exit skew (milliseconds; see
  ``doc/source/observability.rst`` for the caveats). Without a handshake
  (single process, or ``HEAT_TPU_TELEMETRY_HANDSHAKE=0``) each shard falls
  back to its import-time anchor and the merged report says so
  (``clock.aligned``).

- **Collective skew & straggler attribution.** When collection is on
  (:func:`enable` / ``HEAT_TPU_TELEMETRY=1``) the single
  ``MeshCommunication._guarded`` chokepoint wraps every collective /
  layout-op invocation in :func:`collective_window`: the enter/exit wall
  times land in a bounded window log and a per-site duration histogram, each
  window identified by ``(site, ambient request tag, seq)`` — SPMD symmetry
  makes the k-th guarded call *of one request* at one site the same
  collective on every rank, even when concurrent tenants interleave in a
  different order per process. :func:`merge` lines the windows up across
  ranks by that identity: the cross-rank skew is ``max(enter) - min(enter)``
  and the rank that entered last is the straggler. The merged report carries
  ``skew.<op>`` histograms, a per-rank straggler scoreboard naming the
  slowest rank, and the merged trace draws flow arrows linking the same
  collective across process tracks (worst skews first). The same identity
  also powers the **sequence-consistency gate** (the runtime twin of the
  static ``spmd-divergent-collective`` rule in :mod:`heat_tpu.analysis`):
  every rank's per-tag ordered site list must match the lowest rank's, and
  ``merge --check`` fails on the first divergence naming the rank, the
  index, and the expected/actual sites — the signature a rank-dependent
  branch around a collective produces. Needs no clock alignment (local
  ordering only), so it works even when the handshake degraded.

- **Flight recorder.** An always-on bounded ring of the last
  ``HEAT_TPU_FLIGHT_EVENTS`` lifecycle / resilience / fallback events per
  process (:func:`flight_record`; fed by the diagnostics tee hooks and the
  scheduler's lifecycle ledger). On the typed failure paths — fault-plan
  firings, signature quarantine, ``CheckpointCorrupt``, a circuit breaker
  opening, ``DrainTimeout`` — the ring is dumped automatically (rate-limited,
  on a background thread so no caller lock ever waits on a disk) to
  ``HEAT_TPU_FLIGHT_DIR``, so a chaos-CI failure or a multi-process hang
  ships a post-mortem artifact instead of a bare traceback.
  :func:`flight_dump` does the same on demand.

Zero-cost contract (same discipline as diagnostics/profiler/resilience)
-----------------------------------------------------------------------
Idle (the default), the one hook on a hot path — the collective-window check
in ``MeshCommunication._guarded`` — is a single module-attribute read
(``telemetry._collecting``) and a branch not taken. Nothing is EVER injected
into traced program bodies — window timing is host-side, around the trace-time
invocation — so compiled HLO is byte-identical with collection on, off, or
never touched (gated with the profiler's HLO-parity suite). The flight
recorder's feeds are failure-path machinery, never a compute path.

Thread-safety
-------------
Every registry — the window log, per-site sequence numbers and duration
histograms, the flight ring and dump ledger, the process/clock identity —
mutates under the one module ``_lock``, which is a strict LEAF: no code
holding it calls into any other locking module (shard payloads are built
under the lock, written outside it; auto-dumps run on their own thread).
``_collecting`` is the relaxed hot-path switch, read bare like
``diagnostics._enabled``; ``_in_flight_dump`` is a thread-local reentrancy
guard.

Env knobs
---------
- ``HEAT_TPU_TELEMETRY=1``          — start with collective-window collection
  on (read at import, like its diagnostics/profiler siblings).
- ``HEAT_TPU_FLIGHT_DIR=path``      — flight-recorder dump directory
  (default: ``<tempdir>/heat-tpu-flight``; read at dump time — a cold path,
  so tests repoint it without reloads).
- ``HEAT_TPU_FLIGHT=0``             — disable the *automatic* failure dumps
  (the ring still records; on-demand dumps still work; read at dump time).
- ``HEAT_TPU_FLIGHT_EVENTS=N``      — ring capacity (default 512; applied at
  import and re-applied by :func:`reset`).
- ``HEAT_TPU_TELEMETRY_WINDOWS=N``  — collective-window ring capacity
  (default 16384; applied at import and by :func:`reset`). An overflowed
  ring invalidates the cross-rank sequence gate (oldest windows dropped),
  and ``merge --check`` then FAILS rather than silently passing — long
  collection runs that need the gate raise this.
- ``HEAT_TPU_TELEMETRY_HANDSHAKE=0``— skip the clock handshake at bootstrap.

Stdlib-only at module load (like diagnostics/profiler/resilience): the merge
half must run in tooling that never touches the JAX backend.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import math
import os
import socket
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

try:
    from . import diagnostics, profiler, resilience
except ImportError:  # standalone file-path load (no parent package): degrade —
    diagnostics = resilience = None  # merge still needs Histogram, so load the
    import importlib.util as _ilu    # stdlib-only sibling by file path
    import sys as _sys

    def _load_sibling(name: str):
        mod = _sys.modules.get(f"_heat_tpu_{name}")
        if mod is not None:
            return mod
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)), f"{name}.py")
        try:
            spec = _ilu.spec_from_file_location(f"_heat_tpu_{name}", path)
            mod = _ilu.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except Exception:  # ht: ignore[silent-except] -- best-effort standalone load: callers treat None as merge-degraded (histograms kept raw)
            return None
        _sys.modules.setdefault(f"_heat_tpu_{name}", mod)
        return mod

    profiler = _load_sibling("profiler")
    del _ilu, _sys

__all__ = [
    "SCHEMA",
    "MERGED_SCHEMA",
    "TRACE_SCHEMA",
    "FLIGHT_SCHEMA",
    "PID_STRIDE",
    "enable",
    "disable",
    "collecting",
    "reset",
    "set_process_info",
    "process_info",
    "record_clock_anchor",
    "clock_info",
    "collective_window",
    "windows",
    "duration_snapshots",
    "flight_record",
    "flight_events",
    "flight_dump",
    "flight_dir",
    "dump_shard",
    "shard_payload",
    "load_shards",
    "load_ops_beats",
    "OPS_BEAT_PREFIX",
    "merge",
    "merged_trace",
    "write_report",
    "write_trace",
    "main",
]

SCHEMA = "heat-tpu-telemetry/1"
MERGED_SCHEMA = "heat-tpu-telemetry-merged/1"
TRACE_SCHEMA = "heat-tpu-telemetry-trace/1"
FLIGHT_SCHEMA = "heat-tpu-flight/1"

#: filename prefix of per-process shards inside a telemetry directory
SHARD_PREFIX = "telemetry-shard-"

#: pid range per process in the merged trace: process ``i`` owns
#: ``[(i+1)*PID_STRIDE, (i+2)*PID_STRIDE)`` — request rid ``r`` maps to
#: ``(i+1)*PID_STRIDE + r``, the per-process collective track sits at the top
#: of the range. No single-process trace has ever come near 10^6 request ids
#: (the profiler's request table is capped at 8192 entries).
PID_STRIDE = 1_000_000

# Hot-path gate, read as ``telemetry._collecting`` by MeshCommunication's
# chokepoint: one attribute load + branch when off — the zero-cost contract.
_collecting: bool = False

_lock = threading.RLock()

_MAX_WINDOWS = 16_384
_DEFAULT_FLIGHT_EVENTS = 512
_MAX_AUTO_DUMPS = 16
_AUTO_MIN_INTERVAL_NS = 5_000_000_000  # >= 5 s between auto-dumps per trigger

# process identity + the clock anchor (rewritten by the bootstrap handshake)
_process: Dict[str, Any] = {
    "index": 0,
    "count": 1,
    "pid": os.getpid(),
    "host": socket.gethostname(),
}
_clock: Dict[str, Any] = {
    # import-time fallback anchor: aligns nothing across processes, but keeps
    # per-process timestamps small and the shard schema uniform
    "anchor_ns": time.monotonic_ns(),
    "anchors_ns": None,
    "aligned": False,
}

# collective windows: (site, seq, enter_ns, exit_ns, tag); seq counts per
# (site, ambient request tag) — SPMD symmetry makes the k-th guarded call of
# request X at one site the SAME collective on every rank, even when two
# tenants' requests interleave in a different order per process (the async
# executor's default shape; a bare per-site counter would pair unrelated
# collectives across ranks and attribute phantom skew)
def _windows_capacity() -> int:
    """Window-ring capacity: ``HEAT_TPU_TELEMETRY_WINDOWS`` (default 16384;
    applied at import and re-applied by :func:`reset`). An overflowed ring
    drops its oldest windows, which invalidates the cross-rank sequence
    gate — long jobs that need the gate raise the cap."""
    try:
        return max(256, int(os.environ.get("HEAT_TPU_TELEMETRY_WINDOWS", "")
                            or _MAX_WINDOWS))
    except ValueError:
        return _MAX_WINDOWS


_windows: "deque[tuple]" = deque(maxlen=_windows_capacity())
_site_seq: Dict[Tuple[str, Optional[str]], int] = {}
_durations: Dict[str, Any] = {}  # site -> profiler.Histogram

# flight recorder: bounded ring + the ledger of dumps already written
def _flight_capacity() -> int:
    try:
        return max(16, int(os.environ.get("HEAT_TPU_FLIGHT_EVENTS", "") or
                           _DEFAULT_FLIGHT_EVENTS))
    except ValueError:
        return _DEFAULT_FLIGHT_EVENTS


_flight: "deque[dict]" = deque(maxlen=_flight_capacity())
_flight_dumps: List[str] = []
_flight_seq = itertools.count(1)
_auto_dumps: int = 0
_last_auto_ns: Dict[str, int] = {}
_in_flight_dump = threading.local()

#: resilience-event kinds whose occurrence auto-dumps the flight ring — the
#: typed failure paths the ISSUE names (breaker opens match on the transition
#: detail instead, see :func:`_on_resilience_event`)
_AUTO_DUMP_KINDS = frozenset({
    "fault",          # a fault-plan entry fired
    "quarantine",     # the executor evicted a signature to the eager path
    "corrupt",        # CheckpointCorrupt on a hard restore/verify path (the
                      # CheckpointManager step SCAN records a softer
                      # "corrupt-step" that rides the ring without dumping —
                      # re-scanning a known-bad step must not burn budget)
    "data-loss",      # donated buffer invalidated by a failed call
    "drain-timeout",  # DispatchScheduler.drain could not flush
    "swap-failed",    # a model hot-swap rolled back (ht.serving.swap_state)
    # supervision-plane aborts (ISSUE 14): every typed abort ships its
    # post-mortem (the watchdog additionally dumps its own
    # `supervision.watchdog` trigger before posting the sentinel)
    "peer-failed",           # a peer stopped heartbeating past the budget
    "collective-timeout",    # the collective watchdog flagged a stuck window
    "coordination-timeout",  # a supervised coordination wait exhausted
    "peer-dead",             # the injected peer-death fault fired (this rank)
    "peer-failover",         # a serving pool shed typed after a peer failure
    "slo-burn",              # an ops-plane tenant burn-rate alert went UP
                             # (ISSUE 18): the transition event's detail
                             # carries the offending window's per-shard
                             # pressure breakdown, so the post-mortem shows
                             # WHERE the budget burned — only the OFF->ON
                             # edge is typed (clears ride the ring without
                             # dumping), so one regression dumps exactly once
    # deliberately NOT here: "cache-corrupt" — a corrupt compile-cache or
    # result-cache entry is self-healing (typed rejection, then recompile /
    # recompute), so it rides the ring as post-mortem context without
    # spending dump budget on a failure the very next dispatch repairs
})


def _utcnow() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


# ------------------------------------------------------------------ switches
def enable() -> None:
    """Turn collective-window collection on (the flight recorder and shard
    dumps are always available; this gates only the per-collective timing
    in ``MeshCommunication._guarded``)."""
    global _collecting
    _collecting = True


def disable() -> None:
    """Stop collecting collective windows (collected data is kept until
    :func:`reset`)."""
    global _collecting
    _collecting = False


def collecting() -> bool:
    """Whether collective-window collection is currently on."""
    return _collecting


def reset() -> None:
    """Drop collected windows, per-site sequence counters, duration
    histograms, and the flight ring (the dump ledger and rate-limit state are
    kept — they describe files already on disk). Process identity and the
    clock anchor survive; the collecting switch is untouched. The flight
    ring is rebuilt at the current ``HEAT_TPU_FLIGHT_EVENTS`` capacity and
    the window ring at ``HEAT_TPU_TELEMETRY_WINDOWS``, so an in-process env
    change takes effect at the next reset."""
    global _flight, _windows
    with _lock:
        _windows = deque(maxlen=_windows_capacity())
        _site_seq.clear()
        _durations.clear()
        _flight = deque(maxlen=_flight_capacity())


# ------------------------------------------------------------------ identity & clock
def set_process_info(index: int, count: int) -> None:
    """Record this process's rank in the job (called by the communication
    bootstrap; defaults to 0-of-1 for single-process runs)."""
    with _lock:
        _process["index"] = int(index)
        _process["count"] = int(count)
        _process["pid"] = os.getpid()


def process_info() -> Tuple[int, int]:
    """``(process_index, process_count)`` as recorded by the bootstrap."""
    with _lock:
        return _process["index"], _process["count"]


def record_clock_anchor(anchor_ns: int, anchors_ns: Sequence[int]) -> None:
    """Install the boot-time clock-offset handshake result: ``anchor_ns`` is
    THIS process's ``time.monotonic_ns()`` sampled right after the global
    barrier, ``anchors_ns`` the allgathered anchors of every process
    (index-ordered). From here on, aligned time is
    ``(t_monotonic_ns - anchor_ns) / 1e3`` microseconds — t=0 is the barrier
    instant on every rank, to within the barrier's exit skew."""
    with _lock:
        _clock["anchor_ns"] = int(anchor_ns)
        _clock["anchors_ns"] = [int(a) for a in anchors_ns]
        _clock["aligned"] = True


def clock_info() -> Dict[str, Any]:
    """The current clock-anchor state (``anchor_ns`` / ``anchors_ns`` /
    ``aligned``)."""
    with _lock:
        return dict(_clock)


def _clock_payload() -> Dict[str, Any]:
    now_ns = time.monotonic_ns()
    with _lock:
        payload = {
            "anchor_monotonic_ns": _clock["anchor_ns"],
            "anchors_monotonic_ns": (
                list(_clock["anchors_ns"]) if _clock["anchors_ns"] else None
            ),
            "aligned": bool(_clock["aligned"]),
            "dumped_at_monotonic_ns": now_ns,
        }
    if profiler is not None:
        # The profiler timeline's origin expressed on the monotonic clock:
        # perf_counter and monotonic are the same clock source here, so the
        # difference sampled once converts any profiler timestamp to a
        # monotonic instant (and from there, via the anchor, to aligned time).
        payload["profiler_origin_monotonic_us"] = now_ns / 1e3 - profiler._now_us()
    return payload


# ------------------------------------------------------------------ collective windows
@contextlib.contextmanager
def collective_window(site: str):
    """Time one collective (or layout-op) invocation at ``site`` into the
    window log and the per-site duration histogram. The sequence number is
    taken at ENTER and counts per (site, ambient profiler request tag), so
    two ranks' k-th ``comm.psum`` *of the same request* carry the same
    ``(site, tag, seq)`` identity and the merger can compute their cross-rank
    enter skew — correct even when concurrent tenants interleave in a
    different order on each process. Callers gate on
    ``telemetry._collecting`` (the communication chokepoint does); timing is
    host-side only — nothing enters the traced body."""
    site = str(site)
    tag = None
    if profiler is not None and hasattr(profiler, "current_request_tag"):
        tag = profiler.current_request_tag()
    with _lock:
        key = (site, tag)
        seq = _site_seq.get(key, 0) + 1
        _site_seq[key] = seq
    t0 = time.monotonic_ns()
    try:
        yield
    finally:
        t1 = time.monotonic_ns()
        with _lock:
            _windows.append((site, seq, t0, t1, tag))
            h = _durations.get(site)
            if h is None and profiler is not None:
                h = _durations[site] = profiler.Histogram()
            if h is not None:
                h.observe((t1 - t0) / 1e9)


def windows() -> List[tuple]:
    """The recorded collective windows
    ``(site, seq, enter_ns, exit_ns, request_tag)``."""
    with _lock:
        return list(_windows)


def duration_snapshots() -> Dict[str, dict]:
    """Per-site collective duration histogram snapshots."""
    with _lock:
        return {site: h.snapshot() for site, h in sorted(_durations.items())}


# ------------------------------------------------------------------ flight recorder
def flight_record(source: str, site: str, detail: str = "",
                  kind: str = "") -> None:
    """Append one event to the flight ring (always-on; the ring is bounded so
    this can never become the leak it exists to diagnose). ``source`` names
    the feeding subsystem (``resilience`` / ``fallback`` / ``lifecycle`` /
    ``manual``), ``kind`` the event type within it."""
    rec = {
        "t": _utcnow(),
        "t_mono_us": time.monotonic_ns() / 1e3,
        "source": str(source),
        "kind": str(kind),
        "site": str(site),
        "detail": str(detail),
    }
    with _lock:
        _flight.append(rec)


def flight_events() -> List[dict]:
    """The current flight-ring contents, oldest first."""
    with _lock:
        return list(_flight)


def flight_dir() -> str:
    """Where flight dumps land: ``HEAT_TPU_FLIGHT_DIR`` or a per-host temp
    default. Read at dump time (dumps are cold paths; tests repoint the env
    var without reloads)."""
    return os.environ.get("HEAT_TPU_FLIGHT_DIR") or os.path.join(
        tempfile.gettempdir(), "heat-tpu-flight"
    )


def flight_dump(reason: str, path: Optional[str] = None) -> Optional[str]:
    """Write the flight ring (plus process/clock identity and the resilience
    snapshot) as one post-mortem JSON artifact; returns the path, or None when
    the directory is unwritable (counted via ``diagnostics.record_fallback``
    — a failed post-mortem must not raise out of a failure path that is
    already unwinding)."""
    with _lock:
        payload = {
            "schema": FLIGHT_SCHEMA,
            "generated_at": _utcnow(),
            "reason": str(reason),
            "process": dict(_process),
            "events": list(_flight),
            "prior_dumps": list(_flight_dumps),
        }
        seq = next(_flight_seq)
        index = _process["index"]
    payload["clock"] = _clock_payload()
    if resilience is not None:
        payload["resilience"] = resilience.resilience_stats()
    if path is None:
        safe = "".join(c if c.isalnum() or c in "-_" else "-" for c in str(reason))
        path = os.path.join(
            flight_dir(), f"flight-p{index}-{seq:03d}-{safe[:48]}.json"
        )
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        _atomic_json(path, payload, "telemetry.flight")
    except OSError as exc:
        if diagnostics is not None:
            diagnostics.record_fallback("telemetry.flight", repr(exc))
        return None
    with _lock:
        _flight_dumps.append(path)
    return path


def flight_dumps() -> List[str]:
    """Paths of every flight dump this process has written."""
    with _lock:
        return list(_flight_dumps)


def _maybe_auto_dump(trigger: str) -> None:
    """Schedule an automatic flight dump for a typed failure ``trigger`` —
    rate-limited (one per trigger per 5 s, :data:`_MAX_AUTO_DUMPS` per
    process), skipped while a dump is already running on this thread, and
    executed on a daemon thread so no caller lock ever waits on the disk."""
    if getattr(_in_flight_dump, "active", False):
        return
    if os.environ.get("HEAT_TPU_FLIGHT") == "0":
        return
    global _auto_dumps
    now = time.monotonic_ns()
    with _lock:
        if _auto_dumps >= _MAX_AUTO_DUMPS:
            return
        last = _last_auto_ns.get(trigger)
        if last is not None and now - last < _AUTO_MIN_INTERVAL_NS:
            return
        _last_auto_ns[trigger] = now
        _auto_dumps += 1
    try:
        threading.Thread(
            target=_auto_dump_thread, args=(trigger,),
            name="heat-tpu-flight-dump", daemon=True,
        ).start()
    except RuntimeError:
        # thread creation can fail once interpreter finalization has begun
        # (the atexit-drain path): a lost post-mortem must never propagate
        # into the failure path that triggered it — refund and move on
        _refund_auto_dump()


def _refund_auto_dump() -> None:
    # the reservation bought nothing: give it back so a later real failure
    # can still produce a post-mortem — the per-trigger rate limit still
    # spaces the retries
    global _auto_dumps
    with _lock:
        _auto_dumps -= 1


def _auto_dump_thread(trigger: str) -> None:
    _in_flight_dump.active = True
    written = None
    try:
        written = flight_dump(trigger)
    except Exception:  # ht: ignore[silent-except] -- accounted by the refund below + the flight ring already holds the triggering event; a dump-thread crash must not kill the process or stay charged against the budget
        pass
    finally:
        _in_flight_dump.active = False
    if written is None:
        _refund_auto_dump()


def _on_resilience_event(site: str, kind: str, detail: str) -> None:
    """The diagnostics ``_resilience_tee``: every resilience event enters the
    flight ring; the typed failure kinds (and breaker transitions INTO open)
    additionally trigger an automatic post-mortem dump."""
    if getattr(_in_flight_dump, "active", False):
        return  # a dump's own retry/exhaustion events must not recurse
    flight_record("resilience", site, detail, kind=kind)
    if kind in _AUTO_DUMP_KINDS:
        _maybe_auto_dump(kind)
    elif kind == "breaker" and "->open" in detail.split(":", 1)[0]:
        _maybe_auto_dump("breaker-open")


def _on_fallback_event(site: str, reason: str) -> None:
    """The diagnostics ``_fallback_tee``: eager-path fallbacks enter the ring
    (context for the post-mortem) but do not trigger dumps themselves."""
    if getattr(_in_flight_dump, "active", False):
        return
    flight_record("fallback", site, reason, kind="fallback")


# ------------------------------------------------------------------ shard dump
def shard_payload() -> dict:
    """This process's full telemetry shard as a JSON-able dict (see the
    module header for the section inventory)."""
    payload: Dict[str, Any] = {
        "schema": SCHEMA,
        "generated_at": _utcnow(),
    }
    with _lock:
        payload["process"] = dict(_process)
        payload["collectives"] = {
            "windows": [list(w) for w in _windows],
            "windows_cap": _windows.maxlen,
            "durations": {
                site: h.snapshot() for site, h in sorted(_durations.items())
            },
        }
        payload["flight"] = {
            "events": list(_flight),
            "dumps": list(_flight_dumps),
        }
    payload["clock"] = _clock_payload()
    payload["diagnostics"] = diagnostics.report() if diagnostics is not None else {}
    payload["trace"] = (
        profiler.trace_snapshot()
        if profiler is not None and hasattr(profiler, "trace_snapshot")
        else {}
    )
    return payload


def dump_shard(directory: str) -> str:
    """Write this process's telemetry shard to
    ``<directory>/telemetry-shard-pNNNN.json`` (atomically, so a crash
    mid-dump can never leave a torn shard for :func:`merge` to choke on).
    Returns the path."""
    payload = shard_payload()
    index = payload["process"]["index"]
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"{SHARD_PREFIX}p{index:04d}.json")
    return _atomic_json(path, payload, "telemetry.shard", indent=None)


# ------------------------------------------------------------------ merge
def load_shards(directory: str) -> List[dict]:
    """Read every ``telemetry-shard-*.json`` under ``directory``, schema- and
    identity-checked, ordered by process index. Raises ``ValueError`` on a
    wrong schema or a duplicated process index (two jobs dumped into one
    directory)."""
    shards: List[dict] = []
    try:
        names = sorted(os.listdir(directory))
    except FileNotFoundError:
        return []
    for name in names:
        if not (name.startswith(SHARD_PREFIX) and name.endswith(".json")):
            continue
        path = os.path.join(directory, name)
        with open(path) as f:
            shard = json.load(f)
        if shard.get("schema") != SCHEMA:
            raise ValueError(
                f"{path}: schema {shard.get('schema')!r} is not {SCHEMA!r}"
            )
        shards.append(shard)
    shards.sort(key=lambda s: s["process"]["index"])
    seen: Dict[int, str] = {}
    for shard in shards:
        idx = shard["process"]["index"]
        if idx in seen:
            raise ValueError(
                f"duplicate telemetry shard for process {idx} "
                f"(two jobs dumped into one directory?)"
            )
        seen[idx] = shard.get("generated_at", "")
    return shards


def _resolve_shards(shards: Union[str, Sequence[dict]]) -> List[dict]:
    if isinstance(shards, str):
        return load_shards(shards)
    shards = sorted(shards, key=lambda s: s["process"]["index"])
    seen: set = set()
    for shard in shards:
        idx = shard["process"]["index"]
        if idx in seen:
            # same contract as load_shards: double-counting a rank would
            # silently corrupt every merged sum
            raise ValueError(f"duplicate telemetry shard for process {idx}")
        seen.add(idx)
    return shards


def _clocks_aligned(shards: List[dict]) -> bool:
    """Whether cross-rank timestamp comparisons are meaningful: every shard
    carries a handshake anchor. (A single shard is trivially 'aligned' with
    itself — there is nothing cross-rank to compare.)"""
    return len(shards) == 1 or all(s["clock"].get("aligned") for s in shards)


def _hist_from(snap: dict):
    return profiler.Histogram.from_snapshot(snap) if profiler is not None else None


#: executor-stat keys that are PEAKS or point-in-time gauges: summing them
#: across ranks would fabricate a global value no process ever saw (four
#: ranks peaking at depth 10 did NOT make a depth-40 queue) — they max-fold.
#: ``sched_shards`` is a per-process CONFIGURATION value, not a tally: ranks
#: agree on it in any sane deployment, and max-folding keeps a mixed fleet
#: readable instead of summing shard counts into nonsense. The ``per_shard``
#: list is per-process structure — the merge keeps the first shard's copy
#: (cross-rank per-shard detail lives in the per_process section).
_MAX_FOLD_KEYS = frozenset({"queue_depth_peak", "queue_depth", "sched_shards"})


def _merge_numeric_tree(dst: Dict[str, Any], src: Dict[str, Any]) -> None:
    """Fold ``src`` into ``dst``: counters sum, peak/gauge keys (and any
    ``*_peak``) take the max, nested dicts recurse, anything else (labels,
    bools, lists) is kept from the first shard that had it."""
    for key, val in src.items():
        if isinstance(val, bool):
            dst.setdefault(key, val)
        elif isinstance(val, (int, float)):
            cur = dst.get(key, 0)
            if not (isinstance(cur, (int, float)) and not isinstance(cur, bool)):
                cur = 0
            if key in _MAX_FOLD_KEYS or key.endswith("_peak"):
                dst[key] = max(cur, val)
            else:
                dst[key] = cur + val
        elif isinstance(val, dict):
            sub = dst.setdefault(key, {})
            if isinstance(sub, dict):
                _merge_numeric_tree(sub, val)
        else:
            dst.setdefault(key, val)


def _window_key(win) -> Tuple[str, Optional[str], int]:
    """The cross-rank matching identity of one window record:
    ``(site, request_tag, seq)`` — tolerant of pre-tag 4-tuple fixtures."""
    tag = win[4] if len(win) > 4 else None
    return (str(win[0]), tag, int(win[1]))


def _aligned_windows(shard: dict) -> Dict[tuple, Tuple[float, float]]:
    """``{(site, tag, seq): (enter_us, exit_us)}`` on the aligned clock."""
    anchor_us = shard["clock"]["anchor_monotonic_ns"] / 1e3
    out: Dict[tuple, Tuple[float, float]] = {}
    for win in shard.get("collectives", {}).get("windows", ()):
        out[_window_key(win)] = (
            win[2] / 1e3 - anchor_us, win[3] / 1e3 - anchor_us
        )
    return out


def _compute_skew(shards: List[dict]) -> dict:
    """Cross-rank skew per collective sequence number, plus the straggler
    scoreboard. A collective participates only when >= 2 shards recorded its
    (site, seq) — single-rank windows have no skew to measure. When the
    clocks are NOT aligned (a skipped or degraded handshake), cross-rank
    enter deltas would include arbitrary per-process boot offsets — the
    result is marked invalid and carries NO attribution rather than a
    confidently-named phantom straggler."""
    if not _clocks_aligned(shards):
        return {
            "valid": False,
            "reason": "clock handshake missing or degraded on >=1 shard: "
                      "cross-rank enter times are not comparable",
            "collectives_measured": 0,
            "sites": {},
            "scoreboard": {},
            "slowest_rank": None,
        }
    per_shard = {s["process"]["index"]: _aligned_windows(s) for s in shards}
    groups: Dict[tuple, Dict[int, Tuple[float, float]]] = {}
    for idx, wins in per_shard.items():
        for key, span in wins.items():
            groups.setdefault(key, {})[idx] = span
    sites: Dict[str, dict] = {}
    scoreboard: Dict[int, dict] = {
        s["process"]["index"]: {
            "straggler_count": 0, "total_skew_us": 0.0,
            "worst_skew_us": 0.0, "worst_site": None, "worst_seq": None,
        }
        for s in shards
    }
    measured = 0
    for (site, _tag, seq), spans in groups.items():
        if len(spans) < 2:
            continue
        measured += 1
        enters = {idx: span[0] for idx, span in spans.items()}
        lo, hi = min(enters.values()), max(enters.values())
        skew_us = hi - lo
        straggler = max(enters, key=lambda i: (enters[i], i))
        entry = sites.get(site)
        if entry is None:
            entry = sites[site] = {
                "collectives": 0, "max_skew_us": 0.0, "max_skew_seq": None,
                "max_skew_rank": None, "straggler_counts": {}, "_hist": (
                    profiler.Histogram() if profiler is not None else None
                ),
            }
        entry["collectives"] += 1
        if skew_us >= entry["max_skew_us"]:
            entry["max_skew_us"] = skew_us
            entry["max_skew_seq"] = seq
            entry["max_skew_rank"] = straggler
        entry["straggler_counts"][straggler] = (
            entry["straggler_counts"].get(straggler, 0) + 1
        )
        if entry["_hist"] is not None:
            entry["_hist"].observe(skew_us / 1e6)
        board = scoreboard[straggler]
        board["straggler_count"] += 1
        board["total_skew_us"] += skew_us
        if skew_us > board["worst_skew_us"]:
            board["worst_skew_us"] = skew_us
            board["worst_site"] = site
            board["worst_seq"] = seq
    for site, entry in sites.items():
        hist = entry.pop("_hist")
        entry["histogram"] = hist.snapshot() if hist is not None else None
        entry["max_skew_us"] = round(entry["max_skew_us"], 3)
        # "slowest" at a site = the rank that straggled where it MATTERED:
        # the rank behind the worst skew (a count-based mode would let many
        # µs-noise wins outvote one catastrophic stall)
        entry["slowest_rank"] = entry.pop("max_skew_rank")
        entry["straggler_counts"] = {
            str(k): v for k, v in sorted(entry["straggler_counts"].items())
        }
    for board in scoreboard.values():
        board["total_skew_us"] = round(board["total_skew_us"], 3)
        board["worst_skew_us"] = round(board["worst_skew_us"], 3)
    slowest = None
    if measured:
        # overall slowest rank: the one that accumulated the most skew, with
        # straggle count as the tiebreak
        slowest = max(
            scoreboard,
            key=lambda i: (scoreboard[i]["total_skew_us"],
                           scoreboard[i]["straggler_count"], i),
        )
    return {
        "valid": True,
        "collectives_measured": measured,
        "sites": {k: sites[k] for k in sorted(sites)},
        "scoreboard": {str(k): scoreboard[k] for k in sorted(scoreboard)},
        "slowest_rank": slowest,
    }


def _site_op(site: str) -> str:
    """``comm.psum`` -> ``psum`` (the ``skew.<op>`` histogram names)."""
    return site.rsplit(".", 1)[-1]


_MAX_SEQUENCE_DIVERGENCES = 16


def _sequence_check(shards: List[dict]) -> dict:
    """Cross-rank collective-sequence consistency — the runtime twin of the
    static ``spmd-divergent-collective`` rule. Every rank's windows, ordered
    by enter time and grouped by the ambient request tag (SPMD symmetry is
    per REQUEST: concurrent tenants may interleave differently per process,
    but one request's guarded calls must be the same ordered site list on
    every rank), are compared element-wise against the lowest rank. The
    first mismatch per (tag, rank) is reported with the diverging rank, the
    index into the sequence, and the expected/actual sites — the exact hang
    signature a rank-dependent branch around a collective produces. Clock
    alignment is NOT required: only per-rank local ordering is compared.

    A shard whose bounded window ring overflowed (>= its recorded capacity)
    dropped its oldest windows, so sequence comparison would report phantom
    divergence — the check marks itself invalid instead."""
    if len(shards) < 2:
        return {
            "valid": True, "consistent": True, "tags_checked": 0,
            "windows_checked": 0, "divergences": [],
        }
    cap = min(
        int(s.get("collectives", {}).get("windows_cap") or _MAX_WINDOWS)
        for s in shards
    )
    overflowed = [
        s["process"]["index"] for s in shards
        if len(s.get("collectives", {}).get("windows", ())) >= cap
    ]
    if overflowed:
        return {
            "valid": False,
            "reason": f"window ring overflowed on rank(s) {overflowed}: "
                      "oldest windows were dropped, sequences are not "
                      "comparable (raise HEAT_TPU_TELEMETRY_WINDOWS)",
            "consistent": True, "tags_checked": 0, "windows_checked": 0,
            "divergences": [],
        }
    per_rank: Dict[int, Dict[Optional[str], List[str]]] = {}
    windows_checked = 0
    for shard in shards:
        idx = shard["process"]["index"]
        wins = sorted(
            shard.get("collectives", {}).get("windows", ()),
            key=lambda w: (w[2], w[1]),
        )
        tagmap: Dict[Optional[str], List[str]] = {}
        for win in wins:
            tag = win[4] if len(win) > 4 else None
            tagmap.setdefault(tag, []).append(str(win[0]))
            windows_checked += 1
        per_rank[idx] = tagmap
    ranks = sorted(per_rank)
    reference = ranks[0]
    tags = sorted(
        {t for m in per_rank.values() for t in m},
        key=lambda t: (t is not None, t or ""),
    )
    divergences: List[dict] = []
    for tag in tags:
        ref_seq = per_rank[reference].get(tag, [])
        for rank in ranks[1:]:
            seq = per_rank[rank].get(tag, [])
            if seq == ref_seq:
                continue
            n = min(len(seq), len(ref_seq))
            at = next(
                (i for i in range(n) if seq[i] != ref_seq[i]), n
            )
            divergences.append({
                "tag": tag,
                "rank": rank,
                "reference_rank": reference,
                "index": at,
                "expected": ref_seq[at] if at < len(ref_seq) else None,
                "actual": seq[at] if at < len(seq) else None,
                "expected_len": len(ref_seq),
                "actual_len": len(seq),
            })
            if len(divergences) >= _MAX_SEQUENCE_DIVERGENCES:
                break
        if len(divergences) >= _MAX_SEQUENCE_DIVERGENCES:
            break
    return {
        "valid": True,
        "consistent": not divergences,
        "tags_checked": len(tags),
        "windows_checked": windows_checked,
        "divergences": divergences,
    }


def merge(shards: Union[str, Sequence[dict]]) -> dict:
    """Fold N telemetry shards (a directory or loaded dicts) into ONE global
    report: exact counter sums, folded spans and collective tallies, merged
    latency histograms (the associative bucket fold), summed executor /
    lifecycle stats, cross-rank ``skew.<op>`` histograms with the straggler
    scoreboard, the collective-sequence consistency section (``sequence``:
    per-tag ordered site lists compared across ranks, first divergence per
    rank named), and per-process breakdowns. Raises ``ValueError`` on zero
    shards or inconsistent process counts."""
    shards = _resolve_shards(shards)
    if not shards:
        raise ValueError("no telemetry shards to merge")
    counts = {s["process"].get("count") for s in shards}
    if len(counts) > 1:
        raise ValueError(
            f"shards disagree on process count ({sorted(counts)}): "
            "they are not from one job"
        )
    counters: Dict[str, float] = {}
    spans: Dict[str, Dict[str, float]] = {}
    collectives: Dict[Tuple[str, str, int], Dict[str, int]] = {}
    hists: Dict[str, Any] = {}
    raw_hists: Dict[str, List[dict]] = {}
    executor: Dict[str, Any] = {}
    processes: Dict[str, dict] = {}
    aligned = all(s["clock"].get("aligned") for s in shards) and len(shards) > 1
    for shard in shards:
        idx = shard["process"]["index"]
        diag = shard.get("diagnostics") or {}
        for name, val in (diag.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + val
        for name, agg in (diag.get("spans") or {}).items():
            cur = spans.setdefault(
                name, {"count": 0, "total_s": 0.0, "max_s": 0.0}
            )
            cur["count"] += agg.get("count", 0)
            cur["total_s"] += agg.get("total_s", 0.0)
            cur["max_s"] = max(cur["max_s"], agg.get("max_s", 0.0))
        for rec in diag.get("collectives") or ():
            key = (rec["op"], str(rec["axis"]), int(rec["participants"]))
            cur = collectives.setdefault(key, {"count": 0, "bytes": 0})
            cur["count"] += rec["count"]
            cur["bytes"] += rec["bytes"]
        prof = diag.get("profiler") or {}
        for name, snap in (prof.get("histograms") or {}).items():
            if profiler is not None:
                h = hists.get(name)
                if h is None:
                    hists[name] = _hist_from(snap)
                else:
                    h.merge(_hist_from(snap))
            else:  # degraded standalone merge: keep the raw snapshots
                raw_hists.setdefault(name, []).append(snap)
        if isinstance(diag.get("executor"), dict):
            _merge_numeric_tree(executor, diag["executor"])
        processes[str(idx)] = {
            "host": shard["process"].get("host"),
            "pid": shard["process"].get("pid"),
            "generated_at": shard.get("generated_at"),
            "counters": dict((diag.get("counters") or {})),
            "requests_total": prof.get("requests_total", 0),
            "flight_events": len(shard.get("flight", {}).get("events", ())),
            "flight_dumps": list(shard.get("flight", {}).get("dumps", ())),
            "collective_windows": len(
                shard.get("collectives", {}).get("windows", ())
            ),
        }
    skew = _compute_skew(shards)
    sequence = _sequence_check(shards)
    for site, entry in skew["sites"].items():
        if entry.get("histogram") is not None and profiler is not None:
            hists[f"skew.{_site_op(site)}"] = _hist_from(entry["histogram"])
    report = {
        "schema": MERGED_SCHEMA,
        "generated_at": _utcnow(),
        "processes": len(shards),
        "process_count": shards[0]["process"].get("count"),
        "clock": {
            "aligned": aligned,
            "anchors_monotonic_ns": shards[0]["clock"].get("anchors_monotonic_ns"),
        },
        "counters": {k: counters[k] for k in sorted(counters)},
        "spans": {k: spans[k] for k in sorted(spans)},
        "collectives": [
            {"op": op, "axis": axis, "participants": parts,
             "count": agg["count"], "bytes": agg["bytes"]}
            for (op, axis, parts), agg in sorted(collectives.items())
        ],
        "histograms": (
            {name: hists[name].snapshot() for name in sorted(hists)}
            if profiler is not None
            else {name: {"unmerged": snaps} for name, snaps in sorted(raw_hists.items())}
        ),
        "executor": executor,
        "skew": skew,
        "sequence": sequence,
        "per_process": processes,
    }
    return report


# ------------------------------------------------------------------ merged trace
def _remap_rids(trace: dict) -> dict:
    """Keep every request pid inside its process's :data:`PID_STRIDE` range.

    Profiler request ids come from an unbounded counter (only the request
    TABLE is capped), so a long-lived serving process can exceed the stride
    and bleed into the next process's pid range. When any rid is that large,
    renumber them densely (1..k, insertion order — k is bounded by the
    capped table/slice stores) and keep the original id visible in the tag."""
    rids = [e["id"] for e in trace.get("requests", ())]
    rids += [s[0] for s in trace.get("slices", ()) if s[0] is not None]
    if not rids or max(rids) < PID_STRIDE - 1:
        return trace
    mapping: Dict[int, int] = {}
    for rid in rids:
        if rid not in mapping:
            mapping[rid] = len(mapping) + 1
    return {
        "requests": [
            {**e, "id": mapping[e["id"]], "tag": f"{e['tag']} (rid {e['id']})"}
            for e in trace.get("requests", ())
        ],
        "slices": [
            [mapping.get(s[0]), *s[1:]] if s[0] is not None else list(s)
            for s in trace.get("slices", ())
        ],
        "counter_events": list(trace.get("counter_events", ())),
    }


def merged_trace(shards: Union[str, Sequence[dict]], *,
                 max_flows: int = 64) -> dict:
    """ONE Chrome/Perfetto trace for the whole job: each process's profiler
    timeline re-emitted into its own pid range (``p<i>/…`` track groups, so
    request tracks AND counter tracks from different ranks never collide or
    sum), timestamps aligned onto the handshake clock and rebased so the
    earliest event sits at t=0, a per-process ``collectives`` track built from
    the telemetry windows, and flow arrows linking the ``max_flows``
    worst-skew collectives across the process tracks."""
    shards = _resolve_shards(shards)
    if not shards:
        raise ValueError("no telemetry shards to merge")
    # pass 1: per-shard profiler->aligned shift and the global rebase
    shifts: Dict[int, float] = {}
    traces: Dict[int, dict] = {}
    min_ts = math.inf
    for shard in shards:
        idx = shard["process"]["index"]
        clock = shard["clock"]
        anchor_us = clock["anchor_monotonic_ns"] / 1e3
        origin_us = clock.get("profiler_origin_monotonic_us")
        shift = (origin_us - anchor_us) if origin_us is not None else -anchor_us
        shifts[idx] = shift
        trace = traces[idx] = _remap_rids(shard.get("trace") or {})
        for s in trace.get("slices", ()):
            min_ts = min(min_ts, s[4] + shift)
        for c in trace.get("counter_events", ()):
            min_ts = min(min_ts, c[1] + shift)
        for win in shard.get("collectives", {}).get("windows", ()):
            min_ts = min(min_ts, win[2] / 1e3 - anchor_us)
    rebase = -min_ts if min_ts is not math.inf and min_ts < 0 else 0.0
    events: List[dict] = []
    flow_groups: Dict[tuple, Dict[int, float]] = {}
    for shard in shards:
        idx = shard["process"]["index"]
        base = (idx + 1) * PID_STRIDE
        label = f"p{idx}"
        anchor_us = shard["clock"]["anchor_monotonic_ns"] / 1e3
        if profiler is not None and traces.get(idx):
            events.extend(profiler.trace_events(
                traces[idx], pid_offset=base,
                ts_shift_us=shifts[idx] + rebase, process_label=label,
            ))
        wins = shard.get("collectives", {}).get("windows", ())
        if wins:
            cpid = base + PID_STRIDE - 1
            events.append({"name": "process_name", "ph": "M", "pid": cpid,
                           "tid": 0, "args": {"name": f"{label}/collectives"}})
            events.append({"name": "process_sort_index", "ph": "M",
                           "pid": cpid, "tid": 0, "args": {"sort_index": cpid}})
            for win in wins:
                site, seq, t0, t1 = win[0], win[1], win[2], win[3]
                ts = t0 / 1e3 - anchor_us + rebase
                dur = max((t1 - t0) / 1e3, 1e-3)
                events.append({
                    "name": str(site), "cat": "collective", "ph": "X",
                    "ts": round(ts, 3), "dur": round(dur, 3),
                    "pid": cpid, "tid": 0, "args": {"seq": int(seq)},
                })
                flow_groups.setdefault(_window_key(win), {})[idx] = ts
    # flow arrows for the worst skews: same collective, every process track.
    # Without aligned clocks a "worst skew" ranking would order arbitrary
    # boot offsets — emit no arrows at all (the per-process tracks stay,
    # each self-consistent on its own clock).
    if not _clocks_aligned(shards):
        flow_groups.clear()
    ranked = sorted(
        ((max(g.values()) - min(g.values()), key, g)
         for key, g in flow_groups.items() if len(g) >= 2),
        key=lambda item: -item[0],
    )[:max(0, max_flows)]
    for flow_id, (_, (site, _tag, seq), group) in enumerate(ranked, start=1):
        members = sorted(group.items())
        for j, (idx, ts) in enumerate(members):
            ph = "s" if j == 0 else ("f" if j == len(members) - 1 else "t")
            ev = {
                "name": site, "cat": "collective-skew", "ph": ph,
                "id": flow_id, "pid": (idx + 1) * PID_STRIDE + PID_STRIDE - 1,
                "tid": 0, "ts": round(ts + 0.0005, 3), "args": {"seq": seq},
            }
            if ph == "f":
                ev["bp"] = "e"  # bind to the enclosing slice, not its end
            events.append(ev)
    return {
        "schema": TRACE_SCHEMA,
        "displayTimeUnit": "ms",
        "traceEvents": events,
    }


# ------------------------------------------------------------------ artifact writers
def _atomic_json(path: str, payload: dict, site: str, *, indent=2,
                 sort_keys: bool = True) -> str:
    """The one JSON-artifact writer: through ``resilience.atomic_write`` when
    the resilience module is present (a crash mid-dump leaves the previous
    artifact, never a torn one), plain otherwise (standalone file-path
    loads). Every telemetry artifact — shards, flight post-mortems, merged
    reports/traces — routes here."""
    def _write(target: str) -> None:
        with open(target, "w") as f:
            json.dump(payload, f, indent=indent, sort_keys=sort_keys)
            f.write("\n")

    if resilience is not None:
        resilience.atomic_write(path, _write, site=site)
    else:
        _write(path)
    return path


def write_report(report: dict, path: str) -> str:
    """Write a merged report atomically; returns ``path``."""
    return _atomic_json(path, report, "telemetry.report")


def write_trace(trace: dict, path: str) -> str:
    """Write a merged trace atomically; returns ``path``."""
    return _atomic_json(path, trace, "telemetry.trace",
                        indent=None, sort_keys=False)


# ------------------------------------------------------------------ ops beats
#: filename prefix of per-rank ops-beat files (must match ``ops.BEAT_PREFIX``;
#: duplicated here because a standalone file-path load of this module has no
#: package to import ops from — tests/test_ops.py asserts the two agree)
OPS_BEAT_PREFIX = "ops-beat-r"


def load_ops_beats(directory: str) -> Dict[str, dict]:
    """Read every ``ops-beat-r<rank>.json`` under ``directory`` into
    ``{rank: beat}`` — one LATEST beat per rank (each write replaces the
    rank's file atomically, so there is never more than one). Unparseable
    files raise: a torn beat must not pass silently as a healthy rank."""
    out: Dict[str, dict] = {}
    for name in sorted(os.listdir(directory)):
        if not (name.startswith(OPS_BEAT_PREFIX) and name.endswith(".json")):
            continue
        path = os.path.join(directory, name)
        with open(path) as f:
            beat = json.load(f)
        rank = str(beat.get("rank", name[len(OPS_BEAT_PREFIX):-len(".json")]))
        out[rank] = beat
    return out


def _fold_ops_section(beats: Dict[str, dict]) -> dict:
    """The ``ops`` section of a merged report (``merge --from-ops``): the
    per-rank beats plus sums of the WINDOWED rates.

    Disjointness rule (why folding live scrapes alongside shards cannot
    double-count): the shard counters merged above are CUMULATIVE since
    process start, while every ops number is a windowed delta/rate over the
    last sample interval — the two live in different units over different
    spans, so they land in disjoint report sections (``counters`` /
    ``executor`` vs ``ops``) and are never added together. One beat per rank
    (latest-wins files), so the cross-rank sums here are exact for the
    beats' own windows.

    The per-tenant COST cells (``device_s`` / ``flops`` /
    ``collective_bytes`` from the forensics meters) are the one cumulative
    family riding the beats; they still obey the disjointness rule — they
    fold into their own ``tenant_cost`` sub-section here (exact cross-rank
    sums of per-rank cumulative meters), never into the windowed totals and
    never into the shard ``counters`` section."""
    ranks = {r: beats[r] for r in sorted(beats, key=lambda x: (len(x), x))}
    totals = {"rps": 0.0, "shed_rate": 0.0, "queue_depth": 0}
    alerts = []
    tenant_cost: Dict[str, Dict[str, float]] = {}
    for rank, beat in ranks.items():
        totals["rps"] += beat.get("rps") or 0.0
        totals["shed_rate"] += beat.get("shed_rate") or 0.0
        totals["queue_depth"] += beat.get("queue_depth") or 0
        for tenant, cell in (beat.get("tenants") or {}).items():
            if cell.get("alert"):
                alerts.append({"rank": rank, "tenant": tenant,
                               "burn_1m": cell.get("burn_1m")})
            if any(cell.get(k) for k in
                   ("device_s", "flops", "collective_bytes")):
                cost = tenant_cost.setdefault(
                    tenant, {"device_s": 0.0, "flops": 0.0,
                             "collective_bytes": 0.0})
                cost["device_s"] += cell.get("device_s") or 0.0
                cost["flops"] += cell.get("flops") or 0.0
                cost["collective_bytes"] += cell.get("collective_bytes") or 0.0
    return {
        "schema": "heat-tpu-ops-merged/1",
        "ranks": ranks,
        "totals": {k: round(v, 6) if isinstance(v, float) else v
                   for k, v in totals.items()},
        "alerts": alerts,
        "tenant_cost": {t: {k: round(v, 6) for k, v in c.items()}
                        for t, c in sorted(tenant_cost.items())},
    }


def _render_top(ranks: Dict[str, dict]) -> str:
    """The ``telemetry top`` table: one row per rank, nested rows per tenant
    with SLO state — the terminal view of :func:`heat_tpu.core.ops
    .cluster_snapshot`."""
    lines = [f"{'RANK':>4}  {'RPS':>8}  {'SHED/S':>8}  {'HIT%':>6}  "
             f"{'DEPTH':>5}  {'DRAIN':>5}  {'SEQ':>6}"]
    for rank in sorted(ranks, key=lambda r: (len(r), r)):
        beat = ranks[rank]
        hit = beat.get("cache_hit_rate")
        lines.append(
            f"{rank:>4}  {beat.get('rps') or 0.0:>8.2f}  "
            f"{beat.get('shed_rate') or 0.0:>8.2f}  "
            f"{(hit * 100 if hit is not None else float('nan')):>6.1f}  "
            f"{beat.get('queue_depth') or 0:>5d}  "
            f"{'yes' if beat.get('draining') else '-':>5}  "
            f"{beat.get('seq') or 0:>6d}")
        for tenant, cell in sorted((beat.get("tenants") or {}).items()):
            p99 = cell.get("p99_ms")
            burn = cell.get("burn_1m")
            dev = cell.get("device_s")
            lines.append(
                f"      {tenant:<16} p99 "
                f"{(f'{p99:.2f}ms' if p99 is not None else '-'):>10}  "
                f"burn1m {(f'{burn:.2f}' if burn is not None else '-'):>6}  "
                f"cost {(f'{dev:.3f}s' if dev else '-'):>9}  "
                f"{'ALERT' if cell.get('alert') else 'ok'}")
    return "\n".join(lines)


def _render_slow(shards: List[dict], tenant: Optional[str],
                 limit: int) -> Tuple[int, str]:
    """The ``telemetry slow`` view: the slowest forensic exemplars across a
    directory of shards — each with its critical path, so "why was this
    slow" is answerable from merged artifacts offline. Exemplars ride shard
    dumps inside the ``diagnostics.forensics`` provider section (written
    when the run was armed with ``HEAT_TPU_FORENSICS=1``)."""
    rows: List[Tuple[Any, dict]] = []
    for shard in shards:
        rank = (shard.get("process") or {}).get("index", "?")
        fx = (shard.get("diagnostics") or {}).get("forensics") or {}
        for t, recs in (fx.get("exemplars") or {}).items():
            if tenant is not None and t != tenant:
                continue
            rows.extend((rank, r) for r in recs)
    if not rows:
        return 1, ("no forensic exemplars in these shards — was the run "
                   "armed with HEAT_TPU_FORENSICS=1?")
    rows.sort(key=lambda pr: (-pr[1].get("total_s", 0.0),
                              pr[1].get("rid", 0)))
    lines = []
    for rank, r in rows[:max(1, limit)]:
        lines.append(
            f"#{r.get('rid')} tenant={r.get('tenant')} rank={rank} "
            f"total={r.get('total_s', 0.0) * 1e3:.2f}ms "
            f"dominant={r.get('dominant')}")
        path = " -> ".join(
            f"{leg.get('stage')} {leg.get('share', 0.0) * 100:.0f}% "
            f"({leg.get('seconds', 0.0) * 1e3:.2f}ms)"
            for leg in r.get("critical_path") or [])
        lines.append(f"    path: {path or '(empty)'}")
    return 0, "\n".join(lines)


def _top_once(directory: Optional[str]) -> Tuple[int, str]:
    """One ``top`` refresh: beats from ``--dir`` files, else the live
    cluster fold over the coordination channel."""
    if directory:
        ranks = load_ops_beats(directory)
        if not ranks:
            return 1, f"no {OPS_BEAT_PREFIX}*.json beats under {directory}"
        return 0, _render_top(ranks)
    try:
        from . import ops
    except ImportError:
        return 1, ("telemetry top needs --dir in a standalone load "
                   "(no package to reach the live ops plane through)")
    snap = ops.cluster_snapshot()
    return 0, _render_top(snap["ranks"])


# ------------------------------------------------------------------ CLI
def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m heat_tpu.telemetry merge --dir D [--out R] [--trace-out T]
    [--expect N] [--check] [--from-ops DIR]`` — fold a directory of
    per-process shards into one report (and optionally one merged trace).
    Unreadable/torn/inconsistent shards always exit non-zero. ``--expect``
    fails unless exactly N shards merged; ``--check`` (the CI gate)
    additionally requires a COMPLETE job — one shard per process recorded in
    the shards themselves — so a partial collection cannot pass as a global
    report. ``--from-ops`` folds a directory of live ops-beat files into the
    report's separate ``ops`` section (windowed rates; disjoint from the
    cumulative shard counters by construction — see ``_fold_ops_section``).

    ``python -m heat_tpu.telemetry top [--dir D] [--watch N]`` — render the
    per-rank / per-tenant live operations table: from ``ops-beat-r*.json``
    files under ``--dir``, or (no ``--dir``) from the live cluster fold over
    the jax.distributed coordination channel (``ops.cluster_snapshot``).

    ``python -m heat_tpu.telemetry slow --dir D [--limit N] [--tenant T]``
    — print the slowest forensic exemplars recorded in the shards under
    ``D`` (dumped by a run armed with ``HEAT_TPU_FORENSICS=1``), each with
    its per-stage critical path — the offline "why was this slow" view."""
    import argparse

    parser = argparse.ArgumentParser(prog="python -m heat_tpu.telemetry")
    sub = parser.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser("merge", help="fold per-process shards into one report")
    mp.add_argument("--dir", required=True, help="directory holding telemetry-shard-*.json")
    mp.add_argument("--out", help="write the merged report JSON here")
    mp.add_argument("--trace-out", help="write the merged Perfetto trace here")
    mp.add_argument("--expect", type=int, default=None,
                    help="fail unless exactly N shards merged")
    mp.add_argument("--check", action="store_true",
                    help="CI gate: also require one shard per process of the "
                    "job (a partial collection must not pass as global) AND "
                    "cross-rank collective-sequence consistency — the same "
                    "ordered site list per request tag on every rank, the "
                    "runtime twin of the static spmd-divergent-collective "
                    "rule; a divergence names the first diverging rank/site")
    mp.add_argument("--from-ops", metavar="DIR", default=None,
                    help="also fold ops-beat-r*.json live-scrape files from "
                    "DIR into the report's `ops` section (windowed "
                    "rates/deltas — disjoint from the cumulative shard "
                    "counters, so nothing is double-counted)")
    tp = sub.add_parser("top", help="render the per-rank/per-tenant live "
                        "operations table")
    tp.add_argument("--dir", default=None,
                    help="read ops-beat-r*.json files instead of the live "
                    "coordination channel")
    tp.add_argument("--watch", type=float, default=None, metavar="SECONDS",
                    help="refresh every N seconds until interrupted")
    sp = sub.add_parser("slow", help="print the slowest forensic exemplars "
                        "from a directory of telemetry shards")
    sp.add_argument("--dir", required=True,
                    help="directory holding telemetry-shard-*.json")
    sp.add_argument("--limit", type=int, default=10,
                    help="show at most N exemplars (default 10)")
    sp.add_argument("--tenant", default=None,
                    help="only this tenant's exemplars")
    args = parser.parse_args(argv)

    if args.cmd == "slow":
        try:
            shards = load_shards(args.dir)
            if not shards:
                raise ValueError(
                    f"no {SHARD_PREFIX}*.json shards under {args.dir}")
        except (ValueError, OSError, KeyError) as exc:
            print(f"telemetry slow FAILED: {type(exc).__name__}: {exc}")
            return 1
        rc, text = _render_slow(shards, args.tenant, args.limit)
        print(text)
        return rc

    if args.cmd == "top":
        try:
            while True:
                rc, text = _top_once(args.dir)
                if args.watch is not None and rc == 0:
                    print("\x1b[2J\x1b[H", end="")
                print(text)
                if args.watch is None or rc != 0:
                    return rc
                time.sleep(max(0.1, args.watch))
        except KeyboardInterrupt:
            return 0
        except OSError as exc:
            print(f"telemetry top FAILED: {type(exc).__name__}: {exc}")
            return 1

    try:
        shards = load_shards(args.dir)
        if not shards:
            raise ValueError(f"no {SHARD_PREFIX}*.json shards under {args.dir}")
        if args.expect is not None and len(shards) != args.expect:
            raise ValueError(
                f"expected {args.expect} shards, found {len(shards)}"
            )
        if args.check:
            recorded = shards[0]["process"].get("count")
            if recorded is not None and len(shards) != recorded:
                raise ValueError(
                    f"incomplete job: {len(shards)} shard(s) for a "
                    f"{recorded}-process job"
                )
        report = merge(shards)
        if args.from_ops:
            report["ops"] = _fold_ops_section(load_ops_beats(args.from_ops))
        # the trace is the expensive half (every slice re-serialised): only
        # build it when someone asked for it
        trace = merged_trace(shards) if args.trace_out else None
    except (ValueError, OSError, KeyError) as exc:
        print(f"telemetry merge FAILED: {type(exc).__name__}: {exc}")
        return 1
    if args.out:
        write_report(report, args.out)
        print(f"merged report -> {args.out}")
    if args.trace_out:
        write_trace(trace, args.trace_out)
        print(f"merged trace  -> {args.trace_out}")
    skew = report["skew"]
    sequence = report["sequence"]
    print(json.dumps({
        "shards": len(shards),
        "aligned": report["clock"]["aligned"],
        "counters": len(report["counters"]),
        "histograms": len(report["histograms"]),
        "collectives_measured": skew["collectives_measured"],
        # an invalid gate must never read as an affirmative "consistent"
        "sequence_consistent": sequence["consistent"] if sequence["valid"] else None,
        "sequence_valid": sequence["valid"],
        "slowest_rank": skew["slowest_rank"],
    }, sort_keys=True))
    if args.check and not sequence["valid"]:
        # a gate that cannot check must not pass as a gate that checked
        print(
            "telemetry merge FAILED: collective-sequence gate could not "
            f"run: {sequence.get('reason', 'unknown')}"
        )
        return 1
    if args.check and sequence["valid"] and not sequence["consistent"]:
        d = sequence["divergences"][0]
        site = d["actual"] or d["expected"]
        have = d["actual"] or "(sequence ended)"
        want = d["expected"] or "(sequence ended)"
        print(
            "telemetry merge FAILED: cross-rank collective-sequence "
            f"divergence: rank {d['rank']} diverges from rank "
            f"{d['reference_rank']} at {site} "
            f"(tag={d['tag']!r}, index {d['index']}: expected {want}, "
            f"got {have}; lengths {d['expected_len']} vs {d['actual_len']}) "
            "— a rank-dependent branch issued a different collective "
            "sequence; this job would hang on a real mesh"
        )
        return 1
    return 0


# ------------------------------------------------------------------ wiring
# Install the flight-recorder tees into diagnostics (it cannot import this
# module — that would be a cycle). Under a standalone file-path load there is
# no shared diagnostics instance, so the ring only sees explicit records.
if diagnostics is not None:
    diagnostics._resilience_tee = _on_resilience_event
    diagnostics._fallback_tee = _on_fallback_event

# Env bootstrap: collection on from the start (the multi-process CI jobs).
if os.environ.get("HEAT_TPU_TELEMETRY") == "1":
    _collecting = True

# Backend-free CLI: `python heat_tpu/core/telemetry.py merge --dir shards/`
# runs the merge as a standalone file-path load — no package import, no JAX
# backend (the `python -m heat_tpu.telemetry` spelling imports the package,
# which initialises JAX; use this form on login/tooling nodes).
if __name__ == "__main__":
    import sys as _main_sys

    _main_sys.exit(main())
