"""Tile decompositions (reference heat/core/tiling.py, 1250 LoC).

The reference uses ``SplitTiles`` to drive ``resplit_``'s tile-wise Isend/Irecv and
``SquareDiagTiles`` to schedule the tiled QR. On TPU neither is needed for data movement
(XLA owns layout changes), but the tile *views* remain useful for algorithms and for API
parity: both classes here index into the global ``jax.Array`` with the same tile grids
the reference computes from lshape maps.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import numpy as np

import jax.numpy as jnp

from .dndarray import DNDarray

__all__ = ["SplitTiles", "SquareDiagTiles"]


class SplitTiles:
    """Tiles by the canonical chunking along every axis (reference ``tiling.py:15``):
    axis ``i`` is cut at the chunk boundaries the communicator assigns to axis ``i``, so
    the grid has ``comm.size`` slots per dimension."""

    def __init__(self, arr: DNDarray):
        self.__arr = arr
        comm = arr.comm
        # tile_dims[d, r] = extent of tile r along dim d (reference tile_dims :109-177)
        dims = np.zeros((arr.ndim, comm.size), dtype=np.int64)
        for d in range(arr.ndim):
            for r in range(comm.size):
                _, lshape, _ = comm.chunk(arr.gshape, d, rank=r)
                dims[d, r] = lshape[d]
        self.__tile_dims = dims
        ends = dims.cumsum(axis=1)
        self.__tile_ends = ends
        # tile_locations[tile_index along split] = owning rank
        locs = np.arange(comm.size, dtype=np.int64)
        self.__tile_locations = locs

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def tile_dimensions(self) -> np.ndarray:
        return self.__tile_dims

    @property
    def tile_ends_g(self) -> np.ndarray:
        return self.__tile_ends

    @property
    def tile_locations(self) -> np.ndarray:
        """Owning shard of each tile along the split axis (reference ``:96``)."""
        return self.__tile_locations

    @property
    def lshape_map(self) -> np.ndarray:
        """(size, ndim) map of every shard's local shape (reference ``:145``)."""
        return self.__arr.comm.lshape_map(self.__arr.gshape, self.__arr.split)

    @staticmethod
    def set_tile_locations(split: int, tile_dims: np.ndarray, arr: DNDarray) -> np.ndarray:
        """Owning rank of each tile along ``split`` (reference ``:109``): under the
        canonical chunking, tile ``r`` along the split axis lives on shard ``r``;
        tiles along other axes are fully local, encoded as the owning rank of the
        split tile."""
        size = arr.comm.size
        shape = tuple(int(np.count_nonzero(np.asarray(tile_dims)[d])) for d in range(len(tile_dims)))
        locs = np.zeros(shape, dtype=np.int64)
        if arr.split is not None:
            idx = [np.newaxis] * len(shape)
            idx[split] = slice(None)
            locs += np.arange(shape[split], dtype=np.int64)[tuple(idx)] % size
        return locs

    def get_tile_size(self, key) -> Tuple[int, ...]:
        """Extent of the tile(s) selected by ``key`` (reference ``:283``)."""
        return tuple(
            int(
                (s.stop if s.stop is not None else self.__arr.gshape[d])
                - (s.start or 0)
            )
            for d, s in enumerate(self._tile_slices(key))
        )

    def _tile_slices(self, key) -> Tuple[slice, ...]:
        if not isinstance(key, tuple):
            key = (key,)
        slices = []
        for d in range(self.__arr.ndim):
            if d < len(key) and key[d] is not None and key[d] is not Ellipsis:
                t = int(key[d])
                start = 0 if t == 0 else int(self.__tile_ends[d, t - 1])
                end = int(self.__tile_ends[d, t])
                slices.append(slice(start, end))
            else:
                slices.append(slice(None))
        return tuple(slices)

    def __getitem__(self, key):
        """View of the requested tile of the global value (reference ``:180``)."""
        return self.__arr.larray[self._tile_slices(key)]

    def __setitem__(self, key, value) -> None:
        sl = self._tile_slices(key)
        new = self.__arr.larray.at[sl].set(jnp.asarray(value, self.__arr.larray.dtype))
        self.__arr._rebind_physical(self.__arr.comm.shard(new, self.__arr.split))


class SquareDiagTiles:
    """Tile grid with square tiles on the diagonal, the decomposition behind tiled QR
    (reference ``tiling.py:330``). ``tiles_per_proc`` splits each shard's rows into that
    many tile rows; column cuts mirror the row cuts so diagonal tiles are square."""

    def __init__(self, arr: DNDarray, tiles_per_proc: int = 2):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        if arr.ndim != 2:
            raise ValueError("SquareDiagTiles requires a 2-D DNDarray")
        if tiles_per_proc < 1:
            raise ValueError("tiles_per_proc must be >= 1")
        self.__arr = arr
        comm = arr.comm
        m, n = arr.gshape

        def _primary_cuts(extent_axis: int) -> List[int]:
            # each shard's chunk along the split axis, divided into tiles_per_proc pieces
            cuts: List[int] = []
            for r in range(comm.size if arr.split is not None else 1):
                _, lshape, _ = comm.chunk(arr.gshape, arr.split if arr.split is not None else extent_axis, rank=r)
                extent = lshape[extent_axis]
                base = extent // tiles_per_proc
                rem = extent % tiles_per_proc
                for t in range(tiles_per_proc):
                    cuts.append(base + (1 if t < rem else 0))
            cuts = [c for c in cuts if c > 0]
            return cuts or [arr.gshape[extent_axis]]

        def _mirror_cuts(primary: List[int], total: int) -> List[int]:
            # mirror the primary cuts up to `total` (square diagonal tiles), remainder
            # appended so the grid always covers the full matrix
            cuts: List[int] = []
            acc = 0
            for c in primary:
                if acc >= total:
                    break
                take = min(c, total - acc)
                cuts.append(take)
                acc += take
            if acc < total:
                cuts.append(total - acc)
            return cuts

        if arr.split == 1:
            col_cuts = _primary_cuts(1)
            row_cuts = _mirror_cuts(col_cuts, m)
        else:
            row_cuts = _primary_cuts(0)
            col_cuts = _mirror_cuts(row_cuts, n)

        self.__row_per_proc_list = [tiles_per_proc] * comm.size
        self.__tile_rows_per_process = [tiles_per_proc] * comm.size
        self.__row_inds = list(np.cumsum([0] + row_cuts))[:-1]
        self.__col_inds = list(np.cumsum([0] + col_cuts))[:-1]
        self.__row_cuts = row_cuts
        self.__col_cuts = col_cuts
        # tile_map[i, j] = owning rank of tile (i, j) (reference tile_map :772)
        tmap = np.zeros((len(row_cuts), len(col_cuts)), dtype=np.int64)
        if arr.split == 0 or arr.split is None:
            for i in range(len(row_cuts)):
                tmap[i, :] = min(i // tiles_per_proc, comm.size - 1)
        else:
            for j in range(len(col_cuts)):
                tmap[:, j] = min(j // tiles_per_proc, comm.size - 1)
        self.__tile_map = tmap

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def tile_columns(self) -> int:
        """Number of tile columns (reference ``:674``)."""
        return len(self.__col_cuts)

    @property
    def tile_rows(self) -> int:
        """Number of tile rows (reference ``:734``)."""
        return len(self.__row_cuts)

    @property
    def tile_map(self) -> np.ndarray:
        """(tile_rows, tile_columns) grid of owning ranks (reference ``:772``)."""
        return self.__tile_map

    @property
    def row_indices(self) -> List[int]:
        """Global start row of each tile row (reference ``:716``)."""
        return self.__row_inds

    @property
    def col_indices(self) -> List[int]:
        """Global start column of each tile column (reference ``:656``)."""
        return self.__col_inds

    @property
    def tile_rows_per_process(self) -> List[int]:
        return self.__tile_rows_per_process

    @property
    def tile_columns_per_process(self) -> List[int]:
        """Number of tile columns on each process (reference ``:765``): with a
        row split every process sees every tile column; with a column split each
        process owns its ``tiles_per_proc`` columns."""
        size = self.__arr.comm.size
        if self.__arr.split == 1:
            owned = [0] * size
            for j in range(self.tile_columns):
                owned[int(self.__tile_map[0, j])] += 1
            return owned
        return [self.tile_columns] * size

    @property
    def lshape_map(self) -> np.ndarray:
        """(size, 2) map of every shard's local shape (reference ``:736``)."""
        return self.__arr.comm.lshape_map(self.__arr.gshape, self.__arr.split)

    @property
    def last_diagonal_process(self) -> int:
        """Rank owning the last tile on the diagonal (reference ``:744``)."""
        k = min(self.tile_rows, self.tile_columns) - 1
        return int(self.__tile_map[k, k])

    def _normalize_key(self, key) -> Tuple:
        """Reference key forms (``:821,1017``): a bare int means a whole tile row;
        tuple entries may be ints or slices over tile indices."""
        if not isinstance(key, tuple):
            key = (key, slice(None))
        if len(key) == 1:
            key = (key[0], slice(None))
        return key

    def _span(self, part, inds: List[int], cuts: List[int]) -> Tuple[int, int]:
        """Global [start, stop) covered by an int or slice of tile indices."""
        n = len(cuts)
        if isinstance(part, slice):
            lo, hi, step = part.indices(n)
            if step != 1 or hi <= lo:
                raise ValueError(f"tile slices must be contiguous, got {part}")
        else:
            lo, hi = int(part), int(part) + 1
        return inds[lo], inds[hi - 1] + cuts[hi - 1]

    def get_start_stop(self, key) -> Tuple[int, int, int, int]:
        """(row start, row stop, col start, col stop) of the tile(s) at the global
        ``key`` (reference ``:821``); accepts a bare int (whole tile row) or
        int/slice pairs like the reference."""
        rs, cs = self._slices(key)
        return rs.start, rs.stop, cs.start, cs.stop

    def local_to_global(self, key, rank: int) -> Tuple:
        """Convert a process-local tile key to global tile indices (reference
        ``:1017``): the split axis's int index is offset by the tiles owned by
        lower ranks; slices pass through unchanged (they already span the axis)."""
        key = self._normalize_key(key)
        i, j = key
        if self.__arr.split == 1:
            off = int(np.sum(self.tile_columns_per_process[:rank]))
            return i, (j if isinstance(j, slice) else j + off)
        off = int(np.sum(self.__tile_rows_per_process[:rank]))
        return (i if isinstance(i, slice) else i + off), j

    def get_tile_size(self, key) -> Tuple[int, int]:
        rs, cs = self._slices(key)
        return rs.stop - rs.start, cs.stop - cs.start

    def _slices(self, key) -> Tuple[slice, slice]:
        i, j = self._normalize_key(key)
        r0, r1 = self._span(i, self.__row_inds, self.__row_cuts)
        c0, c1 = self._span(j, self.__col_inds, self.__col_cuts)
        return slice(r0, r1), slice(c0, c1)

    def __getitem__(self, key):
        """The (i, j) tile of the global value (reference ``local_get`` ``:934``)."""
        return self.__arr.larray[self._slices(key)]

    def __setitem__(self, key, value) -> None:
        """Set the (i, j) tile (reference ``local_set`` ``:954``)."""
        sl = self._slices(key)
        new = self.__arr.larray.at[sl].set(jnp.asarray(value, self.__arr.larray.dtype))
        self.__arr._rebind_physical(self.__arr.comm.shard(new, self.__arr.split))

    # local_get/local_set alias the global accessors: every shard sees the global value
    local_get = __getitem__
    local_set = __setitem__

    def match_tiles(self, tiles_to_match: "SquareDiagTiles") -> None:
        """Align tilings for Q/R pairs (reference ``:1079``). Canonical chunkings always
        agree here, so this only validates compatibility."""
        if self.__arr.comm.size != tiles_to_match.arr.comm.size:
            raise ValueError("tilings live on different communicators")
