"""Trigonometric and hyperbolic functions (reference heat/core/trigonometrics.py, 24 exports)."""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = [
    "arccos",
    "acos",
    "arccosh",
    "acosh",
    "arcsin",
    "asin",
    "arcsinh",
    "asinh",
    "arctan",
    "atan",
    "arctanh",
    "atanh",
    "arctan2",
    "atan2",
    "cos",
    "cosh",
    "deg2rad",
    "degrees",
    "rad2deg",
    "radians",
    "sin",
    "sinh",
    "tan",
    "tanh",
]


def arccos(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.arccos, x, out)


acos = arccos


def arccosh(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.arccosh, x, out)


acosh = arccosh


def arcsin(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.arcsin, x, out)


asin = arcsin


def arcsinh(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.arcsinh, x, out)


asinh = arcsinh


def arctan(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.arctan, x, out)


atan = arctan


def arctanh(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.arctanh, x, out)


atanh = arctanh


def arctan2(x1, x2, out=None, where=None) -> DNDarray:
    return _operations.binary_op(jnp.arctan2, x1, x2, out, where)


atan2 = arctan2


def cos(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.cos, x, out)


def cosh(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.cosh, x, out)


def deg2rad(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.deg2rad, x, out)


radians = deg2rad


def rad2deg(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.rad2deg, x, out)


degrees = rad2deg


def sin(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.sin, x, out)


def sinh(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.sinh, x, out)


def tan(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.tan, x, out)


def tanh(x, out=None) -> DNDarray:
    return _operations.local_op(jnp.tanh, x, out)
