"""Type system: Heat's datatype class hierarchy over JAX dtypes.

Mirrors reference ``heat/core/types.py`` (1054 LoC): a class hierarchy
``datatype → bool/number → integer/floating/complexfloating → concrete types`` where each
concrete class knows its backend dtype (``jax_type()`` here, ``torch_type()`` in the
reference, ``types.py:85-493``), plus the query/promotion helpers ``canonical_heat_type``
(``:494``), ``heat_type_of`` (``:567``), ``can_cast`` (``:673``), ``promote_types``
(``:838``), ``result_type`` (``:870``) and ``finfo``/``iinfo`` (``:952``).

TPU-first deltas: ``bfloat16`` is a first-class type (the MXU's native input dtype);
``float16`` exists for completeness; ``float64`` is available because x64 mode is enabled
at package import, but the *default* floating type stays ``float32`` exactly like the
reference.
"""

from __future__ import annotations

import builtins
from typing import Any, Type, Union

import numpy as np

import jax.numpy as jnp

__all__ = [
    "iscomplex",
    "isreal",
    "datatype",
    "number",
    "integer",
    "signedinteger",
    "unsignedinteger",
    "floating",
    "flexible",
    "complexfloating",
    "bool",
    "bool_",
    "int8",
    "byte",
    "int16",
    "short",
    "int32",
    "int",
    "int64",
    "long",
    "uint8",
    "ubyte",
    "float16",
    "half",
    "bfloat16",
    "float32",
    "float",
    "float_",
    "float64",
    "double",
    "complex64",
    "cfloat",
    "csingle",
    "complex128",
    "cdouble",
    "canonical_heat_type",
    "heat_type_of",
    "heat_type_is_complexfloating",
    "heat_type_is_exact",
    "heat_type_is_inexact",
    "issubdtype",
    "iscomplexobj",
    "promote_types",
    "result_type",
    "can_cast",
    "finfo",
    "iinfo",
]


class _DatatypeMeta(type):
    def __repr__(cls):
        return f"heat_tpu.{cls.__name__}"

    def __str__(cls):
        return cls.__name__

    def __instancecheck__(cls, instance):
        # ht.float32(...) returns a DNDarray, so instance checks refer to the hierarchy.
        return super().__instancecheck__(instance)


class datatype(metaclass=_DatatypeMeta):
    """Base class of the type hierarchy (reference ``types.py:40``).

    Calling a concrete type casts data to a :class:`~heat_tpu.core.dndarray.DNDarray`
    of that type: ``ht.float32([1, 2])`` (reference ``types.py:85``).
    """

    _jax_type = None
    _char = None

    def __new__(cls, *value, device=None, comm=None):
        from . import factories

        if cls._jax_type is None:
            raise TypeError(f"cannot instantiate abstract type {cls.__name__}")
        if len(value) == 0:
            value = ((0,),)  # zero scalar, like the reference's default
        if len(value) != 1:
            raise TypeError(f"{cls.__name__} takes at most 1 argument, got {len(value)}")
        return factories.array(value[0], dtype=cls, device=device, comm=comm)

    @classmethod
    def jax_type(cls):
        """The backing ``jnp`` dtype (reference ``torch_type()``)."""
        if cls._jax_type is None:
            raise TypeError(f"abstract type {cls.__name__} has no backend dtype")
        return cls._jax_type

    # keep the reference's name so ported user code works
    torch_type = jax_type

    @classmethod
    def char(cls):
        """Short dtype character code (reference ``types.py`` per-class ``char``)."""
        if cls._char is None:
            raise TypeError(f"abstract type {cls.__name__} has no character code")
        return cls._char


class bool(datatype):  # noqa: A001 — shadows builtins.bool on purpose, like the reference
    _jax_type = jnp.bool_
    _char = "u1"


bool_ = bool


class number(datatype):
    pass


class integer(number):
    pass


class signedinteger(integer):
    pass


class unsignedinteger(integer):
    pass


class floating(number):
    pass


class flexible(datatype):
    pass


class complexfloating(number):
    pass


class int8(signedinteger):
    _jax_type = jnp.int8
    _char = "b"


byte = int8


class int16(signedinteger):
    _jax_type = jnp.int16
    _char = "h"


short = int16


class int32(signedinteger):
    _jax_type = jnp.int32
    _char = "i"


int = int32  # noqa: A001


class int64(signedinteger):
    _jax_type = jnp.int64
    _char = "l"


long = int64


class uint8(unsignedinteger):
    _jax_type = jnp.uint8
    _char = "B"


ubyte = uint8


class float16(floating):
    _jax_type = jnp.float16
    _char = "e"


half = float16


class bfloat16(floating):
    """TPU-native 16-bit float: MXU inputs are bf16, accumulation is f32."""

    _jax_type = jnp.bfloat16
    _char = "E"


class float32(floating):
    _jax_type = jnp.float32
    _char = "f"


float = float32  # noqa: A001
float_ = float32


class float64(floating):
    _jax_type = jnp.float64
    _char = "d"


double = float64


class complex64(complexfloating):
    _jax_type = jnp.complex64
    _char = "F"


cfloat = complex64
csingle = complex64


class complex128(complexfloating):
    _jax_type = jnp.complex128
    _char = "D"


cdouble = complex128


# --------------------------------------------------------------------------- registries
_HEAT_TYPES = [
    bool,
    uint8,
    int8,
    int16,
    int32,
    int64,
    float16,
    bfloat16,
    float32,
    float64,
    complex64,
    complex128,
]

__JAX_TO_HEAT = {np.dtype(t._jax_type): t for t in _HEAT_TYPES}

__CANONICAL = {}
for _t in _HEAT_TYPES:
    __CANONICAL[_t] = _t
    __CANONICAL[_t.__name__] = _t
    __CANONICAL[np.dtype(_t._jax_type)] = _t
    __CANONICAL[np.dtype(_t._jax_type).name] = _t
# python builtins
__CANONICAL[builtins.bool] = bool
__CANONICAL[builtins.int] = int64
__CANONICAL[builtins.float] = float32
__CANONICAL[builtins.complex] = complex128
# numpy scalar classes
for _np_t in (np.bool_, np.uint8, np.int8, np.int16, np.int32, np.int64,
              np.float16, np.float32, np.float64, np.complex64, np.complex128):
    __CANONICAL[_np_t] = __JAX_TO_HEAT[np.dtype(_np_t)]


def canonical_heat_type(a_type: Any) -> Type[datatype]:
    """Canonicalise str / numpy / jax / python / heat dtypes to the heat class
    (reference ``types.py:494``)."""
    if isinstance(a_type, type) and issubclass(a_type, datatype):
        if a_type._jax_type is None:
            raise TypeError(f"data type {a_type!r} is abstract")
        return a_type
    try:
        hashed = __CANONICAL.get(a_type)
        if hashed is not None:
            return hashed
    except TypeError:
        pass
    try:
        return __JAX_TO_HEAT[np.dtype(a_type)]
    except (TypeError, KeyError):
        raise TypeError(f"data type {a_type!r} is not understood") from None


def heat_type_of(obj: Any) -> Type[datatype]:
    """Heat type of an arbitrary object's elements (reference ``types.py:567``)."""
    from .dndarray import DNDarray

    if isinstance(obj, DNDarray):
        return obj.dtype
    dt = getattr(obj, "dtype", None)
    if dt is not None:
        return canonical_heat_type(dt)
    if isinstance(obj, (list, tuple)):
        return canonical_heat_type(np.asarray(obj).dtype)
    return canonical_heat_type(type(obj))


def heat_type_is_exact(ht_dtype: Any) -> builtins.bool:
    """True for integer/bool types (reference ``types.py:645``)."""
    t = canonical_heat_type(ht_dtype)
    return issubclass(t, integer) or t is bool


def heat_type_is_inexact(ht_dtype: Any) -> builtins.bool:
    """True for floating/complex types (reference ``types.py:658``)."""
    t = canonical_heat_type(ht_dtype)
    return issubclass(t, (floating, complexfloating))


def heat_type_is_complexfloating(ht_dtype: Any) -> builtins.bool:
    t = canonical_heat_type(ht_dtype)
    return issubclass(t, complexfloating)


def issubdtype(arg1: Any, arg2: Any) -> builtins.bool:
    """NumPy-style abstract dtype comparison on the heat hierarchy."""
    abstract = {
        number, integer, signedinteger, unsignedinteger, floating, complexfloating,
        flexible, datatype,
    }
    t1 = arg1 if (isinstance(arg1, type) and issubclass(arg1, datatype)) else canonical_heat_type(arg1)
    if isinstance(arg2, type) and issubclass(arg2, datatype):
        return issubclass(t1, arg2)
    return issubclass(t1, canonical_heat_type(arg2))


def iscomplexobj(x: Any) -> builtins.bool:
    return heat_type_is_complexfloating(heat_type_of(x))


def promote_types(type1: Any, type2: Any) -> Type[datatype]:
    """Smallest type safely holding both (reference ``types.py:838``). Uses JAX's promotion
    lattice (x64 enabled), which includes bfloat16; e.g.
    ``promote_types(bfloat16, float16) → float32``."""
    t1 = canonical_heat_type(type1)
    t2 = canonical_heat_type(type2)
    return canonical_heat_type(jnp.promote_types(t1.jax_type(), t2.jax_type()))


def result_type(*arrays_and_types: Any) -> Type[datatype]:
    """Promotion over arrays, scalars and dtypes (reference ``types.py:870``).

    Python float/complex scalars are *weak* (torch semantics, which the reference
    inherits): they promote int arrays to the default float (f32) — not to f64 — and
    never widen an existing float dtype.
    """
    from .dndarray import DNDarray

    args = []
    weak_float = False
    strong_f64 = False
    strong_c128 = False
    for a in arrays_and_types:
        if isinstance(a, DNDarray):
            args.append(a.larray)
            dt = np.dtype(a.dtype.jax_type())
        elif isinstance(a, type) and issubclass(a, datatype):
            args.append(a.jax_type())
            dt = np.dtype(a.jax_type())
        elif isinstance(a, builtins.bool):
            args.append(a)
            continue
        elif isinstance(a, (builtins.float, builtins.complex)) and not isinstance(
            a, (np.floating, np.complexfloating)
        ):
            weak_float = True
            args.append(a)
            continue
        elif isinstance(a, builtins.int) and not isinstance(a, np.integer):
            args.append(a)
            continue
        else:
            args.append(a)
            dt = np.dtype(getattr(a, "dtype", np.asarray(a).dtype))
        strong_f64 |= dt == np.float64
        strong_c128 |= dt == np.complex128
    res = canonical_heat_type(jnp.result_type(*args))
    if weak_float:
        if res is float64 and not strong_f64:
            return float32
        if res is complex128 and not strong_c128:
            return complex64
    return res


def can_cast(from_: Any, to: Any, casting: str = "intuitive") -> builtins.bool:
    """Whether a cast is permitted under the given rule (reference ``types.py:673``).

    Rules: ``"no"``, ``"safe"``, ``"same_kind"``, ``"unsafe"`` (NumPy semantics) plus the
    reference's default ``"intuitive"`` (safe + int64→float32 style convenience casts).
    """
    from .dndarray import DNDarray

    if isinstance(from_, DNDarray):
        from_t = from_.dtype
    elif isinstance(from_, (builtins.int, builtins.float, builtins.complex, builtins.bool)):
        return np.can_cast(from_, np.dtype(canonical_heat_type(to).jax_type()))
    else:
        from_t = canonical_heat_type(from_)
    to_t = canonical_heat_type(to)
    if casting == "no":
        return from_t is to_t
    if casting == "unsafe":
        return True
    f_np, t_np = np.dtype(from_t.jax_type()), np.dtype(to_t.jax_type())

    def _kind(d):
        if d == np.dtype(jnp.bfloat16):
            return "f"
        return d.kind

    if casting == "same_kind":
        order = {"b": 0, "u": 1, "i": 1, "f": 2, "c": 3}
        return order[_kind(f_np)] <= order[_kind(t_np)]
    if casting in ("safe", "intuitive"):
        if f_np == t_np:
            return True
        # bfloat16 is outside numpy's native lattice; treat like float16-width float
        if _kind(f_np) == "f" and f_np.itemsize <= 2:
            f_np = np.dtype(np.float16)
        if _kind(t_np) == "f" and t_np.itemsize <= 2:
            t_np = np.dtype(np.float16)
        safe = np.can_cast(f_np, t_np)
        if casting == "safe":
            return safe
        # "intuitive": also allow any-int → any-float and float↔complex width-matched
        if not safe:
            if _kind(f_np) in "biu" and _kind(t_np) in "fc":
                return True
        return safe
    raise ValueError(f"invalid casting rule {casting!r}")


class finfo:
    """Machine limits for floating types (reference ``types.py:952``)."""

    def __new__(cls, dtype):
        t = canonical_heat_type(dtype)
        if not issubclass(t, (floating, complexfloating)):
            raise TypeError(f"data type {t!r} not inexact")
        return super().__new__(cls)

    def __init__(self, dtype):
        t = canonical_heat_type(dtype)
        info = jnp.finfo(t.jax_type())
        self.bits = info.bits
        self.eps = builtins.float(info.eps)
        self.max = builtins.float(info.max)
        self.min = builtins.float(info.min)
        self.tiny = builtins.float(info.tiny)
        self.dtype = t

    def __repr__(self):
        return f"finfo(dtype={self.dtype}, eps={self.eps}, max={self.max}, min={self.min})"


class iinfo:
    """Machine limits for integer types (reference ``types.py:1007``)."""

    def __new__(cls, dtype):
        t = canonical_heat_type(dtype)
        if not (issubclass(t, integer) or t is bool):
            raise TypeError(f"data type {t!r} not exact")
        return super().__new__(cls)

    def __init__(self, dtype):
        t = canonical_heat_type(dtype)
        if t is bool:
            self.bits, self.max, self.min = 8, 1, 0
        else:
            info = jnp.iinfo(t.jax_type())
            self.bits = info.bits
            self.max = builtins.int(info.max)
            self.min = builtins.int(info.min)
        self.dtype = t

    def __repr__(self):
        return f"iinfo(dtype={self.dtype}, max={self.max}, min={self.min})"


# module-level (not per-call lambdas): the dispatch executor caches compiled
# programs by operation identity, and a fresh lambda per call would never hit
def _iscomplex_value(v):
    import jax.numpy as jnp

    if jnp.iscomplexobj(v):
        return jnp.iscomplexobj(v) & (jnp.imag(v) != 0)
    return jnp.zeros(v.shape, jnp.bool_)


def _isreal_value(v):
    import jax.numpy as jnp

    return jnp.isreal(v)


def iscomplex(x):
    """Test element-wise if input is complex (reference ``types.py:766``)."""
    from . import _operations

    return _operations.local_op(_iscomplex_value, x)


def isreal(x):
    """Test element-wise if input is real-valued (reference ``types.py:788``)."""
    from . import _operations

    return _operations.local_op(_isreal_value, x)
