"""Version information for heat_tpu.

Mirrors the reference's version module (heat/core/version.py) with a plain
semantic version triple.
"""

major: int = 0
"""Major version number."""
minor: int = 2
"""Minor version number."""
micro: int = 0
"""Micro (patch) version number."""
extension: str = "dev0"
"""Pre-release tag (PEP 440 suffix, e.g. ``dev0``; empty for releases)."""

if not extension:
    __version__ = f"{major}.{minor}.{micro}"
else:
    __version__ = f"{major}.{minor}.{micro}.{extension}"
