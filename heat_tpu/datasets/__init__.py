"""Packaged sample datasets (reference heat/datasets/: iris/diabetes files used by
tests and demos). Files here are synthesized deterministically by :func:`generate` at
build/test time rather than shipped as binary blobs."""

import os

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))


def path(name: str) -> str:
    """Absolute path of a packaged dataset file, generating it on first use."""
    p = os.path.join(_DIR, name)
    if not os.path.exists(p):
        generate()
    return p


def generate() -> None:
    """Create the sample data files: a 150x4 'flowers' table (iris-shaped: three
    100-per-class gaussian clusters) as CSV and HDF5."""
    rng = np.random.default_rng(20260729)
    blocks = []
    for center in ((5.0, 3.4, 1.5, 0.2), (5.9, 2.8, 4.3, 1.3), (6.6, 3.0, 5.6, 2.0)):
        blocks.append(rng.normal(center, 0.3, size=(50, 4)))
    data = np.vstack(blocks).astype(np.float32)
    np.savetxt(os.path.join(_DIR, "flowers.csv"), data, delimiter=";", fmt="%.4f")
    try:
        import h5py

        with h5py.File(os.path.join(_DIR, "flowers.h5"), "w") as f:
            f.create_dataset("data", data=data)
    except ImportError:
        pass
