"""Packaged sample datasets (reference heat/datasets/: iris/diabetes files used by
tests and demos across csv/h5/nc, plus train/test split files). Files here are
synthesized deterministically by :func:`generate` at build/test time rather than
shipped as binary blobs — same shapes and roles as the reference's files, fresh values.

Reference inventory mirrored (heat/datasets/):
- ``iris.csv/.h5/.nc``            → ``flowers.csv/.h5/.nc`` (150×4, 3 classes)
- ``iris_X_train/X_test.csv``     → ``flowers_X_train/X_test.csv`` (120/30 × 4)
- ``iris_y_train/y_test.csv``     → ``flowers_y_train/y_test.csv``
- ``iris_labels.csv``             → ``flowers_labels.csv`` (one label per sample)
- ``diabetes.h5``                 → ``sugar.h5`` (442×10 regression table)
"""

import os

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))


def path(name: str) -> str:
    """Absolute path of a packaged dataset file, generating it on first use."""
    p = os.path.join(_DIR, name)
    if not os.path.exists(p):
        generate()
    return p


def _flowers(rng) -> tuple:
    blocks, labels = [], []
    for k, center in enumerate(
        ((5.0, 3.4, 1.5, 0.2), (5.9, 2.8, 4.3, 1.3), (6.6, 3.0, 5.6, 2.0))
    ):
        blocks.append(rng.normal(center, 0.3, size=(50, 4)))
        labels.append(np.full(50, k, dtype=np.int64))
    return np.vstack(blocks).astype(np.float32), np.concatenate(labels)


def generate() -> None:
    """Create the sample data files (see module docstring for the inventory)."""
    rng = np.random.default_rng(20260729)
    data, labels = _flowers(rng)
    np.savetxt(os.path.join(_DIR, "flowers.csv"), data, delimiter=";", fmt="%.4f")
    np.savetxt(os.path.join(_DIR, "flowers_labels.csv"), labels, fmt="%d")

    # deterministic stratified 80/20 split (reference ships fixed split files)
    perm = rng.permutation(150)
    train, test = perm[:120], perm[120:]
    np.savetxt(os.path.join(_DIR, "flowers_X_train.csv"), data[train], delimiter=";", fmt="%.4f")
    np.savetxt(os.path.join(_DIR, "flowers_X_test.csv"), data[test], delimiter=";", fmt="%.4f")
    np.savetxt(os.path.join(_DIR, "flowers_y_train.csv"), labels[train], fmt="%d")
    np.savetxt(os.path.join(_DIR, "flowers_y_test.csv"), labels[test], fmt="%d")

    # regression table shaped like the reference's diabetes.h5 (442×10 + target)
    n, d = 442, 10
    X = rng.standard_normal((n, d)).astype(np.float32)
    w = rng.standard_normal(d).astype(np.float32)
    y = (X @ w + 0.1 * rng.standard_normal(n)).astype(np.float32)

    try:
        import h5py

        with h5py.File(os.path.join(_DIR, "flowers.h5"), "w") as f:
            f.create_dataset("data", data=data)
        with h5py.File(os.path.join(_DIR, "sugar.h5"), "w") as f:
            f.create_dataset("x", data=X)
            f.create_dataset("y", data=y)
    except ImportError:
        pass

    try:
        import netCDF4 as nc

        with nc.Dataset(os.path.join(_DIR, "flowers.nc"), "w") as f:
            f.createDimension("samples", data.shape[0])
            f.createDimension("features", data.shape[1])
            var = f.createVariable("data", np.float32, ("samples", "features"))
            var[...] = data
    except ImportError:
        pass
