"""Distributed FFTs (reference heat/fft/)."""

from .fft import *
from . import fft
