"""numpy.fft-compatible distributed FFTs (reference heat/fft/fft.py, 1120 LoC).

The reference's strategy (``__fft_op`` ``fft.py:40-137``): a transform along a non-split
axis is purely local torch.fft; a transform along the split axis is a *pencil
decomposition* — transpose the axis to 0, ``resplit(1)``, transform locally,
``resplit_(0)``, transpose back. On TPU the pencil dance is exactly what XLA SPMD emits
for an FFT over a sharded dimension (all-to-all re-layout, local FFT, all-to-all back),
so every wrapper here is one ``jnp.fft`` call plus split bookkeeping: real/complex
transforms that change the last-axis length keep the split unless it sits on the
transformed axis, in which case the output stays sharded the same way the input was.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import numpy as np

import jax.numpy as jnp

from ..core import types
from ..core._operations import wrap_result
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in
from ..core.stride_tricks import sanitize_axis

__all__ = [
    "fft",
    "fft2",
    "fftfreq",
    "fftn",
    "fftshift",
    "hfft",
    "hfft2",
    "hfftn",
    "ifft",
    "ifft2",
    "ifftn",
    "ifftshift",
    "ihfft",
    "ihfft2",
    "ihfftn",
    "irfft",
    "irfft2",
    "irfftn",
    "rfft",
    "rfft2",
    "rfftfreq",
    "rfftn",
]


def _fft_op(x: DNDarray, op, n=None, axis=-1, norm=None) -> DNDarray:
    """Single-axis transform (reference ``__fft_op`` ``fft.py:40``)."""
    sanitize_in(x)
    axis = sanitize_axis(x.gshape, axis)
    result = op(x.larray, n=n, axis=axis, norm=norm)
    return wrap_result(result, x, x.split)


def _fftn_op(x: DNDarray, op, s=None, axes=None, norm=None) -> DNDarray:
    """n-D transform (reference ``__fftn_op`` ``fft.py:139``)."""
    sanitize_in(x)
    if axes is not None:
        axes = tuple(sanitize_axis(x.gshape, ax) for ax in axes)
    result = op(x.larray, s=s, axes=axes, norm=norm)
    return wrap_result(result, x, x.split)


def fft(x: DNDarray, n: Optional[int] = None, axis: int = -1, norm: Optional[str] = None) -> DNDarray:
    """1-D discrete Fourier transform (reference ``fft.py:256``)."""
    return _fft_op(x, jnp.fft.fft, n, axis, norm)


def ifft(x: DNDarray, n: Optional[int] = None, axis: int = -1, norm: Optional[str] = None) -> DNDarray:
    """Inverse 1-D DFT (reference ``fft.py:465``)."""
    return _fft_op(x, jnp.fft.ifft, n, axis, norm)


def fft2(x: DNDarray, s=None, axes: Tuple[int, int] = (-2, -1), norm: Optional[str] = None) -> DNDarray:
    """2-D DFT (reference ``fft.py:293``)."""
    return _fftn_op(x, jnp.fft.fft2, s, axes, norm)


def ifft2(x: DNDarray, s=None, axes: Tuple[int, int] = (-2, -1), norm: Optional[str] = None) -> DNDarray:
    """Inverse 2-D DFT (reference ``fft.py:502``)."""
    return _fftn_op(x, jnp.fft.ifft2, s, axes, norm)


def fftn(x: DNDarray, s=None, axes=None, norm: Optional[str] = None) -> DNDarray:
    """n-D DFT (reference ``fft.py:334``)."""
    return _fftn_op(x, jnp.fft.fftn, s, axes, norm)


def ifftn(x: DNDarray, s=None, axes=None, norm: Optional[str] = None) -> DNDarray:
    """Inverse n-D DFT (reference ``fft.py:543``)."""
    return _fftn_op(x, jnp.fft.ifftn, s, axes, norm)


def rfft(x: DNDarray, n: Optional[int] = None, axis: int = -1, norm: Optional[str] = None) -> DNDarray:
    """1-D DFT of a real input (reference ``fft.py:837``)."""
    if types.heat_type_is_complexfloating(x.dtype):
        raise TypeError("rfft requires a real input; use fft for complex data")
    return _fft_op(x, jnp.fft.rfft, n, axis, norm)


def irfft(x: DNDarray, n: Optional[int] = None, axis: int = -1, norm: Optional[str] = None) -> DNDarray:
    """Inverse of rfft (reference ``fft.py:647``)."""
    return _fft_op(x, jnp.fft.irfft, n, axis, norm)


def rfft2(x: DNDarray, s=None, axes: Tuple[int, int] = (-2, -1), norm: Optional[str] = None) -> DNDarray:
    """2-D real DFT (reference ``fft.py:874``)."""
    if types.heat_type_is_complexfloating(x.dtype):
        raise TypeError("rfft2 requires a real input; use fft2 for complex data")
    return _fftn_op(x, jnp.fft.rfft2, s, axes, norm)


def irfft2(x: DNDarray, s=None, axes: Tuple[int, int] = (-2, -1), norm: Optional[str] = None) -> DNDarray:
    """Inverse 2-D real DFT (reference ``fft.py:684``)."""
    return _fftn_op(x, jnp.fft.irfft2, s, axes, norm)


def rfftn(x: DNDarray, s=None, axes=None, norm: Optional[str] = None) -> DNDarray:
    """n-D real DFT (reference ``fft.py:915``)."""
    if types.heat_type_is_complexfloating(x.dtype):
        raise TypeError("rfftn requires a real input; use fftn for complex data")
    return _fftn_op(x, jnp.fft.rfftn, s, axes, norm)


def irfftn(x: DNDarray, s=None, axes=None, norm: Optional[str] = None) -> DNDarray:
    """Inverse n-D real DFT (reference ``fft.py:725``)."""
    return _fftn_op(x, jnp.fft.irfftn, s, axes, norm)


def hfft(x: DNDarray, n: Optional[int] = None, axis: int = -1, norm: Optional[str] = None) -> DNDarray:
    """DFT of a Hermitian-symmetric signal (reference ``fft.py:375``)."""
    return _fft_op(x, jnp.fft.hfft, n, axis, norm)


def ihfft(x: DNDarray, n: Optional[int] = None, axis: int = -1, norm: Optional[str] = None) -> DNDarray:
    """Inverse of hfft (reference ``fft.py:580``)."""
    return _fft_op(x, jnp.fft.ihfft, n, axis, norm)


def hfft2(x: DNDarray, s=None, axes: Tuple[int, int] = (-2, -1), norm: Optional[str] = None) -> DNDarray:
    """2-D Hermitian DFT (reference ``fft.py:416``)."""
    return hfftn(x, s=s, axes=axes, norm=norm)


def hfftn(x: DNDarray, s=None, axes=None, norm: Optional[str] = None) -> DNDarray:
    """n-D Hermitian DFT (reference ``fft.py:440``; numpy.fft has no hfftn — semantics
    follow torch.fft.hfftn: ``hfftn(x) = irfftn(conj(x))`` with inverse normalization)."""
    sanitize_in(x)
    if axes is not None:
        axes = tuple(sanitize_axis(x.gshape, ax) for ax in axes)
    xv = jnp.conj(x.larray)
    # hfftn(x, norm) == irfftn(conj(x), norm-swapped): "backward" applies no forward
    # scaling, which is irfftn's "forward" behaviour (numpy hfft = irfft(conj(a), n)*n)
    inv = {None: "forward", "backward": "forward", "forward": "backward", "ortho": "ortho"}[norm]
    result = jnp.fft.irfftn(xv, s=s, axes=axes, norm=inv)
    return wrap_result(result, x, x.split)


def ihfft2(x: DNDarray, s=None, axes: Tuple[int, int] = (-2, -1), norm: Optional[str] = None) -> DNDarray:
    """Inverse 2-D Hermitian DFT (reference ``fft.py:605``)."""
    return ihfftn(x, s=s, axes=axes, norm=norm)


def ihfftn(x: DNDarray, s=None, axes=None, norm: Optional[str] = None) -> DNDarray:
    """Inverse n-D Hermitian DFT (``ihfftn(x) = conj(rfftn(x))`` with inverse norm)."""
    sanitize_in(x)
    if types.heat_type_is_complexfloating(x.dtype):
        raise TypeError("ihfftn requires a real input")
    if axes is not None:
        axes = tuple(sanitize_axis(x.gshape, ax) for ax in axes)
    inv = {None: "forward", "backward": "forward", "forward": "backward", "ortho": "ortho"}[norm]
    result = jnp.conj(jnp.fft.rfftn(x.larray, s=s, axes=axes, norm=inv))
    return wrap_result(result, x, x.split)


def fftfreq(n: int, d: float = 1.0, dtype=None, split: Optional[int] = None, device=None, comm=None) -> DNDarray:
    """Sample frequencies of a DFT (reference ``fft.py:963``)."""
    from ..core import factories

    result = jnp.fft.fftfreq(n, d=d)
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype).jax_type())
    return factories.array(result, split=split, device=device, comm=comm)


def rfftfreq(n: int, d: float = 1.0, dtype=None, split: Optional[int] = None, device=None, comm=None) -> DNDarray:
    """Sample frequencies of a real DFT (reference ``fft.py:1032``)."""
    from ..core import factories

    result = jnp.fft.rfftfreq(n, d=d)
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype).jax_type())
    return factories.array(result, split=split, device=device, comm=comm)


def fftshift(x: DNDarray, axes=None) -> DNDarray:
    """Shift the zero-frequency component to the center (reference ``fft.py:1002``)."""
    sanitize_in(x)
    if axes is not None:
        axes = tuple(sanitize_axis(x.gshape, ax) for ax in axes) if isinstance(axes, (tuple, list)) else sanitize_axis(x.gshape, axes)
    result = jnp.fft.fftshift(x.larray, axes=axes)
    return wrap_result(result, x, x.split)


def ifftshift(x: DNDarray, axes=None) -> DNDarray:
    """Inverse of fftshift (reference ``fft.py:1070``)."""
    sanitize_in(x)
    if axes is not None:
        axes = tuple(sanitize_axis(x.gshape, ax) for ax in axes) if isinstance(axes, (tuple, list)) else sanitize_axis(x.gshape, axes)
    result = jnp.fft.ifftshift(x.larray, axes=axes)
    return wrap_result(result, x, x.split)
