"""numpy.fft-compatible distributed FFTs (reference heat/fft/fft.py, 1120 LoC).

The reference's strategy (``__fft_op`` ``fft.py:40-137``): a transform along a non-split
axis is purely local torch.fft; a transform along the split axis is a *pencil
decomposition* — move the distribution to another axis (all-to-all resplit), transform
locally, resplit back. The TPU build keeps that pencil explicit (``_pencil_split``):
handing XLA an FFT over a sharded axis trips a hard CHECK in its SPMD partitioner
(``fft_handler.cc``: per-partition size divisibility) that aborts the whole process,
so the resplit-first schedule is a correctness requirement, not a tuning choice.
Transforms along unsplit axes are one local ``jnp.fft`` call plus split bookkeeping.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..core import types
from ..core._operations import wrap_result
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in
from ..core.stride_tricks import sanitize_axis

__all__ = [
    "fft",
    "fft2",
    "fftfreq",
    "fftn",
    "fftshift",
    "hfft",
    "hfft2",
    "hfftn",
    "ifft",
    "ifft2",
    "ifftn",
    "ifftshift",
    "ihfft",
    "ihfft2",
    "ihfftn",
    "irfft",
    "irfft2",
    "irfftn",
    "rfft",
    "rfft2",
    "rfftfreq",
    "rfftn",
]


def _pencil_split(x: DNDarray, transformed: Tuple[int, ...]) -> Optional[int]:
    """The reference's pencil decomposition (``fft.py:100-126``): a transform along
    the split axis first moves the distribution to an untransformed axis (resplit =
    all-to-all), falling back to full replication when every axis is transformed.

    This is mandatory, not an optimisation: XLA's SPMD FFT partitioner hard-CHECKs
    ``size_per_partition % num_partitions == 0`` (fft_handler.cc) and *aborts the
    process* when a sharded transform axis doesn't satisfy it.
    """
    for ax in range(x.ndim):
        if ax not in transformed:
            return ax
    return None


def _fft_backend_supported() -> bool:
    """Whether the default accelerator backend lowers FFT (some TPU runtimes report
    UNIMPLEMENTED for every fft HLO — and the failed compile poisons the issuing
    process). Delegates to the shared subprocess capability probe in
    :func:`heat_tpu.core.devices.accelerator_capabilities`; override with
    HEAT_TPU_FFT_BACKEND=cpu|device."""
    from ..core.devices import accelerator_capabilities

    return accelerator_capabilities()["fft"]


def _run_fft(op, value, **kw):
    """Run one jnp.fft op, falling back to the host CPU backend when the
    accelerator cannot lower FFT (the result is re-sharded by the caller's
    wrap_result, so distribution semantics are unchanged — only the transform
    itself executes on host)."""
    if _fft_backend_supported():
        return op(value, **kw)
    from ..core.devices import cpu_fallback_device

    cpu = cpu_fallback_device()
    with jax.default_device(cpu):
        return op(jax.device_put(value, cpu), **kw)


def _fft_op(x: DNDarray, op, n=None, axis=-1, norm=None) -> DNDarray:
    """Single-axis transform (reference ``__fft_op`` ``fft.py:40``)."""
    sanitize_in(x)
    axis = sanitize_axis(x.gshape, axis)
    if x.split == axis and x.is_distributed():
        from ..core.manipulations import resplit

        tmp = _pencil_split(x, (axis,))
        xr = resplit(x, tmp)
        result = _run_fft(op, xr.larray, n=n, axis=axis, norm=norm)
        return resplit(wrap_result(result, xr, tmp), x.split)
    result = _run_fft(op, x.larray, n=n, axis=axis, norm=norm)
    return wrap_result(result, x, x.split)


def _fftn_op(x: DNDarray, op, s=None, axes=None, norm=None) -> DNDarray:
    """n-D transform (reference ``__fftn_op`` ``fft.py:139``)."""
    sanitize_in(x)
    if axes is not None:
        axes = tuple(sanitize_axis(x.gshape, ax) for ax in axes)
    if axes is not None:
        transformed = axes
    elif s is not None:
        # numpy _cook_nd_args: s without axes transforms the LAST len(s) axes
        transformed = tuple(range(x.ndim - len(tuple(s)), x.ndim))
    else:
        transformed = tuple(range(x.ndim))
    if x.split is not None and x.split in transformed and x.is_distributed():
        from ..core.manipulations import resplit

        tmp = _pencil_split(x, transformed)
        xr = resplit(x, tmp)
        result = _run_fft(op, xr.larray, s=s, axes=axes, norm=norm)
        return resplit(wrap_result(result, xr, tmp), x.split)
    result = _run_fft(op, x.larray, s=s, axes=axes, norm=norm)
    return wrap_result(result, x, x.split)


def fft(x: DNDarray, n: Optional[int] = None, axis: int = -1, norm: Optional[str] = None) -> DNDarray:
    """1-D discrete Fourier transform (reference ``fft.py:256``)."""
    return _fft_op(x, jnp.fft.fft, n, axis, norm)


def ifft(x: DNDarray, n: Optional[int] = None, axis: int = -1, norm: Optional[str] = None) -> DNDarray:
    """Inverse 1-D DFT (reference ``fft.py:465``)."""
    return _fft_op(x, jnp.fft.ifft, n, axis, norm)


def fft2(x: DNDarray, s=None, axes: Tuple[int, int] = (-2, -1), norm: Optional[str] = None) -> DNDarray:
    """2-D DFT (reference ``fft.py:293``)."""
    # numpy: an explicit axes=None means ALL axes (fftn semantics), not the last two
    return _fftn_op(x, jnp.fft.fftn, s, axes, norm) if axes is None else _fftn_op(x, jnp.fft.fft2, s, axes, norm)


def ifft2(x: DNDarray, s=None, axes: Tuple[int, int] = (-2, -1), norm: Optional[str] = None) -> DNDarray:
    """Inverse 2-D DFT (reference ``fft.py:502``)."""
    # numpy: an explicit axes=None means ALL axes (ifftn semantics), not the last two
    return _fftn_op(x, jnp.fft.ifftn, s, axes, norm) if axes is None else _fftn_op(x, jnp.fft.ifft2, s, axes, norm)


def fftn(x: DNDarray, s=None, axes=None, norm: Optional[str] = None) -> DNDarray:
    """n-D DFT (reference ``fft.py:334``)."""
    return _fftn_op(x, jnp.fft.fftn, s, axes, norm)


def ifftn(x: DNDarray, s=None, axes=None, norm: Optional[str] = None) -> DNDarray:
    """Inverse n-D DFT (reference ``fft.py:543``)."""
    return _fftn_op(x, jnp.fft.ifftn, s, axes, norm)


def rfft(x: DNDarray, n: Optional[int] = None, axis: int = -1, norm: Optional[str] = None) -> DNDarray:
    """1-D DFT of a real input (reference ``fft.py:837``)."""
    if types.heat_type_is_complexfloating(x.dtype):
        raise TypeError("rfft requires a real input; use fft for complex data")
    return _fft_op(x, jnp.fft.rfft, n, axis, norm)


def irfft(x: DNDarray, n: Optional[int] = None, axis: int = -1, norm: Optional[str] = None) -> DNDarray:
    """Inverse of rfft (reference ``fft.py:647``)."""
    return _fft_op(x, jnp.fft.irfft, n, axis, norm)


def rfft2(x: DNDarray, s=None, axes: Tuple[int, int] = (-2, -1), norm: Optional[str] = None) -> DNDarray:
    """2-D real DFT (reference ``fft.py:874``)."""
    if types.heat_type_is_complexfloating(x.dtype):
        raise TypeError("rfft2 requires a real input; use fft2 for complex data")
    # numpy: an explicit axes=None means ALL axes (rfftn semantics), not the last two
    return _fftn_op(x, jnp.fft.rfftn, s, axes, norm) if axes is None else _fftn_op(x, jnp.fft.rfft2, s, axes, norm)


def irfft2(x: DNDarray, s=None, axes: Tuple[int, int] = (-2, -1), norm: Optional[str] = None) -> DNDarray:
    """Inverse 2-D real DFT (reference ``fft.py:684``)."""
    # numpy: an explicit axes=None means ALL axes (irfftn semantics), not the last two
    return _fftn_op(x, jnp.fft.irfftn, s, axes, norm) if axes is None else _fftn_op(x, jnp.fft.irfft2, s, axes, norm)


def rfftn(x: DNDarray, s=None, axes=None, norm: Optional[str] = None) -> DNDarray:
    """n-D real DFT (reference ``fft.py:915``)."""
    if types.heat_type_is_complexfloating(x.dtype):
        raise TypeError("rfftn requires a real input; use fftn for complex data")
    return _fftn_op(x, jnp.fft.rfftn, s, axes, norm)


def irfftn(x: DNDarray, s=None, axes=None, norm: Optional[str] = None) -> DNDarray:
    """Inverse n-D real DFT (reference ``fft.py:725``)."""
    return _fftn_op(x, jnp.fft.irfftn, s, axes, norm)


def hfft(x: DNDarray, n: Optional[int] = None, axis: int = -1, norm: Optional[str] = None) -> DNDarray:
    """DFT of a Hermitian-symmetric signal (reference ``fft.py:375``)."""
    return _fft_op(x, jnp.fft.hfft, n, axis, norm)


def ihfft(x: DNDarray, n: Optional[int] = None, axis: int = -1, norm: Optional[str] = None) -> DNDarray:
    """Inverse of hfft (reference ``fft.py:580``)."""
    return _fft_op(x, jnp.fft.ihfft, n, axis, norm)


def hfft2(x: DNDarray, s=None, axes: Tuple[int, int] = (-2, -1), norm: Optional[str] = None) -> DNDarray:
    """2-D Hermitian DFT (reference ``fft.py:416``)."""
    return hfftn(x, s=s, axes=axes, norm=norm)  # axes=None -> all axes, numpy semantics


def hfftn(x: DNDarray, s=None, axes=None, norm: Optional[str] = None) -> DNDarray:
    """n-D Hermitian DFT (reference ``fft.py:440``; numpy.fft has no hfftn — semantics
    follow torch.fft.hfftn: ``hfftn(x) = irfftn(conj(x))`` with inverse normalization)."""
    sanitize_in(x)
    if axes is not None:
        axes = tuple(sanitize_axis(x.gshape, ax) for ax in axes)
    # hfftn(x, norm) == irfftn(conj(x), norm-swapped): "backward" applies no forward
    # scaling, which is irfftn's "forward" behaviour (numpy hfft = irfft(conj(a), n)*n)
    inv = {None: "forward", "backward": "forward", "forward": "backward", "ortho": "ortho"}[norm]
    op = lambda v, s=None, axes=None, norm=None: jnp.fft.irfftn(jnp.conj(v), s=s, axes=axes, norm=norm)
    return _fftn_op(x, op, s, axes, inv)


def ihfft2(x: DNDarray, s=None, axes: Tuple[int, int] = (-2, -1), norm: Optional[str] = None) -> DNDarray:
    """Inverse 2-D Hermitian DFT (reference ``fft.py:605``)."""
    return ihfftn(x, s=s, axes=axes, norm=norm)  # axes=None -> all axes, numpy semantics


def ihfftn(x: DNDarray, s=None, axes=None, norm: Optional[str] = None) -> DNDarray:
    """Inverse n-D Hermitian DFT (``ihfftn(x) = conj(rfftn(x))`` with inverse norm)."""
    sanitize_in(x)
    if types.heat_type_is_complexfloating(x.dtype):
        raise TypeError("ihfftn requires a real input")
    if axes is not None:
        axes = tuple(sanitize_axis(x.gshape, ax) for ax in axes)
    inv = {None: "forward", "backward": "forward", "forward": "backward", "ortho": "ortho"}[norm]
    op = lambda v, s=None, axes=None, norm=None: jnp.conj(jnp.fft.rfftn(v, s=s, axes=axes, norm=norm))
    return _fftn_op(x, op, s, axes, inv)


def fftfreq(n: int, d: float = 1.0, dtype=None, split: Optional[int] = None, device=None, comm=None) -> DNDarray:
    """Sample frequencies of a DFT (reference ``fft.py:963``)."""
    from ..core import factories

    result = jnp.fft.fftfreq(n, d=d)
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype).jax_type())
    return factories.array(result, split=split, device=device, comm=comm)


def rfftfreq(n: int, d: float = 1.0, dtype=None, split: Optional[int] = None, device=None, comm=None) -> DNDarray:
    """Sample frequencies of a real DFT (reference ``fft.py:1032``)."""
    from ..core import factories

    result = jnp.fft.rfftfreq(n, d=d)
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype).jax_type())
    return factories.array(result, split=split, device=device, comm=comm)


def fftshift(x: DNDarray, axes=None) -> DNDarray:
    """Shift the zero-frequency component to the center (reference ``fft.py:1002``)."""
    sanitize_in(x)
    if axes is not None:
        axes = tuple(sanitize_axis(x.gshape, ax) for ax in axes) if isinstance(axes, (tuple, list)) else sanitize_axis(x.gshape, axes)
    result = jnp.fft.fftshift(x.larray, axes=axes)
    return wrap_result(result, x, x.split)


def ifftshift(x: DNDarray, axes=None) -> DNDarray:
    """Inverse of fftshift (reference ``fft.py:1070``)."""
    sanitize_in(x)
    if axes is not None:
        axes = tuple(sanitize_axis(x.gshape, ax) for ax in axes) if isinstance(axes, (tuple, list)) else sanitize_axis(x.gshape, axes)
    result = jnp.fft.ifftshift(x.larray, axes=axes)
    return wrap_result(result, x, x.split)
