"""Graph algorithms (reference heat/graph/)."""

from .laplacian import *
from . import laplacian
