"""Graph Laplacians (reference heat/graph/laplacian.py, 142 LoC)."""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

import heat_tpu as ht
from ..core.dndarray import DNDarray

__all__ = ["Laplacian"]


class Laplacian:
    """Adjacency construction + Laplacian forms (reference ``laplacian.py:13``)."""

    def __init__(
        self,
        similarity: Callable,
        weighted: bool = True,
        definition: str = "norm_sym",
        mode: str = "fully_connected",
        threshold_key: str = "upper",
        threshold_value: float = 1.0,
        neighbours: int = 10,
    ):
        self.similarity_metric = similarity
        self.weighted = weighted
        if definition not in ("simple", "norm_sym"):
            raise NotImplementedError(
                "Only simple and normalized symmetric Laplacians are supported"
            )
        self.definition = definition
        if mode not in ("eNeighbour", "fully_connected"):
            raise NotImplementedError(
                "Only eNeighbour and fully-connected graphs are supported"
            )
        self.mode = mode
        if threshold_key not in ("upper", "lower"):
            raise ValueError(f"threshold_key must be 'upper' or 'lower', got {threshold_key}")
        self.epsilon = (threshold_key, threshold_value)
        self.neighbours = neighbours

    def _normalized_symmetric_L(self, A: DNDarray) -> DNDarray:
        """L_sym = I - D^{-1/2} A D^{-1/2} (reference ``laplacian.py:74``)."""
        degree = ht.sum(A, axis=1).resplit(None)
        deg = jnp.where(degree.larray == 0, 1.0, degree.larray)
        lv = A.larray / jnp.sqrt(deg)[:, None]
        lv = lv / jnp.sqrt(deg)[None, :]
        lv = -lv
        n = A.gshape[0]
        idx = jnp.arange(n)
        lv = lv.at[idx, idx].set(1.0)
        from ..core._operations import wrap_result

        return wrap_result(lv, A, A.split)

    def _simple_L(self, A: DNDarray) -> DNDarray:
        """L = D - A (reference ``laplacian.py:98``)."""
        degree = ht.sum(A, axis=1)
        return ht.diag(degree.resplit(None)).resplit(A.split) - A

    def construct(self, X: DNDarray) -> DNDarray:
        """Build the Laplacian of the similarity graph of ``X``
        (reference ``laplacian.py:113``)."""
        S = self.similarity_metric(X)
        if self.mode == "eNeighbour":
            key, value = self.epsilon
            sv = S.larray
            if key == "upper":
                adj = jnp.where(sv < value, sv if self.weighted else 1.0, 0.0)
            else:
                adj = jnp.where(sv > value, sv if self.weighted else 1.0, 0.0)
            n = S.gshape[0]
            idx = jnp.arange(n)
            adj = adj.at[idx, idx].set(0.0)
            from ..core._operations import wrap_result

            S = wrap_result(adj.astype(sv.dtype), S, S.split)
        if self.definition == "simple":
            return self._simple_L(S)
        return self._normalized_symmetric_L(S)
