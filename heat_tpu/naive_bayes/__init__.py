"""Naive Bayes estimators (reference heat/naive_bayes/)."""

from .gaussianNB import *
from . import gaussianNB
