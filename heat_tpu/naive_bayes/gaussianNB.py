"""Gaussian Naive Bayes (reference heat/naive_bayes/gaussianNB.py, 522 LoC).

The reference maintains per-class running means/variances merged across ranks and
batches with the pairwise update formula (``__update_mean_variance``
``gaussianNB.py:128``). With global sharded arrays one masked reduction per class gives
the same statistics; ``partial_fit`` keeps the reference's streaming-merge semantics for
API parity.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

import heat_tpu as ht
from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray

__all__ = ["GaussianNB"]


class GaussianNB(ClassificationMixin, BaseEstimator):
    """Gaussian NB classifier (reference ``gaussianNB.py:13``)."""

    def __init__(self, priors: Optional[DNDarray] = None, var_smoothing: float = 1e-9):
        self.priors = priors
        self.var_smoothing = var_smoothing
        self.classes_ = None
        self.theta_ = None
        self.var_ = None
        self.class_count_ = None
        self.class_prior_ = None
        self.epsilon_ = None

    def fit(self, x: DNDarray, y: DNDarray, sample_weight: Optional[DNDarray] = None) -> "GaussianNB":
        """Fit from scratch (reference ``gaussianNB.py:71``)."""
        self.classes_ = None
        return self.partial_fit(x, y, classes=None, sample_weight=sample_weight)

    def partial_fit(
        self,
        x: DNDarray,
        y: DNDarray,
        classes: Optional[DNDarray] = None,
        sample_weight: Optional[DNDarray] = None,
    ) -> "GaussianNB":
        """Incremental fit on a batch (reference ``gaussianNB.py:197``): merges batch
        statistics into the running per-class moments."""
        if not isinstance(x, DNDarray):
            raise ValueError(f"x needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError(f"expected x to be 2-D, got {x.ndim}-D")
        yv = (y.larray if isinstance(y, DNDarray) else jnp.asarray(y)).reshape(-1)
        xv = x.larray.astype(jnp.float64)
        w = None
        if sample_weight is not None:
            w = (sample_weight.larray if isinstance(sample_weight, DNDarray) else jnp.asarray(sample_weight)).reshape(-1).astype(jnp.float64)

        if self.classes_ is None:
            if classes is not None:
                cls = np.asarray(classes.larray if isinstance(classes, DNDarray) else classes)
            else:
                cls = np.unique(np.asarray(yv))
            self.classes_ = ht.array(cls, comm=x.comm)
            n_features = x.gshape[1]
            n_classes = len(cls)
            self.theta_ = jnp.zeros((n_classes, n_features), jnp.float64)
            self.var_ = jnp.zeros((n_classes, n_features), jnp.float64)
            self.class_count_ = jnp.zeros((n_classes,), jnp.float64)
        cls_vals = jnp.asarray(np.asarray(self.classes_.larray))

        # max variance smoothing from the pooled data (reference gaussianNB.py:251)
        self.epsilon_ = self.var_smoothing * float(jnp.var(xv, axis=0).max())

        new_theta, new_var, new_count = [], [], []
        for i in range(cls_vals.shape[0]):
            mask = (yv == cls_vals[i]).astype(jnp.float64)
            wi = mask if w is None else mask * w
            n_new = jnp.sum(wi)
            mu_new = jnp.where(n_new > 0, jnp.sum(xv * wi[:, None], axis=0) / jnp.maximum(n_new, 1.0), 0.0)
            var_new = jnp.where(
                n_new > 0,
                jnp.sum(((xv - mu_new) ** 2) * wi[:, None], axis=0) / jnp.maximum(n_new, 1.0),
                0.0,
            )
            # pairwise merge with the running stats (reference __update_mean_variance :128)
            n_old = self.class_count_[i]
            mu_old, var_old = self.theta_[i], self.var_[i]
            n_tot = n_old + n_new
            mu_tot = jnp.where(n_tot > 0, (n_old * mu_old + n_new * mu_new) / jnp.maximum(n_tot, 1.0), 0.0)
            ssd = (
                n_old * var_old
                + n_new * var_new
                + jnp.where(n_tot > 0, (n_old * n_new / jnp.maximum(n_tot, 1.0)) * (mu_old - mu_new) ** 2, 0.0)
            )
            var_tot = jnp.where(n_tot > 0, ssd / jnp.maximum(n_tot, 1.0), 0.0)
            new_theta.append(mu_tot)
            new_var.append(var_tot)
            new_count.append(n_tot)
        self.theta_ = jnp.stack(new_theta)
        self.var_ = jnp.stack(new_var)
        self.class_count_ = jnp.stack(new_count)

        if self.priors is not None:
            pv = jnp.asarray(
                self.priors.larray if isinstance(self.priors, DNDarray) else self.priors
            ).astype(jnp.float64)
            if pv.shape[0] != cls_vals.shape[0]:
                raise ValueError("Number of priors must match number of classes.")
            if not bool(jnp.isclose(pv.sum(), 1.0)):
                raise ValueError("The sum of the priors should be 1.")
            if bool((pv < 0).any()):
                raise ValueError("Priors must be non-negative.")
            self.class_prior_ = pv
        else:
            total = jnp.sum(self.class_count_)
            self.class_prior_ = self.class_count_ / jnp.maximum(total, 1.0)
        return self

    def __joint_log_likelihood(self, x: DNDarray) -> jnp.ndarray:
        """Per-class joint log likelihood (reference ``gaussianNB.py:383``)."""
        xv = x.larray.astype(jnp.float64)
        var = self.var_ + self.epsilon_
        jll = []
        for i in range(self.theta_.shape[0]):
            prior = jnp.log(jnp.maximum(self.class_prior_[i], 1e-300))
            n_ij = -0.5 * jnp.sum(jnp.log(2.0 * jnp.pi * var[i]))
            n_ij = n_ij - 0.5 * jnp.sum(((xv - self.theta_[i]) ** 2) / var[i], axis=1)
            jll.append(prior + n_ij)
        return jnp.stack(jll, axis=1)

    def predict(self, x: DNDarray) -> DNDarray:
        """Most probable class per sample (reference ``gaussianNB.py:334``)."""
        if self.classes_ is None:
            raise RuntimeError("fit needs to be called before predict")
        jll = self.__joint_log_likelihood(x)
        idx = jnp.argmax(jll, axis=1)
        labels = jnp.take(jnp.asarray(np.asarray(self.classes_.larray)), idx)
        from ..core._operations import wrap_result

        return wrap_result(labels, x, 0 if x.split is not None else None)

    def predict_proba(self, x: DNDarray) -> DNDarray:
        """Class probabilities (reference ``gaussianNB.py:355``)."""
        jll = self.__joint_log_likelihood(x)
        log_prob = jll - self.logsumexp(jll, axis=1, keepdims=True)
        from ..core._operations import wrap_result

        return wrap_result(jnp.exp(log_prob), x, 0 if x.split is not None else None)

    def predict_log_proba(self, x: DNDarray) -> DNDarray:
        """Log class probabilities (reference ``gaussianNB.py:370``)."""
        jll = self.__joint_log_likelihood(x)
        log_prob = jll - self.logsumexp(jll, axis=1, keepdims=True)
        from ..core._operations import wrap_result

        return wrap_result(log_prob, x, 0 if x.split is not None else None)

    @staticmethod
    def logsumexp(a, axis=None, b=None, keepdims: bool = False, return_sign: bool = False):
        """Stable log-sum-exp (reference ``gaussianNB.py:400``); ``return_sign``
        additionally returns the sign of the sum like scipy's."""
        import jax.scipy.special as jsp

        av = a.larray if isinstance(a, DNDarray) else jnp.asarray(a)
        return jsp.logsumexp(av, axis=axis, b=b, keepdims=keepdims, return_sign=return_sign)
