"""Neural networks (reference heat/nn/). The reference's ``__getattr__`` falls through
to ``torch.nn`` (``nn/__init__.py:18-31``); torch layers cannot execute on TPU, so the
native module system in :mod:`.modules` is the fallthrough surface here."""

from .data_parallel import *
from .modules import *
from .attention import *
from .recurrent import *
from . import attention, data_parallel, functional, modules, recurrent
