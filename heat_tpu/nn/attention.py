"""Sequence-parallel attention: the long-context machinery of the framework.

The reference has no attention at all (SURVEY §2.4: "no ring attention … no attention
anywhere"); its long-axis machinery is halo exchange, resplit pencils and the ring
rotation of ``spatial/distance.py:209``. On TPU the same ring schedule, applied to
attention, is *ring attention* (blockwise online-softmax attention with k/v chunks
rotating over the ICI torus via ``ppermute``) — so the TPU build promotes attention to
a first-class op with three execution strategies:

- **dense** — one device or replicated inputs: plain blockwise attention, XLA-fused.
- **ring** (``ring_attention``) — q/k/v sharded on the *sequence* axis. P steps; at
  each step every device attends its local queries against the currently-held k/v
  chunk with a running (m, l, o) online-softmax accumulator, then rotates k/v one
  neighbour around the ring. Peak memory per device is O(T/P) and the k/v transfer
  overlaps the matmuls — the standard TPU context-parallel schedule.
- **Ulysses** (``ulysses_attention``) — q/k/v sharded on sequence; two ``all_to_all``
  reshards flip the sharding to the *head* axis, attention runs dense per head-shard,
  and a final ``all_to_all`` flips back. Cheaper than the ring when heads ≥ devices
  and the full sequence fits per device.

``scaled_dot_product_attention`` is the torch-parity entry point
(torch.nn.functional.scaled_dot_product_attention semantics); on a DNDarray whose
sequence axis is split it dispatches to the ring automatically.

All accumulation is float32 regardless of input dtype (bf16 inputs stay bf16 on the
MXU, ``preferred_element_type`` lifts the products).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.kernels.flash_attention import flash_attention, use_flash

from ..core.dndarray import DNDarray

__all__ = [
    "scaled_dot_product_attention",
    "ring_attention",
    "ring_attention_zigzag",
    "zigzag_order",
    "zigzag_inverse",
    "ulysses_attention",
    "MultiheadAttention",
    "TransformerEncoderLayer",
    "TransformerEncoder",
    "TransformerDecoderLayer",
    "TransformerDecoder",
    "Transformer",
]

_NEG_INF = float(np.finfo(np.float32).min)


def _attention_weights(q, k, mask, is_causal, scale):
    """Normalized (row-stochastic, fully-masked rows → 0) attention weights in
    f32 — the shared score/causal/mask/stabilized-softmax pipeline of the XLA
    paths (with and without dropout)."""
    d = q.shape[-1]
    s = (1.0 / math.sqrt(d)) if scale is None else scale
    scores = jnp.einsum(
        "...qd,...kd->...qk", q, k, preferred_element_type=jnp.float32
    ) * jnp.float32(s)
    if is_causal:
        causal = jnp.arange(q.shape[-2])[:, None] >= jnp.arange(k.shape[-2])[None, :]
        scores = jnp.where(causal, scores, _NEG_INF)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, _NEG_INF)
        else:
            scores = scores + mask.astype(jnp.float32)
    # rows where everything is masked: keep them finite; their weights are 0
    m = jnp.maximum(jnp.max(scores, axis=-1, keepdims=True), _NEG_INF / 2)
    p = jnp.exp(scores - m)
    return p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)


def _dense_attention(q, k, v, mask=None, is_causal=False, scale=None):
    """Single-device exact attention on local arrays, f32 accumulation.

    q: (..., Tq, D), k/v: (..., Tk, D). Causal masking is top-left aligned
    (position i attends keys ≤ i), matching torch sdpa. On TPU, unmasked
    block-even shapes run the flash Pallas kernel (streaming VMEM, no (T,T)
    score matrix in HBM); everything else takes the XLA path below.
    """

    if use_flash(q, k, v, mask, scale):
        return flash_attention(q, k, v, is_causal, scale, mask)
    pw = _attention_weights(q, k, mask, is_causal, scale)
    return jnp.einsum(
        "...qk,...kd->...qd", pw, v, preferred_element_type=jnp.float32
    ).astype(q.dtype)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p: float = 0.0,
                                 is_causal: bool = False,
                                 scale: Optional[float] = None,
                                 enable_gqa: bool = False,
                                 dropout_key=None):
    """torch.nn.functional.scaled_dot_product_attention semantics (full signature:
    ``attn_mask, dropout_p, is_causal, scale, enable_gqa``).

    Inputs are (..., T, D) — typically (B, H, T, D). On plain arrays this is one
    fused XLA program. On DNDarrays split along the sequence axis (dim -2) it runs
    :func:`ring_attention` under ``shard_map`` — context parallelism without the
    caller changing a line.

    ``enable_gqa`` broadcasts grouped k/v heads (Hkv dividing Hq) like torch.
    ``dropout_p`` applies torch's train-time inverted attention dropout (drop
    probabilities after softmax, rescale kept ones by 1/(1-p)) and needs an
    explicit ``dropout_key`` (jax has no ambient RNG state); it forces the XLA
    path.
    """
    if not 0.0 <= dropout_p <= 1.0:
        raise ValueError(f"dropout_p must be in [0, 1], got {dropout_p}")
    if dropout_p:
        if dropout_key is None:
            raise ValueError(
                "dropout_p > 0 needs an explicit dropout_key PRNG key (jax has no "
                "ambient RNG state like torch)"
            )
    if enable_gqa:
        hq = query.shape[-3]
        hkv = key.shape[-3]
        if value.shape[-3] != hkv:
            raise ValueError(
                f"enable_gqa needs key and value to share a head count, got "
                f"{hkv} and {value.shape[-3]}"
            )
        if hq != hkv:
            if hq % hkv:
                raise ValueError(f"enable_gqa needs Hkv | Hq, got {hkv}, {hq}")
            rep = hq // hkv
            key = _repeat_kv_heads(key, rep)
            value = _repeat_kv_heads(value, rep)
    if dropout_p:
        if isinstance(query, DNDarray) and query.split == query.ndim - 2:
            import warnings

            warnings.warn(
                "scaled_dot_product_attention dropout forfeits the ring-attention "
                "path on sequence-split inputs: the (T, T) weight matrix is "
                "materialized densely. Use dropout_p=0 for long-context runs.",
                stacklevel=2,
            )
        q_ = query.larray if isinstance(query, DNDarray) else query
        k_ = key.larray if isinstance(key, DNDarray) else key
        v_ = value.larray if isinstance(value, DNDarray) else value
        m_ = attn_mask.larray if isinstance(attn_mask, DNDarray) else attn_mask
        out = _dense_attention_dropout(q_, k_, v_, m_, is_causal, scale,
                                       dropout_p, dropout_key)
        if isinstance(query, DNDarray):
            from ..core._operations import wrap_result

            return wrap_result(out, query, query.split)
        return out
    if isinstance(query, DNDarray):
        from ..core._operations import wrap_result

        seq_axis = query.ndim - 2
        if (
            query.split == seq_axis
            and isinstance(key, DNDarray) and key.split == seq_axis
            and isinstance(value, DNDarray) and value.split == seq_axis
            and attn_mask is None
            and query.comm.is_distributed()
            and isinstance(query.comm.axis_name, str)
            and query.shape[seq_axis] % query.comm.size == 0
            and key.shape[seq_axis] % query.comm.size == 0
        ):
            out = _ring_sharded(
                query.larray, key.larray, value.larray, query.comm,
                is_causal=is_causal, scale=scale,
            )
            return wrap_result(out, query, query.split)
        q = query.larray
        k = key.larray if isinstance(key, DNDarray) else key
        v = value.larray if isinstance(value, DNDarray) else value
        m = attn_mask.larray if isinstance(attn_mask, DNDarray) else attn_mask
        out = _dense_attention(q, k, v, m, is_causal, scale)
        return wrap_result(out, query, query.split)
    k = key.larray if isinstance(key, DNDarray) else key
    v = value.larray if isinstance(value, DNDarray) else value
    m = attn_mask.larray if isinstance(attn_mask, DNDarray) else attn_mask
    return _dense_attention(query, k, v, m, is_causal, scale)


def _online_attend(q_blk, q_pos, o, m, l, k_blk, v_blk, k_pos, s, masked: bool):
    """One online-softmax block merge shared by the ring variants: returns the
    updated (o, m, l) accumulator after q_blk attends k_blk/v_blk, optionally
    causal-masked by the global positions."""
    scores = jnp.einsum(
        "...qd,...kd->...qk", q_blk, k_blk, preferred_element_type=jnp.float32
    ) * jnp.float32(s)
    if masked:
        scores = jnp.where(q_pos[:, None] >= k_pos[None, :], scores, _NEG_INF)
    m_blk = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    m_safe = jnp.maximum(m_new, _NEG_INF / 2)
    corr = jnp.exp(m - m_safe)
    pij = jnp.exp(scores - m_safe[..., None])
    l_new = l * corr + jnp.sum(pij, axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "...qk,...kd->...qd", pij, v_blk, preferred_element_type=jnp.float32
    )
    return o_new, m_new, l_new


def _repeat_kv_heads(x, rep: int):
    """GQA: tile k/v heads to match the query head count (torch enable_gqa)."""
    if isinstance(x, DNDarray):
        from ..core._operations import wrap_result

        v = jnp.repeat(x.larray, rep, axis=-3)
        split = x.split  # the head axis is -3; seq/batch splits survive the repeat
        return wrap_result(v, x, split)
    return jnp.repeat(x, rep, axis=-3)


def _dense_attention_dropout(q, k, v, mask, is_causal, scale, dropout_p, key):
    """Dense attention with torch's train-time inverted attention dropout: drop
    probabilities after softmax, rescale kept ones by 1/(1-p)."""
    if dropout_p == 1.0:  # torch: every weight dropped, output all-zero
        return jnp.zeros(q.shape[:-1] + (v.shape[-1],), q.dtype)
    pw = _attention_weights(q, k, mask, is_causal, scale)
    keep = jax.random.bernoulli(key, 1.0 - dropout_p, pw.shape)
    pw = jnp.where(keep, pw / (1.0 - dropout_p), 0.0)
    return jnp.einsum(
        "...qk,...kd->...qd", pw, v, preferred_element_type=jnp.float32
    ).astype(q.dtype)


def ring_attention(q, k, v, axis_name: str, is_causal: bool = False,
                   scale: Optional[float] = None):
    """Ring attention over sequence-sharded chunks — call inside ``shard_map``.

    q/k/v: local chunks (..., T_local, D) of a global (..., T, D); the sequence axis
    is sharded over ``axis_name``. P steps of blockwise attention with an online
    softmax; k/v rotate one neighbour per step (ppermute), so no device ever holds
    more than 1/P of the keys. Equivalent to dense softmax(qkᵀ)v up to fp error.
    """
    p = lax.psum(1, axis_name)  # ht: ignore[collective-uncontracted] -- axis-name shard_map-body kernel API: no communicator in scope by design; callers (attention()/_ring_sharded) own the comm
    my = lax.axis_index(axis_name)
    tq = q.shape[-2]
    tk = k.shape[-2]
    d = q.shape[-1]
    s = (1.0 / math.sqrt(d)) if scale is None else scale
    q_pos = my * tq + jnp.arange(tq)

    # derive the accumulators from q so they carry q's device-varying type under
    # shard_map's representation checks (a fresh jnp.zeros would be "replicated")
    zero_q = jnp.sum(q.astype(jnp.float32) * 0, axis=-1)  # (..., Tq) of zeros
    o0 = jnp.zeros_like(q, jnp.float32)
    m0 = zero_q + _NEG_INF
    l0 = zero_q
    perm = [(i, (i - 1) % p) for i in range(p)]  # after s steps, device i holds chunk (i+s) % p

    def attend(o, m, l, k_c, v_c, src):
        k_pos = src * tk + jnp.arange(tk)
        return _online_attend(q, q_pos, o, m, l, k_c, v_c, k_pos, s, is_causal)

    def step(carry, step_idx):
        k_c, v_c, o, m, l = carry
        o, m, l = attend(o, m, l, k_c, v_c, (my + step_idx) % p)
        k_next = lax.ppermute(k_c, axis_name, perm)  # ht: ignore[collective-uncontracted] -- axis-name shard_map-body kernel API: no communicator in scope by design; callers (attention()/_ring_sharded) own the comm
        v_next = lax.ppermute(v_c, axis_name, perm)  # ht: ignore[collective-uncontracted] -- axis-name shard_map-body kernel API: no communicator in scope by design; callers (attention()/_ring_sharded) own the comm
        return (k_next, v_next, o, m, l), None

    # scan only the p-1 steps that are followed by a rotation; the last block is
    # consumed outside the scan so its k/v are never ppermuted onward (that final
    # rotation would be dead inter-chip traffic XLA cannot eliminate from the carry)
    o, m, l = o0, m0, l0
    if p > 1:
        (k, v, o, m, l), _ = lax.scan(
            step, (k, v, o, m, l), jnp.arange(p - 1)
        )
    o, m, l = attend(o, m, l, k, v, (my + p - 1) % p)
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def _ring_sharded(q, k, v, comm, is_causal=False, scale=None):
    """Launch :func:`ring_attention` under shard_map on ``comm``'s mesh.

    q/k/v are global (B, H, T, D)-like jax.Arrays sequence-sharded on dim -2.
    """
    from jax import shard_map

    mesh = comm.mesh
    axis = comm.axis_name
    ndim = q.ndim
    spec = P(*([None] * (ndim - 2) + [axis, None]))

    fn = shard_map(
        partial(ring_attention, axis_name=axis, is_causal=is_causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def zigzag_order(t: int, p: int) -> np.ndarray:
    """Sequence permutation for the zigzag causal layout: the sequence is cut into
    ``2p`` chunks and device ``i`` holds chunks ``(i, 2p-1-i)``. Apply with
    ``x[..., zigzag_order(T, p), :]`` before :func:`ring_attention_zigzag`; invert
    with :func:`zigzag_inverse`."""
    if t % (2 * p):
        raise ValueError(
            f"zigzag layout needs the sequence length divisible by 2*p, got t={t}, p={p}"
        )
    c = t // (2 * p)
    order = []
    for i in range(p):
        order.extend(range(i * c, (i + 1) * c))
        order.extend(range((2 * p - 1 - i) * c, (2 * p - i) * c))
    return np.asarray(order, dtype=np.int32)


def zigzag_inverse(t: int, p: int) -> np.ndarray:
    """Inverse permutation of :func:`zigzag_order`."""
    order = zigzag_order(t, p)
    inv = np.empty_like(order)
    inv[order] = np.arange(t, dtype=np.int32)
    return inv


def ring_attention_zigzag(q, k, v, axis_name: str, scale: Optional[float] = None):
    """Load-balanced CAUSAL ring attention — call inside ``shard_map`` with inputs
    in the zigzag layout (:func:`zigzag_order`).

    The plain causal ring wastes half its FLOPs: in SPMD lockstep every device
    executes every step, but device ``i`` only *needs* the k/v chunks ``≤ i`` —
    the rest are fully masked compute. With the zigzag assignment (device ``i``
    holds sequence chunks ``i`` and ``2p-1-i``) every step has exactly one
    always-needed half-product (high queries × low keys) and one
    predicate-selected half-product, so per-device work is ``T²/2p²`` per step —
    half the plain ring — and uniform across devices. This is the standard
    long-context balance trick (e.g. llama3-style context parallelism).

    q/k/v: local (..., 2c, D) chunks where the first ``c`` rows are the device's
    LOW chunk and the last ``c`` its HIGH chunk. Output is in the same layout.
    """
    p = lax.psum(1, axis_name)  # ht: ignore[collective-uncontracted] -- axis-name shard_map-body kernel API: no communicator in scope by design; callers (attention()/_ring_sharded) own the comm
    my = lax.axis_index(axis_name)
    two_c = q.shape[-2]
    c = two_c // 2
    d = q.shape[-1]
    s = (1.0 / math.sqrt(d)) if scale is None else scale

    q_lo, q_hi = q[..., :c, :], q[..., c:, :]
    # global chunk ids: lo = my, hi = 2p-1-my; positions inside a chunk are local
    lo_pos = my * c + jnp.arange(c)

    def hi_pos_of(dev):
        return (2 * p - 1 - dev) * c + jnp.arange(c)

    def attend_block(q_blk, q_positions, o, m, l, k_blk, v_blk, k_positions,
                     masked: bool):
        return _online_attend(
            q_blk, q_positions, o, m, l, k_blk, v_blk, k_positions, s, masked
        )

    zero = jnp.sum(q_lo.astype(jnp.float32) * 0, axis=-1)
    acc_lo = (jnp.zeros_like(q_lo, jnp.float32), zero + _NEG_INF, zero)
    acc_hi = (jnp.zeros_like(q_hi, jnp.float32), zero + _NEG_INF, zero)
    perm = [(i, (i - 1) % p) for i in range(p)]

    # step 0 (self): lo×lo and hi×hi are diagonal blocks (masked); hi×lo is full
    k_lo, k_hi = k[..., :c, :], k[..., c:, :]
    v_lo, v_hi = v[..., :c, :], v[..., c:, :]
    acc_lo = attend_block(q_lo, lo_pos, *acc_lo, k_lo, v_lo, lo_pos, True)
    acc_hi = attend_block(q_hi, hi_pos_of(my), *acc_hi, k_hi, v_hi, hi_pos_of(my), True)
    acc_hi = attend_block(q_hi, hi_pos_of(my), *acc_hi, k_lo, v_lo, lo_pos, False)

    def attend_pair(kc, vc, src, acc_lo, acc_hi):
        k_lo, k_hi = kc[..., :c, :], kc[..., c:, :]
        v_lo, v_hi = vc[..., :c, :], vc[..., c:, :]
        # hi queries × src's LOW keys: always needed (2p-1-my > src for src != my)
        acc_hi = attend_block(q_hi, hi_pos_of(my), *acc_hi, k_lo, v_lo,
                              src * c + jnp.arange(c), False)
        # the predicate-selected half: LOW q × src's low k (src < my), else
        # HIGH q × src's high k (src > my) — both full blocks, same shapes
        pred = src < my
        q_sel = jnp.where(pred, q_lo, q_hi)
        k_sel = jnp.where(pred, k_lo, k_hi)
        v_sel = jnp.where(pred, v_lo, v_hi)
        o_sel, m_sel, l_sel = (
            jnp.where(pred, acc_lo[0], acc_hi[0]),
            jnp.where(pred, acc_lo[1], acc_hi[1]),
            jnp.where(pred, acc_lo[2], acc_hi[2]),
        )
        upd = attend_block(
            q_sel, jnp.zeros(c, jnp.int32), o_sel, m_sel, l_sel,
            k_sel, v_sel, jnp.zeros(c, jnp.int32), False,
        )
        acc_lo = tuple(jnp.where(pred, u, a) for u, a in zip(upd, acc_lo))
        acc_hi = tuple(jnp.where(pred, a, u) for a, u in zip(acc_hi, upd))
        return acc_lo, acc_hi

    def step(carry, step_idx):
        kc, vc, acc_lo, acc_hi = carry
        # rotate the HELD pair onward while attending it — both only read kc/vc,
        # so the ICI transfer overlaps the matmuls (same structure as the plain
        # ring); the final pair is consumed outside the scan with no dead hop
        k_next = lax.ppermute(kc, axis_name, perm)  # ht: ignore[collective-uncontracted] -- axis-name shard_map-body kernel API: no communicator in scope by design; callers (attention()/_ring_sharded) own the comm
        v_next = lax.ppermute(vc, axis_name, perm)  # ht: ignore[collective-uncontracted] -- axis-name shard_map-body kernel API: no communicator in scope by design; callers (attention()/_ring_sharded) own the comm
        acc_lo, acc_hi = attend_pair(kc, vc, (my + step_idx) % p, acc_lo, acc_hi)
        return (k_next, v_next, acc_lo, acc_hi), None

    if p > 1:
        kc = lax.ppermute(k, axis_name, perm)  # ht: ignore[collective-uncontracted] -- axis-name shard_map-body kernel API: no communicator in scope by design; callers (attention()/_ring_sharded) own the comm
        vc = lax.ppermute(v, axis_name, perm)  # ht: ignore[collective-uncontracted] -- axis-name shard_map-body kernel API: no communicator in scope by design; callers (attention()/_ring_sharded) own the comm
        if p > 2:
            (kc, vc, acc_lo, acc_hi), _ = lax.scan(
                step, (kc, vc, acc_lo, acc_hi), jnp.arange(1, p - 1)
            )
        acc_lo, acc_hi = attend_pair(kc, vc, (my + p - 1) % p, acc_lo, acc_hi)
    o_lo = acc_lo[0] / jnp.maximum(acc_lo[2], 1e-30)[..., None]
    o_hi = acc_hi[0] / jnp.maximum(acc_hi[2], 1e-30)[..., None]
    return jnp.concatenate([o_lo, o_hi], axis=-2).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, is_causal: bool = False,
                      scale: Optional[float] = None):
    """Ulysses / all-to-all sequence parallelism — call inside ``shard_map``.

    q/k/v: (B, H, T_local, D) sequence-sharded chunks with H divisible by the mesh
    size. Two all_to_alls flip the sharding sequence→heads, attention runs dense on
    the full sequence for H/P heads, one all_to_all flips back.
    """
    # (B, H, T/P, D) -> (B, H/P, T, D): split heads axis (1), concat seq axis (2)
    qh = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2, tiled=True)  # ht: ignore[collective-uncontracted] -- axis-name shard_map-body kernel API: no communicator in scope by design; callers (attention()/_ring_sharded) own the comm
    kh = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)  # ht: ignore[collective-uncontracted] -- axis-name shard_map-body kernel API: no communicator in scope by design; callers (attention()/_ring_sharded) own the comm
    vh = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)  # ht: ignore[collective-uncontracted] -- axis-name shard_map-body kernel API: no communicator in scope by design; callers (attention()/_ring_sharded) own the comm
    o = _dense_attention(qh, kh, vh, is_causal=is_causal, scale=scale)
    return lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1, tiled=True)  # ht: ignore[collective-uncontracted] -- axis-name shard_map-body kernel API: no communicator in scope by design; callers (attention()/_ring_sharded) own the comm


from .modules import Module


class MultiheadAttention(Module):
    """torch.nn.MultiheadAttention semantics (batch_first, self- or cross-attention).

    Packed in-projection weight (3E, E) + out-projection (E, E), both with torch's
    xavier_uniform_ / zero-bias init, so state_dicts map 1:1 (with ``kdim``/``vdim``
    differing from ``embed_dim``, separate ``q/k/v_proj_weight`` under torch's
    names, like torch's ``_qkv_same_embed_dim=False`` path). ``apply(params, x)``
    is self-attention; ``apply(params, (q, k, v))`` is cross-attention. On
    sequence-split DNDarray inputs the underlying sdpa runs the ring schedule.

    ``dropout`` is torch's attention-weight dropout: active only under
    ``apply(..., train=True, key=...)`` (explicit PRNG key — jax has no ambient
    RNG state); the eval-style ``mha(q, k, v)`` call never drops, like torch
    modules in ``.eval()``.
    """

    def __init__(self, embed_dim: int, num_heads: int, dropout: float = 0.0,
                 bias: bool = True, batch_first: bool = True,
                 kdim: Optional[int] = None, vdim: Optional[int] = None):
        if embed_dim % num_heads:
            raise ValueError("embed_dim must be divisible by num_heads")
        if not 0.0 <= dropout <= 1.0:
            raise ValueError(f"dropout must be in [0, 1], got {dropout}")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        self.bias = bias
        self.batch_first = batch_first
        self.kdim = embed_dim if kdim is None else kdim
        self.vdim = embed_dim if vdim is None else vdim
        # torch: packed (3E, E) in-projection only when q/k/v share the embed dim;
        # otherwise separate q/k/v weights under torch's exact param names
        self._qkv_same_embed_dim = self.kdim == embed_dim and self.vdim == embed_dim

    def init(self, key):
        e = self.embed_dim
        # torch's _reset_parameters: xavier_uniform_ on every projection weight,
        # zeros on both biases
        xavier = lambda k, shape: jax.random.uniform(
            k, shape, jnp.float32,
            -math.sqrt(6.0 / sum(shape)), math.sqrt(6.0 / sum(shape)),
        )
        if self._qkv_same_embed_dim:
            k1, k2 = jax.random.split(key)
            params = {
                "in_proj_weight": xavier(k1, (3 * e, e)),
                "out_proj_weight": xavier(k2, (e, e)),
            }
        else:
            kq, kk, kv, k2 = jax.random.split(key, 4)
            params = {
                "q_proj_weight": xavier(kq, (e, e)),
                "k_proj_weight": xavier(kk, (e, self.kdim)),
                "v_proj_weight": xavier(kv, (e, self.vdim)),
                "out_proj_weight": xavier(k2, (e, e)),
            }
        if self.bias:
            params["in_proj_bias"] = jnp.zeros((3 * e,), jnp.float32)
            params["out_proj_bias"] = jnp.zeros((e,), jnp.float32)
        return params

    def apply(self, params, x, *, key=None, train=False, attn_mask=None,
              is_causal: bool = False, key_padding_mask=None):
        if isinstance(x, tuple):
            q_in, k_in, v_in = x
        else:
            q_in = k_in = v_in = x
        unwrap = lambda t: t.larray if isinstance(t, DNDarray) else t
        attn_mask = unwrap(attn_mask) if attn_mask is not None else None
        if attn_mask is not None and attn_mask.dtype == jnp.bool_:
            # torch.nn.MultiheadAttention convention: True = NOT allowed to attend
            # — the INVERSE of torch sdpa's (and our sdpa path's) True = attend.
            # Float masks are additive in both conventions.
            attn_mask = ~attn_mask
        if key_padding_mask is not None:
            # (B, S): bool True = ignore that key for every query; floats are an
            # additive bias (both torch conventions); merged additively so it
            # broadcasts over heads and queries
            from ..core.kernels.flash_attention import _as_bias

            kpm = unwrap(key_padding_mask)
            pad = (
                jnp.where(kpm, jnp.float32(_NEG_INF), jnp.float32(0))
                if kpm.dtype == jnp.bool_
                else kpm.astype(jnp.float32)
            )[:, None, None, :]  # (B, 1, 1, S)
            attn_mask = pad if attn_mask is None else _as_bias(attn_mask) + pad
        proto = q_in if isinstance(q_in, DNDarray) else None
        seq_axis_in = 1 if self.batch_first else 0
        seq_split = (
            proto is not None
            and proto.split == seq_axis_in
            and isinstance(k_in, DNDarray) and k_in.split == seq_axis_in
            and isinstance(v_in, DNDarray) and v_in.split == seq_axis_in
        )
        q_in, k_in, v_in = unwrap(q_in), unwrap(k_in), unwrap(v_in)
        if not self.batch_first:
            q_in, k_in, v_in = (jnp.swapaxes(t, 0, 1) for t in (q_in, k_in, v_in))

        e = self.embed_dim
        b = params.get("in_proj_bias")
        bias_of = lambda i: b[i * e:(i + 1) * e] if b is not None else 0.0
        if self._qkv_same_embed_dim:
            w = params["in_proj_weight"]
            proj = lambda t, i: t @ w[i * e:(i + 1) * e].T + bias_of(i)
            q, k, v = proj(q_in, 0), proj(k_in, 1), proj(v_in, 2)
        else:
            q = q_in @ params["q_proj_weight"].T + bias_of(0)
            k = k_in @ params["k_proj_weight"].T + bias_of(1)
            v = v_in @ params["v_proj_weight"].T + bias_of(2)

        def split_heads(t):  # (B, T, E) -> (B, H, T, hd)
            bsz, tlen, _ = t.shape
            return t.reshape(bsz, tlen, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)

        qh, kh, vh = split_heads(q), split_heads(k), split_heads(v)
        comm = proto.comm if proto is not None else None
        if train and self.dropout > 0.0:
            # torch: attention-weight dropout only in train mode; needs an
            # explicit PRNG key (jax has no ambient RNG state)
            if key is None:
                raise ValueError(
                    "MultiheadAttention with dropout > 0 needs apply(..., key=...) "
                    "in train mode (jax has no ambient RNG state like torch)"
                )
            if seq_split:
                import warnings

                warnings.warn(
                    "MultiheadAttention dropout forfeits the ring-attention path: "
                    "the (T, T) weight matrix is materialized densely. For "
                    "long-context training use dropout=0 (or drop residual "
                    "streams instead).",
                    stacklevel=2,
                )
            o = _dense_attention_dropout(
                qh, kh, vh, attn_mask, is_causal, None, self.dropout, key
            )
        elif (
            seq_split
            and attn_mask is None
            and comm is not None
            and comm.is_distributed()
            and isinstance(comm.axis_name, str)
            and qh.shape[2] % comm.size == 0
            and kh.shape[2] % comm.size == 0
        ):
            # the documented long-context path: sequence-split input → ring schedule
            o = _ring_sharded(qh, kh, vh, comm, is_causal=is_causal)
        else:
            o = _dense_attention(qh, kh, vh, mask=attn_mask, is_causal=is_causal)
        bsz, _, tlen, _ = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(bsz, tlen, e)
        o = o @ params["out_proj_weight"].T
        if self.bias:
            o = o + params["out_proj_bias"]
        if not self.batch_first:
            o = jnp.swapaxes(o, 0, 1)
        if proto is not None:
            from ..core._operations import wrap_result

            # output has the query's (B, T, E) / (T, B, E) shape: batch and sequence
            # splits both survive in either layout (only the embed axis is mixed by
            # the projections)
            keep = proto.split if proto.split in (0, 1) else None
            return wrap_result(o, proto, keep)
        return o

    def __call__(self, query, key=None, value=None, key_padding_mask=None,
                 need_weights: bool = False, attn_mask=None,
                 average_attn_weights: bool = True, is_causal: bool = False):
        """torch call convention: ``mha(q, k, v)`` returns ``(output, None)`` when
        ``need_weights=False`` (weights are never materialized — blockwise kernels
        don't form the T×T matrix). ``key_padding_mask`` is (B, S) with True =
        ignore that key, like torch."""
        if need_weights:
            raise NotImplementedError(
                "need_weights=True would materialize the T×T attention matrix; "
                "blockwise/ring execution never forms it"
            )
        if key is None:
            key = query
        if value is None:
            value = key
        x = query if (key is query and value is query) else (query, key, value)
        # honor the bound train/key context like base Module.__call__ (the
        # ``key`` name here is the attention key tensor, so the RNG key can only
        # arrive via _bind from a parent apply(..., train=True, key=...) or via
        # .train() mode)
        rng_key, train = self._resolve_ctx()
        out = self.apply(
            self.params, x, key=rng_key, train=train, attn_mask=attn_mask,
            is_causal=is_causal, key_padding_mask=key_padding_mask,
        )
        return out, None


def _keyed_dropout(x, p: float, key, train: bool):
    """Inverted dropout on a jax.Array or DNDarray (explicit key; inert in eval)
    — delegates to :func:`heat_tpu.nn.functional.dropout`, which preserves any
    split (elementwise op)."""
    from . import functional as F

    return F.dropout(x, p, training=train, key=key)


def _resolve_activation(activation):
    """'relu' / 'gelu' / any callable — the torch TransformerXLayer contract."""
    if callable(activation):
        return activation
    if activation in ("relu", "gelu"):
        from . import functional as F

        return getattr(F, activation)
    raise ValueError(
        f"activation must be 'relu', 'gelu' or a callable, got {activation!r}"
    )


class _FeedForwardMixin:
    """The linear1 → activation → dropout → linear2 → dropout block shared by the
    encoder and decoder layers (expects self.linear1/linear2/activation/dropout_p)."""

    def _ff_block(self, params, x, key, train):
        k1, k2 = jax.random.split(key) if key is not None else (None, None)
        h = self.activation(self.linear1.apply(params["linear1"], x))
        h = _keyed_dropout(h, self.dropout_p, k1, train)
        h = self.linear2.apply(params["linear2"], h)
        return _keyed_dropout(h, self.dropout_p, k2, train)


class _LayerStack(Module):
    """N fresh-parameter deep copies of a layer plus an optional final norm —
    the shared container shape of TransformerEncoder and TransformerDecoder."""

    def __init__(self, layer, num_layers: int, norm=None):
        import copy

        self.layers = [copy.deepcopy(layer) for _ in range(num_layers)]
        self.num_layers = num_layers
        self.norm = norm

    def named_submodules(self):
        subs = [(str(i), m) for i, m in enumerate(self.layers)]
        if self.norm is not None:
            subs.append(("norm", self.norm))
        return subs

    def init(self, key):
        ks = jax.random.split(key, self.num_layers + 1)
        params = {str(i): m.init(k) for (i, m), k in
                  zip(enumerate(self.layers), ks)}
        if self.norm is not None:
            params["norm"] = self.norm.init(ks[-1])
        return params

    def _run_stack(self, params, x, key, call):
        """Thread x through the layers (per-layer key split), then the final norm.
        ``call(layer, layer_params, x, k)`` runs one layer."""
        ks = (
            jax.random.split(key, self.num_layers)
            if key is not None
            else [None] * self.num_layers
        )
        for i, (layer, k) in enumerate(zip(self.layers, ks)):
            x = call(layer, params[str(i)], x, k)
        if self.norm is not None:
            x = self.norm.apply(params["norm"], x)
        return x


class TransformerEncoderLayer(_FeedForwardMixin, Module):
    """torch.nn.TransformerEncoderLayer semantics (self-attention + feedforward,
    post-norm by default, ``norm_first`` pre-norm variant).

    The reference exposes this via its torch fall-through (``nn/__init__.py:18-31``);
    here it composes the native :class:`MultiheadAttention` (ring dispatch on
    sequence-split DNDarrays), :class:`~heat_tpu.nn.modules.Linear` and LayerNorm,
    so the whole layer jits to one XLA program. ``batch_first`` defaults True (the
    TPU-natural layout, unlike torch's False default — see the deviations page);
    dropout needs ``apply(..., train=True, key=...)``.
    """

    def __init__(self, d_model: int, nhead: int, dim_feedforward: int = 2048,
                 dropout: float = 0.1, activation="relu",
                 layer_norm_eps: float = 1e-5, batch_first: bool = True,
                 norm_first: bool = False, bias: bool = True):
        from .modules import LayerNorm, Linear

        self.self_attn = MultiheadAttention(
            d_model, nhead, dropout=dropout, bias=bias, batch_first=batch_first
        )
        self.linear1 = Linear(d_model, dim_feedforward, bias=bias)
        self.linear2 = Linear(dim_feedforward, d_model, bias=bias)
        self.norm1 = LayerNorm(d_model, eps=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, eps=layer_norm_eps)
        self.dropout_p = dropout
        self.norm_first = norm_first
        self.activation = _resolve_activation(activation)

    def init(self, key):
        ks = jax.random.split(key, 5)
        return {
            "self_attn": self.self_attn.init(ks[0]),
            "linear1": self.linear1.init(ks[1]),
            "linear2": self.linear2.init(ks[2]),
            "norm1": self.norm1.init(ks[3]),
            "norm2": self.norm2.init(ks[4]),
        }

    def _sa_block(self, params, x, key, train, src_mask, src_key_padding_mask,
                  is_causal):
        k_attn, k_drop = (
            jax.random.split(key) if key is not None else (None, None)
        )
        out = self.self_attn.apply(
            params["self_attn"], x, key=k_attn, train=train, attn_mask=src_mask,
            key_padding_mask=src_key_padding_mask, is_causal=is_causal,
        )
        return _keyed_dropout(out, self.dropout_p, k_drop, train)

    def apply(self, params, src, *, key=None, train=False, src_mask=None,
              src_key_padding_mask=None, is_causal: bool = False):
        k_sa, k_ff = jax.random.split(key) if key is not None else (None, None)
        norm1 = lambda v: self.norm1.apply(params["norm1"], v)
        norm2 = lambda v: self.norm2.apply(params["norm2"], v)
        x = src
        if self.norm_first:
            x = x + self._sa_block(params, norm1(x), k_sa, train, src_mask,
                                   src_key_padding_mask, is_causal)
            x = x + self._ff_block(params, norm2(x), k_ff, train)
        else:
            x = norm1(x + self._sa_block(params, x, k_sa, train, src_mask,
                                         src_key_padding_mask, is_causal))
            x = norm2(x + self._ff_block(params, x, k_ff, train))
        return x

    def __call__(self, src, src_mask=None, src_key_padding_mask=None,
                 is_causal: bool = False, *, key=None, train=None):
        key, train = self._resolve_ctx(key, train)
        return self.apply(
            self.params, src, key=key, train=train, src_mask=src_mask,
            src_key_padding_mask=src_key_padding_mask, is_causal=is_causal,
        )


class TransformerEncoder(_LayerStack):
    """torch.nn.TransformerEncoder: N independently-parameterised copies of an
    encoder layer (same hyperparameters, fresh params per layer), plus an
    optional final norm."""

    def __init__(self, encoder_layer: TransformerEncoderLayer, num_layers: int,
                 norm=None):
        super().__init__(encoder_layer, num_layers, norm)

    def apply(self, params, src, *, key=None, train=False, src_mask=None,
              src_key_padding_mask=None, is_causal: bool = False):
        return self._run_stack(
            params, src, key,
            lambda layer, p, x, k: layer.apply(
                p, x, key=k, train=train, src_mask=src_mask,
                src_key_padding_mask=src_key_padding_mask, is_causal=is_causal,
            ),
        )

    def __call__(self, src, src_mask=None, src_key_padding_mask=None,
                 is_causal: bool = False, *, key=None, train=None):
        key, train = self._resolve_ctx(key, train)
        return self.apply(
            self.params, src, key=key, train=train, src_mask=src_mask,
            src_key_padding_mask=src_key_padding_mask, is_causal=is_causal,
        )


class TransformerDecoderLayer(_FeedForwardMixin, Module):
    """torch.nn.TransformerDecoderLayer semantics: masked self-attention over the
    target, cross-attention into the encoder memory, then feedforward — each with
    residual + LayerNorm (post-norm default, ``norm_first`` pre-norm).

    Same composition story as :class:`TransformerEncoderLayer`; the reference
    reaches this through its torch fall-through (``nn/__init__.py:18-31``).
    """

    def __init__(self, d_model: int, nhead: int, dim_feedforward: int = 2048,
                 dropout: float = 0.1, activation="relu",
                 layer_norm_eps: float = 1e-5, batch_first: bool = True,
                 norm_first: bool = False, bias: bool = True):
        from .modules import LayerNorm, Linear

        self.self_attn = MultiheadAttention(
            d_model, nhead, dropout=dropout, bias=bias, batch_first=batch_first
        )
        self.multihead_attn = MultiheadAttention(
            d_model, nhead, dropout=dropout, bias=bias, batch_first=batch_first
        )
        self.linear1 = Linear(d_model, dim_feedforward, bias=bias)
        self.linear2 = Linear(dim_feedforward, d_model, bias=bias)
        self.norm1 = LayerNorm(d_model, eps=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, eps=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, eps=layer_norm_eps)
        self.dropout_p = dropout
        self.norm_first = norm_first
        self.activation = _resolve_activation(activation)

    def init(self, key):
        ks = jax.random.split(key, 7)
        return {
            "self_attn": self.self_attn.init(ks[0]),
            "multihead_attn": self.multihead_attn.init(ks[1]),
            "linear1": self.linear1.init(ks[2]),
            "linear2": self.linear2.init(ks[3]),
            "norm1": self.norm1.init(ks[4]),
            "norm2": self.norm2.init(ks[5]),
            "norm3": self.norm3.init(ks[6]),
        }

    def _attn_block(self, attn, params, q, kv, key, train, mask, padding_mask,
                    is_causal):
        k_attn, k_drop = (
            jax.random.split(key) if key is not None else (None, None)
        )
        x = q if kv is None else (q, kv, kv)
        out = attn.apply(
            params, x, key=k_attn, train=train, attn_mask=mask,
            key_padding_mask=padding_mask, is_causal=is_causal,
        )
        return _keyed_dropout(out, self.dropout_p, k_drop, train)

    def apply(self, params, tgt, memory=None, *, key=None, train=False,
              tgt_mask=None, memory_mask=None, tgt_key_padding_mask=None,
              memory_key_padding_mask=None, tgt_is_causal: bool = False,
              memory_is_causal: bool = False):
        if memory is None:
            raise ValueError("TransformerDecoderLayer needs the encoder memory")
        k_sa, k_ca, k_ff = (
            jax.random.split(key, 3) if key is not None else (None, None, None)
        )
        norm = lambda i, v: getattr(self, f"norm{i}").apply(params[f"norm{i}"], v)
        sa = lambda v, k: self._attn_block(
            self.self_attn, params["self_attn"], v, None, k, train, tgt_mask,
            tgt_key_padding_mask, tgt_is_causal,
        )
        ca = lambda v, k: self._attn_block(
            self.multihead_attn, params["multihead_attn"], v, memory, k, train,
            memory_mask, memory_key_padding_mask, memory_is_causal,
        )
        x = tgt
        if self.norm_first:
            x = x + sa(norm(1, x), k_sa)
            x = x + ca(norm(2, x), k_ca)
            x = x + self._ff_block(params, norm(3, x), k_ff, train)
        else:
            x = norm(1, x + sa(x, k_sa))
            x = norm(2, x + ca(x, k_ca))
            x = norm(3, x + self._ff_block(params, x, k_ff, train))
        return x

    def __call__(self, tgt, memory, tgt_mask=None, memory_mask=None,
                 tgt_key_padding_mask=None, memory_key_padding_mask=None,
                 tgt_is_causal: bool = False, memory_is_causal: bool = False,
                 *, key=None, train=None):
        key, train = self._resolve_ctx(key, train)
        return self.apply(
            self.params, tgt, memory, key=key, train=train, tgt_mask=tgt_mask,
            memory_mask=memory_mask, tgt_key_padding_mask=tgt_key_padding_mask,
            memory_key_padding_mask=memory_key_padding_mask,
            tgt_is_causal=tgt_is_causal, memory_is_causal=memory_is_causal,
        )


class TransformerDecoder(_LayerStack):
    """torch.nn.TransformerDecoder: N fresh-parameter copies of a decoder layer
    plus an optional final norm."""

    def __init__(self, decoder_layer: TransformerDecoderLayer, num_layers: int,
                 norm=None):
        super().__init__(decoder_layer, num_layers, norm)

    def apply(self, params, tgt, memory=None, *, key=None, train=False,
              **mask_kwargs):
        return self._run_stack(
            params, tgt, key,
            lambda layer, p, x, k: layer.apply(
                p, x, memory, key=k, train=train, **mask_kwargs
            ),
        )

    def __call__(self, tgt, memory, *, key=None, train=None, **mask_kwargs):
        key, train = self._resolve_ctx(key, train)
        return self.apply(self.params, tgt, memory, key=key, train=train,
                          **mask_kwargs)


class Transformer(Module):
    """torch.nn.Transformer semantics: an encoder-decoder pair sharing one set of
    hyperparameters, plus the ``generate_square_subsequent_mask`` helper.

    ``forward(src, tgt)`` runs ``decoder(tgt, encoder(src))``; all the usual mask
    and padding arguments pass through. ``batch_first`` defaults True (the
    TPU-natural layout — see the deviations page)."""

    def __init__(self, d_model: int = 512, nhead: int = 8,
                 num_encoder_layers: int = 6, num_decoder_layers: int = 6,
                 dim_feedforward: int = 2048, dropout: float = 0.1,
                 activation="relu", layer_norm_eps: float = 1e-5,
                 batch_first: bool = True, norm_first: bool = False,
                 bias: bool = True):
        from .modules import LayerNorm

        self.d_model = d_model
        self.nhead = nhead
        self.batch_first = batch_first
        self.encoder = TransformerEncoder(
            TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                layer_norm_eps, batch_first, norm_first, bias,
            ),
            num_encoder_layers,
            norm=LayerNorm(d_model, eps=layer_norm_eps),
        )
        self.decoder = TransformerDecoder(
            TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                layer_norm_eps, batch_first, norm_first, bias,
            ),
            num_decoder_layers,
            norm=LayerNorm(d_model, eps=layer_norm_eps),
        )

    def init(self, key):
        k1, k2 = jax.random.split(key)
        return {"encoder": self.encoder.init(k1), "decoder": self.decoder.init(k2)}

    def apply(self, params, src, tgt=None, *, key=None, train=False,
              src_mask=None, tgt_mask=None, memory_mask=None,
              src_key_padding_mask=None, tgt_key_padding_mask=None,
              memory_key_padding_mask=None, src_is_causal: bool = False,
              tgt_is_causal: bool = False, memory_is_causal: bool = False):
        if tgt is None:
            raise ValueError("Transformer needs both src and tgt")
        k1, k2 = jax.random.split(key) if key is not None else (None, None)
        memory = self.encoder.apply(
            params["encoder"], src, key=k1, train=train, src_mask=src_mask,
            src_key_padding_mask=src_key_padding_mask, is_causal=src_is_causal,
        )
        return self.decoder.apply(
            params["decoder"], tgt, memory, key=k2, train=train,
            tgt_mask=tgt_mask, memory_mask=memory_mask,
            tgt_key_padding_mask=tgt_key_padding_mask,
            memory_key_padding_mask=memory_key_padding_mask,
            tgt_is_causal=tgt_is_causal, memory_is_causal=memory_is_causal,
        )

    def __call__(self, src, tgt, *, key=None, train=None, **mask_kwargs):
        key, train = self._resolve_ctx(key, train)
        return self.apply(self.params, src, tgt, key=key, train=train,
                          **mask_kwargs)

    @staticmethod
    def generate_square_subsequent_mask(sz: int):
        """(sz, sz) additive f32 mask: 0 on/below the diagonal, -inf above —
        torch's causal-mask helper, usable as ``attn_mask``/``tgt_mask``."""
        return jnp.where(
            jnp.arange(sz)[:, None] >= jnp.arange(sz)[None, :],
            jnp.float32(0), jnp.float32(-jnp.inf),
        )
