"""Data-parallel model wrappers (reference heat/nn/data_parallel.py, 375 LoC).

The reference installs per-parameter backward hooks that Allreduce gradients (blocking
``:220`` or non-blocking ``:240`` with wait-handles resolved by the *next* iteration's
forward-pre-hooks). On TPU that machinery vanishes: the batch is one global array
sharded over the mesh's data axis, the loss is a global mean, and ``jax.grad`` under
``jit`` yields gradients whose cross-shard psum XLA inserts automatically. What remains
of ``DataParallel`` is the module veneer: identical parameter initialization everywhere
(seed-derived, the reference broadcasts instead) and split bookkeeping on the batch.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax

from ..core.communication import Communication, sanitize_comm
from ..core.dndarray import DNDarray
from .modules import Module

__all__ = ["DataParallel", "DataParallelMultiGPU"]


class DataParallel(Module):
    """Run the same model on every shard of a split batch (reference ``:22``).

    ``blocking_parameter_updates`` is kept for API parity; under XLA there is no
    blocking/non-blocking distinction — gradient reduction is fused into the step
    program and overlapped by the compiler.
    """

    def __init__(
        self,
        module: Module,
        comm: Optional[Communication] = None,
        optimizer=None,
        blocking_parameter_updates: bool = False,
    ):
        if not isinstance(module, Module):
            raise TypeError(
                f"module must be a heat_tpu.nn.Module (torch modules cannot execute on "
                f"TPU), got {type(module)}"
            )
        self.module = module
        self.comm = sanitize_comm(comm)
        self.blocking_parameter_updates = blocking_parameter_updates
        # identical initial parameters on every process (reference seeds torch and
        # broadcasts, data_parallel.py:105-106); params a user already set (e.g.
        # pretrained weights) are kept — jax arrays are deterministic across processes
        if not hasattr(module, "_params"):
            module.reset_parameters(seed=0)
        if optimizer is not None:
            optimizers = optimizer if isinstance(optimizer, (list, tuple)) else [optimizer]
            for opt in optimizers:
                opt._attach(self)

    # parameters live on the wrapped module
    @property
    def params(self):
        return self.module.params

    @params.setter
    def params(self, value):
        self.module.params = value

    def init(self, key):
        return self.module.init(key)

    def apply(self, params, x, *, key=None, train=False):
        return self.module.apply(params, x, key=key, train=train)

    def forward(self, x, **kwargs):
        return self.module(x, **kwargs)

    def __call__(self, x, **kwargs):
        return self.module(x, **kwargs)


class DataParallelMultiGPU(DataParallel):
    """Node-local DP tier (reference ``:313``: torch-DDP within a node, designed to pair
    with DASO for the global tier). On TPU the node boundary is the ICI/DCN boundary of
    a 2-D mesh; this wrapper is the same veneer with the communicator expected to carry
    that mesh — see ``heat_tpu.optim.DASO``."""

    def __init__(self, module: Module, optimizer=None, comm: Optional[Communication] = None):
        super().__init__(module, comm=comm, optimizer=optimizer)
