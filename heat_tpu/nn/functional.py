"""Functional nn ops (reference ``heat.nn.functional`` is ``torch.nn.functional``
via the fall-through in ``heat/nn/__init__.py:18-31``; the reference MNIST example
uses ``F.relu``/``F.max_pool2d``/``F.log_softmax``/``F.nll_loss``,
``examples/nn/mnist.py:26-43``).

Every function accepts a ``jax.Array`` or a :class:`DNDarray` (unwrapped, computed
globally, re-wrapped with the batch split preserved). Shapes follow torch NCHW
conventions; the convs/pools lower to XLA ops that tile onto the MXU/VPU.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dndarray import DNDarray

__all__ = [
    "relu",
    "leaky_relu",
    "gelu",
    "elu",
    "sigmoid",
    "tanh",
    "softmax",
    "log_softmax",
    "linear",
    "conv2d",
    "max_pool2d",
    "avg_pool2d",
    "dropout",
    "dropout2d",
    "batch_norm",
    "layer_norm",
    "flatten",
    "one_hot",
    "nll_loss",
    "cross_entropy",
    "mse_loss",
    "l1_loss",
]


def _pair(v) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


def _unwrap(x):
    return (x.larray, x) if isinstance(x, DNDarray) else (x, None)


def _rewrap(value, proto: Optional[DNDarray], split_rule="batch"):
    if proto is None:
        return value
    from ..core._operations import wrap_result

    split = proto.split if proto.split == 0 else None
    if split_rule == "scalar":
        split = None
    return wrap_result(value, proto, split)


def _elementwise(fn):
    def wrapped(x, *args, **kwargs):
        v, proto = _unwrap(x)
        out = fn(v, *args, **kwargs)
        if proto is None:
            return out
        from ..core._operations import wrap_result

        return wrap_result(out, proto, proto.split)

    return wrapped


relu = _elementwise(jax.nn.relu)
gelu = _elementwise(jax.nn.gelu)
elu = _elementwise(jax.nn.elu)
sigmoid = _elementwise(jax.nn.sigmoid)
tanh = _elementwise(jnp.tanh)


def leaky_relu(x, negative_slope: float = 0.01):
    v, proto = _unwrap(x)
    out = jax.nn.leaky_relu(v, negative_slope)
    return _rewrap(out, proto) if proto is not None else out


def softmax(x, dim: int = -1):
    v, proto = _unwrap(x)
    out = jax.nn.softmax(v, axis=dim)
    return _rewrap(out, proto) if proto is not None else out


def log_softmax(x, dim: int = -1):
    v, proto = _unwrap(x)
    out = jax.nn.log_softmax(v, axis=dim)
    return _rewrap(out, proto) if proto is not None else out


def linear(x, weight, bias=None):
    """``y = x @ W.T + b`` with torch's (out, in) weight layout."""
    v, proto = _unwrap(x)
    out = v @ weight.T
    if bias is not None:
        out = out + bias
    return _rewrap(out, proto) if proto is not None else out


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups: int = 1):
    """2-D convolution, torch semantics: x (N,C,H,W), weight (O, C/groups, kH, kW)."""
    v, proto = _unwrap(x)
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)
    out = jax.lax.conv_general_dilated(
        v,
        weight.astype(v.dtype),
        window_strides=(sh, sw),
        padding=((ph, ph), (pw, pw)),
        rhs_dilation=(dh, dw),
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    if bias is not None:
        out = out + bias.astype(out.dtype).reshape(1, -1, 1, 1)
    return _rewrap(out, proto) if proto is not None else out


def max_pool2d(x, kernel_size, stride=None, padding=0):
    """Max pooling over the two trailing spatial dims (torch semantics)."""
    v, proto = _unwrap(x)
    kh, kw = _pair(kernel_size)
    sh, sw = _pair(stride) if stride is not None else (kh, kw)
    ph, pw = _pair(padding)
    out = jax.lax.reduce_window(
        v,
        -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) else jnp.iinfo(v.dtype).min,
        jax.lax.max,
        window_dimensions=(1, 1, kh, kw),
        window_strides=(1, 1, sh, sw),
        padding=((0, 0), (0, 0), (ph, ph), (pw, pw)),
    )
    return _rewrap(out, proto) if proto is not None else out


def avg_pool2d(x, kernel_size, stride=None, padding=0):
    """Average pooling over the two trailing spatial dims (torch semantics:
    zero-padded positions count toward the divisor)."""
    v, proto = _unwrap(x)
    kh, kw = _pair(kernel_size)
    sh, sw = _pair(stride) if stride is not None else (kh, kw)
    ph, pw = _pair(padding)
    out = jax.lax.reduce_window(
        v,
        jnp.zeros((), v.dtype),
        jax.lax.add,
        window_dimensions=(1, 1, kh, kw),
        window_strides=(1, 1, sh, sw),
        padding=((0, 0), (0, 0), (ph, ph), (pw, pw)),
    ) / (kh * kw)
    return _rewrap(out, proto) if proto is not None else out


def dropout(x, p: float = 0.5, training: bool = True, key: Optional[jax.Array] = None):
    v, proto = _unwrap(x)
    if not training or p == 0.0:
        return x
    if key is None:
        raise ValueError("dropout in training mode needs an explicit PRNG key")
    keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
    out = jnp.where(keep, v / (1.0 - p), 0.0)
    return _rewrap(out, proto) if proto is not None else out


def dropout2d(x, p: float = 0.5, training: bool = True, key: Optional[jax.Array] = None):
    """Channel dropout: zeroes entire (N, C) feature maps (torch.nn.Dropout2d)."""
    v, proto = _unwrap(x)
    if not training or p == 0.0:
        return x
    if key is None:
        raise ValueError("dropout2d in training mode needs an explicit PRNG key")
    mask_shape = v.shape[:2] + (1,) * (v.ndim - 2)
    keep = jax.random.bernoulli(key, 1.0 - p, mask_shape)
    out = jnp.where(keep, v / (1.0 - p), 0.0)
    return _rewrap(out, proto) if proto is not None else out


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training: bool = False, momentum: float = 0.1, eps: float = 1e-5):
    """Batch normalization over all dims except the channel dim (dim 1).

    Returns ``(out, batch_mean, batch_var)`` — the stats so stateful callers can
    maintain running estimates (jax arrays are immutable; there is no in-place
    buffer update like torch's)."""
    v, proto = _unwrap(x)
    axes = (0,) + tuple(range(2, v.ndim))
    if training or running_mean is None:
        mean = jnp.mean(v, axis=axes)
        var = jnp.var(v, axis=axes)
    else:
        mean, var = running_mean, running_var
    shape = (1, -1) + (1,) * (v.ndim - 2)
    out = (v - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + eps)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    out = _rewrap(out, proto) if proto is not None else out
    return out, mean, var


def layer_norm(x, normalized_shape, weight=None, bias=None, eps: float = 1e-5):
    v, proto = _unwrap(x)
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    axes = tuple(range(v.ndim - len(normalized_shape), v.ndim))
    mean = jnp.mean(v, axis=axes, keepdims=True)
    var = jnp.var(v, axis=axes, keepdims=True)
    out = (v - mean) / jnp.sqrt(var + eps)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return _rewrap(out, proto) if proto is not None else out


def flatten(x, start_dim: int = 0, end_dim: int = -1):
    v, proto = _unwrap(x)
    nd = v.ndim
    end = end_dim if end_dim >= 0 else nd + end_dim
    shape = v.shape[:start_dim] + (-1,) + v.shape[end + 1 :]
    out = v.reshape(shape)
    if proto is not None:
        split = proto.split if proto.split is not None and proto.split < start_dim else (
            0 if proto.split == 0 else None
        )
        from ..core._operations import wrap_result

        return wrap_result(out, proto, split)
    return out


def one_hot(x, num_classes: int):
    v, proto = _unwrap(x)
    out = jax.nn.one_hot(v, num_classes)
    return _rewrap(out, proto) if proto is not None else out


def nll_loss(log_probs, target, reduction: str = "mean"):
    """Negative log likelihood over log-probabilities (torch semantics)."""
    lp, _ = _unwrap(log_probs)
    t, _ = _unwrap(target)
    picked = jnp.take_along_axis(lp, t[:, None].astype(jnp.int64), axis=1)[:, 0]
    if reduction == "mean":
        return -jnp.mean(picked)
    if reduction == "sum":
        return -jnp.sum(picked)
    return -picked


def cross_entropy(logits, target, reduction: str = "mean"):
    lg, _ = _unwrap(logits)
    return nll_loss(jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1), target, reduction)


def mse_loss(pred, target, reduction: str = "mean"):
    p, _ = _unwrap(pred)
    t, _ = _unwrap(target)
    sq = (p - t) ** 2
    if reduction == "mean":
        return jnp.mean(sq)
    if reduction == "sum":
        return jnp.sum(sq)
    return sq


def l1_loss(pred, target, reduction: str = "mean"):
    p, _ = _unwrap(pred)
    t, _ = _unwrap(target)
    d = jnp.abs(p - t)
    if reduction == "mean":
        return jnp.mean(d)
    if reduction == "sum":
        return jnp.sum(d)
    return d
