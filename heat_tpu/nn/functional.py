"""Functional nn ops (reference ``heat.nn.functional`` is ``torch.nn.functional``
via the fall-through in ``heat/nn/__init__.py:18-31``; the reference MNIST example
uses ``F.relu``/``F.max_pool2d``/``F.log_softmax``/``F.nll_loss``,
``examples/nn/mnist.py:26-43``).

Every function accepts a ``jax.Array`` or a :class:`DNDarray` (unwrapped, computed
globally, re-wrapped with the batch split preserved). Shapes follow torch NCHW
conventions; the convs/pools lower to XLA ops that tile onto the MXU/VPU.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dndarray import DNDarray

__all__ = [
    "relu",
    "leaky_relu",
    "gelu",
    "elu",
    "sigmoid",
    "tanh",
    "softmax",
    "log_softmax",
    "linear",
    "conv1d",
    "conv2d",
    "max_pool1d",
    "max_pool2d",
    "avg_pool1d",
    "avg_pool2d",
    "dropout",
    "dropout2d",
    "batch_norm",
    "layer_norm",
    "group_norm",
    "flatten",
    "one_hot",
    "nll_loss",
    "cross_entropy",
    "mse_loss",
    "l1_loss",
    "silu",
    "mish",
    "softplus",
    "hardtanh",
    "embedding",
    "conv_transpose2d",
    "adaptive_avg_pool2d",
    "adaptive_max_pool2d",
    "pad",
    "normalize",
    "cosine_similarity",
    "pairwise_distance",
    "binary_cross_entropy",
    "binary_cross_entropy_with_logits",
    "smooth_l1_loss",
    "huber_loss",
    "scaled_dot_product_attention",
]

# torch exposes sdpa under torch.nn.functional; same surface here (the
# implementation lives with the ring/flash dispatch in ``..nn.attention``)
from .attention import scaled_dot_product_attention  # noqa: E402


def _pair(v) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


def _str_padding(padding: str, strides):
    """torch's conv padding strings: 'valid' = no padding; 'same' = output keeps
    the input's spatial extent (torch requires stride 1 for 'same')."""
    if padding == "valid":
        return "VALID"
    if padding == "same":
        if any(s != 1 for s in strides):
            raise ValueError("padding='same' requires stride 1 (torch semantics)")
        return "SAME"
    raise ValueError(f"padding must be an int, a tuple, 'same' or 'valid', got {padding!r}")


def _unwrap(x):
    return (x.larray, x) if isinstance(x, DNDarray) else (x, None)


def _p(x):
    """Unwrap a parameter-like argument (weight/bias/running stats): DNDarrays are
    legal everywhere a tensor is in torch's functional API — the reference layer IS
    torch, so ``F.conv2d(ht_array, ht_weight)`` must work, not raise from deep
    inside XLA."""
    return x.larray if isinstance(x, DNDarray) else x


def _rewrap(value, proto: Optional[DNDarray], split_rule="batch"):
    if proto is None:
        return value
    from ..core._operations import wrap_result

    split = proto.split if proto.split == 0 else None
    if split_rule == "scalar":
        split = None
    return wrap_result(value, proto, split)


def _elementwise(fn):
    def wrapped(x, *args, **kwargs):
        v, proto = _unwrap(x)
        out = fn(v, *args, **kwargs)
        if proto is None:
            return out
        from ..core._operations import wrap_result

        return wrap_result(out, proto, proto.split)

    return wrapped


relu = _elementwise(jax.nn.relu)
elu = _elementwise(jax.nn.elu)


_gelu_impl = _elementwise(jax.nn.gelu)


def gelu(x, approximate: str = "none"):
    """torch.nn.functional.gelu: EXACT erf form by default (jax.nn.gelu defaults to
    the tanh approximation — ~1e-3 divergence from the reference's torch numerics);
    pass approximate='tanh' for the fast form."""
    if approximate not in ("none", "tanh"):
        raise ValueError(f"approximate must be 'none' or 'tanh', got {approximate!r}")
    return _gelu_impl(x, approximate=(approximate == "tanh"))
sigmoid = _elementwise(jax.nn.sigmoid)
tanh = _elementwise(jnp.tanh)


def leaky_relu(x, negative_slope: float = 0.01):
    v, proto = _unwrap(x)
    out = jax.nn.leaky_relu(v, negative_slope)
    return _rewrap(out, proto) if proto is not None else out


def softmax(x, dim: int = -1):
    v, proto = _unwrap(x)
    out = jax.nn.softmax(v, axis=dim)
    return _rewrap(out, proto) if proto is not None else out


def log_softmax(x, dim: int = -1):
    v, proto = _unwrap(x)
    out = jax.nn.log_softmax(v, axis=dim)
    return _rewrap(out, proto) if proto is not None else out


def linear(x, weight, bias=None):
    """``y = x @ W.T + b`` with torch's (out, in) weight layout."""
    v, proto = _unwrap(x)
    weight, bias = _p(weight), _p(bias)
    out = v @ weight.T
    if bias is not None:
        out = out + bias
    return _rewrap(out, proto) if proto is not None else out


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups: int = 1):
    """2-D convolution, torch semantics: x (N,C,H,W), weight (O, C/groups, kH, kW)."""
    v, proto = _unwrap(x)
    weight, bias = _p(weight), _p(bias)
    sh, sw = _pair(stride)
    dh, dw = _pair(dilation)
    if isinstance(padding, str):
        pad = _str_padding(padding, (sh, sw))
    else:
        ph, pw = _pair(padding)
        pad = ((ph, ph), (pw, pw))
    out = jax.lax.conv_general_dilated(
        v,
        weight.astype(v.dtype),
        window_strides=(sh, sw),
        padding=pad,
        rhs_dilation=(dh, dw),
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    if bias is not None:
        out = out + bias.astype(out.dtype).reshape(1, -1, 1, 1)
    return _rewrap(out, proto) if proto is not None else out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups: int = 1):
    """1-D convolution, torch semantics: x (N,C,L), weight (O, C/groups, k).

    The distributed-along-L counterpart is :func:`heat_tpu.signal.convolve`
    (reference ``core/signal.py``); this is the local nn-layer op."""
    v, proto = _unwrap(x)
    weight, bias = _p(weight), _p(bias)
    s = stride if isinstance(stride, int) else stride[0]
    d = dilation if isinstance(dilation, int) else dilation[0]
    if isinstance(padding, str):
        pad = _str_padding(padding, (s,))
    else:
        p = padding if isinstance(padding, int) else padding[0]
        pad = ((p, p),)
    out = jax.lax.conv_general_dilated(
        v,
        weight.astype(v.dtype),
        window_strides=(s,),
        padding=pad,
        rhs_dilation=(d,),
        feature_group_count=groups,
        dimension_numbers=("NCH", "OIH", "NCH"),
    )
    if bias is not None:
        out = out + bias.astype(out.dtype).reshape(1, -1, 1)
    return _rewrap(out, proto) if proto is not None else out


def max_pool1d(x, kernel_size, stride=None, padding=0):
    """Max pooling over the trailing length dim (torch semantics)."""
    v, proto = _unwrap(x)
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = (stride if isinstance(stride, int) else stride[0]) if stride is not None else k
    p = padding if isinstance(padding, int) else padding[0]
    out = jax.lax.reduce_window(
        v,
        -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) else jnp.iinfo(v.dtype).min,
        jax.lax.max,
        window_dimensions=(1, 1, k),
        window_strides=(1, 1, s),
        padding=((0, 0), (0, 0), (p, p)),
    )
    return _rewrap(out, proto) if proto is not None else out


def avg_pool1d(x, kernel_size, stride=None, padding=0):
    """Average pooling over the trailing length dim (torch semantics: zero-padded
    positions count toward the divisor).

    Implemented as a depthwise all-ones convolution rather than
    ``lax.reduce_window(add)``: this jax build cannot reverse-differentiate
    windowed sums under jit ("Linearization failed to produce known values"),
    while conv grads are solid — and the MXU likes convs anyway."""
    v, proto = _unwrap(x)
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = (stride if isinstance(stride, int) else stride[0]) if stride is not None else k
    p = padding if isinstance(padding, int) else padding[0]
    c = v.shape[1]
    ones = jnp.ones((c, 1, k), v.dtype)
    out = jax.lax.conv_general_dilated(
        v, ones, window_strides=(s,), padding=((p, p),),
        feature_group_count=c, dimension_numbers=("NCH", "OIH", "NCH"),
    ) / k
    return _rewrap(out, proto) if proto is not None else out


def max_pool2d(x, kernel_size, stride=None, padding=0):
    """Max pooling over the two trailing spatial dims (torch semantics)."""
    v, proto = _unwrap(x)
    kh, kw = _pair(kernel_size)
    sh, sw = _pair(stride) if stride is not None else (kh, kw)
    ph, pw = _pair(padding)
    out = jax.lax.reduce_window(
        v,
        -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) else jnp.iinfo(v.dtype).min,
        jax.lax.max,
        window_dimensions=(1, 1, kh, kw),
        window_strides=(1, 1, sh, sw),
        padding=((0, 0), (0, 0), (ph, ph), (pw, pw)),
    )
    return _rewrap(out, proto) if proto is not None else out


def avg_pool2d(x, kernel_size, stride=None, padding=0):
    """Average pooling over the two trailing spatial dims (torch semantics:
    zero-padded positions count toward the divisor).

    Depthwise all-ones convolution instead of ``lax.reduce_window(add)`` — see
    :func:`avg_pool1d` for why (windowed sums don't reverse-differentiate under
    jit on this jax build)."""
    v, proto = _unwrap(x)
    kh, kw = _pair(kernel_size)
    sh, sw = _pair(stride) if stride is not None else (kh, kw)
    ph, pw = _pair(padding)
    c = v.shape[1]
    ones = jnp.ones((c, 1, kh, kw), v.dtype)
    out = jax.lax.conv_general_dilated(
        v, ones, window_strides=(sh, sw), padding=((ph, ph), (pw, pw)),
        feature_group_count=c, dimension_numbers=("NCHW", "OIHW", "NCHW"),
    ) / (kh * kw)
    return _rewrap(out, proto) if proto is not None else out


def dropout(x, p: float = 0.5, training: bool = True, key: Optional[jax.Array] = None):
    v, proto = _unwrap(x)
    if not training or p == 0.0:
        return x
    if key is None:
        raise ValueError("dropout in training mode needs an explicit PRNG key")
    keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
    out = jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)
    if proto is None:
        return out
    from ..core._operations import wrap_result

    # elementwise: any split survives (not just batch)
    return wrap_result(out, proto, proto.split)


def dropout2d(x, p: float = 0.5, training: bool = True, key: Optional[jax.Array] = None):
    """Channel dropout: zeroes entire (N, C) feature maps (torch.nn.Dropout2d)."""
    v, proto = _unwrap(x)
    if not training or p == 0.0:
        return x
    if key is None:
        raise ValueError("dropout2d in training mode needs an explicit PRNG key")
    mask_shape = v.shape[:2] + (1,) * (v.ndim - 2)
    keep = jax.random.bernoulli(key, 1.0 - p, mask_shape)
    out = jnp.where(keep, v / (1.0 - p), 0.0)
    return _rewrap(out, proto) if proto is not None else out


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training: bool = False, momentum: float = 0.1, eps: float = 1e-5):
    """Batch normalization over all dims except the channel dim (dim 1).

    Returns ``(out, batch_mean, batch_var)`` — the stats so stateful callers can
    maintain running estimates (jax arrays are immutable; there is no in-place
    buffer update like torch's)."""
    v, proto = _unwrap(x)
    running_mean, running_var = _p(running_mean), _p(running_var)
    weight, bias = _p(weight), _p(bias)
    axes = (0,) + tuple(range(2, v.ndim))
    if training or running_mean is None:
        mean = jnp.mean(v, axis=axes)
        var = jnp.var(v, axis=axes)
    else:
        mean, var = running_mean, running_var
    shape = (1, -1) + (1,) * (v.ndim - 2)
    out = (v - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + eps)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    out = _rewrap(out, proto) if proto is not None else out
    return out, mean, var


def layer_norm(x, normalized_shape, weight=None, bias=None, eps: float = 1e-5):
    v, proto = _unwrap(x)
    weight, bias = _p(weight), _p(bias)
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    axes = tuple(range(v.ndim - len(normalized_shape), v.ndim))
    mean = jnp.mean(v, axis=axes, keepdims=True)
    var = jnp.var(v, axis=axes, keepdims=True)
    out = (v - mean) / jnp.sqrt(var + eps)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    if proto is None:
        return out
    from ..core._operations import wrap_result

    # statistics are per-position over the trailing normalized axes, so any
    # split on a leading axis (batch OR sequence) survives untouched
    keep = proto.split if (proto.split is not None and proto.split not in axes) else None
    return wrap_result(out, proto, keep)


def flatten(x, start_dim: int = 0, end_dim: int = -1):
    v, proto = _unwrap(x)
    nd = v.ndim
    end = end_dim if end_dim >= 0 else nd + end_dim
    shape = v.shape[:start_dim] + (-1,) + v.shape[end + 1 :]
    out = v.reshape(shape)
    if proto is not None:
        split = proto.split if proto.split is not None and proto.split < start_dim else (
            0 if proto.split == 0 else None
        )
        from ..core._operations import wrap_result

        return wrap_result(out, proto, split)
    return out


def one_hot(x, num_classes: int):
    v, proto = _unwrap(x)
    out = jax.nn.one_hot(v, num_classes)
    return _rewrap(out, proto) if proto is not None else out


def _nll_core(lp2, tflat, weight, ignore_index):
    """Shared masking machinery of nll_loss/cross_entropy on flattened (M, C)
    log-probs: returns (picked, w, keep) with ignored targets zero-weighted."""
    keep = tflat != ignore_index
    safe = jnp.where(keep, tflat, 0)
    picked = jnp.take_along_axis(lp2, safe[:, None], axis=1)[:, 0]
    w = weight[safe] if weight is not None else jnp.ones_like(picked)
    w = jnp.where(keep, w, 0.0)
    return picked, w, keep


def _class_flatten(x, target):
    """torch loss shapes: input (N, C) or (N, C, d1..dk) with the class dim at
    axis 1; returns ((M, C) view, flat int targets, target shape)."""
    t = target.astype(jnp.int32)
    if x.ndim > 2:
        c = x.shape[1]
        x2 = jnp.moveaxis(x, 1, -1).reshape(-1, c)
    else:
        x2 = x
    return x2, t.reshape(-1), t.shape


def _loss_reduce(per, w, reduction, out_shape, proto):
    if reduction == "mean":
        # all-ignored batches divide 0/0 -> NaN, matching torch
        return jnp.sum(per) / jnp.sum(w)
    if reduction == "sum":
        return jnp.sum(per)
    out = per.reshape(out_shape)
    return _rewrap(out, proto) if proto is not None else out


def nll_loss(log_probs, target, weight=None, ignore_index: int = -100,
             reduction: str = "mean"):
    """Negative log likelihood over log-probabilities (torch semantics incl.
    per-class ``weight``, ``ignore_index`` and K-dimensional (N, C, d1..dk)
    inputs; ignored targets contribute 0 and are excluded from the
    weighted-mean denominator)."""
    lp, plp = _unwrap(log_probs)
    t, pt = _unwrap(target)
    weight = _p(weight)
    lp2, tflat, tshape = _class_flatten(lp, t)
    picked, w, _keep = _nll_core(lp2, tflat, weight, ignore_index)
    per = -picked * w
    proto = plp if plp is not None else pt
    return _loss_reduce(per, w, reduction, tshape, proto)


def cross_entropy(logits, target, weight=None, ignore_index: int = -100,
                  reduction: str = "mean", label_smoothing: float = 0.0):
    """Softmax cross-entropy on raw logits (torch semantics incl. ``weight``,
    ``ignore_index``, ``label_smoothing`` — the target distribution becomes
    (1-ls)·onehot + ls/C — and K-dimensional (N, C, d1..dk) inputs)."""
    lg, plg = _unwrap(logits)
    t, pt = _unwrap(target)
    weight = _p(weight)
    lg2, tflat, tshape = _class_flatten(lg.astype(jnp.float32), t)
    lp2 = jax.nn.log_softmax(lg2, axis=-1)
    picked, w, keep = _nll_core(lp2, tflat, weight, ignore_index)
    per = -picked * w
    if label_smoothing:
        c = lp2.shape[-1]
        smooth = jnp.sum(lp2 * (weight if weight is not None else 1.0), axis=-1) / c
        per = (1.0 - label_smoothing) * per - label_smoothing * jnp.where(keep, smooth, 0.0)
    proto = plg if plg is not None else pt
    return _loss_reduce(per, w, reduction, tshape, proto)


def _ew_loss_reduce(loss, reduction, proto):
    """Shared reduction tail of the elementwise losses; 'none' re-wraps
    DNDarray inputs (any split survives — same-shape output)."""
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    if proto is not None:
        from ..core._operations import wrap_result

        return wrap_result(loss, proto, proto.split)
    return loss


def mse_loss(pred, target, reduction: str = "mean"):
    p, pp = _unwrap(pred)
    t, pt = _unwrap(target)
    return _ew_loss_reduce((p - t) ** 2, reduction, pp if pp is not None else pt)


def l1_loss(pred, target, reduction: str = "mean"):
    p, pp = _unwrap(pred)
    t, pt = _unwrap(target)
    return _ew_loss_reduce(jnp.abs(p - t), reduction, pp if pp is not None else pt)


silu = _elementwise(jax.nn.silu)
mish = _elementwise(jax.nn.mish)


def softplus(x, beta: float = 1.0, threshold: float = 20.0):
    """torch.nn.functional.softplus: linear above ``threshold`` for stability."""
    return _elementwise(
        lambda v: jnp.where(v * beta > threshold, v, jax.nn.softplus(v * beta) / beta)
    )(x)


def hardtanh(x, min_val: float = -1.0, max_val: float = 1.0):
    return _elementwise(lambda v: jnp.clip(v, min_val, max_val))(x)


def embedding(x, weight, padding_idx: Optional[int] = None):
    """Row lookup (torch.nn.functional.embedding). The ``padding_idx`` row takes no
    gradient (torch zeroes its grad every backward), so a zero-initialized padding
    row stays exactly zero for the whole training run."""
    v, proto = _unwrap(x)
    weight = _p(weight)
    out = jnp.take(weight, v.astype(jnp.int32), axis=0)
    if padding_idx is not None:
        # block exactly the cotangents that would scatter-add into the padding row —
        # O(batch) masking instead of an O(vocab) copy of the weight per forward
        if padding_idx < 0:  # torch normalizes negative indices
            padding_idx = padding_idx + weight.shape[0]
        idx = v.astype(jnp.int32) == padding_idx
        out = jnp.where(idx[..., None], jax.lax.stop_gradient(out), out)
    if proto is not None:
        from ..core._operations import wrap_result

        return wrap_result(out, proto, proto.split)
    return out


def group_norm(x, num_groups: int, weight=None, bias=None, eps: float = 1e-5):
    """torch.nn.functional.group_norm over (N, C, *spatial)."""
    v, proto = _unwrap(x)
    weight, bias = _p(weight), _p(bias)
    n, c = v.shape[:2]
    if c % num_groups:
        raise ValueError(f"num_channels {c} not divisible by num_groups {num_groups}")
    grouped = v.reshape(n, num_groups, c // num_groups, *v.shape[2:])
    axes = tuple(range(2, grouped.ndim))
    mean = jnp.mean(grouped, axis=axes, keepdims=True)
    var = jnp.var(grouped, axis=axes, keepdims=True)
    out = ((grouped - mean) / jnp.sqrt(var + eps)).reshape(v.shape)
    shape = (1, -1) + (1,) * (v.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return _rewrap(out, proto) if proto is not None else out


def conv_transpose2d(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups: int = 1, dilation=1):
    """torch.nn.functional.conv_transpose2d: x (N,C,H,W), weight (C, O/groups, kH, kW).

    Implemented as the standard fractionally-strided convolution: dilate the input
    by ``stride``, convolve with the spatially-flipped, in/out-swapped kernel.
    """
    v, proto = _unwrap(x)
    weight, bias = _p(weight), _p(bias)
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    oph, opw = _pair(output_padding)
    dh, dw = _pair(dilation)
    cin, og, kh, kw = weight.shape
    # (in, out/groups, kh, kw) -> (out, in/groups, kh, kw), spatially flipped
    w = weight.reshape(groups, cin // groups, og, kh, kw)
    w = jnp.flip(w, axis=(-2, -1)).transpose(0, 2, 1, 3, 4).reshape(groups * og, cin // groups, kh, kw)
    pad_h = dh * (kh - 1) - ph
    pad_w = dw * (kw - 1) - pw
    out = jax.lax.conv_general_dilated(
        v,
        w.astype(v.dtype),
        window_strides=(1, 1),
        padding=((pad_h, pad_h + oph), (pad_w, pad_w + opw)),
        lhs_dilation=(sh, sw),
        rhs_dilation=(dh, dw),
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    if bias is not None:
        out = out + bias.astype(out.dtype).reshape(1, -1, 1, 1)
    return _rewrap(out, proto) if proto is not None else out


def _adaptive_windows(in_size: int, out_size: int):
    """torch's adaptive pooling windows: start=floor(i*I/O), end=ceil((i+1)*I/O)."""
    starts = [(i * in_size) // out_size for i in range(out_size)]
    ends = [-(-((i + 1) * in_size) // out_size) for i in range(out_size)]
    return starts, ends


def _adaptive_pool2d(v, output_size, reduce_fn):
    oh, ow = _pair(output_size)
    h, w = v.shape[-2], v.shape[-1]
    hs, he = _adaptive_windows(h, oh)
    ws, we = _adaptive_windows(w, ow)
    rows = []
    for i in range(oh):
        cols = []
        for j in range(ow):
            cols.append(reduce_fn(v[..., hs[i]:he[i], ws[j]:we[j]], axis=(-2, -1)))
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)


def adaptive_avg_pool2d(x, output_size):
    """torch.nn.functional.adaptive_avg_pool2d over (..., H, W)."""
    v, proto = _unwrap(x)
    out = _adaptive_pool2d(v, output_size, jnp.mean)
    return _rewrap(out, proto) if proto is not None else out


def adaptive_max_pool2d(x, output_size):
    v, proto = _unwrap(x)
    out = _adaptive_pool2d(v, output_size, jnp.max)
    return _rewrap(out, proto) if proto is not None else out


def normalize(x, p: float = 2.0, dim: int = 1, eps: float = 1e-12):
    """torch.nn.functional.normalize: x / max(||x||_p, eps) along ``dim``."""
    v, proto = _unwrap(x)
    n = jnp.sum(jnp.abs(v) ** p, axis=dim, keepdims=True) ** (1.0 / p)
    out = v / jnp.maximum(n, eps)
    if proto is None:
        return out
    from ..core._operations import wrap_result

    return wrap_result(out, proto, proto.split)


def cosine_similarity(x1, x2, dim: int = 1, eps: float = 1e-8):
    """torch.nn.functional.cosine_similarity (clamps each norm at eps)."""
    v1, p1 = _unwrap(x1)
    v2, p2 = _unwrap(x2)
    n1 = jnp.maximum(jnp.linalg.norm(v1, axis=dim), eps)
    n2 = jnp.maximum(jnp.linalg.norm(v2, axis=dim), eps)
    out = jnp.sum(v1 * v2, axis=dim) / (n1 * n2)
    proto = p1 if p1 is not None else p2
    if proto is None:
        return out
    from ..core._operations import wrap_result

    d = dim if dim >= 0 else proto.ndim + dim
    # reduced-axis bookkeeping like every reduction: splits before d survive,
    # splits after d shift down by one, the reduced axis itself replicates
    keep = None
    if proto.split is not None:
        if proto.split < d:
            keep = proto.split
        elif proto.split > d:
            keep = proto.split - 1
    return wrap_result(out, proto, keep)


def pairwise_distance(x1, x2, p: float = 2.0, eps: float = 1e-6,
                      keepdim: bool = False):
    """torch.nn.functional.pairwise_distance: ||x1 - x2 + eps||_p over the last dim."""
    v1, p1 = _unwrap(x1)
    v2, p2 = _unwrap(x2)
    diff = jnp.abs(v1 - v2 + eps)
    out = jnp.sum(diff ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)
    proto = p1 if p1 is not None else p2
    if proto is None:
        return out
    from ..core._operations import wrap_result

    keep = proto.split if (proto.split is not None and proto.split < proto.ndim - 1) else None
    return wrap_result(out, proto, keep)


def pad(x, pad_widths, mode: str = "constant", value: float = 0.0):
    """torch.nn.functional.pad: ``pad_widths`` pairs up from the LAST dim —
    (left, right[, top, bottom[, ...]])."""
    v, proto = _unwrap(x)
    if len(pad_widths) % 2:
        raise ValueError("pad_widths must hold (before, after) pairs")
    npairs = len(pad_widths) // 2
    cfg = [(0, 0)] * (v.ndim - npairs) + [
        (int(pad_widths[2 * i]), int(pad_widths[2 * i + 1])) for i in range(npairs - 1, -1, -1)
    ]
    modes = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}
    jmode = modes.get(mode)
    if jmode is None:
        raise ValueError(f"unsupported pad mode {mode!r}; expected one of {sorted(modes)}")
    out = (
        jnp.pad(v, cfg, mode="constant", constant_values=value)
        if jmode == "constant"
        else jnp.pad(v, cfg, mode=jmode)
    )
    return _rewrap(out, proto) if proto is not None else out


def binary_cross_entropy(pred, target, weight=None, reduction: str = "mean"):
    """torch semantics: inputs are probabilities; log clamped at -100;
    ``weight`` rescales per element (broadcastable)."""
    p, pp = _unwrap(pred)
    t, pt = _unwrap(target)
    lo = jnp.maximum(jnp.log(p), -100.0)
    l1 = jnp.maximum(jnp.log1p(-p), -100.0)
    loss = -(t * lo + (1.0 - t) * l1)
    if weight is not None:
        loss = loss * _p(weight)
    return _ew_loss_reduce(loss, reduction, pp if pp is not None else pt)


def binary_cross_entropy_with_logits(pred, target, weight=None,
                                     reduction: str = "mean",
                                     pos_weight=None):
    """Numerically-stable sigmoid + BCE (torch semantics; ``weight`` rescales
    per element, ``pos_weight`` rescales the positive class)."""
    z, pp = _unwrap(pred)
    t, pt = _unwrap(target)
    # log(1+exp(-|z|)) + max(z,0) - z*t   (with optional positive-class weight)
    log_sig = jax.nn.log_sigmoid(z)
    log_sig_neg = jax.nn.log_sigmoid(-z)
    if pos_weight is not None:
        loss = -(_p(pos_weight) * t * log_sig + (1.0 - t) * log_sig_neg)
    else:
        loss = -(t * log_sig + (1.0 - t) * log_sig_neg)
    if weight is not None:
        loss = loss * _p(weight)
    return _ew_loss_reduce(loss, reduction, pp if pp is not None else pt)


def smooth_l1_loss(pred, target, reduction: str = "mean", beta: float = 1.0):
    """torch semantics: quadratic below ``beta``, linear above; ``beta=0`` is pure
    L1 (guarded separately — a 0/0 in the untaken where-branch would NaN the grad)."""
    p, pp = _unwrap(pred)
    t, pt = _unwrap(target)
    d = jnp.abs(p - t)
    if beta == 0.0:
        loss = d
    else:
        loss = jnp.where(d < beta, 0.5 * d * d / beta, d - 0.5 * beta)
    return _ew_loss_reduce(loss, reduction, pp if pp is not None else pt)


def huber_loss(pred, target, reduction: str = "mean", delta: float = 1.0):
    """torch semantics: smooth_l1 scaled by delta (quadratic below ``delta``)."""
    p, pp = _unwrap(pred)
    t, pt = _unwrap(target)
    d = jnp.abs(p - t)
    loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
    return _ew_loss_reduce(loss, reduction, pp if pp is not None else pt)
