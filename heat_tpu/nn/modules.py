"""Neural-network modules (reference heat/nn/: falls through to ``torch.nn``,
``nn/__init__.py:18-31``).

The reference trains *torch* modules locally and glues them together with MPI gradient
hooks. Torch modules cannot execute on TPU, so the TPU build ships a small native
module system in the idiomatic JAX shape: a module is a *structure* whose parameters
live in an explicit pytree, ``init(key)`` creates them, ``apply(params, x)`` is a pure
function jittable end-to-end. A convenience stateful veneer (``__call__`` using the
internally held params) preserves the torch-like feel of the reference examples.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dndarray import DNDarray

__all__ = [
    "Module",
    "Linear",
    "ReLU",
    "Tanh",
    "Sigmoid",
    "LogSoftmax",
    "Flatten",
    "Dropout",
    "Sequential",
    "MSELoss",
    "NLLLoss",
    "CrossEntropyLoss",
]


def _to_value(x):
    return x.larray if isinstance(x, DNDarray) else x


class Module:
    """Base module: explicit-parameter pytrees + pure ``apply``."""

    def init(self, key: jax.Array) -> Any:
        """Create this module's parameter pytree."""
        return ()

    def apply(self, params: Any, x: jax.Array, *, key: Optional[jax.Array] = None, train: bool = False) -> jax.Array:
        """Pure forward pass."""
        raise NotImplementedError()

    # ------------------------------------------------------------- stateful veneer
    @property
    def params(self):
        if not hasattr(self, "_params"):
            self._params = self.init(jax.random.key(0))
        return self._params

    @params.setter
    def params(self, value):
        self._params = value

    def reset_parameters(self, seed: int = 0) -> None:
        """Re-create parameters from a seed — every process derives identical values,
        the property the reference enforces by seed-broadcast + param Bcast
        (``nn/data_parallel.py:105-106``)."""
        self._params = self.init(jax.random.key(seed))

    def __call__(self, x, *, key=None, train: bool = False):
        value = self.apply(self.params, _to_value(x), key=key, train=train)
        if isinstance(x, DNDarray):
            from ..core._operations import wrap_result

            return wrap_result(value, x, x.split if x.split == 0 else None)
        return value


class Linear(Module):
    """Affine layer y = x W + b (torch.nn.Linear semantics, He-uniform init)."""

    def __init__(self, in_features: int, out_features: int, bias: bool = True):
        self.in_features = in_features
        self.out_features = out_features
        self.bias = bias

    def init(self, key):
        k1, k2 = jax.random.split(key)
        bound = 1.0 / np.sqrt(self.in_features)
        # float32 params regardless of the global x64 flag — the TPU-native precision
        w = jax.random.uniform(
            k1, (self.in_features, self.out_features), jnp.float32, -bound, bound
        )
        if not self.bias:
            return {"weight": w}
        b = jax.random.uniform(k2, (self.out_features,), jnp.float32, -bound, bound)
        return {"weight": w, "bias": b}

    def apply(self, params, x, *, key=None, train=False):
        y = x @ params["weight"]
        if self.bias:
            y = y + params["bias"]
        return y


class ReLU(Module):
    def apply(self, params, x, *, key=None, train=False):
        return jnp.maximum(x, 0.0)


class Tanh(Module):
    def apply(self, params, x, *, key=None, train=False):
        return jnp.tanh(x)


class Sigmoid(Module):
    def apply(self, params, x, *, key=None, train=False):
        return jax.nn.sigmoid(x)


class LogSoftmax(Module):
    def __init__(self, dim: int = -1):
        self.dim = dim

    def apply(self, params, x, *, key=None, train=False):
        return jax.nn.log_softmax(x, axis=self.dim)


class Flatten(Module):
    def apply(self, params, x, *, key=None, train=False):
        return x.reshape(x.shape[0], -1)


class Dropout(Module):
    def __init__(self, p: float = 0.5):
        self.p = p

    def apply(self, params, x, *, key=None, train=False):
        if not train or self.p == 0.0:
            return x
        if key is None:
            raise ValueError("Dropout in train mode needs an explicit PRNG key")
        keep = jax.random.bernoulli(key, 1.0 - self.p, x.shape)
        return jnp.where(keep, x / (1.0 - self.p), 0.0)


class Sequential(Module):
    """Chained modules (torch.nn.Sequential semantics)."""

    def __init__(self, *layers: Module):
        self.layers = list(layers)

    def init(self, key):
        keys = jax.random.split(key, max(len(self.layers), 1))
        return [layer.init(k) for layer, k in zip(self.layers, keys)]

    def apply(self, params, x, *, key=None, train=False):
        keys = (
            jax.random.split(key, max(len(self.layers), 1))
            if key is not None
            else [None] * len(self.layers)
        )
        for layer, p, k in zip(self.layers, params, keys):
            x = layer.apply(p, x, key=k, train=train)
        return x


# ------------------------------------------------------------------------- losses
class MSELoss:
    """Mean squared error. The global mean over a batch sharded on the mesh makes the
    gradient all-reduce implicit — this IS the reference's blocking Allreduce hook
    (``nn/data_parallel.py:220-238``), emitted by XLA instead of written by hand."""

    def __call__(self, pred, target):
        p, t = _to_value(pred), _to_value(target)
        return jnp.mean((p - t) ** 2)


class NLLLoss:
    """Negative log likelihood over log-probabilities (torch.nn.NLLLoss semantics)."""

    def __call__(self, log_probs, target):
        lp, t = _to_value(log_probs), _to_value(target)
        picked = jnp.take_along_axis(lp, t[:, None].astype(jnp.int64), axis=1)
        return -jnp.mean(picked)


class CrossEntropyLoss:
    """Softmax cross-entropy on raw logits (torch.nn.CrossEntropyLoss semantics)."""

    def __call__(self, logits, target):
        lg, t = _to_value(logits), _to_value(target)
        lp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
        picked = jnp.take_along_axis(lp, t[:, None].astype(jnp.int64), axis=1)
        return -jnp.mean(picked)
